"""Shared hand-parser for jax.profiler trace JSON (no tensorboard dep).

The tensorboard_plugin_profile converter is incompatible with this box's
TF, so the raw Chrome-trace JSON is parsed directly.  On this backend the
XLA op events live at pid 3 / tid 3; each carries ``hlo_category`` and
``bytes_accessed`` in its args.
"""
import collections
import glob
import gzip
import json

XLA_PID = XLA_TID = 3


def xla_events(trace_dir):
    """XLA op events of the newest trace under ``trace_dir``."""
    path = sorted(glob.glob(
        trace_dir + "/plugins/profile/*/*.trace.json.gz"))[-1]
    with gzip.open(path, "rt") as f:
        trace = json.load(f)
    return [e for e in trace["traceEvents"]
            if e.get("ph") == "X" and e.get("pid") == XLA_PID
            and e.get("tid") == XLA_TID]


def aggregate(events, key_fn):
    """Sum durations/calls/bytes of ``events`` grouped by ``key_fn``.

    Returns (groups, total_s): groups maps key -> [dur_s, calls,
    hlo_category, bytes_accessed], sorted by descending time.
    """
    groups = collections.defaultdict(lambda: [0.0, 0, "", 0.0])
    total = 0.0
    for e in events:
        dur = e.get("dur", 0) / 1e6          # us -> s
        total += dur
        args = e.get("args", {})
        rec = groups[key_fn(e, args)]
        rec[0] += dur
        rec[1] += 1
        rec[2] = args.get("hlo_category", rec[2])
        try:
            rec[3] += float(args.get("bytes_accessed", 0) or 0)
        except (TypeError, ValueError):
            pass
    ordered = dict(sorted(groups.items(), key=lambda kv: -kv[1][0]))
    return ordered, total
