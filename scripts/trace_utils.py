"""Shared hand-parser for jax.profiler trace JSON (no tensorboard dep).

The implementation moved into the observability subsystem
(dtdl_tpu/obs/trace.py, PR 3) so the serving/training tracer and the
profile scripts read Chrome-trace JSON with one parser; this module
stays as the import path the profile scripts (and any user scripts)
already use: ``from trace_utils import aggregate, xla_events``.
"""
import os
import sys

# the scripts run from scripts/ (cwd) without the repo root on sys.path
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from dtdl_tpu.obs.trace import (  # noqa: E402,F401
    XLA_PID, XLA_TID, aggregate, xla_events,
)
