#!/usr/bin/env python
"""The audit gate: lint the repo, optionally audit the pinned programs.

Usage::

    python scripts/audit.py [paths...]            # lint (default: dtdl_tpu/)
    python scripts/audit.py --list-rules          # the rule catalog
    python scripts/audit.py --programs            # + jaxpr/HLO contract audits
    python scripts/audit.py --programs --rebase   # regenerate baselines.json
    python scripts/audit.py --json                # machine-readable findings

Exit status: 0 when every finding is suppressed (``# audit: ok[rule-id]
reason`` on the offending or preceding line) and — under ``--programs``
— the census matches dtdl_tpu/analysis/baselines.json; 1 otherwise.
The lint half is pure AST (sub-second) and is what
tests/test_analysis_gate.py runs inside tier-1; ``--programs`` builds
and compiles the real train/megatron/decode/verify programs (tens of
seconds on CPU) — the same check the slow-marked
tests/test_analysis_contracts.py and bench.py's ``audit`` row run.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

_REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(_REPO))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("paths", nargs="*", default=None,
                   help="files/dirs to lint (default: dtdl_tpu/)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule-id (prefix) filter")
    p.add_argument("--programs", action="store_true",
                   help="also audit the pinned programs (compiles; see "
                        "dtdl_tpu/analysis/contracts.py)")
    p.add_argument("--rebase", action="store_true",
                   help="with --programs: write the observed census as "
                        "the new baselines.json")
    p.add_argument("--json", action="store_true",
                   help="emit findings as JSON instead of the report")
    args = p.parse_args(argv)

    from dtdl_tpu.analysis import lint_paths, render_report, rule_docs

    if args.list_rules:
        for rid, doc in rule_docs().items():
            print(f"{rid:24s} {doc}")
        return 0

    paths = args.paths or [str(_REPO / "dtdl_tpu")]
    only = args.rules.split(",") if args.rules else None
    findings = lint_paths(paths, root=str(_REPO), only_rules=only)

    reports = {}
    if args.programs:
        from dtdl_tpu.analysis import contracts
        runnable, skipped = contracts.runnable_programs()
        for name in skipped:
            print(f"{name}: SKIPPED (needs "
                  f"{contracts.MIN_DEVICES[name]} devices; run under "
                  f"XLA_FLAGS=--xla_force_host_platform_device_count=8 "
                  f"to audit it on CPU)", file=sys.stderr)
        reports = contracts.audit_programs(runnable)
        for rep in reports.values():
            findings.extend(rep.pop("_findings"))
        if args.rebase:
            path = contracts.save_baseline(reports)
            print(f"baseline written: {path}", file=sys.stderr)
        else:
            findings.extend(contracts.compare_to_baseline(
                reports, contracts.load_baseline()))

    if args.json:
        out = {"findings": [vars(f) | {"detail": f.detail}
                            for f in findings]}
        if reports:
            out["programs"] = {k: {kk: vv for kk, vv in v.items()
                                   if kk != "_findings"}
                               for k, v in reports.items()}
        print(json.dumps(out, indent=2, default=str))
    else:
        if reports:
            for name, rep in sorted(reports.items()):
                cc = {**rep["jaxpr_collectives"],
                      **rep["hlo_collectives"]}
                cstr = ", ".join(f"{k} x{v['count']}"
                                 for k, v in cc.items()) or "none"
                print(f"{name}: collectives [{cstr}], "
                      f"host_transfers={rep['host_transfers']}, "
                      f"donated {rep['n_donated_args']}/"
                      f"{rep['n_expected_donated']} args "
                      f"({rep['donated_bytes']} B)")
        if findings:
            print(render_report(
                findings,
                header=f"{len(findings)} unsuppressed finding(s):"))
        else:
            print("audit clean: no unsuppressed findings")
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
