"""Flash-attention block-size sweep (round-4 roofline artifact).

Times fwd+bwd of the Pallas kernel alone for block_q x block_k combos on
the real chip.  Default geometry is the bench headline (B=8 H=4 D=128
S=4096 bf16); pass ``B H S D`` on the command line for others (e.g.
``8 8 4096 64`` for the head_dim-64 check in LM_ROOFLINE.md section 2).
"""
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from dtdl_tpu.ops.attention import flash_attention

_defaults = (8, 4, 4096, 128)
_args = [int(x) for x in sys.argv[1:5]]
B, H, S, D = tuple(_args) + _defaults[len(_args):]
COMBOS = [(bq, bk) for bq in (256, 512, 1024) for bk in (256, 512, 1024)]
COMBOS += [(1024, 2048), (2048, 1024), (2048, 2048)]

rng = np.random.default_rng(0)
q = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.bfloat16)
k = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.bfloat16)
v = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.bfloat16)

# useful causal matmul flops (fwd 2 mm + bwd counted 2x fwd)
useful = 3 * 2 * 2 * B * H * S * S * D * 0.5

for bq, bk in COMBOS:
    try:
        def loss(q, k, v, bq=bq, bk=bk):
            o = flash_attention(q, k, v, causal=True, block_q=bq, block_k=bk)
            return jnp.sum(o.astype(jnp.float32))

        f = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        g = f(q, k, v)
        float(jnp.sum(g[0].astype(jnp.float32)))
        n = 20
        t0 = time.perf_counter()
        for _ in range(n):
            g = f(q, k, v)
        float(jnp.sum(g[0].astype(jnp.float32)))
        dt = (time.perf_counter() - t0) / n
        print(json.dumps({"bq": bq, "bk": bk, "ms": round(dt * 1e3, 3),
                          "useful_tflops": round(useful / dt / 1e12, 1),
                          "pct_peak": round(100 * useful / dt / 197e12, 1)}),
              flush=True)
    except Exception as e:
        print(json.dumps({"bq": bq, "bk": bk,
                          "error": f"{type(e).__name__}: {e}"[:120]}),
              flush=True)
