"""Measure the segmented-1F1B schedule win against the old lockstep scan.

The round-5 segmentation (megatron.py `_value_and_grad_1f1b`) claims the
warmup/cooldown lanes the lockstep scan wasted are real cost:
total (tf+tb)·T/v lockstep vs (tf+tb)·(T-(vS-1))/v segmented.  On this
box the 8-device mesh is virtual (one CPU core executes every device's
program serially), so wall-clock per step is proportional to TOTAL
executed ops across devices — exactly the quantity segmentation
reduces — making the single-core host a faithful scale model of the
schedule's cost, if not of its latency.

The old schedule is loaded from git history (commit 87ed655, the last
lockstep revision) into a throwaway module so both versions run the
IDENTICAL config in one process.  Expected ratio for S=4, M=4, v=1:
lockstep 3·(M+2(S-1)) = 30 chunk-units vs segmented 30-9 = 21 → ~1.4x.

Run:  python scripts/pp_schedule_bench.py
"""
import importlib.util
import json
import os
import subprocess
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass  # older jax: the XLA_FLAGS fallback above supplies the devices

sys.path.insert(0, REPO_ROOT)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

LOCKSTEP_REV = "87ed655"


def load_old_megatron():
    src = subprocess.run(
        ["git", "-C", REPO_ROOT, "show",
         f"{LOCKSTEP_REV}:dtdl_tpu/parallel/megatron.py"],
        capture_output=True, text=True, check=True).stdout
    with tempfile.NamedTemporaryFile("w", suffix="_megatron_old.py",
                                     delete=False) as f:
        f.write(src)
        path = f.name
    spec = importlib.util.spec_from_file_location("megatron_lockstep", path)
    mod = importlib.util.module_from_spec(spec)
    # dataclass decoration resolves cls.__module__ through sys.modules
    sys.modules["megatron_lockstep"] = mod
    spec.loader.exec_module(mod)
    return mod


def time_step(M, label, iters=6, warmup=2):
    from dtdl_tpu.runtime.mesh import build_mesh

    mesh = build_mesh((1, 1, 4, 2), M.AXES, devices=jax.devices())
    cfg = M.MegatronConfig(
        vocab_size=128, d_model=128, n_heads=4, d_ff=512,
        n_stages=4, layers_per_stage=2, n_microbatches=4,
        max_seq=256, dtype=jnp.float32)
    params = M.place_params(mesh, cfg,
                            M.init_params(cfg, jax.random.PRNGKey(0)))
    opt = optax.sgd(0.01)
    opt_state = M.init_optimizer(cfg, mesh, opt, params)
    step = M.make_megatron_train_step(cfg, mesh, opt)
    rng = np.random.default_rng(0)
    B, S = 8, 256
    batch = M.shard_lm_batch(mesh, {
        "tokens": rng.integers(0, 128, (B, S)).astype(np.int32),
        "targets": rng.integers(0, 128, (B, S)).astype(np.int32),
        "mask": np.ones((B, S), np.float32),
    })
    args = (batch["tokens"], batch["targets"], batch["mask"])
    for _ in range(warmup):
        params, opt_state, loss, _ = step(params, opt_state, *args)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, loss, _ = step(params, opt_state, *args)
    final = float(loss)
    dt = (time.perf_counter() - t0) / iters
    assert np.isfinite(final)
    return {"schedule": label, "step_ms": round(dt * 1e3, 1),
            "loss": round(final, 6)}


if __name__ == "__main__":
    old = load_old_megatron()
    from dtdl_tpu.parallel import megatron as new

    r_old = time_step(old, "lockstep")
    r_new = time_step(new, "segmented")
    ratio = r_old["step_ms"] / r_new["step_ms"]
    print(json.dumps({"lockstep": r_old, "segmented": r_new,
                      "speedup": round(ratio, 3),
                      "loss_equal": r_old["loss"] == r_new["loss"],
                      "predicted_speedup": round(30 / 21, 3)}))
