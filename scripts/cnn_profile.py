"""Per-op profile of a CNN train step (round-4 PyramidNet bs-sweep).

Usage: python scripts/cnn_profile.py [pyramidnet|resnet50] [batch] [n_top]
Aggregates XLA op time by hlo category from the raw trace JSON (shared
parser in scripts/trace_utils.py).
"""
import json
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dtdl_tpu.models import pyramidnet, resnet50
from dtdl_tpu.parallel import choose_strategy
from dtdl_tpu.train import init_state, make_train_step
from trace_utils import aggregate, xla_events

MODEL = sys.argv[1] if len(sys.argv) > 1 else "pyramidnet"
if MODEL not in ("pyramidnet", "resnet50"):
    sys.exit(f"unknown model {MODEL!r}: expected pyramidnet|resnet50")
BS = int(sys.argv[2]) if len(sys.argv) > 2 else 256
NTOP = int(sys.argv[3]) if len(sys.argv) > 3 else 20
TRACE_DIR = f"/tmp/cnn_trace_{MODEL}_{BS}"

strategy = choose_strategy("auto")
if MODEL == "resnet50":
    model, shape, classes = resnet50(dtype=jnp.bfloat16, s2d_stem=True), \
        (224, 224, 3), 1000
else:
    model, shape, classes = pyramidnet(dtype=jnp.bfloat16), (32, 32, 3), 10
state = strategy.replicate(init_state(
    model, jax.random.PRNGKey(0), jnp.zeros((1,) + shape),
    optax.sgd(0.1, momentum=0.9)))
step = make_train_step(strategy)
rng = np.random.default_rng(0)
batch = strategy.shard_batch({
    "image": jnp.asarray(rng.normal(size=(BS,) + shape), jnp.float32),
    "label": jnp.asarray(rng.integers(0, classes, BS))})
compiled = step.lower(state, batch).compile()
for _ in range(5):
    state, m = compiled(state, batch)
float(m["loss"])

jax.profiler.start_trace(TRACE_DIR)
for _ in range(3):
    state, m = compiled(state, batch)
float(m["loss"])
jax.profiler.stop_trace()

groups, total = aggregate(
    xla_events(TRACE_DIR), lambda e, args: args.get("hlo_category", "?"))
print(json.dumps({"model": MODEL, "bs": BS,
                  "total_ms_per_step": round(total / 3 * 1e3, 3)}))
for cat, (dur, n, _, b) in list(groups.items())[:NTOP]:
    print(json.dumps({
        "cat": cat, "calls_per_step": n // 3,
        "ms_per_step": round(dur / 3 * 1e3, 3),
        "pct": round(100 * dur / total, 2),
        "gb_per_step": round(b / 3e9, 3),
        "gbps": round(b / 1e9 / dur, 1) if dur else 0}))
