"""LM step-time sweep for the roofline analysis (round 4).

Times the causal-LM train step across (size, bs, seq, vocab-chunk)
configs on the real chip, and compares XLA cost_analysis FLOPs against
an analytic matmul-FLOP count — cost_analysis cannot see inside Pallas
kernels, so the flash-attention FLOPs are missing from the reported MFU.
"""
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from bench import lm_analytic_flops, peak_flops_per_chip
from dtdl_tpu.models import transformer_lm
from dtdl_tpu.parallel import choose_strategy
from dtdl_tpu.train import init_state, make_lm_train_step


def bench(size, bs, seq, chunk, remat=None, iters=30, warmup=5):
    strategy = choose_strategy("auto")
    overrides = {} if remat is None else {"remat": remat}
    model = transformer_lm(size, max_seq=seq, **overrides)
    tx = optax.adamw(3e-4)
    state = strategy.replicate(init_state(
        model, jax.random.PRNGKey(0), jnp.zeros((1, seq), jnp.int32), tx))
    step = make_lm_train_step(strategy, vocab_chunk_size=chunk)
    rng = np.random.default_rng(0)
    batches = [strategy.shard_batch({
        "tokens": jnp.asarray(
            rng.integers(0, model.vocab_size, (bs, seq)), jnp.int32),
    }) for _ in range(4)]
    compiled = step.lower(state, batches[0]).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    xla_flops = float(ca.get("flops") or 0)

    for i in range(warmup):
        state, m = compiled(state, batches[i % 4])
    float(m["loss"])
    t0 = time.perf_counter()
    for i in range(iters):
        state, m = compiled(state, batches[i % 4])
    loss = float(m["loss"])
    dt = time.perf_counter() - t0
    assert np.isfinite(loss)
    step_ms = 1e3 * dt / iters
    af = lm_analytic_flops(model, bs, seq)
    peak = peak_flops_per_chip()
    row = {
        "size": size, "bs": bs, "seq": seq, "chunk": chunk,
        "remat": model.remat,
        "step_ms": round(step_ms, 3),
        "tokens_per_sec": round(bs * (seq - 1) * iters / dt, 0),
        "xla_flops": xla_flops, "analytic_flops": af,
    }
    if peak:   # omit MFU on chips without a known bf16 peak (bench.py's
        row["mfu_xla"] = round(xla_flops * iters / dt / peak, 4)   # pattern)
        row["mfu_analytic"] = round(af * iters / dt / peak, 4)
    return row


if __name__ == "__main__":
    configs = [
        ("small", 8, 4096, 0),
        ("small", 32, 4096, 4096),
        ("base", 8, 4096, 0),
        ("base", 16, 4096, 4096),
        ("base", 32, 4096, 4096),
        ("base", 32, 2048, 4096),
        # round-5 'large' sweep (LM_ROOFLINE.md §6): remat off fits at
        # bs 4 and wins; the preset default (remat=True) shown at bs 8
        ("large", 4, 4096, 0, False),
        ("large", 4, 4096, 4096, False),
        ("large", 8, 4096, 4096, False),
        ("large", 8, 4096, 4096, True),
        # long-context rows (LM_ROOFLINE.md §7): MFU holds flat as seq
        # doubles/quadruples at fixed tokens-per-step — the O(seq) flash
        # memory bound in action
        ("base", 4, 8192, 0, False),
        ("base", 2, 16384, 4096, False),
        ("base", 1, 32768, 4096, False),
        ("large", 2, 8192, 0, False),
    ]
    if len(sys.argv) > 1 and sys.argv[1] == "--size":
        if len(sys.argv) < 3:
            raise SystemExit("--size needs a value (small/base/large)")
        configs = [c for c in configs if c[0] == sys.argv[2]]
        if not configs:
            raise SystemExit(f"no sweep configs for size {sys.argv[2]!r}")
    elif len(sys.argv) > 1:
        idx = [int(x) for x in sys.argv[1].split(",")]
        configs = [configs[i] for i in idx]
    for c in configs:
        try:
            row = bench(*c)
        except Exception as e:
            row = {"size": c[0], "bs": c[1], "seq": c[2], "chunk": c[3],
                   "error": f"{type(e).__name__}: {e}"[:200]}
        print(json.dumps(row), flush=True)
