"""Per-op profile of the LM train step (round-4 roofline analysis).

Captures a jax.profiler trace of the 'base' bs=8 seq=4096 train step on
the real chip and aggregates XLA op time by op-name prefix (shared trace
parser in scripts/trace_utils.py).
"""
import json
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dtdl_tpu.models import transformer_lm
from dtdl_tpu.parallel import choose_strategy
from dtdl_tpu.train import init_state, make_lm_train_step
from trace_utils import aggregate, xla_events

SIZE = sys.argv[1] if len(sys.argv) > 1 else "base"
BS = int(sys.argv[2]) if len(sys.argv) > 2 else 8
SEQ = int(sys.argv[3]) if len(sys.argv) > 3 else 4096
CHUNK = int(sys.argv[4]) if len(sys.argv) > 4 else 0
# 5th arg: override the preset's remat (e.g. 'large 4 4096 0 0' = the
# bench headline config, which turns the preset's remat off)
REMAT = ({} if len(sys.argv) <= 5
         else {"remat": bool(int(sys.argv[5]))})
TRACE_DIR = "/tmp/lm_trace"

strategy = choose_strategy("auto")
model = transformer_lm(SIZE, max_seq=SEQ, **REMAT)
state = strategy.replicate(init_state(
    model, jax.random.PRNGKey(0), jnp.zeros((1, SEQ), jnp.int32),
    optax.adamw(3e-4)))
step = make_lm_train_step(strategy, vocab_chunk_size=CHUNK)
rng = np.random.default_rng(0)
batch = strategy.shard_batch({"tokens": jnp.asarray(
    rng.integers(0, model.vocab_size, (BS, SEQ)), jnp.int32)})
compiled = step.lower(state, batch).compile()
for _ in range(5):
    state, m = compiled(state, batch)
float(m["loss"])

jax.profiler.start_trace(TRACE_DIR)
for _ in range(3):
    state, m = compiled(state, batch)
float(m["loss"])
jax.profiler.stop_trace()

groups, total = aggregate(
    xla_events(TRACE_DIR), lambda e, args: e["name"].split(".")[0])
print(json.dumps({"config": {"size": SIZE, "bs": BS, "seq": SEQ,
                             "chunk": CHUNK},
                  "total_s_3steps": round(total, 6)}))
for name, (dur, n, cat, bytes_acc) in list(groups.items())[:30]:
    print(json.dumps({
        "op": name[:60], "cat": cat, "calls": n,
        "time_ms": round(dur * 1e3, 3),
        "pct": round(100 * dur / total, 2),
        "gb_accessed": round(bytes_acc / 1e9, 3),
        "gbps": round(bytes_acc / 1e9 / dur, 1) if dur else 0,
    }))
