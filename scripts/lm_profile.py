"""Per-op profile of the LM train step (round-4 roofline analysis).

Captures a jax.profiler trace of the 'base' bs=8 seq=4096 train step on
the real chip and aggregates XLA op time by category / op name from the
raw trace events (pid 3 tid 3 = XLA ops on this backend; the
tensorboard_plugin_profile converter is incompatible with the installed
TF, so the trace JSON is parsed by hand).
"""
import collections
import glob
import gzip
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dtdl_tpu.models import transformer_lm
from dtdl_tpu.parallel import choose_strategy
from dtdl_tpu.train import init_state, make_lm_train_step

SIZE = sys.argv[1] if len(sys.argv) > 1 else "base"
BS = int(sys.argv[2]) if len(sys.argv) > 2 else 8
SEQ = int(sys.argv[3]) if len(sys.argv) > 3 else 4096
CHUNK = int(sys.argv[4]) if len(sys.argv) > 4 else 0
TRACE_DIR = "/tmp/lm_trace"

strategy = choose_strategy("auto")
model = transformer_lm(SIZE, max_seq=SEQ)
state = strategy.replicate(init_state(
    model, jax.random.PRNGKey(0), jnp.zeros((1, SEQ), jnp.int32),
    optax.adamw(3e-4)))
step = make_lm_train_step(strategy, vocab_chunk_size=CHUNK)
rng = np.random.default_rng(0)
batch = strategy.shard_batch({"tokens": jnp.asarray(
    rng.integers(0, model.vocab_size, (BS, SEQ)), jnp.int32)})
compiled = step.lower(state, batch).compile()
for _ in range(5):
    state, m = compiled(state, batch)
float(m["loss"])

jax.profiler.start_trace(TRACE_DIR)
for _ in range(3):
    state, m = compiled(state, batch)
float(m["loss"])
jax.profiler.stop_trace()

path = sorted(glob.glob(TRACE_DIR + "/plugins/profile/*/*.trace.json.gz"))[-1]
with gzip.open(path, "rt") as f:
    trace = json.load(f)

events = [e for e in trace["traceEvents"]
          if e.get("ph") == "X" and e.get("pid") == 3 and e.get("tid") == 3]
by_name = collections.defaultdict(lambda: [0.0, 0, "", 0.0])
total = 0.0
for e in events:
    dur = e.get("dur", 0) / 1e6  # us -> s
    total += dur
    args = e.get("args", {})
    key = e["name"].split(".")[0]
    rec = by_name[key]
    rec[0] += dur
    rec[1] += 1
    rec[2] = args.get("hlo_category", rec[2])
    try:
        rec[3] += float(args.get("bytes_accessed", 0) or 0)
    except (TypeError, ValueError):
        pass

rows = sorted(by_name.items(), key=lambda kv: -kv[1][0])
print(json.dumps({"config": {"size": SIZE, "bs": BS, "seq": SEQ,
                             "chunk": CHUNK},
                  "total_s_3steps": round(total, 6)}))
for name, (dur, n, cat, bytes_acc) in rows[:30]:
    print(json.dumps({
        "op": name[:60], "cat": cat, "calls": n,
        "time_ms": round(dur * 1e3, 3),
        "pct": round(100 * dur / total, 2),
        "gb_accessed": round(bytes_acc / 1e9, 3),
        "gbps": round(bytes_acc / 1e9 / dur, 1) if dur else 0,
    }))
