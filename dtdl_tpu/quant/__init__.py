"""int8 quantization for serving: weight-only matmuls + int8 KV.

The serve-stack entry point is one engine kwarg away —

    engine = InferenceEngine(model, params,
                             quantize_weights=True,   # int8 weights
                             kv_dtype="int8")         # int8 KV arena

— which quantizes the (f32/bf16) params into the QuantizedParams
pytree, swaps the model for its ``quantize=True`` clone (dequant-in-
kernel matmuls), and builds the int8+scales KV arena the attention
paths consume.  Same three compiled program families, zero new
programs; see dtdl_tpu/quant/core.py for the recipe and the byte
arithmetic, tests/test_quant.py for the parity contracts.  Kernel
round 2 adds the fp8 variants (``quantize_weights='w8f'`` /
``kv_dtype='fp8'``) through the same schema.
"""

from dtdl_tpu.quant.core import (  # noqa: F401
    FP8_DTYPE, FP8_MAX, Fp8UnsupportedError, SCALE_SUFFIX,
    canon_kv_dtype, canon_weight_quant, dequantize_params, fp8_supported,
    kv_quantize, kv_scale_dtype, quantize_params, quantize_tensor,
    tree_bytes, weight_dtypes,
)
from dtdl_tpu.quant.layers import QuantDenseGeneral  # noqa: F401
