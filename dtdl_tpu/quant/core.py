"""Symmetric int8 quantization for the serve stack (weights + KV).

Decode is HBM-bandwidth-bound: a batch of slots advancing one token
re-reads every parameter byte and every live KV byte once, so tokens/sec
is (param_bytes + kv_bytes) / bandwidth (SCALING.md "Serving latency
model") and shrinking the bytes IS the speedup.  This module is the
byte-shrinking half of that equation; the kernels that consume its
output live in dtdl_tpu/quant/layers.py (weights) and
models/transformer.py (KV).

**Weights** — the LLM.int8/AWQ-style *weight-only* recipe: every matmul
kernel is stored as an int8 tensor plus an f32 scale per OUTPUT feature
(symmetric per-channel: ``scale_c = max|w[..., c]| / 127``).  Because
the scale is constant along the contracted dims, it factors out of the
matmul —

    x @ (q * s)  ==  (x @ q) * s        (s per output column)

— so the dequant is a cheap multiply on the small matmul *output*, the
int8 kernel is converted to the compute dtype inside the fused matmul
read (registers/VMEM, never a materialized f32 weight copy in HBM), and
HBM parameter traffic drops to one byte per weight.  Activations stay in
the model dtype throughout: accuracy is per-channel-rounding only,
|w - q·s| <= s/2 elementwise, and the serve contract is the measured
logits-parity tolerance in tests/test_quant.py, not an asserted one.

Quantized sites (the matmul weights, i.e. where the decode bytes are):
attention q/k/v/out projections, the SwiGLU wi/wg/wo, and MoE expert
wi/wg/wo (per-expert per-output-channel scales).  Deliberately NOT
quantized: the embedding (its decode-path read is a one-row gather, not
a matmul sweep, and it doubles as the output head — quantizing it
perturbs every logit directly for no bandwidth win on the gather),
RMSNorm scales and the MoE router (O(d) vectors, noise in the byte
budget, high sensitivity).

**KV** — int8 cache rows with an f32 scale per (row, head, position)
for the dense arena and per (page, head, in-page position) for the
paged pool: quantize-on-scatter (each new K/V row is scaled off its own
max — write-once, so append-only pages never need rescaling), dequant
fused into the attention einsums on gather (the key scale multiplies
the [.., positions]-shaped logits, the value scale folds into the
softmax weights — no dequantized [.., D] copy is ever materialized).
See models/transformer.py `_verify_attend_slots` / `_paged_attend_slots`.

**fp8 (kernel round 2)** — the same two recipes with a float8_e4m3fn
payload: weights under ``quantize_weights='w8f'`` (per-output-channel
``amax/448`` scales, stored bf16), KV under ``kv_dtype='fp8'``
(write-once per-position bf16 scales).  Same pytree schema, same
scale-sidecar naming, same fused dequant sites — only the payload and
scale dtypes change, which is why ``quantize_params`` reads both off
the quantized clone's schema instead of hardcoding int8.  Two fp8
traps are handled centrally: casts to fp8 do NOT saturate (overflow is
NaN — every quantizer clips to ±448 in f32 first), and the stored bf16
scale must be EXACTLY the divisor used at quantize time (each scale is
round-tripped through bf16 before the divide).  Builds without the
dtype refuse by name at engine construction
(:class:`Fp8UnsupportedError`), never inside a traced function.

The **QuantizedParams pytree** returned by :func:`quantize_params` is a
plain nested dict with the SAME module paths as the source params —
each quantized kernel keeps its name and gains an ``<name>_scale``
sibling — matching what ``model.clone(quantize=True)`` declares, so the
serving engine can swap quantized weights in without touching any
program structure (same three compiled program families, pinned by
RecompileSentinel in tests/test_quant.py).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

#: suffix linking a quantized tensor to its scale in the params pytree
SCALE_SUFFIX = "_scale"

#: float8_e4m3fn when this jax build ships it (ml_dtypes), else None —
#: the capability gate behind every fp8 entry point.  ±448 is the
#: format's finite max; casts do NOT saturate (overflow -> NaN), so
#: every fp8 quantizer here clips in f32 first.
FP8_DTYPE = getattr(jnp, "float8_e4m3fn", None)
FP8_MAX = 448.0


class Fp8UnsupportedError(ValueError):
    """fp8 was requested in a configuration that cannot serve it —
    raised by name at ENGINE CONSTRUCTION (quantize_weights='w8f' /
    kv_dtype='fp8' on a jax build without float8_e4m3fn, or fp8 weights
    under a mesh whose sharding rules aren't a named preset with a
    quant rule map), never from inside a traced function."""


def fp8_supported() -> bool:
    """Whether this jax build can represent fp8 (float8_e4m3fn)."""
    return FP8_DTYPE is not None


def _is_fp8(dtype) -> bool:
    return FP8_DTYPE is not None and np.dtype(dtype) == np.dtype(FP8_DTYPE)


def canon_kv_dtype(kv_dtype):
    """Normalize a ``kv_dtype`` argument: ``None`` (store K/V at the
    model dtype — today's behavior), int8 (accepts ``jnp.int8`` /
    ``np.int8`` / ``"int8"``) or fp8 (``"fp8"`` / ``"float8_e4m3fn"`` /
    the dtype itself), anything else is a named error."""
    if kv_dtype is None:
        return None
    if kv_dtype == "fp8" or (isinstance(kv_dtype, str)
                             and kv_dtype == "float8_e4m3fn"):
        if FP8_DTYPE is None:
            raise Fp8UnsupportedError(
                "kv_dtype='fp8' needs a jax build with float8_e4m3fn "
                "(ml_dtypes); this one has none")
        return FP8_DTYPE
    try:
        if np.dtype(kv_dtype) == np.dtype(np.int8):
            return jnp.int8
        if _is_fp8(kv_dtype):
            return FP8_DTYPE
    except TypeError:
        pass
    raise ValueError(f"kv_dtype must be None (model dtype), int8 or "
                     f"fp8, got {kv_dtype!r}")


def kv_scale_dtype(kv_dtype):
    """Scale-sidecar dtype for a quantized KV arena: f32 for int8
    (legacy layout, pinned by the round-7 byte receipts), bf16 for fp8
    — a 4-byte scale per position would eat half of fp8's win over
    int8+f32, and bf16's 8 mantissa bits are what the fp8 payload can
    resolve anyway."""
    kv_dtype = canon_kv_dtype(kv_dtype)
    if kv_dtype is None:
        return None
    return jnp.bfloat16 if _is_fp8(kv_dtype) else jnp.float32


def canon_weight_quant(mode):
    """Normalize a ``quantize_weights`` argument: ``False``/``None`` ->
    ``False``; ``True`` / ``"int8"`` / int8 -> ``True`` (the round-12
    int8 recipe); ``"w8f"`` / ``"fp8"`` / fp8 -> ``"w8f"``
    (per-channel-scaled float8_e4m3fn).  Anything else is a named
    error, raised here so the engine refuses at construction."""
    if mode is None or mode is False:
        return False
    if mode is True or mode == "int8":
        return True
    if mode in ("w8f", "fp8"):
        if FP8_DTYPE is None:
            raise Fp8UnsupportedError(
                "quantize_weights='w8f' needs a jax build with "
                "float8_e4m3fn (ml_dtypes); this one has none")
        return "w8f"
    try:
        if np.dtype(mode) == np.dtype(np.int8):
            return True
        if _is_fp8(mode):
            return "w8f"
    except TypeError:
        pass
    raise ValueError(f"quantize_weights must be False, True/'int8' or "
                     f"'w8f' (fp8), got {mode!r}")


def weight_dtypes(mode):
    """(payload, scale) dtypes of a quantized weight for ``mode`` (a
    :func:`canon_weight_quant` output): int8+f32 or fp8+bf16."""
    if mode == "w8f":
        return FP8_DTYPE, jnp.bfloat16
    return jnp.int8, jnp.float32


def quantize_tensor(w, scale_shape, dtype=jnp.int8):
    """Symmetric per-channel quantization of one weight tensor.

    ``scale_shape`` is ``w.shape`` with every *contracted* (input) dim
    set to 1 — the keepdims layout the quantized modules declare, which
    is what makes this function generic over Dense / DenseGeneral /
    per-expert kernels: the 1-dims name the reduction axes.  ``dtype``
    selects the payload: int8 (default — returns ``(q int8, scale
    f32)`` with ``|w - q·scale| <= scale/2``) or float8_e4m3fn
    (``scale_c = max|w[..., c]| / 448``, scale stored bf16 — the weight
    is divided by the bf16-ROUNDED scale so the stored sidecar is
    exactly the dequant multiplier, and clipped to ±448 in f32 before
    the cast because fp8 casts overflow to NaN, not saturate).
    All-zero channels get scale 1 so nothing divides by zero.
    """
    w = jnp.asarray(w)
    if len(scale_shape) != w.ndim or any(
            s not in (1, d) for s, d in zip(scale_shape, w.shape)):
        raise ValueError(f"scale shape {tuple(scale_shape)} does not "
                         f"broadcast against weight shape {w.shape}")
    axes = tuple(i for i, s in enumerate(scale_shape) if s == 1)
    w32 = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=axes, keepdims=True) if axes \
        else jnp.abs(w32)
    if _is_fp8(dtype):
        scale = jnp.where(amax > 0, amax / FP8_MAX, 1.0)
        scale = scale.astype(jnp.bfloat16).astype(jnp.float32)
        q = jnp.clip(w32 / scale, -FP8_MAX, FP8_MAX).astype(FP8_DTYPE)
        return q, scale.astype(jnp.bfloat16)
    if np.dtype(dtype) != np.dtype(np.int8):
        raise ValueError(f"quantize_tensor supports int8 or fp8 "
                         f"payloads, got {np.dtype(dtype)}")
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def quantize_params(model, params, mode=True):
    """f32/bf16 params -> the QuantizedParams pytree of
    ``model.clone(quantize=mode)``.

    ``model`` is the UNQUANTIZED model the params belong to; its
    quantized clone's abstract param tree (``jax.eval_shape`` of init —
    no compute) is the schema: wherever that tree carries a
    ``<name>_scale`` sibling, ``params[<name>]`` is quantized with
    :func:`quantize_tensor` (the scale's keepdims shape names the
    reduction axes, the schema leaf's DTYPE names the payload — int8 or
    fp8, so one walk serves both recipes); every other leaf passes
    through untouched (embed, norms, router — see module docstring).
    Structure mismatches raise with the offending path instead of
    silently dropping weights.  ``mode`` is a
    :func:`canon_weight_quant` value (``True`` int8, ``'w8f'`` fp8).
    """
    import flax.linen as nn

    qmodel = model.clone(quantize=canon_weight_quant(mode) or True)
    params = nn.unbox(params)
    shapes = nn.unbox(jax.eval_shape(
        qmodel.init, jax.random.PRNGKey(0),
        jnp.zeros((1, 1), jnp.int32))["params"])

    def conv(src, ref, path):
        if not isinstance(ref, dict):
            return src
        if not isinstance(src, dict):
            raise ValueError(f"params mismatch at {'/'.join(path)}: "
                             f"expected a dict, got {type(src).__name__}")
        out = {}
        for name, sub in ref.items():
            base = name[:-len(SCALE_SUFFIX)]
            if name.endswith(SCALE_SUFFIX) and base in ref:
                continue                      # emitted with its tensor
            if name not in src:
                raise ValueError(f"params are missing "
                                 f"{'/'.join(path + (name,))}")
            if f"{name}{SCALE_SUFFIX}" in ref:
                if f"{name}{SCALE_SUFFIX}" in src:
                    # a scale sibling in the SOURCE means the tree is
                    # already quantized — re-quantizing would drop the
                    # real scales and re-round the int8 payload as if
                    # it were float weights (silent garbage)
                    raise ValueError(
                        f"params already carry "
                        f"{'/'.join(path + (name + SCALE_SUFFIX,))}: "
                        f"the tree is already quantized")
                q, s = quantize_tensor(
                    src[name], ref[f"{name}{SCALE_SUFFIX}"].shape,
                    dtype=ref[name].dtype)
                out[name], out[f"{name}{SCALE_SUFFIX}"] = q, s
            else:
                out[name] = conv(src[name], sub, path + (name,))
        extra = set(src) - set(out)
        if extra:
            raise ValueError(f"unexpected params under "
                             f"{'/'.join(path) or '<root>'}: "
                             f"{sorted(extra)}")
        return out

    return conv(params, shapes, ())


def dequantize_params(qparams):
    """Inverse of :func:`quantize_params` up to per-channel rounding:
    every ``(q, <name>_scale)`` pair becomes the f32 ``q * scale`` —
    the reference the parity tests diff the in-kernel dequant against."""
    def conv(tree):
        if not isinstance(tree, dict):
            return tree
        out = {}
        for name, sub in tree.items():
            base = name[:-len(SCALE_SUFFIX)]
            if name.endswith(SCALE_SUFFIX) and base in tree:
                continue
            scale = tree.get(f"{name}{SCALE_SUFFIX}")
            if scale is not None:
                out[name] = jnp.asarray(sub, jnp.float32) * scale
            else:
                out[name] = conv(sub)
        return out
    return conv(qparams)


def kv_quantize(x, dtype=jnp.int8):
    """Per-(…, position) symmetric quantization for a K/V tensor
    ``[..., D]``: returns ``(q [..., D], scale [...])`` with
    ``x ≈ q * scale[..., None]``.  The scale comes from the new row's
    own max — write-once, so a cache position never needs rescaling
    after later writes (the append-only discipline quantized KV arenas
    require).  ``dtype`` int8 (default) keeps the round-7 layout
    (int8 payload, f32 scale); float8_e4m3fn stores an fp8 payload with
    a bf16 scale (:func:`kv_scale_dtype`) — the row is divided by the
    bf16-ROUNDED scale and clipped to ±448 in f32 before the cast
    (fp8 casts overflow to NaN, not saturate)."""
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=-1)
    if _is_fp8(dtype):
        scale = jnp.where(amax > 0, amax / FP8_MAX, 1.0)
        scale = scale.astype(jnp.bfloat16).astype(jnp.float32)
        q = jnp.clip(x32 / scale[..., None],
                     -FP8_MAX, FP8_MAX).astype(FP8_DTYPE)
        return q, scale.astype(jnp.bfloat16)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.round(x32 / scale[..., None]).astype(jnp.int8)
    return q, scale


def tree_bytes(tree) -> int:
    """Total bytes of a pytree of arrays or ShapeDtypeStructs — the
    byte receipts ``InferenceEngine.compile_stats`` reports.  Generic
    over every payload the arenas use (``np.dtype`` itemsize covers the
    ml_dtypes fp8 types: float8_e4m3fn is 1 byte)."""
    return int(sum(math.prod(leaf.shape) * np.dtype(leaf.dtype).itemsize
                   for leaf in jax.tree.leaves(tree)))
