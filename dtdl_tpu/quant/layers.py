"""Quantized weight-only dense layers — dequant-in-kernel matmuls.

:class:`QuantDenseGeneral` is a drop-in for the bias-free
``nn.DenseGeneral`` the transformer's projections use: same module name,
same ``kernel`` param name and shape (so a quantized params tree keeps
the f32 tree's module paths — dtdl_tpu/quant/core.py), plus a
``kernel_scale`` param in the keepdims per-output-feature layout.  The
forward is the scale-fused ``lax.dot_general``:

    y = dot_general(x, q.astype(dtype)) * scale

The payload→dtype convert is element-wise on a dot operand, which XLA
fuses into the matmul's HBM read — the weight crosses HBM as ONE byte
per element and no f32/bf16 copy of it is ever materialized.  Because
the scale is per output channel (constant along every contracted dim)
the output multiply is *exactly* the dequantized matmul, not an
approximation of it: the only error vs f32 is the per-channel rounding
of the stored payload (|w - q·s| <= s/2 for int8,
dtdl_tpu/quant/core.py).  ``mode`` picks the payload/scale dtype pair:
``True``/'int8' -> int8 + f32 (round 12), ``'w8f'`` -> float8_e4m3fn +
bf16 (kernel round 2 — fp8's relative-precision grid replaces int8's
fixed 127-step one, so the error bound is multiplicative, ~2^-3
relative, instead of the additive s/2).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from dtdl_tpu.quant.core import weight_dtypes


class QuantDenseGeneral(nn.Module):
    """Bias-free ``nn.DenseGeneral`` over a quantized kernel + a
    per-output-feature scale (see module docstring).  ``axis`` names the
    input dims to contract (the transformer uses ``-1`` for q/k/v/mlp
    and ``(-2, -1)`` for the attention out-projection); params are
    ``kernel`` ``[*in_dims, *features]`` and ``kernel_scale``
    ``[1…1, *features]`` in the dtypes ``mode`` selects — init yields
    placeholder zeros/ones, real values come from ``quantize_params``
    (a quantized model is never trained, only served)."""

    features: Any          # int or tuple of output feature dims
    axis: Any = -1         # int or tuple of input axes to contract
    dtype: Any = jnp.bfloat16
    mode: Any = True       # True/'int8' -> int8+f32, 'w8f' -> fp8+bf16

    @nn.compact
    def __call__(self, x):
        features = (self.features if isinstance(self.features, tuple)
                    else (self.features,))
        axis = self.axis if isinstance(self.axis, tuple) else (self.axis,)
        axis = tuple(sorted(a % x.ndim for a in axis))
        in_shape = tuple(x.shape[a] for a in axis)
        n_in = len(in_shape)
        payload_dtype, scale_dtype = weight_dtypes(self.mode)
        kernel = self.param(
            "kernel",
            lambda *_: jnp.zeros(in_shape + features, payload_dtype))
        scale = self.param(
            "kernel_scale",
            lambda *_: jnp.ones((1,) * n_in + features, scale_dtype))
        y = jax.lax.dot_general(
            x.astype(self.dtype), kernel.astype(self.dtype),
            ((axis, tuple(range(n_in))), ((), ())))
        # scale-fused dequant: f32 multiply on the (small) matmul output,
        # cast back to the compute dtype — bitwise the dequantized matmul
        # for f32 models, one rounding for bf16
        return (y * scale.reshape(features).astype(jnp.float32)
                ).astype(self.dtype)
