"""Elastic multi-host training: peer liveness, collective watchdogs,
generation-fenced re-rendezvous, shrink-to-survivors resume (ISSUE 12).

The multi-process training plane was fail-stop: one dead or wedged peer
hung every survivor inside a collective forever (the barrier timeout of
PR 5 *names* the hang; nothing recovers from it).  This module is the
training-plane twin of the PR 9 serving-fleet state machine — the same
detect → abort → re-form → resume shape, over workers instead of
replicas:

* **peer liveness** — every worker holds a heartbeat *lease* in the
  host-side control-plane store (:class:`~dtdl_tpu.parallel.kvstore.
  HostKVStore`): a beat thread refreshes ``hb/{rank}`` every
  ``heartbeat_s`` and the store stamps arrivals on ONE clock.  A peer
  whose lease goes quiet for ``watchdog_s`` is *dead* (crashed host,
  partitioned network) and survivors learn it without waiting out a
  step deadline.
* **collective/step watchdogs** — the gradient exchange runs under a
  deadline.  A missing contribution past ``step_timeout_s`` (the
  wedged-peer case: lease fresh, gradients absent) or an expired lease
  aborts the step with a named :class:`PeerLostError` — never a silent
  hang.  :class:`StepWatchdog` offers the same deadline for plain
  shard_map loops (``Trainer(watchdog=...)``), where the hung
  collective is abandoned on a daemon thread exactly like the PR 5
  barrier timeout.
* **generation-fenced re-rendezvous** — survivors re-form through
  :func:`rendezvous`: the store's generation is CAS-bumped (concurrent
  proposers coalesce), joiners register under the new epoch, and the
  provisional leader (lowest joined rank) closes membership after a
  quiet window.  Every step-plane key and barrier carries the epoch, so
  a stale peer waking from a stall can never write into the new world:
  it is refused by a named :class:`~dtdl_tpu.parallel.kvstore.
  StaleGenerationError` — mirroring PR 9's generation-fenced replica
  restart.  Rendezvous itself is retry/timeout/backoff-bounded (store
  ops ride :class:`~dtdl_tpu.parallel.kvstore.RetryingStore`).
* **shrink-to-survivors resume** — the new world restores the last
  *committed* snapshot (PR 5 integrity manifests; the commit marker
  lives in the store, written only after the blob is durable), and the
  world-size-agnostic :class:`~dtdl_tpu.data.sharding.
  GlobalBatchSampler` re-slices the identical remaining sample stream
  over the survivors: the replayed window drops no sample and
  double-counts none, and the post-shrink timeline is bitwise equal to
  a fault-free run of the surviving world restored from the same
  snapshot.  :func:`~dtdl_tpu.runtime.mesh.shrink_mesh` is the
  device-plane counterpart for multi-device hosts.

Aggregation is host-mediated (workers push gradient trees into the
store, pull the rank-ordered sum — the MXNet ``dist_sync`` idiom the
KVStore module documents), which is precisely what makes shrink
possible: no XLA collective holds a ticket for the ghost.  Tests and
the bench drill host workers as threads sharing one store and one JAX
runtime — the PR 9 CPU-testable construction — with every failure edge
injected deterministically through :func:`~dtdl_tpu.resil.faults.
peer_site`.  Every event on the failure path is named and cataloged
(``elastic_*`` in obs/trace.py): detection, abort, re-form, restore,
fence — no silent hangs anywhere.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Callable, Optional

import jax
import numpy as np

# NOTE: dtdl_tpu.ckpt.checkpoint is imported lazily inside the
# restore/commit methods — the checkpoint layer itself imports
# resil.faults (its injection sites), so a module-level import here
# would be circular through the resil package __init__.
from dtdl_tpu.obs.observer import NULL_OBSERVER
from dtdl_tpu.parallel.kvstore import (  # noqa: F401  (re-exported)
    HostKVStore, RetryingStore, StaleGenerationError, StoreTimeoutError,
    store_barrier,
)
from dtdl_tpu.resil.faults import InjectedFault, fire, peer_site


class PeerLostError(RuntimeError):
    """A peer is dead (expired lease) or wedged (step deadline expired):
    the step was aborted instead of waiting on a ghost.  ``lost`` names
    the ranks when they are known; survivors should re-rendezvous."""

    def __init__(self, lost=(), generation: Optional[int] = None,
                 reason: str = ""):
        self.lost = tuple(sorted(lost))
        self.generation = generation
        gen = f" at generation {generation}" if generation is not None \
            else ""
        who = f"peer(s) {list(self.lost)}" if self.lost else "a peer"
        super().__init__(f"{who} lost{gen}: {reason}")


class RendezvousError(RuntimeError):
    """A (re-)rendezvous did not form a world within its timeout —
    fewer than ``min_world`` survivors showed up, or the store is
    unreachable.  Named so the launcher can requeue instead of hanging."""


@dataclasses.dataclass(frozen=True)
class World:
    """One formed training world: the epoch and its sorted membership."""

    generation: int
    ranks: tuple
    rank: int                       # this worker's original id

    @property
    def index(self) -> int:
        """Position among the survivors — the data-shard coordinate."""
        return self.ranks.index(self.rank)

    @property
    def size(self) -> int:
        return len(self.ranks)

    @property
    def is_leader(self) -> bool:
        return self.index == 0


@dataclasses.dataclass
class ElasticConfig:
    """Knobs of the detect → abort → re-form → resume machine.

    ``watchdog_s`` is the lease TTL (dead-peer detection bound);
    ``step_timeout_s`` the per-step collective deadline (wedged-peer
    bound, deliberately ≫ watchdog so a crash is attributed to the
    lease, not the deadline).  The deadline must comfortably exceed the
    worst-case gap between the fastest and slowest peer *entering* the
    exchange — including a post-re-form restore and any first-call
    compile — or a merely slow peer reads as wedged and the world
    churns through spurious re-forms (they converge, since a slow peer
    stays a member of every formed world, but each costs a restore;
    warm the compiled step before arming the machine, the PR 9 router
    lesson).  ``join_grace_s`` is how long a forming rendezvous stays
    open after its last joiner — it must cover the spread of the
    survivors' abort times; ``heartbeat_s <= 0`` disables the liveness
    layer (bench baseline)."""

    heartbeat_s: float = 0.05
    watchdog_s: float = 0.3
    step_timeout_s: float = 5.0
    poll_s: float = 0.02
    join_grace_s: float = 0.25
    rendezvous_timeout_s: float = 10.0
    min_world: int = 1
    snapshot_every: int = 2


class HeartbeatLease:
    """Publishes this worker's lease: ``hb/{rank}`` refreshed every
    ``heartbeat_s`` from a daemon thread (host-side only — zero device
    syncs).  The *store* stamps each beat, so lease age is judged on
    one clock.  The beat thread fires the ``peer_site(rank,
    'heartbeat')`` fault point, making partitioned-peer scenarios
    (beats stop, main loop runs on) deterministically injectable."""

    def __init__(self, store, rank: int, heartbeat_s: float):
        self.store = store
        self.rank = rank
        self.heartbeat_s = heartbeat_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._beats = 0

    def start(self) -> "HeartbeatLease":
        if self.heartbeat_s <= 0 or self._thread is not None:
            return self
        self._beat()                        # lease live before step 0
        self._thread = threading.Thread(
            target=self._run, name=f"elastic-hb-{self.rank}", daemon=True)
        self._thread.start()
        return self

    def _beat(self) -> None:
        fire(peer_site(self.rank, "heartbeat"))   # may stall/raise
        self._beats += 1
        self.store.set(f"hb/{self.rank}", self._beats)

    def _run(self) -> None:
        while not self._stop.wait(self.heartbeat_s):
            try:
                self._beat()
            except InjectedFault:
                return                      # injected beat-thread death
            except Exception:
                return          # a dead store ends the lease — honest

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)
            self._thread = None


def dead_peers(store, ranks, watchdog_s: float):
    """Ranks whose lease has gone quiet for longer than ``watchdog_s``
    (or never beat at all) — the liveness verdict survivors act on."""
    dead = []
    for r in ranks:
        age = store.age(f"hb/{r}")
        if age is None or age > watchdog_s:
            dead.append(r)
    return tuple(dead)


def rendezvous(store, rank: int, cfg: ElasticConfig,
               observer=NULL_OBSERVER, prev_world: Optional[World] = None
               ) -> World:
    """Generation-fenced world formation (module docstring, item c).

    Survivors of ``prev_world`` CAS-bump the store generation (one bump
    no matter how many propose) and join the new round; fresh workers
    join the bootstrap round.  The provisional leader — lowest joined
    rank — publishes membership once the round has been quiet for
    ``join_grace_s`` and at least ``min_world`` joined.  The fence: a
    worker that a *formed* world excludes (it stalled through the whole
    window, or arrived after bootstrap closed) is refused with a named
    :class:`StaleGenerationError`; fewer than ``min_world`` joiners
    raise :class:`RendezvousError` at the deadline.  Store ops should
    ride :class:`RetryingStore` for bounded transient-fault retries.
    """
    fire(peer_site(rank, "join"))           # the late-joiner fault point
    my_gen = prev_world.generation if prev_world is not None else -1
    deadline = time.monotonic() + cfg.rendezvous_timeout_s
    while True:
        latest = store.get("world/latest", None)
        if latest is not None:
            lgen, lranks = latest
            if lgen > my_gen and rank not in lranks:
                raise StaleGenerationError(
                    f"worker {rank} fenced out: world generation {lgen} "
                    f"formed without it (last member of generation "
                    f"{my_gen}) — a stale peer cannot rejoin")
        gen = store.generation
        if prev_world is not None and gen == prev_world.generation:
            gen = store.bump_generation(gen)    # propose the new round
        store.set(f"rdzv/{gen}/join/{rank}", rank)
        while True:
            ranks = store.get(f"world/{gen}", None)
            if ranks is not None:
                if rank not in ranks:
                    raise StaleGenerationError(
                        f"worker {rank} fenced out: it joined generation "
                        f"{gen} after membership closed on {list(ranks)}")
                world = World(gen, tuple(ranks), rank)
                observer.event("elastic_rendezvous", generation=gen,
                               rank=rank, size=world.size,
                               ranks=str(list(ranks)))
                return world
            if store.generation != gen:
                break                           # a newer round started
            joined = sorted(
                int(k.rsplit("/", 1)[1])
                for k in store.keys(f"rdzv/{gen}/join/"))
            if (joined and joined[0] == rank
                    and len(joined) >= cfg.min_world):
                quiet = store.newest_age(f"rdzv/{gen}/join/")
                if quiet is not None and quiet >= cfg.join_grace_s:
                    # provisional leader closes the round
                    store.set(f"world/{gen}", tuple(joined))
                    store.set("world/latest", (gen, tuple(joined)))
                    continue
            if time.monotonic() > deadline:
                raise RendezvousError(
                    f"rendezvous at generation {gen} formed no world "
                    f"within {cfg.rendezvous_timeout_s}s (joined: "
                    f"{joined}, min_world: {cfg.min_world})")
            time.sleep(cfg.poll_s)


def exchange_grads(store, world: World, step: int, grads, cfg: ElasticConfig):
    """Push this worker's gradient tree, pull the rank-ordered sum —
    the deadline-guarded collective (module docstring, item b).

    The wait is sliced: between slices the liveness view is consulted
    (an expired lease aborts within ``watchdog_s`` — no need to wait
    out the step deadline for a crashed peer) and the epoch is checked
    (a bumped generation means the world moved on; the caller
    re-rendezvouses).  Expiry raises :class:`PeerLostError` naming the
    missing ranks.  Summation is in ``world.ranks`` order — the
    deterministic reduction the bitwise shrink contract relies on.
    """
    gen = world.generation
    store.check_generation(gen)
    prefix = f"g/{gen}/{step}/"
    store.set(prefix + str(world.rank), grads)
    # GC: nobody can still need this worker's step-2 contribution (a
    # peer posting step s has consumed every step s-1 tree)
    store.delete(f"g/{gen}/{step - 2}/{world.rank}")
    deadline = time.monotonic() + cfg.step_timeout_s
    total = None
    for r in world.ranks:
        while True:
            try:
                tree = store.wait(prefix + str(r), timeout_s=cfg.poll_s)
                break
            except StoreTimeoutError:
                if store.generation != gen:
                    raise PeerLostError(
                        (), gen, f"world generation advanced past {gen} "
                        f"mid-step — re-rendezvous")
                if cfg.heartbeat_s > 0:
                    dead = dead_peers(store, world.ranks, cfg.watchdog_s)
                    if dead:
                        raise PeerLostError(
                            dead, gen, f"heartbeat lease expired "
                            f"(watchdog_s={cfg.watchdog_s})")
                if time.monotonic() > deadline:
                    missing = tuple(
                        q for q in world.ranks
                        if store.get(prefix + str(q), None) is None)
                    raise PeerLostError(
                        missing, gen, f"step {step} gradient exchange "
                        f"deadline ({cfg.step_timeout_s}s) expired")
        total = tree if total is None else jax.tree.map(np.add, total,
                                                        tree)
    return total


class StepWatchdog:
    """Deadline on a blocking host↔device wait (the drain/sync of a
    shard_map step): ``run(fn)`` executes ``fn`` on a worker thread and
    raises a named :class:`PeerLostError` if it does not settle within
    ``timeout_s`` — a dead peer inside an XLA collective can never
    again hang the host silently.  The abandoned wait keeps blocking on
    the daemon thread (collectives cannot be cancelled), the same
    treat-as-fatal contract as ``bootstrap.barrier(timeout_s)``."""

    def __init__(self, timeout_s: float, name: str = "train_step",
                 observer=None):
        self.timeout_s = timeout_s
        self.name = name
        self.observer = observer or NULL_OBSERVER
        self.n_timeouts = 0

    def run(self, fn: Callable, *args, **kwargs):
        done = threading.Event()
        box: list = []

        def _work():
            try:
                box.append(("ok", fn(*args, **kwargs)))
            except BaseException as e:       # surfaced to the caller
                box.append(("err", e))
            finally:
                done.set()

        t = threading.Thread(target=_work, daemon=True,
                             name=f"dtdl-watchdog-{self.name}")
        t.start()
        if not done.wait(self.timeout_s):
            self.n_timeouts += 1
            self.observer.event("elastic_step_timeout", phase=self.name,
                                timeout_s=self.timeout_s)
            raise PeerLostError(
                (), None, f"{self.name} did not settle within "
                f"{self.timeout_s}s — a peer is dead or wedged inside "
                f"the collective")
        kind, value = box[0]
        if kind == "err":
            raise value
        return value


class ElasticWorker:
    """One logical training process of the elastic world (thread-hosted
    in tests/bench — the PR 9 construction — one per host in a real
    deployment).  Drives the full machine: heartbeat lease up, join the
    world, loop deadline-guarded steps, and on :class:`PeerLostError`
    abort → re-rendezvous → restore the last committed snapshot →
    re-shard → continue at the smaller world.  A fence verdict
    (:class:`StaleGenerationError` from rendezvous) ends the worker
    with ``fenced`` set and the error recorded — named, never silent.

    The training step is functional: ``grad_fn(state, batch) -> grads``
    (jitted by the caller), ``apply_fn(state, summed_grads, world_size)
    -> state``, ``batch_fn(indices) -> batch``; data order comes from a
    world-size-agnostic :class:`GlobalBatchSampler`, so the sample
    stream is identical across any shrink (zero lost / zero
    double-counted, pinned by tests/test_elastic.py).
    """

    def __init__(self, store, rank: int, *, init_fn, grad_fn, apply_fn,
                 batch_fn, sampler, total_steps: int,
                 cfg: Optional[ElasticConfig] = None,
                 ckpt_dir: Optional[str] = None, observer=None,
                 audit_samples: bool = False):
        self.store = store
        self.rank = rank
        self.init_fn = init_fn
        self.grad_fn = grad_fn
        self.apply_fn = apply_fn
        self.batch_fn = batch_fn
        self.sampler = sampler
        self.total_steps = total_steps
        self.cfg = cfg or ElasticConfig()
        self.ckpt_dir = ckpt_dir
        self.observer = observer or NULL_OBSERVER
        self.audit_samples = audit_samples

        self.state = None
        self.step = 0
        self.world: Optional[World] = None
        self.error: Optional[BaseException] = None
        self.fenced = False
        self.done = False
        self.stopped_t: Optional[float] = None
        # host-side drill telemetry: (event, monotonic t, info) — the
        # bench row reads detect/re-form/first-step latencies from here
        self.events: list = []
        # opt-in (audit_samples=True): (generation, step) -> the shard
        # indices THIS worker actually fed its grad step — the raw
        # material of the zero-lost/zero-dup audit.  Logging what was
        # consumed (not what the sampler would say) keeps the audit
        # falsifiable, and the opt-in gate keeps a long production run
        # from accumulating an unbounded index log.
        self.sample_log: dict = {}

    # ---- lifecycle ----------------------------------------------------

    def _mark(self, name: str, **info) -> None:
        self.events.append((name, time.monotonic(), info))

    def _on_world(self, world: World) -> None:
        """Enter a formed world: validate the shard math, then restore
        the last committed snapshot (or cold-start when none exists)."""
        self.world = world
        self.sampler.check_world(world.size)
        self._mark("world", generation=world.generation, size=world.size)
        committed = self.store.get("ckpt/committed", None)
        if committed is None:
            self.state = self.init_fn()
            self.step = 0
            return
        from dtdl_tpu.ckpt.checkpoint import load_weights
        self.state = load_weights(committed["path"], self.init_fn())
        self.step = int(committed["step"])
        self.observer.event("elastic_restore", rank=self.rank,
                            generation=world.generation,
                            step=self.step, path=committed["path"])
        self._mark("restore", step=self.step)

    def _commit_snapshot(self) -> None:
        """Leader-only: durable blob + manifest first (PR 5 integrity),
        THEN the store commit marker — a crash mid-save leaves the
        previous marker intact and survivors just replay a bit more."""
        from dtdl_tpu.ckpt.checkpoint import save_weights
        path = os.path.join(self.ckpt_dir,
                            f"elastic_{self.step:06d}.msgpack")
        save_weights(path, self.state)
        self.store.set("ckpt/committed", {"step": self.step,
                                          "path": path})
        self.observer.event("elastic_snapshot", step=self.step,
                            generation=self.world.generation)

    def run(self) -> None:
        cfg = self.cfg
        hb = HeartbeatLease(self.store, self.rank, cfg.heartbeat_s)
        try:
            hb.start()
            self._on_world(rendezvous(self.store, self.rank, cfg,
                                      self.observer))
            while self.step < self.total_steps:
                fire(peer_site(self.rank, "step"))   # crash/stall point
                world = self.world
                local = self.sampler.shard(self.step, world.index,
                                           world.size)
                grads = jax.device_get(
                    self.grad_fn(self.state, self.batch_fn(local)))
                try:
                    total = exchange_grads(self.store, world, self.step,
                                           grads, cfg)
                except (PeerLostError, StaleGenerationError) as e:
                    lost = getattr(e, "lost", ())
                    self.observer.event(
                        "elastic_peer_lost", rank=self.rank,
                        generation=world.generation, step=self.step,
                        lost=str(list(lost)), reason=str(e))
                    self._mark("peer_lost", step=self.step,
                               lost=tuple(lost))
                    # survivors re-form; the rendezvous fence decides
                    # whether WE are still welcome (a ghost gets the
                    # named StaleGenerationError here)
                    self._on_world(rendezvous(self.store, self.rank,
                                              cfg, self.observer,
                                              prev_world=world))
                    continue
                self.state = self.apply_fn(self.state, total, world.size)
                if self.audit_samples:
                    self.sample_log[(world.generation, self.step)] = \
                        np.asarray(local)
                self._mark("applied", step=self.step,
                           generation=world.generation)
                self.step += 1
                if (self.ckpt_dir and world.is_leader
                        and self.step % cfg.snapshot_every == 0):
                    self._commit_snapshot()
            self.done = True
        except StaleGenerationError as e:
            self.fenced = True
            self.error = e
            self.observer.event("elastic_stale_fenced", rank=self.rank,
                                reason=str(e))
            self._mark("fenced")
        except BaseException as e:          # injected crashes included
            self.error = e
            self._mark("died", error=type(e).__name__)
        finally:
            hb.stop()
            self.stopped_t = time.monotonic()


def run_workers(workers, timeout_s: float = 60.0):
    """Host the workers on threads and join them — the CPU-testable
    world driver tests and the bench drill share.  A worker that fails
    to finish within ``timeout_s`` fails the run by name (the harness
    must never itself hang on a hang)."""
    threads = [threading.Thread(target=w.run, daemon=True,
                                name=f"elastic-w{w.rank}")
               for w in workers]
    for t in threads:
        t.start()
    deadline = time.monotonic() + timeout_s
    for w, t in zip(workers, threads):
        t.join(max(0.0, deadline - time.monotonic()))
        if t.is_alive():
            raise RuntimeError(
                f"elastic worker {w.rank} still running after "
                f"{timeout_s}s — the drill harness refuses to hang")
    return workers


def effective_sample_log(workers) -> dict:
    """The surviving timeline's step → consumed-indices map, built from
    what each worker's grad step ACTUALLY fed (``audit_samples=True``
    logs): for each step, take the HIGHEST generation any worker
    applied it at (an older generation's application was discarded by
    the post-shrink restore) and concatenate every worker's shard at
    that generation, sorted.  The zero-lost/zero-dup audit compares
    this multiset against the sampler's pure stream — a shard that
    dropped or double-consumed an index makes the comparison fail,
    which the sampler-side recomputation alone could not detect."""
    top: dict = {}
    for w in workers:
        for (gen, step), _ in w.sample_log.items():
            top[step] = max(top.get(step, gen), gen)
    out: dict = {}
    for step, gen in top.items():
        shards = [w.sample_log[(gen, step)] for w in workers
                  if (gen, step) in w.sample_log]
        out[step] = np.sort(np.concatenate(shards))
    return out
