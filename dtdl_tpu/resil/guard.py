"""Step anomaly guard: on-device finite checks, lag-harvested policies.

A NaN/Inf loss or gradient burst is the most common way a long training
run dies — and the naive defense (``if not np.isfinite(float(loss))``
in the step loop) is a per-step host↔device sync, the exact stall PR 1
eliminated.  The guard splits the job across the async boundary:

* **in-jit** (:meth:`StepGuard.select`, folded into the compiled step by
  ``make_train_step(..., guard=)``): compute the global gradient norm,
  test ``isfinite(loss) & isfinite(grad_norm)`` (plus an optional
  ``grad_norm_limit``), and **select the old state when the step is
  bad** — a poisoned update never reaches the parameters, no matter how
  late the host learns about it.  The badness flag and the grad norm
  ride the step's metric dict through the PR-1 MetricsQueue, so the
  guard adds ZERO host↔device syncs (pinned by the sync-counting
  harness in tests/test_obs.py).  When no fault fires the select is
  ``where(False, old, new) == new`` elementwise — guarded training is
  bitwise identical to unguarded (pinned by tests/test_resil.py).

* **host-side** (:meth:`StepGuard.observe`, fed each drained per-step
  metric dict by train_epoch / Trainer): count bad steps and apply the
  policy, up to ``harvest lag`` steps after the fact — safe, because
  the in-jit select already suppressed the bad updates:

  - ``skip``     — log/count; a skipped step leaves the state exactly
    as if its batch had been dropped from the stream.  After
    ``max_consecutive`` bad steps in a row it escalates to
    :class:`GuardEscalationError` (a burst that long is divergence or
    broken data, not a transient).
  - ``raise``    — :class:`AnomalousStepError` on the first bad step.
  - ``rollback`` — after ``max_consecutive`` consecutive bad steps,
    raise :class:`GuardRollback`; the Trainer catches it, restores the
    last good snapshot, and resumes mid-epoch.  After ``max_rollbacks``
    rollbacks it escalates — a run that keeps rolling back is not
    making progress.

The replica-consistency rule: ``select`` must see only replica-invariant
inputs (the metric-synced loss, post-``grad_sync`` gradients), so every
replica takes the same branch and the replicated state stays bitwise
identical — the step factories order the calls accordingly.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import optax


class AnomalousStepError(RuntimeError):
    """policy='raise': a non-finite (or over-limit) step was observed."""


class GuardEscalationError(RuntimeError):
    """The consecutive-bad-step (or rollback-budget) threshold tripped."""


class GuardRollback(Exception):
    """Control-flow signal: restore the last good snapshot and continue.

    Raised by :meth:`StepGuard.observe` under policy='rollback'; caught
    by ``Trainer._run``.  Deliberately NOT a RuntimeError so generic
    ``except RuntimeError`` recovery code cannot swallow it."""


class StepGuard:
    """Anomaly guard folded into a compiled train step (module docstring).

    One instance guards one logical training run: it is closed over by
    the jitted step (the pure :meth:`select` piece) and fed drained
    metrics on the host (:meth:`observe`).  Counters — ``n_bad``,
    ``n_rollbacks``, ``consecutive`` — are host state, lag-harvested.
    """

    POLICIES = ("skip", "raise", "rollback")

    def __init__(self, policy: str = "skip", max_consecutive: int = 3,
                 grad_norm_limit: Optional[float] = None,
                 max_rollbacks: int = 3, observer=None):
        if policy not in self.POLICIES:
            raise ValueError(f"unknown guard policy {policy!r} "
                             f"(one of {self.POLICIES})")
        if max_consecutive < 1:
            raise ValueError(f"max_consecutive must be >= 1, got "
                             f"{max_consecutive}")
        from dtdl_tpu.obs.observer import NULL_OBSERVER
        self.policy = policy
        self.max_consecutive = max_consecutive
        self.grad_norm_limit = grad_norm_limit
        self.max_rollbacks = max_rollbacks
        self.observer = observer or NULL_OBSERVER
        # host-side counters (updated at harvest, not dispatch)
        self.n_steps = 0
        self.n_bad = 0
        self.consecutive = 0
        self.n_rollbacks = 0
        self.last_bad: Optional[dict] = None
        self._win_prev = {"steps": 0, "bad_steps": 0, "rollbacks": 0}

    # ---- the in-jit piece (pure, traceable) --------------------------

    def select(self, old_state, new_state, loss, grads):
        """Suppress the update when the step is anomalous.

        ``loss`` must already be replica-invariant (metric-synced) and
        ``grads`` post-``grad_sync`` — see the module docstring.  Returns
        ``(state, {'bad_step', 'grad_norm'})``; the extra metrics ride
        the step's existing metric pytree through the async queue.
        """
        gnorm = optax.global_norm(grads)
        bad = jnp.logical_not(jnp.isfinite(loss) & jnp.isfinite(gnorm))
        if self.grad_norm_limit is not None:
            bad = jnp.logical_or(bad, gnorm > self.grad_norm_limit)
        # one Conditional over the whole state, not a select per leaf:
        # both branches are already-computed values, so XLA forwards the
        # chosen tree (measurably cheaper than N selects on CPU; under
        # shard_map the cond lowers to selects on the replicated flag)
        guarded = jax.lax.cond(bad, lambda: old_state, lambda: new_state)
        return guarded, {"bad_step": bad.astype(jnp.float32),
                         "grad_norm": gnorm}

    # ---- the host-side piece (lag-harvested) -------------------------

    def observe(self, vals: dict) -> None:
        """Apply the policy to one drained per-step metric dict.

        Called once per step *at the drain boundary* — up to ``lag``
        steps after dispatch, which is safe because the in-jit select
        already kept the bad update out of the state."""
        self.n_steps += 1
        if not vals.get("bad_step", 0.0):
            self.consecutive = 0
            return
        self.n_bad += 1
        self.consecutive += 1
        self.last_bad = {"loss": vals.get("loss"),
                         "grad_norm": vals.get("grad_norm")}
        self.observer.event("guard_bad_step", **self.last_bad)
        detail = (f"anomalous step (loss={vals.get('loss')}, "
                  f"grad_norm={vals.get('grad_norm')}): update suppressed "
                  f"on device")
        if self.policy == "raise":
            raise AnomalousStepError(detail)
        if self.consecutive >= self.max_consecutive:
            if self.policy == "rollback":
                self.consecutive = 0
                self.n_rollbacks += 1
                if self.n_rollbacks > self.max_rollbacks:
                    raise GuardEscalationError(
                        f"{self.n_rollbacks} rollbacks exceeded the budget "
                        f"of {self.max_rollbacks} — the run is not making "
                        f"progress; last bad step: {self.last_bad}")
                self.observer.event("guard_rollback",
                                    n_rollbacks=self.n_rollbacks)
                raise GuardRollback(detail)
            raise GuardEscalationError(
                f"{self.max_consecutive} consecutive anomalous steps under "
                f"policy='skip' — this is divergence or broken data, not a "
                f"transient; last bad step: {self.last_bad}")

    def summary(self) -> dict:
        """Run-level counters for reports/bench rows."""
        return {"guard_steps": self.n_steps, "guard_bad_steps": self.n_bad,
                "guard_rollbacks": self.n_rollbacks}

    def window(self) -> dict:
        """Counter increments since the last :meth:`window` call — the
        no-arg delta source a :class:`~dtdl_tpu.obs.export.
        MetricsExporter` samples at drain boundaries (register as
        ``exporter.add_source("guard", guard.window)``; the source name
        supplies the ``guard_`` prefix, so keys here are bare).  The
        derived ``bad_step_ratio`` gauge plus the good/bad counter pair
        are exactly the fields ``default_train_slos()`` judges — the
        training twin of the serve ``window()`` sources."""
        cur = {"steps": self.n_steps, "bad_steps": self.n_bad,
               "rollbacks": self.n_rollbacks}
        out = {k: cur[k] - self._win_prev[k] for k in cur}
        self._win_prev = cur
        out["good_steps"] = out["steps"] - out["bad_steps"]
        out["bad_step_ratio"] = (out["bad_steps"] / out["steps"]
                                 if out["steps"] else 0.0)
        return out
