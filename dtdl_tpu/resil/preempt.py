"""Preemption watcher: SIGTERM → durable snapshot → exact resume.

Spot/preemptible capacity (Varuna/Bamboo-style training economics) and
cluster maintenance both speak the same protocol: the host gets SIGTERM
and a grace window.  The watcher converts the signal into a flag the
training loop polls at step boundaries — never mid-dispatch — so the
response is always a *consistent* snapshot: the Trainer saves (params,
optimizer state, step, epoch, iteration-in-epoch), waits for durability
(which also writes the snapshot's commit marker), and returns with
``trainer.preempted`` set.  ``Trainer.resume()`` in the replacement
process continues bitwise-exactly, mid-epoch included (the loader's
(seed, epoch)-keyed order + ``iter_from`` replay — see
dtdl_tpu/data/loader.py).

Signal handlers are process-global state: the watcher installs via
context manager (or explicit :meth:`install`/:meth:`uninstall`) and
restores the previous handlers on exit, so tests and nested uses
compose.  Handlers can only be installed from the main thread (a Python
``signal`` rule); the flag read is safe from anywhere.
"""

from __future__ import annotations

import signal


class PreemptionWatcher:
    """Latches SIGTERM (by default) into a poll-able flag.

    ``requested`` stays True once set — a second SIGTERM during the
    snapshot must not be lost.  ``count`` says how many arrived.
    """

    def __init__(self, signals=(signal.SIGTERM,)):
        self.signals = tuple(signals)
        self._requested = False
        self.count = 0
        self._old: dict = {}

    # ---- signal plumbing ---------------------------------------------

    def _handler(self, signum, frame):
        del signum, frame
        self._requested = True
        self.count += 1

    def install(self) -> "PreemptionWatcher":
        for s in self.signals:
            self._old[s] = signal.signal(s, self._handler)
        return self

    def uninstall(self) -> None:
        for s, old in self._old.items():
            signal.signal(s, old)
        self._old.clear()

    def __enter__(self) -> "PreemptionWatcher":
        return self.install()

    def __exit__(self, *exc) -> bool:
        self.uninstall()
        return False

    # ---- the poll -----------------------------------------------------

    @property
    def requested(self) -> bool:
        return self._requested

    def clear(self) -> None:
        """Re-arm after a handled preemption (tests; long-lived agents)."""
        self._requested = False
