"""Deterministic, seeded fault injection — recovery paths exercised by
tests, not luck.

The reference's checkpoint/resume idioms (SURVEY §5.4) assume nothing
fails mid-write, mid-step, or mid-request.  The ROADMAP north star —
production traffic from millions of users — guarantees the opposite:
preemption, torn snapshot writes, NaN bursts, and hung hosts are
routine.  This module is the harness that makes every one of those
failures *reproducible*: a :class:`FaultPlan` names exactly which
occurrence of which site fails, and how, so the recovery code in ckpt/,
train/, and serve/ is pinned by tests/test_resil.py instead of hoped
about.

Two injection surfaces:

* **product-code sites** — two narrow hooks compiled into the
  checkpoint layer, each a single :func:`fire` call that is a no-op
  dict lookup unless a plan is installed:

  - ``ckpt.pre_rename``  — between the msgpack tmp-file write and its
    ``os.replace`` (the classic torn-checkpoint window);
  - ``ckpt.pre_commit``  — between an orbax snapshot becoming durable
    and its commit marker being written (a preemption mid-finalize).

  A ``crash`` fault at either site raises :class:`InjectedCrash`,
  modeling the process dying at that instant: the test abandons the
  instance and verifies a *fresh* run quarantines the torn artifact and
  falls back to the previous good one.

* **the data boundary** — :class:`LoaderFaults` wraps any loader and
  injects at chosen global batch *yields* (site-local occurrence
  counts), with no product hooks at all: ``raise`` (loader exception),
  ``nan`` (poison every float array — the compiled step's grads go
  non-finite, exercising the on-device guard for real), ``sigterm``
  (deliver a real SIGTERM to this process — the preemption drill), and
  ``stall`` (a slow-host sleep).

Determinism: a fault fires at the Nth call of its site, full stop.
Occurrence counters are **plan-local and monotonic**, so a replay after
rollback/resume within the same plan does NOT re-fire (faults are
transient, like a real NaN burst or preemption); a fresh process builds
a fresh plan and chooses its own occurrence indices.  For randomized
schedules, :meth:`FaultPlan.random` derives the fire steps from a seed
— same seed, same schedule, bit for bit.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import time
from collections import defaultdict
from typing import Iterable, Optional

import numpy as np

KINDS = ("raise", "crash", "sigterm", "sigkill", "stall", "nan",
         "blackhole", "torn")

# the serving-fleet injection surface (dtdl_tpu/serve/fleet.py): every
# replica exposes three sites, so every transition of the router's
# health state machine is deterministically reachable —
#   engine — fired before each compiled-program dispatch of replica i
#            ("raise" at occurrence k == the engine dying on exactly its
#            k-th program call; the Scheduler contains it, the Router
#            sees the passive containment signal);
#   loop   — fired once per worker-thread iteration ("raise" kills the
#            hosting thread = a wedged/dead replica whose heartbeat
#            stops; "stall" freezes the harvest loop for `seconds`,
#            tripping the Router's stall watchdog);
#   probe  — fired on each active health probe of replica i
#            ("blackhole" = the probe gets no answer, "raise" = the
#            health endpoint itself crashing; either way the probe
#            reports failure and the circuit breaker advances).
REPLICA_POINTS = ("engine", "loop", "probe")


# the elastic-training injection surface (dtdl_tpu/resil/elastic.py):
# every ElasticWorker fires three sites, so every detection / abort /
# re-form edge of the training-plane state machine is deterministically
# reachable —
#   step      — fired at the top of each training step of worker `rank`
#               ("crash" at occurrence k == the worker dying right
#               before exchanging step-k gradients: its heartbeat lease
#               stops and survivors abort within watchdog_s; "stall"
#               with `seconds` == a wedged worker whose heartbeat
#               thread keeps beating but whose gradients never arrive —
#               the collective/step watchdog path — and whose late
#               wake-up is then fenced out by generation);
#   heartbeat — fired on each lease beat of worker `rank` ("stall"
#               freezes the beats while the main loop runs on: a
#               partitioned peer whose lease expires);
#   join      — fired when worker `rank` enters (re-)rendezvous
#               ("stall" == a late joiner arriving after the quiet
#               window closed: the formed world excludes it and it is
#               refused by name).
PEER_POINTS = ("step", "heartbeat", "join")


# the control-plane store injection surface (dtdl_tpu/parallel/
# tcpstore.py): the TCP client and server fire three sites so every
# socket-level edge of the store protocol is deterministically
# reachable —
#   rpc     — fired by the CLIENT before each RPC send ("raise" at
#             occurrence k == the connection dying under exactly the
#             k-th RPC: the client's framing layer sees a dead socket,
#             reconnects, and surfaces only TransientStoreError;
#             "blackhole" == the network eats the request — nothing is
#             sent and the client's IO deadline expires into the same
#             transient path; "stall" with `seconds` == a slow link);
#   connect — fired by the CLIENT on each (re)connect attempt ("raise"
#             == connection refused: the coordinator is down or still
#             restarting; the bounded jittered backoff rides it);
#   reply   — fired by the SERVER before each reply frame ("torn" ==
#             half the response frame is written and the connection
#             killed, so the client's torn-frame detection fires BY
#             NAME; "crash" == the coordinator process dies mid-reply
#             — the whole server aborts, nothing else is sent, and a
#             test restarts it from the WAL; "raise" == this one
#             connection is dropped without a reply; "blackhole" ==
#             the reply never comes and the client times out).
STORE_POINTS = ("rpc", "connect", "reply")


def store_site(point: str) -> str:
    """Canonical fault-site name for the TCP control-plane store — one
    of the three socket-level injection points above.  Central so
    tests, the TCPStore client/server, and FaultPlan schedules can
    never drift on spelling."""
    if point not in STORE_POINTS:
        raise ValueError(f"unknown store fault point {point!r} "
                         f"(one of {STORE_POINTS})")
    return f"store.{point}"


def peer_site(rank: int, point: str) -> str:
    """Canonical fault-site name for elastic-training worker ``rank`` —
    one of the three per-worker injection points above (crash / stall /
    late-joiner scenarios per the point docs).  Central so tests, the
    ElasticWorker loop, and FaultPlan schedules can never drift on
    spelling."""
    if point not in PEER_POINTS:
        raise ValueError(f"unknown peer fault point {point!r} "
                         f"(one of {PEER_POINTS})")
    return f"peer{rank}.{point}"


def replica_site(idx: int, point: str) -> str:
    """Canonical fault-site name for serving-fleet replica ``idx`` —
    one of the three per-replica injection points above.  Central so
    tests, the Replica host, and FaultPlan schedules can never drift on
    spelling."""
    if point not in REPLICA_POINTS:
        raise ValueError(f"unknown replica fault point {point!r} "
                         f"(one of {REPLICA_POINTS})")
    return f"replica{idx}.{point}"


class InjectedFault(RuntimeError):
    """A fault deliberately injected by an installed FaultPlan."""


class InjectedCrash(InjectedFault):
    """Models the process dying at a chosen instant (e.g. between a
    checkpoint tmp write and its rename).  Tests abandon the failing
    instance when they catch this — nothing after the raise point ran,
    exactly as if the host had been preempted there."""


@dataclasses.dataclass
class Fault:
    """One scheduled failure: the ``at``-th call of ``site`` (0-based,
    plan-local count) triggers ``kind``.  ``seconds`` is the stall
    duration for ``kind='stall'``."""

    site: str
    at: int
    kind: str = "raise"
    seconds: float = 0.0
    fired: bool = dataclasses.field(default=False, compare=False)

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(one of {KINDS})")
        if self.at < 0:
            raise ValueError(f"fault occurrence index must be >= 0, "
                             f"got {self.at}")


class FaultPlan:
    """A deterministic schedule of failures (see module docstring).

    Build with the fluent :meth:`at` (or :meth:`random` for a seeded
    schedule), then either ``with plan:`` to arm the product-code sites
    for a block, or hand it to :class:`LoaderFaults` for data-boundary
    faults (the wrapper consults the plan directly — no install
    needed).  ``plan.log`` records every fault that actually fired, in
    order, so tests assert the scenario ran as scripted.
    """

    def __init__(self, faults: Iterable[Fault] = ()):
        self.faults: list[Fault] = list(faults)
        self.log: list[tuple[str, int, str]] = []
        self._counts: dict[str, int] = defaultdict(int)

    # ---- schedule construction ---------------------------------------

    def at(self, site: str, at: int, kind: str = "raise",
           seconds: float = 0.0) -> "FaultPlan":
        """Schedule ``kind`` at the ``at``-th occurrence of ``site``."""
        self.faults.append(Fault(site, at, kind, seconds))
        return self

    @classmethod
    def random(cls, seed: int, site: str, n_steps: int, rate: float,
               kind: str = "nan") -> "FaultPlan":
        """Seeded random schedule: each of ``n_steps`` occurrences of
        ``site`` fails independently with probability ``rate``.  Same
        seed, same schedule — the harness stays deterministic even when
        the failure pattern is 'random'."""
        rng = np.random.default_rng(seed)
        plan = cls()
        for i in np.nonzero(rng.random(n_steps) < rate)[0]:
            plan.at(site, int(i), kind)
        return plan

    # ---- firing -------------------------------------------------------

    def fire(self, site: str) -> Optional[Fault]:
        """Record one occurrence of ``site``; trigger any fault scheduled
        for it.  Control-flow kinds (raise/crash/sigterm/sigkill/stall)
        trigger here; data kinds (``nan``, ``blackhole``, ``torn``) are
        returned for the caller — e.g. :class:`LoaderFaults` poisons its
        payload on ``nan``, a fleet Replica's probe reports no-answer on
        ``blackhole``, the TCP store server tears a reply frame on
        ``torn``."""
        i = self._counts[site]
        self._counts[site] += 1
        for f in self.faults:
            if f.site == site and f.at == i and not f.fired:
                f.fired = True
                self.log.append((site, i, f.kind))
                if f.kind in ("raise", "crash"):
                    err = InjectedCrash if f.kind == "crash" else \
                        InjectedFault
                    raise err(f"injected {f.kind} at {site}#{i}")
                if f.kind == "sigterm":
                    os.kill(os.getpid(), signal.SIGTERM)
                elif f.kind == "sigkill":
                    # real, uncatchable process death — the subprocess
                    # elastic drills use this for a worker that
                    # genuinely vanishes (no atexit, no flush, no
                    # goodbye on its sockets)
                    os.kill(os.getpid(), signal.SIGKILL)
                elif f.kind == "stall":
                    time.sleep(f.seconds)
                return f
        return None

    # ---- arming the product-code sites -------------------------------

    def install(self) -> "FaultPlan":
        global _PLAN
        _PLAN = self
        return self

    def uninstall(self) -> None:
        global _PLAN
        if _PLAN is self:
            _PLAN = None

    def __enter__(self) -> "FaultPlan":
        return self.install()

    def __exit__(self, *exc) -> bool:
        self.uninstall()
        return False


_PLAN: Optional[FaultPlan] = None


def fire(site: str) -> Optional[Fault]:
    """The product-code hook: a no-op unless a plan is installed.

    Sites live in checkpoint-critical windows (module docstring); the
    uninstalled cost is one global read and an ``is None`` check, so the
    hook stays in production builds — the harness tests the *same* code
    that ships, not an instrumented twin.  Data kinds (``nan`` /
    ``blackhole`` / ``torn``) are returned to the caller, exactly like
    :meth:`FaultPlan.fire` — the TCP store consults the returned fault
    to decide whether to eat a request or tear a reply frame."""
    if _PLAN is not None:
        return _PLAN.fire(site)
    return None


def poison_batch(batch: dict) -> dict:
    """NaN-fill every float array of a batch (ints — labels, tokens —
    pass through).  A NaN input makes the compiled step's loss and
    gradients non-finite *on device*, which is exactly what the step
    anomaly guard must catch — no host-side shortcut."""
    return {k: (np.full_like(v, np.nan)
                if np.issubdtype(np.asarray(v).dtype, np.floating) else v)
            for k, v in batch.items()}


class LoaderFaults:
    """Loader wrapper injecting faults at chosen global batch yields.

    Delegates the loader protocol (``set_epoch`` / ``__len__`` /
    ``iter_from`` / ``batch_size``) so it drops into every loop flavor,
    including mid-epoch resume.  The occurrence counter is the plan's
    ``site`` count across the wrapper's whole life — epoch boundaries
    and resume replays do NOT reset it, so an injected burst is
    transient: a rollback that replays the same batch indices sees
    clean data, the way a real NaN burst or preemption doesn't replay
    itself.
    """

    def __init__(self, loader, plan: FaultPlan, site: str = "loader"):
        self.loader = loader
        self.plan = plan
        self.site = site

    # ---- loader protocol ---------------------------------------------

    @property
    def batch_size(self):
        return self.loader.batch_size

    def set_epoch(self, epoch: int) -> None:
        self.loader.set_epoch(epoch)

    def __len__(self) -> int:
        return len(self.loader)

    def __iter__(self):
        return self._gen(iter(self.loader))

    def iter_from(self, start_batch: int):
        return self._gen(self.loader.iter_from(start_batch))

    # ---- injection ----------------------------------------------------

    def _gen(self, it):
        for batch in it:
            fault = self.plan.fire(self.site)  # may raise / kill / stall
            if fault is not None and fault.kind == "nan":
                batch = poison_batch(batch)
            yield batch
