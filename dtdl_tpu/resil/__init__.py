"""Fault tolerance: deterministic fault injection, anomaly-guarded
training, preemption-safe checkpointing (ISSUE 5).

Three pieces, wired through train/, ckpt/, and serve/:

* :mod:`~dtdl_tpu.resil.faults` — the seeded :class:`FaultPlan` harness
  that injects failures (loader exceptions, NaN bursts, torn checkpoint
  writes, SIGTERM, slow-host stalls) at chosen occurrences, so every
  recovery path below is exercised by tests/test_resil.py;
* :mod:`~dtdl_tpu.resil.guard` — :class:`StepGuard`, the on-device
  finite check folded into the compiled train step with skip /
  rollback-to-last-good / raise policies, lag-harvested through the
  PR-1 MetricsQueue (zero added per-step syncs);
* :mod:`~dtdl_tpu.resil.preempt` — :class:`PreemptionWatcher`, the
  SIGTERM → durable snapshot → exact mid-epoch resume path;
* :mod:`~dtdl_tpu.resil.elastic` (ISSUE 12) — the elastic
  multi-host training plane: heartbeat peer leases, deadline-guarded
  collectives (:class:`PeerLostError`, never a silent hang),
  generation-fenced re-rendezvous, and shrink-to-survivors resume
  from the last committed snapshot, over the host-side control-plane
  store in :mod:`dtdl_tpu.parallel.kvstore`.

Checkpoint integrity (checksummed msgpack manifests, orbax commit
markers, corrupt-snapshot quarantine + fallback) lives in
dtdl_tpu/ckpt/checkpoint.py; serve-side containment (deadlines,
bounded admission, graceful drain, engine-failure blast-radius) in
dtdl_tpu/serve/scheduler.py.  See README "Fault tolerance" and
SCALING.md "Failure model".
"""

from dtdl_tpu.resil.elastic import (  # noqa: F401
    ElasticConfig, ElasticWorker, HeartbeatLease, PeerLostError,
    RendezvousError, StaleGenerationError, StepWatchdog, World,
    dead_peers, effective_sample_log, exchange_grads, rendezvous,
    run_workers,
)
from dtdl_tpu.resil.faults import (  # noqa: F401
    Fault, FaultPlan, InjectedCrash, InjectedFault, LoaderFaults, fire,
    peer_site, poison_batch, replica_site, store_site,
)
from dtdl_tpu.resil.guard import (  # noqa: F401
    AnomalousStepError, GuardEscalationError, GuardRollback, StepGuard,
)
from dtdl_tpu.resil.preempt import PreemptionWatcher  # noqa: F401
