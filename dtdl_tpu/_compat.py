"""Compatibility shims for older jax releases.

The framework is written against the current public API — ``jax.shard_map``,
``jax.typeof`` with varying-manual-axes (vma) types, ``lax.pcast`` — but some
images ship a jax that predates them (0.4.x).  :func:`install` patches the
closest equivalents onto the jax namespace once, at package import, so every
call site (framework, tests, examples) keeps the one forward-compatible
spelling instead of forking on the jax version:

* ``jax.shard_map`` → ``jax.experimental.shard_map.shard_map`` with
  ``check_rep=False``.  The vma type system *replaced* check_rep; this code
  manages replication explicitly (``pcast`` to varying, collective demotions
  before ``P()`` out_specs), which the legacy static checker cannot always
  re-prove — and with identity ``pcast`` (below) it must not try.
* ``lax.pcast`` → identity.  pcast is a *type* cast between vma sets; it
  never moves data, so on a jax without vma types there is nothing to do.
* ``jax.typeof`` → abstract-value lookup whose ``.vma`` is always the empty
  frozenset — the correct answer on a jax whose avals carry no vma.

Runtime semantics are unchanged: pcast/vma only affect type checking in new
jax, and the values this code marks replicated genuinely are replicated.
"""

from __future__ import annotations

import functools

import jax
import jax.lax

# True once install() had to patch shard_map, i.e. this jax predates the
# vma type system.  Code whose CORRECTNESS (not just spelling) depends on
# vma-typed autodiff must gate on this: with ``check_rep=False`` the
# legacy shard_map transposes ``psum`` to ``psum`` (verified here:
# grad(psum(sum(x))) returns the axis size instead of 1) and inserts no
# pbroadcast-transposes for replicated operands, so differentiating
# *through* collectives inside shard_map yields wrong gradients —
# shard-local, mis-scaled.  Explicit-VJP code (the 1F1B schedule) is
# unaffected: its psums are data movement in a hand-written backward,
# never autodiff'd through.
SHIMMED = False


try:   # every jax this repo supports ships TraceAnnotation, but the obs
    # layer must degrade to pure host tracing rather than hard-dep on it
    from jax.profiler import TraceAnnotation as _TraceAnnotation
except Exception:   # pragma: no cover - profiler-less jax build
    _TraceAnnotation = None


def trace_annotation(name: str):
    """A ``jax.profiler.TraceAnnotation(name)`` when this jax has one,
    else ``None`` — so obs spans show up inside an active jax.profiler
    capture without making the profiler a dependency.  The annotation is
    a TraceMe: ~ns overhead while no capture is running."""
    if _TraceAnnotation is None:
        return None
    return _TraceAnnotation(name)


def tpu_compiler_params(**kwargs):
    """Mosaic compiler params under whichever class name this jax ships
    (``pltpu.CompilerParams`` on current jax, ``pltpu.TPUCompilerParams``
    on 0.4.x), or ``None`` when neither exists / a param is unknown — so
    kernel call sites keep one spelling and simply omit the kwarg when
    the hint is unavailable (it is a scheduling hint, never semantics:
    the interpreter ignores it and Mosaic only uses it to pipeline)."""
    try:
        from jax.experimental.pallas import tpu as pltpu
    except Exception:      # pragma: no cover - pallas-less jax build
        return None
    cls = (getattr(pltpu, "CompilerParams", None)
           or getattr(pltpu, "TPUCompilerParams", None))
    if cls is None:        # pragma: no cover - pallas-less jax build
        return None
    try:
        return cls(**kwargs)
    except TypeError:      # pragma: no cover - param renamed upstream
        return None


class _AvalView:
    """Proxy of an abstract value that answers ``.vma`` on legacy jax."""

    __slots__ = ("_aval",)
    vma: frozenset = frozenset()

    def __init__(self, aval):
        object.__setattr__(self, "_aval", aval)

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_aval"), name)


def install() -> None:
    """Idempotently install the shims (no-op on current jax)."""
    global SHIMMED
    if not hasattr(jax, "shard_map"):
        SHIMMED = True
        from jax.experimental.shard_map import shard_map as _shard_map

        @functools.wraps(_shard_map)
        def shard_map(f, **kwargs):
            kwargs.setdefault("check_rep", False)
            return _shard_map(f, **kwargs)

        jax.shard_map = shard_map

    if not hasattr(jax.lax, "axis_size"):
        def axis_size(axis_name):
            # legacy jax keeps mapped-axis sizes in the trace-time axis
            # env; axis_frame returns the static size directly
            import numpy as np
            if isinstance(axis_name, (tuple, list)):
                return int(np.prod([int(jax.core.axis_frame(a))
                                    for a in axis_name]))
            return int(jax.core.axis_frame(axis_name))

        jax.lax.axis_size = axis_size

    if not hasattr(jax.lax, "pcast"):
        def pcast(x, axis_name, *, to="varying"):
            del axis_name, to
            return x

        jax.lax.pcast = pcast

    if not hasattr(jax, "typeof"):
        def typeof(x):
            return _AvalView(jax.core.get_aval(x))

        jax.typeof = typeof
