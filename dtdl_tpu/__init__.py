"""dtdl_tpu — a TPU-native distributed-training framework.

A ground-up JAX/XLA rebuild of the capabilities of
MyXiaoPao/distributed-training-dl (the reference collection of per-framework
distributed-training examples): single-device training, single-process
multi-device data parallelism, multi-process/multi-host allreduce data
parallelism, dataset sharding/scatter, checkpoint/resume, metric logging, and
per-example CLIs — expressed as SPMD programs over a `jax.sharding.Mesh`, with
gradient synchronization as XLA collectives over ICI/DCN instead of NCCL/MPI.

Subpackages
-----------
runtime   process bootstrap, topology discovery, mesh construction
          (incl. multi-slice hybrid DCN x ICI meshes)
parallel  strategies (SingleDevice / DataParallel incl. hierarchical /
          AutoSharded / KVStore), collectives adapter, ring & Ulysses
          sequence parallelism, 4D megatron (dp x sp x pp x tp + ep)
models    MLP / MNIST-CNN / PyramidNet / ResNet-50 / TransformerLM /
          CaffeNet (prototxt-built) flax modules
ops       flash attention (Pallas TPU kernel), RoPE, classification losses
data      dataset registry, sharded sampling, Python + native C++ loaders
train     jitted step engines and five API flavors: imperative loop,
          Keras fit(), Chainer Trainer, TF1 Estimator, Caffe Solver
ckpt      leader-gated checkpointing (weights / per-epoch / full state)
metrics   metrics bus (stdout / JSONL / TensorBoard sinks)
obs       observability: span tracer (Chrome trace / Perfetto export),
          recompile sentinel, goodput/MFU accounting, streaming
          latency-percentile histograms — one Observer facade that
          every loop flavor and the serve scheduler accept
resil     fault tolerance: deterministic FaultPlan injection harness,
          on-device step anomaly guard (skip/rollback/raise), SIGTERM
          preemption watcher; checkpoint integrity + serve containment
          live in ckpt/ and serve/
launch    local, TPU-VM slice, and SLURM launchers (fail-fast +
          checkpoint-restart elasticity)
utils     flags, seeding, timing, profiling, prototxt parsing
"""

__version__ = "0.1.0"

from dtdl_tpu import _compat

_compat.install()   # jax.shard_map / lax.pcast / jax.typeof on legacy jax

from dtdl_tpu.runtime.mesh import build_mesh, hybrid_mesh, local_mesh  # noqa: F401
from dtdl_tpu.runtime.bootstrap import initialize, is_leader  # noqa: F401
from dtdl_tpu.obs import Observer  # noqa: F401
