"""dtdl_tpu — a TPU-native distributed-training framework.

A ground-up JAX/XLA rebuild of the capabilities of
MyXiaoPao/distributed-training-dl (the reference collection of per-framework
distributed-training examples): single-device training, single-process
multi-device data parallelism, multi-process/multi-host allreduce data
parallelism, dataset sharding/scatter, checkpoint/resume, metric logging, and
per-example CLIs — expressed as SPMD programs over a `jax.sharding.Mesh`, with
gradient synchronization as XLA collectives over ICI/DCN instead of NCCL/MPI.

Subpackages
-----------
runtime   process bootstrap, topology discovery, mesh construction
parallel  parallelism strategies (DP/DDP), collectives adapter
models    MLP / MNIST-CNN / PyramidNet / ResNet flax modules
ops       classification losses (XLA-fused; pallas kernels as they pay off)
train     jitted train-step engine (state, train/eval/predict steps)
utils     flags, seeding, timing
"""

__version__ = "0.1.0"

from dtdl_tpu.runtime.mesh import build_mesh, local_mesh  # noqa: F401
from dtdl_tpu.runtime.bootstrap import initialize, is_leader  # noqa: F401
