"""Topology discovery and description.

Replaces the reference's ad-hoc device accounting (``torch.cuda.device_count``
at reference pytorch/distributed_data_parallel.py:54, ``--gpu_nums`` flags)
with introspection of the JAX device set: chip kind, hosts, per-host device
count, and — on real TPU slices — the ICI coordinate grid.
"""

from __future__ import annotations

import jax


def describe_topology() -> dict:
    devices = jax.devices()
    local = jax.local_devices()
    info = {
        "platform": devices[0].platform,
        "device_kind": getattr(devices[0], "device_kind", "unknown"),
        "num_devices": len(devices),
        "num_local_devices": len(local),
        "num_processes": jax.process_count(),
        "process_index": jax.process_index(),
    }
    coords = getattr(devices[0], "coords", None)
    if coords is not None:
        info["ici_coords"] = {
            d.id: tuple(d.coords) for d in devices if hasattr(d, "coords")}
    return info


def banner() -> str:
    """Human-readable topology banner, printed by the leader at startup.

    The ChainerMN example prints a similar rank-0 banner of run parameters
    (reference chainer/train_mnist_multi.py:64-73).
    """
    t = describe_topology()
    lines = [
        "==========================================",
        f" platform        : {t['platform']} ({t['device_kind']})",
        f" global devices  : {t['num_devices']}",
        f" local devices   : {t['num_local_devices']}",
        f" processes       : {t['num_processes']} (this = {t['process_index']})",
        "==========================================",
    ]
    return "\n".join(lines)
