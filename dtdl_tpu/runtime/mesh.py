"""Device-mesh construction.

The mesh is the framework's device model: where the reference binds work to
devices imperatively (``torch.cuda.set_device`` at reference
pytorch/distributed_data_parallel.py:64, ``CUDA_VISIBLE_DEVICES`` at reference
pytorch/data_parallel.py:49-50), we declare a `jax.sharding.Mesh` and let
shardings place data.  The default mesh puts every addressable device on a
``data`` axis (pure data parallelism — the reference's only strategy), but the
axis set is open: pass ``shape``/``axes`` to carve out ``model`` / ``pipeline``
/ ``sequence`` / ``expert`` axes without redesign.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def build_mesh(shape: tuple[int, ...] | None = None,
               axes: tuple[str, ...] | None = None,
               devices=None) -> Mesh:
    """Build a global mesh over all (or the given) devices.

    With no arguments: a 1-D ``('data',)`` mesh over every addressable device
    — the TPU equivalent of the reference's allreduce data-parallel world.
    ``mesh_utils.create_device_mesh`` lays devices out so that neighboring
    mesh coordinates are ICI neighbors, keeping collectives off DCN wherever
    the topology allows.
    """
    if devices is None:
        devices = jax.devices()
    if shape is None:
        shape = (len(devices),)
    if axes is None:
        axes = (DATA_AXIS,) + tuple(
            f"axis{i}" for i in range(1, len(shape)))
    if int(np.prod(shape)) != len(devices):
        raise ValueError(
            f"mesh shape {shape} needs {int(np.prod(shape))} devices, "
            f"have {len(devices)}")
    if len(shape) == 1:
        dev_array = np.asarray(devices).reshape(shape)
    else:
        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    return Mesh(dev_array, axes)


def local_mesh(axes: tuple[str, ...] = (DATA_AXIS,)) -> Mesh:
    """Mesh over this process's local devices only.

    The single-process multi-device world: equivalent of ``nn.DataParallel``
    (reference pytorch/data_parallel.py:71) / ``MirroredStrategy`` (reference
    tensorflow2/mnist_mirror_strategy.py:12) / ``ParallelUpdater`` (reference
    chainer/train_mnist_gpu.py:87-93).
    """
    devices = jax.local_devices()
    return Mesh(np.asarray(devices).reshape((len(devices),)), axes)


def replicated(mesh: Mesh) -> NamedSharding:
    """Sharding that replicates an array on every mesh device (params)."""
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Sharding that splits an array's leading dim across the data axis."""
    return NamedSharding(mesh, P(axis))
