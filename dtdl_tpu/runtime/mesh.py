"""Device-mesh construction.

The mesh is the framework's device model: where the reference binds work to
devices imperatively (``torch.cuda.set_device`` at reference
pytorch/distributed_data_parallel.py:64, ``CUDA_VISIBLE_DEVICES`` at reference
pytorch/data_parallel.py:49-50), we declare a `jax.sharding.Mesh` and let
shardings place data.  The default mesh puts every addressable device on a
``data`` axis (pure data parallelism — the reference's only strategy), but the
axis set is open: pass ``shape``/``axes`` to carve out ``model`` / ``pipeline``
/ ``sequence`` / ``expert`` axes without redesign.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def build_mesh(shape: tuple[int, ...] | None = None,
               axes: tuple[str, ...] | None = None,
               devices=None) -> Mesh:
    """Build a global mesh over all (or the given) devices.

    With no arguments: a 1-D ``('data',)`` mesh over every addressable device
    — the TPU equivalent of the reference's allreduce data-parallel world.
    ``mesh_utils.create_device_mesh`` lays devices out so that neighboring
    mesh coordinates are ICI neighbors, keeping collectives off DCN wherever
    the topology allows.
    """
    if devices is None:
        devices = jax.devices()
    if shape is None:
        shape = (len(devices),)
    if axes is None:
        axes = (DATA_AXIS,) + tuple(
            f"axis{i}" for i in range(1, len(shape)))
    if int(np.prod(shape)) != len(devices):
        raise ValueError(
            f"mesh shape {shape} needs {int(np.prod(shape))} devices, "
            f"have {len(devices)}")
    if len(shape) == 1:
        dev_array = np.asarray(devices).reshape(shape)
    else:
        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    return Mesh(dev_array, axes)


DCN_AXIS = "dcn"


def hybrid_mesh(ici_shape: tuple[int, ...] | None = None,
                ici_axes: tuple[str, ...] = (DATA_AXIS,),
                dcn_axis: str = DCN_AXIS,
                num_slices: int | None = None,
                devices=None) -> Mesh:
    """Multi-slice mesh: a leading DCN axis over slices, ICI axes within.

    The scaling-book layout for pods-of-slices: collectives named over the
    ICI axes ride the slice's torus; only the ``dcn_axis`` dimension crosses
    the data-center network.  ``DataParallel(mesh, axis=(dcn_axis,) +
    ici_axes)`` then does hierarchical allreduce data parallelism across
    everything.

    Slice membership comes from each device's ``slice_index`` when the
    platform provides it; otherwise (CPU test meshes, single-slice TPUs)
    pass ``num_slices`` to split devices into equal synthetic slices, or
    the process boundary is used (one "slice" per host — the DCN boundary
    in multi-host CPU testing).
    """
    if devices is None:
        devices = jax.devices()
    per_dev = [getattr(d, "slice_index", None) for d in devices]
    with_idx = [d for d, s in zip(devices, per_dev) if s is not None]
    if with_idx and len(with_idx) != len(devices):
        missing = [d for d, s in zip(devices, per_dev) if s is None]
        raise ValueError(
            f"mixed slice metadata: {len(with_idx)} device(s) report a "
            f"slice_index but {len(missing)} do(es) not (e.g. "
            f"{missing[0]!r}). A mesh cannot mix slice-aware and "
            f"slice-less devices — pass an explicit homogeneous `devices` "
            f"list, or `num_slices` with devices that all lack slice_index.")
    slice_ids = sorted({s for s in per_dev if s is not None})
    if not slice_ids:
        slice_ids = [None]
    detected = len(slice_ids) > 1
    if detected and num_slices is not None and num_slices != len(slice_ids):
        raise ValueError(
            f"num_slices={num_slices} conflicts with the platform's "
            f"{len(slice_ids)} detected slices")
    if detected:
        groups = [[d for d in devices if d.slice_index == s]
                  for s in slice_ids]
    else:
        # single real slice (slice_index uniform) or no slice info (CPU):
        # an explicit num_slices splits synthetically — for testing the
        # hierarchical path and for DCN-connected single-slice groups
        if num_slices is None:
            num_slices = max(1, jax.process_count())
        if len(devices) % num_slices:
            raise ValueError(f"{len(devices)} devices not divisible into "
                             f"{num_slices} slices")
        per = len(devices) // num_slices
        groups = [list(devices[i * per:(i + 1) * per])
                  for i in range(num_slices)]
    per = len(groups[0])
    if any(len(g) != per for g in groups):
        raise ValueError(
            f"unequal slice sizes {[len(g) for g in groups]}")
    if ici_shape is None:
        ici_shape = (per,)
    if int(np.prod(ici_shape)) != per:
        raise ValueError(f"ici shape {ici_shape} needs "
                         f"{int(np.prod(ici_shape))} devices/slice, have {per}")
    rows = []
    for g in groups:
        if len(ici_shape) == 1:
            rows.append(np.asarray(g).reshape(ici_shape))
        else:  # ICI-neighbor layout within the slice
            rows.append(mesh_utils.create_device_mesh(ici_shape, devices=g))
    dev_array = np.stack(rows, axis=0)
    return Mesh(dev_array, (dcn_axis,) + tuple(ici_axes))


def shrink_mesh(mesh: Mesh, survivors, axis: str = DATA_AXIS) -> Mesh:
    """Rebuild ``mesh`` keeping only the ``survivors`` coordinates along
    ``axis`` — the device-plane half of shrink-to-survivors elastic
    training (ISSUE 12): after peers are lost, the new world's data axis
    spans exactly the surviving positions, every other axis keeps its
    full extent, and collectives compile against the smaller world
    instead of hanging on ghosts.

    ``survivors`` are axis *coordinates* (positions along ``axis``), not
    device ids — the same indexing the data layer's shard positions use.
    """
    names = mesh.axis_names
    if axis not in names:
        raise ValueError(f"mesh has no axis {axis!r} (axes: {names})")
    ax = names.index(axis)
    extent = mesh.devices.shape[ax]
    surv = sorted(set(int(s) for s in survivors))
    if not surv:
        raise ValueError("shrink_mesh needs at least one survivor")
    bad = [s for s in surv if not 0 <= s < extent]
    if bad:
        raise ValueError(
            f"survivor position(s) {bad} outside axis {axis!r} of "
            f"extent {extent}")
    return Mesh(np.take(mesh.devices, surv, axis=ax), names)


def local_mesh(axes: tuple[str, ...] = (DATA_AXIS,)) -> Mesh:
    """Mesh over this process's local devices only.

    The single-process multi-device world: equivalent of ``nn.DataParallel``
    (reference pytorch/data_parallel.py:71) / ``MirroredStrategy`` (reference
    tensorflow2/mnist_mirror_strategy.py:12) / ``ParallelUpdater`` (reference
    chainer/train_mnist_gpu.py:87-93).
    """
    devices = jax.local_devices()
    return Mesh(np.asarray(devices).reshape((len(devices),)), axes)


def replicated(mesh: Mesh) -> NamedSharding:
    """Sharding that replicates an array on every mesh device (params)."""
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Sharding that splits an array's leading dim across the data axis."""
    return NamedSharding(mesh, P(axis))
