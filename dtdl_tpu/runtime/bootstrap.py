"""Process bootstrap and rendezvous.

TPU-native replacement for the reference's three rendezvous mechanisms:
``dist.init_process_group('nccl', init_method='tcp://...')`` (reference
pytorch/distributed_data_parallel.py:61-62), the synthesized ``TF_CONFIG``
cluster spec (reference tensorflow2/mnist_multi_worker_strategy.py:18-25), and
the MPI communicator (reference chainer/train_mnist_multi.py:49-62).  All
three collapse onto `jax.distributed.initialize(coordinator, num_processes,
process_id)`: one process per TPU host, XLA collectives over ICI/DCN instead
of NCCL/gRPC/MPI.
"""

from __future__ import annotations

import atexit
import logging
import os
import random
import socket
import threading
import time

import jax

log = logging.getLogger("dtdl_tpu")

_initialized = False


def backoff_delay(attempt: int, backoff_s: float, max_backoff_s: float,
                  u: float, jitter: float = 0.5) -> float:
    """THE backoff formula — ``min(backoff_s·2^attempt, max_backoff_s)``
    stretched by ``(1 + jitter·u)`` with ``u ∈ [0, 1)`` supplied by the
    caller's rng (seeded in tests keeps retry schedules deterministic;
    the jitter de-syncs a herd of workers retrying together).  Shared
    by the rendezvous retry in :func:`initialize` and the host-store
    ``RetryingStore`` so tuning cannot drift between them."""
    return min(backoff_s * (2 ** attempt), max_backoff_s) * \
        (1.0 + jitter * u)


def initialize(coordinator: str = "", num_processes: int = 1,
               process_id: int = 0, local_device_ids=None,
               retries: int = 0, backoff_s: float = 1.0,
               max_backoff_s: float = 15.0, store_addr: str = "") -> None:
    """Join (or create) the multi-process cluster.

    No-op for single-process runs — a plain ``python script.py`` works with no
    distributed setup, like the reference's single-GPU scripts.  For
    multi-process, every host calls this with the same coordinator address
    (host:port of process 0) and its own ``process_id``; it subsumes the
    reference's rank/world-size/init-method flag trio and TF_CONFIG.

    ``retries`` bounds re-attempts of the rendezvous itself: a restarted
    worker routinely races the coordinator coming back up (the elastic
    requeue path, ISSUE 12), so connection failures are retried with
    exponential backoff plus jitter — bounded, so a permanently absent
    coordinator still fails loudly with the original error instead of
    retrying forever.

    ``store_addr`` (ISSUE 13) publishes the elastic control-plane
    store's ``host:port`` as ``DTDL_STORE_ADDR`` for everything
    downstream (``dtdl_tpu.parallel.tcpstore.connect()`` reads it) —
    published even for single-process runs, because the control plane
    outlives any one JAX world by design.  The launchers thread it
    through automatically (launch/local env export, the sbatch
    coordinator-host export).
    """
    global _initialized
    if store_addr:
        os.environ["DTDL_STORE_ADDR"] = store_addr
    if num_processes <= 1 and not coordinator:
        return
    if _initialized:
        return
    if not coordinator:
        raise ValueError(
            "--coordinator host:port is required when --num-processes > 1 "
            "(the TPU analogue of the reference's --init-method tcp://...)")
    kwargs = {}
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    for attempt in range(retries + 1):
        log.info("rendezvous: coordinator=%s process %d/%d (host %s)%s",
                 coordinator, process_id, num_processes,
                 socket.gethostname(),
                 f" [attempt {attempt + 1}/{retries + 1}]" if retries
                 else "")
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=num_processes,
                process_id=process_id,
                **kwargs,
            )
            break
        except Exception as e:
            if attempt >= retries:
                raise
            delay = backoff_delay(attempt, backoff_s, max_backoff_s,
                                  random.random())
            log.warning("rendezvous attempt %d failed (%s); retrying "
                        "in %.2fs", attempt + 1, e, delay)
            time.sleep(delay)
    _initialized = True
    atexit.register(_shutdown)


def _shutdown() -> None:
    global _initialized
    if _initialized:
        try:
            jax.distributed.shutdown()
        except Exception:  # pragma: no cover - best-effort teardown
            pass
        _initialized = False


def is_leader() -> bool:
    """True on process 0 — the single writer for checkpoints and logs.

    Standardizes the reference's inconsistent behavior: every DDP rank saved a
    checkpoint (reference pytorch/distributed_data_parallel.py:103-115, rank-0
    guard commented out) while ChainerMN gated outputs on rank 0 (reference
    chainer/train_mnist_multi.py:108-114).  We always gate on the leader.
    """
    return jax.process_index() == 0


class BarrierTimeoutError(RuntimeError):
    """A cross-host barrier did not complete within its timeout — some
    peer process is dead, hung, or partitioned.  The old behavior was to
    hang forever inside ``sync_global_devices``, which turns one dead
    host into a silent whole-job stall; this error names the barrier and
    the budget so the launcher can kill/replace the job instead."""


# default timeout for every barrier in the process (seconds); 0 / unset
# keeps the legacy block-forever behavior, callers can still pass an
# explicit timeout_s per call
_DEFAULT_TIMEOUT = float(os.environ.get("DTDL_BARRIER_TIMEOUT_S", "0")) or None


def barrier(name: str = "barrier", timeout_s: float | None = None) -> None:
    """Cross-host sync point (no-op single-process).

    ``timeout_s`` (or the process-wide ``DTDL_BARRIER_TIMEOUT_S`` env
    default) bounds the wait: on expiry a named
    :class:`BarrierTimeoutError` is raised instead of hanging forever on
    a dead peer.  The timed-out sync keeps waiting on a daemon thread —
    the collective cannot be cancelled — so treat the error as fatal for
    this process (snapshot if possible, then exit); re-entering the same
    barrier after a timeout is not supported.
    """
    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils
    timeout_s = timeout_s if timeout_s is not None else _DEFAULT_TIMEOUT
    if timeout_s is None:
        multihost_utils.sync_global_devices(name)
        return
    done = threading.Event()
    err: list[BaseException] = []

    def _sync():
        try:
            multihost_utils.sync_global_devices(name)
        except BaseException as e:  # surfaced to the caller below
            err.append(e)
        finally:
            done.set()

    t = threading.Thread(target=_sync, daemon=True,
                         name=f"dtdl-barrier-{name}")
    t.start()
    if not done.wait(timeout_s):
        raise BarrierTimeoutError(
            f"barrier {name!r} timed out after {timeout_s}s — a peer "
            f"process is unreachable or dead")
    if err:
        raise err[0]
