"""Process bootstrap and rendezvous.

TPU-native replacement for the reference's three rendezvous mechanisms:
``dist.init_process_group('nccl', init_method='tcp://...')`` (reference
pytorch/distributed_data_parallel.py:61-62), the synthesized ``TF_CONFIG``
cluster spec (reference tensorflow2/mnist_multi_worker_strategy.py:18-25), and
the MPI communicator (reference chainer/train_mnist_multi.py:49-62).  All
three collapse onto `jax.distributed.initialize(coordinator, num_processes,
process_id)`: one process per TPU host, XLA collectives over ICI/DCN instead
of NCCL/gRPC/MPI.
"""

from __future__ import annotations

import atexit
import logging
import socket

import jax

log = logging.getLogger("dtdl_tpu")

_initialized = False


def initialize(coordinator: str = "", num_processes: int = 1,
               process_id: int = 0, local_device_ids=None) -> None:
    """Join (or create) the multi-process cluster.

    No-op for single-process runs — a plain ``python script.py`` works with no
    distributed setup, like the reference's single-GPU scripts.  For
    multi-process, every host calls this with the same coordinator address
    (host:port of process 0) and its own ``process_id``; it subsumes the
    reference's rank/world-size/init-method flag trio and TF_CONFIG.
    """
    global _initialized
    if num_processes <= 1 and not coordinator:
        return
    if _initialized:
        return
    if not coordinator:
        raise ValueError(
            "--coordinator host:port is required when --num-processes > 1 "
            "(the TPU analogue of the reference's --init-method tcp://...)")
    kwargs = {}
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    log.info("rendezvous: coordinator=%s process %d/%d (host %s)",
             coordinator, process_id, num_processes, socket.gethostname())
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )
    _initialized = True
    atexit.register(_shutdown)


def _shutdown() -> None:
    global _initialized
    if _initialized:
        try:
            jax.distributed.shutdown()
        except Exception:  # pragma: no cover - best-effort teardown
            pass
        _initialized = False


def is_leader() -> bool:
    """True on process 0 — the single writer for checkpoints and logs.

    Standardizes the reference's inconsistent behavior: every DDP rank saved a
    checkpoint (reference pytorch/distributed_data_parallel.py:103-115, rank-0
    guard commented out) while ChainerMN gated outputs on rank 0 (reference
    chainer/train_mnist_multi.py:108-114).  We always gate on the leader.
    """
    return jax.process_index() == 0


def barrier(name: str = "barrier") -> None:
    """Cross-host sync point (no-op single-process)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)
