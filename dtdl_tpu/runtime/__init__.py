from dtdl_tpu.runtime.bootstrap import initialize, is_leader, barrier  # noqa: F401
from dtdl_tpu.runtime.mesh import build_mesh, local_mesh, DATA_AXIS, MODEL_AXIS  # noqa: F401
from dtdl_tpu.runtime.topology import describe_topology  # noqa: F401
