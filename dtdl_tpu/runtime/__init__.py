from dtdl_tpu.runtime.bootstrap import initialize, is_leader, barrier  # noqa: F401
from dtdl_tpu.runtime.mesh import (  # noqa: F401
    build_mesh, hybrid_mesh, local_mesh, DATA_AXIS, DCN_AXIS, MODEL_AXIS,
)
from dtdl_tpu.runtime.topology import describe_topology  # noqa: F401
