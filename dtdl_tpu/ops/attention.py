"""Fused multi-head attention — Pallas TPU flash-attention kernels.

The reference has no attention at all (SURVEY §5.7: CNN/MLP only, reference
pytorch/model.py:53-118, chainer/train_mnist_multi.py:15-28); long-context
sequence models are a first-class capability of *this* framework, so the hot
op gets a real TPU kernel rather than a dense softmax(QK^T)V.

Design (the standard TPU flash decomposition):

* forward — grid ``(batch*heads, q_blocks, k_blocks)``; the k dimension is the
  innermost (sequential) grid axis, so VMEM scratch carries the online-softmax
  state (running max ``m``, normalizer ``l``, accumulator ``acc``) across k
  steps.  O(S) memory instead of O(S²); the S×S score matrix never exists.
* backward — two kernels with the same tiling: one accumulates ``dq`` over k
  blocks, one accumulates ``dk``/``dv`` over q blocks, both recomputing the
  probability tile from the saved logsumexp (no S×S residual is stored).
* causal masking skips whole tiles above the diagonal via ``pl.when``, and
  (round 13) the k/v **index maps clamp** masked iterations to the last
  useful block — consecutive grid steps that map to the same block elide
  their DMA, so skipped tiles cost neither MXU time *nor* HBM bandwidth.
* **fused rope** (round 13): ``flash_attention(..., rope=(cos, sin))`` folds
  the rotary embedding into the Q/K tile loads.  The unfused path
  (``ops/rope.py:apply_rope`` before the kernel) reads and writes both
  [B, H, S, D] tensors through HBM once per layer per direction just to
  rotate them; fused, the per-position (cos, sin) rows ride the existing
  HBM→VMEM tile transfer (tables are [S, D] — ~1/(2·B·H) of the tensor
  traffic) and the rotation is VPU work between the DMA and the matmul.
  The backward kernels re-rotate the saved UNROTATED q/k tiles on load
  (recompute, like the probability tiles) and apply the inverse rotation
  to the accumulated dq/dk at finalize — rope is per-row orthogonal, so
  its VJP is the same rotation with the angle negated.
* grid ``dimension_semantics`` mark the two outer axes ``parallel`` and the
  sequential (scratch-carrying) axis ``arbitrary``, so Mosaic's pipeliner
  double-buffers the next iteration's K/V tiles against the current tile's
  matmuls instead of stalling the MXU at the top of each k step.
* block shapes come from a small **static autotune table** keyed on
  (head_dim, seq bucket, causal) — see :data:`_BLOCK_TABLE` — derived from
  the in-repo v5e block sweep (LM_ROOFLINE.md §2: 12%→25% kernel-efficiency
  swings on block shape alone).  Explicit ``block_q``/``block_k`` args
  still override (the tests' fixed geometries).

On non-TPU backends (the 8-virtual-device CPU test mesh, SURVEY §4) the same
kernels run under the Pallas interpreter, so every test exercises the exact
kernel code path the TPU compiles.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from dtdl_tpu import _compat
from dtdl_tpu.ops.rope import rope_rows as _rope_rows

NEG_INF = -1e30


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pallas_kwargs():
    """Shared pallas_call extras: the pipelining hint (outer grid axes
    parallel, the sequential scratch-carrying axis arbitrary) when this
    jax can express it.  All three kernels use 3D grids with the inner
    axis sequential, so one spelling serves them all."""
    cp = _compat.tpu_compiler_params(
        dimension_semantics=("parallel", "parallel", "arbitrary"))
    return {"compiler_params": cp} if cp is not None else {}


def _vma_of(*arrays):
    """Union of manual (shard_map) varying axes across inputs.

    Pallas out_shapes must declare how outputs vary when the kernel runs
    inside shard_map (e.g. under the DataParallel strategy); outside
    shard_map this is empty and the plain ShapeDtypeStruct path is used.
    """
    vma = set()
    for a in arrays:
        vma |= set(getattr(jax.typeof(a), "vma", ()) or ())
    return tuple(sorted(vma))


def _sds(shape, dtype, vma):
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(vma))
    return jax.ShapeDtypeStruct(shape, dtype)


def _zero_pad_rows(x, block_start, valid_total):
    """Zero rows past the logical array end in a ragged tail tile.

    Pallas pads out-of-bounds tile regions (NaN under the interpreter,
    unspecified on hardware); masked-to-zero probabilities times padded
    NaN/garbage still poison matmul accumulations, so padded rows are
    explicitly zeroed before any dot.
    """
    rows = block_start + lax.broadcasted_iota(jnp.int32, x.shape, 0)
    return jnp.where(rows < valid_total, x, 0.0)


# ---------------------------------------------------------------------------
# fused rope: rotation helpers + per-position table rows
# ---------------------------------------------------------------------------

def _rot_half(x):
    """[x1, x2] -> [-x2, x1] on the last (head_dim) axis."""
    d2 = x.shape[-1] // 2
    return jnp.concatenate([-x[..., d2:], x[..., :d2]], axis=-1)


def _rotate(x, c, s):
    """Apply rope to a [rows, d] tile: f32 compute, cast back to x.dtype —
    operation-for-operation the same arithmetic as ops/rope.py:apply_rope
    (x1·c − x2·s ‖ x1·s + x2·c), so fused output bits match unfused."""
    xf = x.astype(jnp.float32)
    return (xf * c + _rot_half(xf) * s).astype(x.dtype)


def _unrotate_f32(g, c, s):
    """Transpose (= inverse: rope is orthogonal per row) rotation of an
    f32 gradient tile — rope with the angle negated."""
    return g * c - _rot_half(g) * s




def mha_reference(q, k, v, *, causal: bool = True, scale: float | None = None):
    """Dense reference attention (numerics oracle for the kernels).

    q,k,v: [batch, heads, seq, head_dim]  (k/v seq may differ from q's).
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# block autotune table
# ---------------------------------------------------------------------------

# seq is bucketed to the next power of two in this range; larger sequences
# use the 32768 entry (same tiling — block shape is seq-independent past
# the knee, only the grid grows).  The sub-128 buckets cover the
# page-granular tile shapes of the paged-attention decode kernel (kernel
# round 2: page sizes 8-64, dtdl_tpu/ops/paged_attention.py), so
# ``strict=True`` receipt checks over serving geometries resolve instead
# of spuriously raising.
_SEQ_BUCKETS = (8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192,
                16384, 32768)


def _build_block_table():
    """(head_dim, seq_bucket, causal) -> (block_q, block_k).

    Derived from the in-repo v5e sweep (LM_ROOFLINE.md §2, re-run round
    13): at head_dim 128 / seq 4096 the 1024×1024 tile is the measured
    knee (25.4% kernel efficiency vs 12-19% for smaller tiles; 2048-row
    blocks fail to compile on VMEM), at head_dim 64 the same shape keeps
    a smaller edge, and below ~4k the grid/DMA overhead of small tiles
    dominates so a block spanning the whole sequence wins (the round-4
    "128×128 loses to XLA dense below seq 4k" finding).  Every entry is
    EXPLICIT so the preset-config receipt test can pin that no model
    geometry silently falls back; per-geometry retunes edit this table,
    never call sites.
    """
    table = {}
    for hd in (16, 32, 64, 128):
        for causal in (False, True):
            for seq in _SEQ_BUCKETS:
                table[(hd, seq, causal)] = ((seq, seq) if seq <= 512
                                            else (1024, 1024))
    return table


_BLOCK_TABLE = _build_block_table()
_BLOCK_DEFAULT = (1024, 1024)


def block_table_entry(head_dim: int, seq: int, causal: bool = True):
    """The explicit autotune-table entry covering (head_dim, seq, causal),
    or None if the geometry has no entry (callers then get
    :data:`_BLOCK_DEFAULT` unless they asked ``strict``)."""
    bucket = next((b for b in _SEQ_BUCKETS if seq <= b), _SEQ_BUCKETS[-1])
    return _BLOCK_TABLE.get((int(head_dim), bucket, bool(causal)))


def resolve_blocks(head_dim: int, seq_q: int, seq_k: int | None = None, *,
                   causal: bool = True, strict: bool = False):
    """(block_q, block_k) for a kernel geometry, from the autotune table.

    ``strict=True`` raises instead of falling back to the default — the
    preset-config receipt tests use it to pin that every shipped model
    geometry resolves to an explicit, swept entry.
    """
    seq = max(int(seq_q), int(seq_k if seq_k is not None else seq_q))
    entry = block_table_entry(head_dim, seq, causal)
    if entry is None:
        if strict:
            raise ValueError(
                f"no explicit attention block-table entry for head_dim="
                f"{head_dim}, seq={seq}, causal={causal} (buckets: "
                f"{_SEQ_BUCKETS}; head_dims: "
                f"{sorted({k[0] for k in _BLOCK_TABLE})})")
        return _BLOCK_DEFAULT
    return entry


# ---------------------------------------------------------------------------
# causal DMA-eliding index maps
# ---------------------------------------------------------------------------

def _kmaps(causal, block_q, block_k, off, lead_b: bool):
    """Index map for K-side blocks in the fwd/dq grids ``(b, i, j)``.

    Causal: iterations whose whole tile sits above the diagonal clamp to
    the last contributing k block — Mosaic skips the DMA when the block
    index repeats, so masked tiles cost no bandwidth (their compute is
    already skipped by the ``pl.when`` guard).  ``lead_b=False`` builds
    the same map for the [S, d] rope tables, which have no batch dim.
    """
    if not causal:
        if lead_b:
            return lambda b, i, j: (b, j, 0)
        return lambda b, i, j: (j, 0)

    def last_block(i):
        return jnp.maximum(((i + 1) * block_q + off - 1) // block_k, 0)

    if lead_b:
        return lambda b, i, j: (b, jnp.minimum(j, last_block(i)), 0)
    return lambda b, i, j: (jnp.minimum(j, last_block(i)), 0)


def _qmaps(causal, block_q, block_k, off, nq, lead_b: bool):
    """Index map for Q-side blocks in the dkv grid ``(b, j, i)``: the
    masked iterations sit at the START of the q loop, so they clamp
    forward to the first contributing q block (which the pipeline then
    prefetches during the dead iterations instead of refetching it)."""
    if not causal:
        if lead_b:
            return lambda b, j, i: (b, i, 0)
        return lambda b, j, i: (i, 0)

    def clamp(i, j):
        first = jnp.maximum((j * block_k - off) // block_q, 0)
        return jnp.minimum(jnp.maximum(i, first), nq - 1)

    if lead_b:
        return lambda b, j, i: (b, clamp(i, j), 0)
    return lambda b, j, i: (clamp(i, j), 0)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, *rest, scale, causal, block_q, block_k,
                seq_k, off, rope):
    if rope:
        (qc_ref, qs_ref, kc_ref, ks_ref,
         o_ref, lse_ref, m_scr, l_scr, acc_scr, qrot_scr) = rest
    else:
        o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)
        if rope:
            # the q tile is the same for every k step: rotate ONCE into
            # scratch (each k tile is fresh data, so rotating it per
            # step is already once per loaded tile)
            qrot_scr[:] = _rotate(q_ref[0], qc_ref[:], qs_ref[:])

    # tiles strictly above the (bottom-aligned) diagonal contribute nothing
    guard = (ki * block_k < (qi + 1) * block_q + off) if causal else (ki >= 0)

    @pl.when(guard)
    def _compute():
        # matmul inputs stay in their native dtype (bf16 on the MXU runs at
        # 2x f32 throughput); preferred_element_type gives f32 accumulation
        q = q_ref[0]                              # [bq, d]
        k = k_ref[0]                              # [bk, d]
        if rope:
            # rotation rides the tile load: f32 compute, cast back to the
            # native dtype — bitwise what apply_rope-then-kernel produces
            q = qrot_scr[:]
            k = _rotate(k, kc_ref[:], ks_ref[:])
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk] f32

        cols = ki * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if causal:
            # bottom-aligned diagonal (== mha_reference's tril(k=sk-sq)):
            # query row i attends keys <= i + (seq_k - seq_q)
            rows = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s = jnp.where(rows + off >= cols, s, NEG_INF)
        if seq_k % block_k:                        # mask padded tail keys
            s = jnp.where(cols < seq_k, s, NEG_INF)

        m_prev = m_scr[:]                          # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                     # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)            # [bq, 1]
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0]                               # [bk, d] native dtype
        if seq_k % block_k:
            v = _zero_pad_rows(v, ki * block_k, seq_k)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = m_new

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        # lse layout [bh, 1, sq]: keeps the trailing block dims TPU-tileable
        lse_ref[0] = (m_scr[:] + jnp.log(l_safe)).reshape(1, -1)


def _fwd(q, k, v, tabs, scale, causal, block_q, block_k):
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    rope = tabs is not None
    grid = (bh, pl.cdiv(sq, block_q), pl.cdiv(sk, block_k))
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, seq_k=sk, off=sk - sq, rope=rope)
    kmap = _kmaps(causal, block_q, block_k, sk - sq, lead_b=True)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), kmap),
        pl.BlockSpec((1, block_k, d), kmap),
    ]
    operands = (q, k, v)
    if rope:
        tmap = _kmaps(causal, block_q, block_k, sk - sq, lead_b=False)
        in_specs += [
            pl.BlockSpec((block_q, d), lambda b, i, j: (i, 0)),
            pl.BlockSpec((block_q, d), lambda b, i, j: (i, 0)),
            pl.BlockSpec((block_k, d), tmap),
            pl.BlockSpec((block_k, d), tmap),
        ]
        operands += tabs
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            _sds((bh, sq, d), q.dtype, _vma_of(q, k, v)),
            _sds((bh, 1, sq), jnp.float32, _vma_of(q, k, v)),
        ],
        scratch_shapes=(_scratch(block_q, d)
                        + ([_vmem((block_q, d), q.dtype)] if rope else [])),
        interpret=_use_interpret(),
        **_pallas_kwargs(),
    )(*operands)
    return o, lse


def _vmem(shape, dtype):
    from jax.experimental.pallas import tpu as pltpu
    return pltpu.VMEM(shape, dtype)


def _scratch(block_q, d):
    return [
        _vmem((block_q, 1), jnp.float32),
        _vmem((block_q, 1), jnp.float32),
        _vmem((block_q, d), jnp.float32),
    ]


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                   scale, causal, block_q, block_k, seq_k, off, rope):
    if rope:
        (qc_ref, qs_ref, kc_ref, ks_ref,
         dq_ref, dq_scr, qrot_scr) = rest
    else:
        dq_ref, dq_scr = rest
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)
        if rope:
            # same once-per-q-tile rotation as the forward kernel
            qrot_scr[:] = _rotate(q_ref[0], qc_ref[:], qs_ref[:])

    guard = (ki * block_k < (qi + 1) * block_q + off) if causal else (ki >= 0)

    @pl.when(guard)
    def _compute():
        # native-dtype (bf16) matmul inputs, f32 accumulation — see _fwd_kernel
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        if rope:
            # recompute the rotation on tile load (like the probability
            # tiles): the residuals stay unrotated
            q = qrot_scr[:]
            k = _rotate(k, kc_ref[:], ks_ref[:])
        lse = lse_ref[0].reshape(block_q, 1)      # [bq, 1]
        delta = delta_ref[0].reshape(block_q, 1)  # [bq, 1]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        cols = ki * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if causal:
            rows = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s = jnp.where(rows + off >= cols, s, NEG_INF)
        if seq_k % block_k:
            s = jnp.where(cols < seq_k, s, NEG_INF)
            k = _zero_pad_rows(k, ki * block_k, seq_k)
            v = _zero_pad_rows(v, ki * block_k, seq_k)
        p = jnp.exp(s - lse)                       # [bq, bk] f32
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)    # [bq, bk]
        ds = p * (dp - delta) * scale              # lse/delta refs are f32
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq = dq_scr[:]
        if rope:
            # the accumulated grad is w.r.t. the ROTATED q; rope is
            # orthogonal per row, so its VJP is the inverse rotation —
            # applied once to the f32 accumulator, then cast
            dq = _unrotate_f32(dq, qc_ref[:], qs_ref[:])
        dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                    scale, causal, block_q, block_k, seq_k, seq_q, off, rope):
    if rope:
        (qc_ref, qs_ref, kc_ref, ks_ref,
         dk_ref, dv_ref, dk_scr, dv_scr, krot_scr) = rest
    else:
        dk_ref, dv_ref, dk_scr, dv_scr = rest
    ki, qi = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)
        if rope:
            # this grid holds the K tile fixed and walks q blocks, so
            # here it is K that rotates once into scratch
            krot_scr[:] = _rotate(k_ref[0], kc_ref[:], ks_ref[:])

    guard = ((qi + 1) * block_q + off > ki * block_k) if causal else (qi >= 0)

    @pl.when(guard)
    def _compute():
        # native-dtype (bf16) matmul inputs, f32 accumulation — see _fwd_kernel
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        if rope:
            q = _rotate(q, qc_ref[:], qs_ref[:])
            k = krot_scr[:]
        lse = lse_ref[0].reshape(block_q, 1)      # f32 (fwd out_shape)
        delta = delta_ref[0].reshape(block_q, 1)  # f32 (computed in _bwd)
        if seq_q % block_q:
            q = _zero_pad_rows(q, qi * block_q, seq_q)
            do = _zero_pad_rows(do, qi * block_q, seq_q)
            lse = _zero_pad_rows(lse, qi * block_q, seq_q)
            delta = _zero_pad_rows(delta, qi * block_q, seq_q)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        cols = ki * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if causal:
            rows = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s = jnp.where(rows + off >= cols, s, NEG_INF)
        if seq_k % block_k:
            s = jnp.where(cols < seq_k, s, NEG_INF)
        p = jnp.exp(s - lse)                       # [bq, bk] f32
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale              # [bq, bk]
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # [bk, d]

    @pl.when(qi == nq - 1)
    def _finalize():
        dk = dk_scr[:]
        if rope:
            dk = _unrotate_f32(dk, kc_ref[:], ks_ref[:])
        dk_ref[0] = dk.astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(scale, causal, block_q, block_k, res, do_4d, tabs=None):
    q, k, v, o, lse = res
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    rope = tabs is not None
    do = do_4d
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)[:, None, :]          # [bh, 1, sq]

    kmap = _kmaps(causal, block_q, block_k, sk - sq, lead_b=True)
    grid_dq = (bh, pl.cdiv(sq, block_q), pl.cdiv(sk, block_k))
    in_specs_dq = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), kmap),
        pl.BlockSpec((1, block_k, d), kmap),
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
    ]
    operands = (q, k, v, do, lse, delta)
    if rope:
        tmap = _kmaps(causal, block_q, block_k, sk - sq, lead_b=False)
        in_specs_dq += [
            pl.BlockSpec((block_q, d), lambda b, i, j: (i, 0)),
            pl.BlockSpec((block_q, d), lambda b, i, j: (i, 0)),
            pl.BlockSpec((block_k, d), tmap),
            pl.BlockSpec((block_k, d), tmap),
        ]
        operands += tabs
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_k=sk,
                          off=sk - sq, rope=rope),
        grid=grid_dq,
        in_specs=in_specs_dq,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=_sds((bh, sq, d), q.dtype, _vma_of(q, k, v, do)),
        scratch_shapes=([_scratch(block_q, d)[2]]
                        + ([_vmem((block_q, d), q.dtype)] if rope else [])),
        interpret=_use_interpret(),
        **_pallas_kwargs(),
    )(*operands)

    nq = pl.cdiv(sq, block_q)
    qmap = _qmaps(causal, block_q, block_k, sk - sq, nq, lead_b=True)
    qmap_s = _qmaps(causal, block_q, block_k, sk - sq, nq, lead_b=False)

    def _lse_map(b, j, i):
        bi, ii, _ = qmap(b, j, i)
        return (bi, 0, ii)

    grid_dkv = (bh, pl.cdiv(sk, block_k), nq)
    in_specs_dkv = [
        pl.BlockSpec((1, block_q, d), qmap),
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, block_q, d), qmap),
        pl.BlockSpec((1, 1, block_q), _lse_map),
        pl.BlockSpec((1, 1, block_q), _lse_map),
    ]
    operands = (q, k, v, do, lse, delta)
    if rope:
        in_specs_dkv += [
            pl.BlockSpec((block_q, d), qmap_s),
            pl.BlockSpec((block_q, d), qmap_s),
            pl.BlockSpec((block_k, d), lambda b, j, i: (j, 0)),
            pl.BlockSpec((block_k, d), lambda b, j, i: (j, 0)),
        ]
        operands += tabs
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_k=sk,
                          seq_q=sq, off=sk - sq, rope=rope),
        grid=grid_dkv,
        in_specs=in_specs_dkv,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            _sds((bh, sk, d), k.dtype, _vma_of(q, k, v, do)),
            _sds((bh, sk, d), v.dtype, _vma_of(q, k, v, do)),
        ],
        scratch_shapes=([_scratch(block_k, d)[2], _scratch(block_k, d)[2]]
                        + ([_vmem((block_k, d), k.dtype)] if rope else [])),
        interpret=_use_interpret(),
        **_pallas_kwargs(),
    )(*operands)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public ops with custom VJP (plain + fused-rope variant)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, block_q, block_k):
    o, _ = _fwd(q, k, v, None, scale, causal, block_q, block_k)
    return o


def _flash_fwd(q, k, v, scale, causal, block_q, block_k):
    o, lse = _fwd(q, k, v, None, scale, causal, block_q, block_k)
    return o, (q, k, v, o, lse)


def _flash_bwd(scale, causal, block_q, block_k, res, do):
    return _bwd(scale, causal, block_q, block_k, res, do)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10))
def _flash_rope(q, k, v, qc, qs, kc, ks, scale, causal, block_q, block_k):
    o, _ = _fwd(q, k, v, (qc, qs, kc, ks), scale, causal, block_q, block_k)
    return o


def _flash_rope_fwd(q, k, v, qc, qs, kc, ks, scale, causal, block_q,
                    block_k):
    o, lse = _fwd(q, k, v, (qc, qs, kc, ks), scale, causal, block_q, block_k)
    # residuals keep q/k UNROTATED — the backward kernels re-rotate on
    # tile load, so the rotation never round-trips HBM
    return o, (q, k, v, o, lse, qc, qs, kc, ks)


def _flash_rope_bwd(scale, causal, block_q, block_k, res, do):
    q, k, v, o, lse, qc, qs, kc, ks = res
    dq, dk, dv = _bwd(scale, causal, block_q, block_k, (q, k, v, o, lse),
                      do, tabs=(qc, qs, kc, ks))
    # rope tables come from rope_frequencies (position constants, never
    # trained) — their cotangents are defined as zero
    return (dq, dk, dv, jnp.zeros_like(qc), jnp.zeros_like(qs),
            jnp.zeros_like(kc), jnp.zeros_like(ks))


_flash_rope.defvjp(_flash_rope_fwd, _flash_rope_bwd)


def _legal_block(seq: int, block: int) -> int:
    """Normalize a block size to Mosaic-legal tiling geometry.

    A block's seq dims must be 128-multiples or span the whole array dim:
    whole-seq when the seq fits in one block (or the 128 floor), else the
    largest 128-multiple <= the request.  Applied **unconditionally** — the
    interpreter (CPU test) path runs the exact tiling geometry the TPU path
    compiles, so CPU green means the TPU grid shape was exercised.
    """
    if seq <= block:
        return seq
    b = max(128, block // 128 * 128)
    return seq if seq <= b else b


def flash_attention(q, k, v, *, causal: bool = True,
                    scale: float | None = None,
                    block_q: int | None = None, block_k: int | None = None,
                    rope=None, rope_positions=None):
    """Flash attention over [batch, heads, seq, head_dim] tensors.

    Differentiable (custom VJP, recompute-based backward); O(seq) memory.
    Falls back to the Pallas interpreter off-TPU so CPU tests run the same
    kernel code.

    ``block_q``/``block_k`` default to the static autotune table
    (:func:`resolve_blocks`, keyed on head_dim / seq bucket / causal —
    LM_ROOFLINE.md §2's sweep; explicit args override).  VMEM per grid
    step ~= bq·bk·4 (score tile) + bq·d·4 (acc) + (bq+bk)·d·8 (rope
    tables): ~6.5 MB at 1024/1024/d=128 with rope.

    ``rope=(cos, sin)`` — the :func:`dtdl_tpu.ops.rope.rope_frequencies`
    tables, [max_seq, head_dim//2] — fuses the rotary embedding into the
    kernels: Q/K rotate on tile load (forward AND backward recompute),
    and dq/dk are inverse-rotated at finalize, so the separate
    apply_rope HBM round-trip disappears.  Numerically the rotation is
    the same f32-compute/native-cast arithmetic as ``apply_rope``.
    ``rope_positions=(pos_q, pos_k)`` gives each row an explicit global
    position (sequence-parallel shards, zigzag layouts); the default is
    k at 0..sk-1 with q bottom-aligned (the self-attention / training
    case: positions 0..seq-1 for both).
    """
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if block_q is None or block_k is None:
        auto_q, auto_k = resolve_blocks(d, sq, sk, causal=causal)
        block_q = block_q if block_q is not None else auto_q
        block_k = block_k if block_k is not None else auto_k
    block_q = _legal_block(sq, block_q)
    block_k = _legal_block(sk, block_k)
    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * h, sk, d)
    vf = v.reshape(b * h, sk, d)
    if rope is None:
        o = _flash(qf, kf, vf, scale, causal, block_q, block_k)
    else:
        cos, sin = rope
        if rope_positions is None:
            if max(sq, sk) > cos.shape[0]:
                # the unfused path failed loudly on a short table (shape
                # mismatch in apply_rope); a silent take-clamp here would
                # instead reuse the last row's rotation for every
                # position past the table — wrong outputs, no error
                raise ValueError(
                    f"rope table covers {cos.shape[0]} positions but "
                    f"seq_q={sq}, seq_k={sk}; build rope_frequencies "
                    f"with max_seq >= the sequence length")
            pos_k = jnp.arange(sk)
            pos_q = jnp.maximum(jnp.arange(sq) + (sk - sq), 0)
        else:
            # explicit positions are data (possibly traced) — the caller
            # owns keeping them inside the table, as with apply_rope
            pos_q, pos_k = rope_positions
        qc, qs = _rope_rows(cos, sin, pos_q)
        kc, ks = _rope_rows(cos, sin, pos_k)
        o = _flash_rope(qf, kf, vf, qc, qs, kc, ks, scale, causal,
                        block_q, block_k)
    return o.reshape(b, h, sq, d)
