"""Fused multi-head attention — Pallas TPU flash-attention kernels.

The reference has no attention at all (SURVEY §5.7: CNN/MLP only, reference
pytorch/model.py:53-118, chainer/train_mnist_multi.py:15-28); long-context
sequence models are a first-class capability of *this* framework, so the hot
op gets a real TPU kernel rather than a dense softmax(QK^T)V.

Design (the standard TPU flash decomposition):

* forward — grid ``(batch*heads, q_blocks, k_blocks)``; the k dimension is the
  innermost (sequential) grid axis, so VMEM scratch carries the online-softmax
  state (running max ``m``, normalizer ``l``, accumulator ``acc``) across k
  steps.  O(S) memory instead of O(S²); the S×S score matrix never exists.
* backward — two kernels with the same tiling: one accumulates ``dq`` over k
  blocks, one accumulates ``dk``/``dv`` over q blocks, both recomputing the
  probability tile from the saved logsumexp (no S×S residual is stored).
* causal masking skips whole tiles above the diagonal via ``pl.when`` so the
  MXU only sees tiles that contribute.

On non-TPU backends (the 8-virtual-device CPU test mesh, SURVEY §4) the same
kernels run under the Pallas interpreter, so every test exercises the exact
kernel code path the TPU compiles.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _vma_of(*arrays):
    """Union of manual (shard_map) varying axes across inputs.

    Pallas out_shapes must declare how outputs vary when the kernel runs
    inside shard_map (e.g. under the DataParallel strategy); outside
    shard_map this is empty and the plain ShapeDtypeStruct path is used.
    """
    vma = set()
    for a in arrays:
        vma |= set(getattr(jax.typeof(a), "vma", ()) or ())
    return tuple(sorted(vma))


def _sds(shape, dtype, vma):
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(vma))
    return jax.ShapeDtypeStruct(shape, dtype)


def _zero_pad_rows(x, block_start, valid_total):
    """Zero rows past the logical array end in a ragged tail tile.

    Pallas pads out-of-bounds tile regions (NaN under the interpreter,
    unspecified on hardware); masked-to-zero probabilities times padded
    NaN/garbage still poison matmul accumulations, so padded rows are
    explicitly zeroed before any dot.
    """
    rows = block_start + lax.broadcasted_iota(jnp.int32, x.shape, 0)
    return jnp.where(rows < valid_total, x, 0.0)


def mha_reference(q, k, v, *, causal: bool = True, scale: float | None = None):
    """Dense reference attention (numerics oracle for the kernels).

    q,k,v: [batch, heads, seq, head_dim]  (k/v seq may differ from q's).
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, block_q, block_k,
                seq_k, off):
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # tiles strictly above the (bottom-aligned) diagonal contribute nothing
    guard = (ki * block_k < (qi + 1) * block_q + off) if causal else (ki >= 0)

    @pl.when(guard)
    def _compute():
        # matmul inputs stay in their native dtype (bf16 on the MXU runs at
        # 2x f32 throughput); preferred_element_type gives f32 accumulation
        q = q_ref[0]                              # [bq, d]
        k = k_ref[0]                              # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk] f32

        cols = ki * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if causal:
            # bottom-aligned diagonal (== mha_reference's tril(k=sk-sq)):
            # query row i attends keys <= i + (seq_k - seq_q)
            rows = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s = jnp.where(rows + off >= cols, s, NEG_INF)
        if seq_k % block_k:                        # mask padded tail keys
            s = jnp.where(cols < seq_k, s, NEG_INF)

        m_prev = m_scr[:]                          # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                     # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)            # [bq, 1]
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0]                               # [bk, d] native dtype
        if seq_k % block_k:
            v = _zero_pad_rows(v, ki * block_k, seq_k)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = m_new

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        # lse layout [bh, 1, sq]: keeps the trailing block dims TPU-tileable
        lse_ref[0] = (m_scr[:] + jnp.log(l_safe)).reshape(1, -1)


def _fwd(q, k, v, scale, causal, block_q, block_k):
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    grid = (bh, pl.cdiv(sq, block_q), pl.cdiv(sk, block_k))
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, seq_k=sk, off=sk - sq)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            _sds((bh, sq, d), q.dtype, _vma_of(q, k, v)),
            _sds((bh, 1, sq), jnp.float32, _vma_of(q, k, v)),
        ],
        scratch_shapes=_scratch(block_q, d),
        interpret=_use_interpret(),
    )(q, k, v)
    return o, lse


def _scratch(block_q, d):
    from jax.experimental.pallas import tpu as pltpu
    return [
        pltpu.VMEM((block_q, 1), jnp.float32),
        pltpu.VMEM((block_q, 1), jnp.float32),
        pltpu.VMEM((block_q, d), jnp.float32),
    ]


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, scale, causal, block_q, block_k, seq_k, off):
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    guard = (ki * block_k < (qi + 1) * block_q + off) if causal else (ki >= 0)

    @pl.when(guard)
    def _compute():
        # native-dtype (bf16) matmul inputs, f32 accumulation — see _fwd_kernel
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0].reshape(block_q, 1)      # [bq, 1]
        delta = delta_ref[0].reshape(block_q, 1)  # [bq, 1]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        cols = ki * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if causal:
            rows = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s = jnp.where(rows + off >= cols, s, NEG_INF)
        if seq_k % block_k:
            s = jnp.where(cols < seq_k, s, NEG_INF)
            k = _zero_pad_rows(k, ki * block_k, seq_k)
            v = _zero_pad_rows(v, ki * block_k, seq_k)
        p = jnp.exp(s - lse)                       # [bq, bk] f32
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)    # [bq, bk]
        ds = p * (dp - delta) * scale              # lse/delta refs are f32
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr,
                    *, scale, causal, block_q, block_k, seq_k, seq_q, off):
    ki, qi = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    guard = ((qi + 1) * block_q + off > ki * block_k) if causal else (qi >= 0)

    @pl.when(guard)
    def _compute():
        # native-dtype (bf16) matmul inputs, f32 accumulation — see _fwd_kernel
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0].reshape(block_q, 1)      # f32 (fwd out_shape)
        delta = delta_ref[0].reshape(block_q, 1)  # f32 (computed in _bwd)
        if seq_q % block_q:
            q = _zero_pad_rows(q, qi * block_q, seq_q)
            do = _zero_pad_rows(do, qi * block_q, seq_q)
            lse = _zero_pad_rows(lse, qi * block_q, seq_q)
            delta = _zero_pad_rows(delta, qi * block_q, seq_q)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        cols = ki * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if causal:
            rows = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s = jnp.where(rows + off >= cols, s, NEG_INF)
        if seq_k % block_k:
            s = jnp.where(cols < seq_k, s, NEG_INF)
        p = jnp.exp(s - lse)                       # [bq, bk] f32
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale              # [bq, bk]
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # [bk, d]

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(scale, causal, block_q, block_k, res, do_4d):
    q, k, v, o, lse = res
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    do = do_4d
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)[:, None, :]          # [bh, 1, sq]

    grid_dq = (bh, pl.cdiv(sq, block_q), pl.cdiv(sk, block_k))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_k=sk,
                          off=sk - sq),
        grid=grid_dq,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=_sds((bh, sq, d), q.dtype, _vma_of(q, k, v, do)),
        scratch_shapes=[_scratch(block_q, d)[2]],
        interpret=_use_interpret(),
    )(q, k, v, do, lse, delta)

    grid_dkv = (bh, pl.cdiv(sk, block_k), pl.cdiv(sq, block_q))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_k=sk,
                          seq_q=sq, off=sk - sq),
        grid=grid_dkv,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            _sds((bh, sk, d), k.dtype, _vma_of(q, k, v, do)),
            _sds((bh, sk, d), v.dtype, _vma_of(q, k, v, do)),
        ],
        scratch_shapes=[
            _scratch(block_k, d)[2], _scratch(block_k, d)[2],
        ],
        interpret=_use_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public op with custom VJP
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, block_q, block_k):
    o, _ = _fwd(q, k, v, scale, causal, block_q, block_k)
    return o


def _flash_fwd(q, k, v, scale, causal, block_q, block_k):
    o, lse = _fwd(q, k, v, scale, causal, block_q, block_k)
    return o, (q, k, v, o, lse)


def _flash_bwd(scale, causal, block_q, block_k, res, do):
    return _bwd(scale, causal, block_q, block_k, res, do)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _legal_block(seq: int, block: int) -> int:
    """Normalize a block size to Mosaic-legal tiling geometry.

    A block's seq dims must be 128-multiples or span the whole array dim:
    whole-seq when the seq fits in one block (or the 128 floor), else the
    largest 128-multiple <= the request.  Applied **unconditionally** — the
    interpreter (CPU test) path runs the exact tiling geometry the TPU path
    compiles, so CPU green means the TPU grid shape was exercised.
    """
    if seq <= block:
        return seq
    b = max(128, block // 128 * 128)
    return seq if seq <= b else b


def flash_attention(q, k, v, *, causal: bool = True,
                    scale: float | None = None,
                    block_q: int = 1024, block_k: int = 1024):
    """Flash attention over [batch, heads, seq, head_dim] tensors.

    Differentiable (custom VJP, recompute-based backward); O(seq) memory.
    Falls back to the Pallas interpreter off-TPU so CPU tests run the same
    kernel code.

    Default 1024x1024 blocks, from a v5e block sweep at the bench headline
    geometry (B=8, H=4, D=128, seq 4096, bf16, fwd+bwd): 8.2 ms vs 11.5 ms
    for the old 512x512 default (1.38x; 50 vs 36 useful TFLOP/s) — bigger
    tiles amortize the bwd recompute's grid/DMA overhead.  The next size up
    is past the knee: 1024x2048 is 9.1 ms and 2048-row blocks fail to
    compile (VMEM).  At D=64/H=8 the sweep gives 1024x1024 a smaller edge
    (17.0 vs 17.9 ms), so one default serves both geometries; earlier
    small-block data (128x128 losing to XLA dense below seq 4k from
    grid/DMA overhead) still holds.  VMEM per step ~= bq*bk*4 (score tile)
    + bq*d*4 (acc): 4.5 MB at 1024/1024/d=128.
    """
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    block_q = _legal_block(sq, block_q)
    block_k = _legal_block(sk, block_k)
    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * h, sk, d)
    vf = v.reshape(b * h, sk, d)
    o = _flash(qf, kf, vf, scale, causal, block_q, block_k)
    return o.reshape(b, h, sq, d)
