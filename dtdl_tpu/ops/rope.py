"""Rotary position embeddings (RoPE).

Shared by the flax TransformerLM (dtdl_tpu/models/transformer.py) and the
manual-SPMD megatron step (dtdl_tpu/parallel/megatron.py).  Position-offset
aware so sequence-parallel shards can rotate their *global* positions
(device i of a ``seq`` axis passes ``offset = i * seq_local``).

Two consumers, two shapes of the same math:

* :func:`apply_rope` — the eager rotation, used by the decode paths (one
  or a handful of query rows against a KV cache — the rotation is noise
  there) and as the numerics oracle.
* the **fused kernel path** (round 13) — training/eval full-sequence
  attention passes the raw (cos, sin) tables to
  ``flash_attention(..., rope=(cos, sin))`` and the rotation happens
  inside the Pallas kernels on tile load, eliminating apply_rope's
  per-layer HBM round-trip of the full [B, H, S, D] Q/K tensors.
  :func:`rope_rows` builds the per-position full-width (D, not D/2)
  table rows the kernels consume: with cc = [c, c] and ss = [s, s],
  ``rope(x) = x·cc + rot_half(x)·ss`` where rot_half([x1, x2]) =
  [-x2, x1] — the identical f32 arithmetic as :func:`apply_rope`.

The reference has no sequence models (SURVEY §5.7); this op exists for the
framework's first-class long-context capability.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, max_seq: int, theta: float = 10000.0,
                     dtype=jnp.float32):
    """Precompute (cos, sin) tables of shape [max_seq, head_dim//2]."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                           dtype=jnp.float32) / head_dim))
    pos = jnp.arange(max_seq, dtype=jnp.float32)
    angles = jnp.outer(pos, inv_freq)
    return jnp.cos(angles).astype(dtype), jnp.sin(angles).astype(dtype)


def rope_rows(cos, sin, positions):
    """Per-position rope rows widened to full head_dim for the fused
    kernels: [len(positions), head_dim] f32 (cc, ss) such that
    ``x·cc + rot_half(x)·ss`` equals :func:`apply_rope` at those
    positions.  Tiny ([S, D] vs the [B, H, S, D] tensors), so gathering
    them outside the kernel costs ~1/(2·B·H) of the traffic the fusion
    removes."""
    c = jnp.take(cos, positions, axis=0).astype(jnp.float32)
    s = jnp.take(sin, positions, axis=0).astype(jnp.float32)
    return (jnp.concatenate([c, c], axis=-1),
            jnp.concatenate([s, s], axis=-1))


def apply_rope(x, cos, sin, offset=0, positions=None):
    """Rotate [batch, heads, seq, head_dim] queries/keys.

    ``offset`` (int or traced scalar) is the global position of the shard's
    first token — the hook contiguous sequence parallelism uses.
    ``positions`` ([seq] int array, overrides ``offset``) gives each local
    row an arbitrary global position — the hook the zigzag ring layout uses
    (dtdl_tpu/parallel/sequence.py zigzag_positions).
    """
    seq = x.shape[2]
    if positions is not None:
        c = jnp.take(cos, positions, axis=0)
        s = jnp.take(sin, positions, axis=0)
    elif isinstance(offset, int) and offset == 0:
        c, s = cos[:seq], sin[:seq]
    else:
        c = jnp.take(cos, offset + jnp.arange(seq), axis=0)
        s = jnp.take(sin, offset + jnp.arange(seq), axis=0)
    c = c[None, None, :, :]
    s = s[None, None, :, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)
    return out.astype(x.dtype)
