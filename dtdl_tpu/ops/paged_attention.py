"""Pallas paged-attention decode kernel (kernel round 2, ISSUE 16).

The serving engine's production memory layout is the block-paged KV
arena (``[n_pages, H, page_size, D]`` pools addressed through per-slot
page tables — dtdl_tpu/models/transformer.py:_paged_attend_slots).  The
round-6 attend gathers the ENTIRE logical view first::

    pages = jnp.take(pool, table, axis=0)        # [B, n_ptab, H, pg, D]
    gat   = pages.transpose(...).reshape(B, H, n_ptab * pg, D)

which materializes ``B * n_ptab * page_size`` K/V rows in scratch HBM
every decode step even though a slot at position ``pos`` only occupies
``ceil((pos+1)/page_size)`` pages — the measured ~15% paged-decode tax
(bench.py --paged, PR 6 known-remaining).  This kernel walks the page
table INSIDE the attention loop instead:

* grid ``(B, H, n_ptab)`` with the page step innermost (sequential);
  batch and head are embarrassingly parallel;
* the table / positions / active mask ride in **scalar prefetch**
  (``pltpu.PrefetchScalarGridSpec``): the K/V BlockSpec index maps read
  ``table[b, j]`` to aim each DMA straight at the *physical* page, so
  tiles stream ``[1, 1, page_size, D]`` chunks from the pooled arena —
  no gathered copy exists at any point;
* pages past a slot's high-water mark (``j > (pos + S - 1) // page``)
  clamp their index map to the last live page — consecutive identical
  block indices elide the DMA (the _kmaps trick in ops/attention.py) —
  and the guarded kernel body skips them entirely, so a 100-token slot
  in a 32K arena reads 1 page, not ``n_ptab``;
* int8/fp8 arenas fuse dequant into the tile loads exactly as the
  gather path does: the per-(page, head, offset) key scales ride a
  sibling ``[1, 1, page_size]`` tile and multiply the f32 logits
  BEFORE masking, the value scales fold into the softmax weights
  (quant/core.py:kv_quantize layout, PR 7);
* online softmax in VMEM scratch (m, l, acc — same recurrence as
  ops/attention.py:_fwd_kernel) finalizes once per (b, h).

Bytes argument (LM_ROOFLINE.md §9): per decode step the gather path
moves ``2 * B * n_ptab * page * H * D`` payload bytes pool->scratch
PLUS the same again scratch->compute; this kernel moves
``2 * B * ceil((pos+1)/page) * page * H * D`` pool->VMEM once.  For the
production long-context shape (n_ptab >> live pages) that is the whole
tax.  Inactive rows read only the reserved garbage page 0 (elided after
the first tile) and write zeros.

Token-identity contract: for every ACTIVE row the masked-logit set,
scale application order, and f32 accumulation dtype match
``_paged_attend_slots`` op-for-op (per-tile max/sum ordering differs —
an online softmax — so outputs agree to bf16 rounding; greedy tokens
are identical, pinned by tests/test_paged_kernel.py under the standing
RecompileSentinel zero-new-programs contract).  Inactive rows return
zeros (the engine discards them; the gather path returns garbage there).

On CPU the kernel runs under the Pallas interpreter (correct but slow —
tests only); ``paged_kernel_enabled`` routes 'auto' to the gather path
off-TPU so serving never eats interpreter overhead by accident.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from dtdl_tpu.ops.attention import (_pallas_kwargs, _sds, _use_interpret,
                                    _vma_of, _vmem)

NEG_INF = -1e30   # matches the gather path's mask fill, NOT -inf


def paged_kernel_enabled(flag) -> bool:
    """Resolve the engine's ``paged_kernel=`` knob to a bool.

    ``True``/``False`` are explicit (True on CPU runs the interpreter —
    tests and debugging); ``'auto'`` enables the kernel only on a real
    TPU backend, the documented CPU/interpret auto-fallback.
    """
    if isinstance(flag, bool):
        return flag
    if flag == "auto":
        return jax.default_backend() == "tpu"
    raise ValueError(
        f"paged_kernel must be True, False or 'auto', got {flag!r}")


def _kernel(tab_ref, pos_ref, act_ref, *refs, scale, page, s_new, quant,
            dtype):
    """Grid (B, H, n_ptab); j = page step, sequential innermost."""
    if quant:
        (q_ref, k_ref, v_ref, ks_ref, vs_ref,
         o_ref, m_scr, l_scr, acc_scr) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = refs
    b, j = pl.program_id(0), pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # high-water page of this row; tiles past it hold no visible keys
    last = jnp.maximum((pos_ref[b] + s_new - 1) // page, 0)
    guard = (act_ref[b] > 0) & (j <= last)

    @pl.when(guard)
    def _compute():
        q = q_ref[0, 0]                            # [S, D] native dtype
        k = k_ref[0, 0]                            # [pg, D] pool dtype
        if quant:
            k = k.astype(dtype)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)    # [S, pg] f32
        if quant:
            # key scale multiplies the logits BEFORE the causal scale
            # and mask — the gather path's exact op order
            s = s * ks_ref[0, 0].astype(jnp.float32)[None, :]
        cols = j * page + lax.broadcasted_iota(
            jnp.int32, (s_new, page), 1)
        qpos = pos_ref[b] + lax.broadcasted_iota(
            jnp.int32, (s_new, page), 0)
        s = jnp.where(cols <= qpos, s * scale, NEG_INF)
        # every active row keeps column 0 of tile j=0, so a fully
        # NEG_INF first tile (the exp(0)=1 hazard) cannot occur
        m_prev = m_scr[:]                          # [S, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                     # [S, pg] f32
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0, 0]                            # [pg, D]
        if quant:
            # value scale folds into the softmax weights (as gather)
            w = (p * vs_ref[0, 0].astype(jnp.float32)[None, :]
                 ).astype(dtype)
            v = v.astype(dtype)
        else:
            w = p.astype(v.dtype)
        pv = lax.dot_general(
            w, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = m_new

    @pl.when(j == nj - 1)
    def _finalize():
        l = l_scr[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)       # inactive rows -> 0
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def paged_attention(q, pages_k, pages_v, page_table, pos, active, *,
                    scale, key_scale=None, value_scale=None):
    """Attend ``q`` [B, H, S, D] (already roped) against a paged arena.

    ``pages_k``/``pages_v``: ``[n_pages, H, page_size, D]`` pools (bf16,
    int8 or fp8 — pass both ``key_scale``/``value_scale``
    ``[n_pages, H, page_size]`` siblings for quantized pools).
    ``page_table`` [B, n_ptab] int32 maps logical to physical pages
    (garbage page 0 for unmapped), ``pos`` [B] the clamped per-row
    positions (``pos_safe``), ``active`` [B] bool.  Returns
    ``[B, H, S, D]`` in q's dtype; inactive rows are zeros.
    """
    from jax.experimental.pallas import tpu as pltpu

    b, h, s_new, d = q.shape
    n_pages, hp, page, dp = pages_k.shape
    assert (hp, dp) == (h, d), (pages_k.shape, q.shape)
    n_ptab = page_table.shape[1]
    quant = key_scale is not None
    if quant != (value_scale is not None):
        raise ValueError("key_scale and value_scale must be passed "
                         "together")

    # block-index maps: scalar-prefetch refs arrive as trailing args.
    # Pages past the high-water mark clamp to it and inactive rows pin
    # to the garbage page — consecutive identical indices elide the DMA.
    def _phys(jj, tab, p_, act, bi):
        last = jnp.maximum((p_[bi] + s_new - 1) // page, 0)
        jc = jnp.minimum(jj, last)
        return jnp.where(act[bi] > 0, tab[bi, jc], 0)

    def q_map(bi, hh, j, tab, p_, act):
        return (bi, hh, 0, 0)

    def kv_map(bi, hh, j, tab, p_, act):
        return (_phys(j, tab, p_, act, bi), hh, 0, 0)

    def scale_map(bi, hh, j, tab, p_, act):
        return (_phys(j, tab, p_, act, bi), hh, 0)

    in_specs = [
        pl.BlockSpec((1, 1, s_new, d), q_map),
        pl.BlockSpec((1, 1, page, d), kv_map),
        pl.BlockSpec((1, 1, page, d), kv_map),
    ]
    operands = [q, pages_k, pages_v]
    if quant:
        in_specs += [
            pl.BlockSpec((1, 1, page), scale_map),
            pl.BlockSpec((1, 1, page), scale_map),
        ]
        operands += [key_scale, value_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, h, n_ptab),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, s_new, d), q_map),
        scratch_shapes=[
            _vmem((s_new, 1), jnp.float32),
            _vmem((s_new, 1), jnp.float32),
            _vmem((s_new, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _kernel, scale=scale, page=page, s_new=s_new, quant=quant,
        dtype=q.dtype)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=_sds((b, h, s_new, d), q.dtype,
                       _vma_of(q, pages_k, pages_v)),
        interpret=_use_interpret(),
        **_pallas_kwargs(),
    )(jnp.asarray(page_table, jnp.int32),
      jnp.asarray(pos, jnp.int32),
      jnp.asarray(active, jnp.int32),
      *operands)
