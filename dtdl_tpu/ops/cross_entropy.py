"""Classification loss ops.

The reference computes softmax cross-entropy through each host framework
(``nn.CrossEntropyLoss`` at reference pytorch/distributed_data_parallel.py:93,
Keras ``sparse_categorical_crossentropy`` at tensorflow2/mnist_single.py:87,
Chainer ``L.Classifier`` default at chainer/train_mnist.py:62).  Here it is
one op: a numerically stable log-sum-exp formulation that XLA fuses into the
final matmul's epilogue.  For the 10-class parity workloads XLA's fusion is
already optimal; a fused Pallas kernel only pays off at large vocab sizes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array,
                          reduction: str = "mean") -> jax.Array:
    """Cross-entropy from integer labels; logits (B, C), labels (B,)."""
    logits = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    true_logit = jnp.take_along_axis(
        logits, labels[:, None].astype(jnp.int32), axis=-1)[:, 0]
    losses = lse - true_logit
    if reduction == "mean":
        return losses.mean()
    if reduction == "sum":
        return losses.sum()
    if reduction == "none":
        return losses
    raise ValueError(f"unknown reduction {reduction!r}")


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Fraction of argmax predictions matching integer labels."""
    return (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32).mean()
