"""Classification loss ops.

The reference computes softmax cross-entropy through each host framework
(``nn.CrossEntropyLoss`` at reference pytorch/distributed_data_parallel.py:93,
Keras ``sparse_categorical_crossentropy`` at tensorflow2/mnist_single.py:87,
Chainer ``L.Classifier`` default at chainer/train_mnist.py:62).  Here it is
one op: a numerically stable log-sum-exp formulation that XLA fuses into the
final matmul's epilogue.  For the 10-class parity workloads XLA's fusion is
already optimal; a fused Pallas kernel only pays off at large vocab sizes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array,
                          reduction: str = "mean") -> jax.Array:
    """Cross-entropy from integer labels; logits (B, C), labels (B,)."""
    logits = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    true_logit = jnp.take_along_axis(
        logits, labels[:, None].astype(jnp.int32), axis=-1)[:, 0]
    losses = lse - true_logit
    if reduction == "mean":
        return losses.mean()
    if reduction == "sum":
        return losses.sum()
    if reduction == "none":
        return losses
    raise ValueError(f"unknown reduction {reduction!r}")


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Fraction of argmax predictions matching integer labels."""
    return (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32).mean()


# ---------------------------------------------------------------------------
# vocab-chunked LM head loss (never materializes [tokens, vocab] logits)
# ---------------------------------------------------------------------------

def _ce_chunks(V: int, chunk_size: int) -> tuple[int, int]:
    vc = min(max(int(chunk_size), 1), V)
    return -(-V // vc), vc


def _vary_like(x, *refs):
    """shard_map VMA pre-cast for scan carries — delegates to the single
    implementation (lazy import: dtdl_tpu.parallel pulls in the megatron
    stack, which itself imports dtdl_tpu.ops)."""
    from dtdl_tpu.parallel.collectives import pvary_like
    return pvary_like(x, *refs)


def _chunk_logits(h, emb, c, vc, V):
    """f32 logits of vocab chunk c: ([T, vc], global col ids, valid mask).

    When the last chunk would run past V the window slides back to keep
    static shapes; columns already covered by the previous chunk come back
    with ``valid=False`` and their logits forced to -inf.
    """
    start = c * vc
    base = jnp.minimum(start, V - vc)
    emb_c = jax.lax.dynamic_slice_in_dim(emb, base, vc, 0)
    cols = base + jnp.arange(vc)
    valid = cols >= start
    logits = jnp.einsum("td,vd->tv", h.astype(jnp.float32),
                        emb_c.astype(jnp.float32))
    logits = jnp.where(valid[None, :], logits, -jnp.inf)
    return logits, cols, valid


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def chunked_lm_loss(h, emb, targets, mask, chunk_size=4096):
    """Masked-sum LM cross entropy with the vocab dim processed in chunks.

    ``h`` [T, D] final hidden states, ``emb`` [V, D] (tied) output
    embedding, ``targets`` [T] int32, ``mask`` [T] f32.  Returns
    ``(loss_sum, correct_sum)`` where correct counts argmax==target hits
    (masked), so callers get accuracy without logits.

    The flash-attention trick applied to the vocab axis: an online
    (max, sumexp) recurrence over [T, chunk] logit tiles — peak memory is
    O(T * chunk) instead of the O(T * V) f32 logits the dense head
    materializes for itself *and* for its backward residual (at V=32k,
    seq 4k, batch 8 that is 2 x 4.2 GB).  The backward pass recomputes
    each tile from the saved (h, lse) — the same recompute-not-store
    contract as dtdl_tpu/ops/attention.py.
    """
    (loss, correct), _ = _chunked_fwd(h, emb, targets, mask, chunk_size)
    return loss, correct


def _chunked_fwd(h, emb, targets, mask, chunk_size):
    V = emb.shape[0]
    n, vc = _ce_chunks(V, chunk_size)
    T = h.shape[0]
    tgt = targets.astype(jnp.int32)

    def step(carry, c):
        m, s, true_l, best, arg = carry
        logits, cols, valid = _chunk_logits(h, emb, c, vc, V)
        cmax = jnp.max(logits, -1)
        m_new = jnp.maximum(m, cmax)
        s = (s * jnp.exp(m - m_new)
             + jnp.sum(jnp.exp(logits - m_new[:, None]), -1))
        hit = (tgt[:, None] == cols[None, :]) & valid[None, :]
        true_l = true_l + jnp.sum(jnp.where(hit, logits, 0.0), -1)
        carg = cols[jnp.argmax(logits, -1)]
        arg = jnp.where(cmax > best, carg, arg)
        best = jnp.maximum(best, cmax)
        return (m_new, s, true_l, best, arg), None

    neg = _vary_like(jnp.full((T,), -jnp.inf, jnp.float32), h, emb, targets)
    zero = _vary_like(jnp.zeros((T,), jnp.float32), h, emb, targets)
    arg0 = _vary_like(jnp.zeros((T,), jnp.int32), h, emb, targets)
    (m, s, true_l, _, arg), _ = jax.lax.scan(
        step, (neg, zero, zero, neg, arg0), jnp.arange(n))
    lse = m + jnp.log(s)
    loss = jnp.sum((lse - true_l) * mask)
    correct = jnp.sum((arg == tgt).astype(jnp.float32) * mask)
    return (loss, correct), (h, emb, targets, mask, lse, true_l, arg)


def _chunked_bwd(chunk_size, res, cot):
    h, emb, targets, mask, lse, true_l, arg = res
    g = cot[0]                  # cotangent of loss_sum
    V, D = emb.shape
    n, vc = _ce_chunks(V, chunk_size)
    tgt = targets.astype(jnp.int32)
    w = (mask * g).astype(jnp.float32)

    def step(carry, c):
        dh, demb = carry
        logits, cols, valid = _chunk_logits(h, emb, c, vc, V)
        p = jnp.where(valid[None, :], jnp.exp(logits - lse[:, None]), 0.0)
        onehot = ((tgt[:, None] == cols[None, :]) & valid[None, :]
                  ).astype(jnp.float32)
        dl = (p - onehot) * w[:, None]              # [T, vc]
        base = jnp.minimum(c * vc, V - vc)
        emb_c = jax.lax.dynamic_slice_in_dim(emb, base, vc, 0)
        dh = dh + jnp.einsum("tv,vd->td", dl, emb_c.astype(jnp.float32))
        demb_c = jnp.einsum("tv,td->vd", dl, h.astype(jnp.float32))
        # in-place tile accumulate: one pass, no stacked [n, vc, D] copy
        # (overlap columns of a slid-back last tile contribute zeros)
        cur = jax.lax.dynamic_slice_in_dim(demb, base, vc, 0)
        demb = jax.lax.dynamic_update_slice_in_dim(demb, cur + demb_c,
                                                   base, 0)
        return (dh, demb), None

    dh0 = _vary_like(jnp.zeros(h.shape, jnp.float32), h, emb, targets, g)
    demb0 = _vary_like(jnp.zeros((V, D), jnp.float32), h, emb, targets, g)
    (dh, demb), _ = jax.lax.scan(step, (dh0, demb0), jnp.arange(n))
    # loss term + the correct_sum output's own mask-cotangent (argmax hits
    # are piecewise-constant in h/emb, so their grads through correct are 0)
    dmask = (lse - true_l) * g + (arg == tgt).astype(jnp.float32) * cot[1]
    dtargets = np.zeros(targets.shape, jax.dtypes.float0)
    return dh.astype(h.dtype), demb.astype(emb.dtype), dtargets, dmask


chunked_lm_loss.defvjp(_chunked_fwd, _chunked_bwd)
