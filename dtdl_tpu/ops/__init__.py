from dtdl_tpu.ops.cross_entropy import (  # noqa: F401
    chunked_lm_loss, softmax_cross_entropy, accuracy,
)
