from dtdl_tpu.ops.cross_entropy import (  # noqa: F401
    softmax_cross_entropy, accuracy,
)
