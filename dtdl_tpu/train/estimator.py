"""Estimator — the TF1-idiom API flavor (model_fn / input_fn / RunConfig).

The reference declares a ``tensorflow/`` (TF1) track that was never written
(reference tensorflow/README.md is zero-byte; declared at README.md:4-20).
TF1's canonical training surface is the Estimator: a ``model_fn`` builds the
graph per mode, an ``input_fn`` supplies data, ``RunConfig`` schedules
checkpoints, and ``train_and_evaluate`` alternates the two — with the key
behavioral contract that **every call restores the latest checkpoint from
model_dir**, so training is resumable by construction and train/evaluate can
run in separate processes.

TPU-native restatement: the "graph per mode" becomes a flax module + optax
transform returned once by ``model_fn(mode, params)``; each mode's step is a
single jitted SPMD program over the strategy's mesh (TRAIN fuses forward/
backward/allreduce/update like the engine's other flavors); the checkpoint
contract is kept exactly — Estimator never holds training state across calls,
it round-trips through model_dir.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from dtdl_tpu.ckpt.checkpoint import Checkpointer
from dtdl_tpu.data.loader import DataLoader, prefetch_to_device, resume_iter
from dtdl_tpu.metrics.device import MetricsQueue
from dtdl_tpu.metrics.report import Reporter, StdoutSink
from dtdl_tpu.parallel.strategy import SingleDevice, Strategy
from dtdl_tpu.train.loop import evaluate as _evaluate
from dtdl_tpu.train.state import init_state
from dtdl_tpu.train.step import (make_eval_step, make_predict_step,
                                 make_train_step)


class ModeKeys:
    """tf.estimator.ModeKeys equivalents."""
    TRAIN = "train"
    EVAL = "eval"
    PREDICT = "infer"


@dataclass
class EstimatorSpec:
    """What ``model_fn`` returns for a mode.

    ``model`` is a flax module (the per-mode "graph"); ``tx`` the optax
    transform (TRAIN mode only); ``loss_fn`` overrides the default softmax
    cross-entropy and must follow the engine's loss contract:
    ``loss_fn(logits, labels, reduction="mean"|"none")`` (the eval step
    requests per-example losses via reduction="none" — see
    dtdl_tpu.ops.softmax_cross_entropy for the reference implementation).
    TF1's ops/hooks collapse into these three fields because the step
    engine owns the rest of the program.
    """
    mode: str
    model: Any
    tx: Any = None
    loss_fn: Any = None


@dataclass
class RunConfig:
    """Checkpoint/logging cadence (tf.estimator.RunConfig surface)."""
    save_checkpoints_steps: int = 1000
    keep_checkpoint_max: int = 5
    log_step_count_steps: int = 100
    tf_random_seed: int = 0


@dataclass
class TrainSpec:
    input_fn: Callable
    max_steps: int


@dataclass
class EvalSpec:
    input_fn: Callable
    steps: int | None = None


def _as_loader(data, batch_size: int = 128) -> DataLoader:
    """input_fn may return a DataLoader or an (features, labels) pair."""
    if isinstance(data, DataLoader) or hasattr(data, "batch_size"):
        return data
    features, labels = data
    # audit: ok[host-sync-asarray] input_fn feature/label pair is caller-supplied host data
    return DataLoader({"image": np.asarray(features),
                       # audit: ok[host-sync-asarray] input_fn feature/label pair is caller-supplied host data
                       "label": np.asarray(labels)}, batch_size)


class Estimator:
    """tf.estimator.Estimator over the jitted step engine.

    ``model_fn(mode, params) -> EstimatorSpec`` (``params`` is the
    hyperparameter dict, TF1 style).  All state lives in ``model_dir``:
    train() restores the latest checkpoint, advances, checkpoints;
    evaluate()/predict() restore and run.  ``strategy`` injects DP/DDP the
    way TF1 injected distribution via RunConfig train_distribute.
    """

    def __init__(self, model_fn: Callable, model_dir: str = "./estimator",
                 config: RunConfig | None = None, params: dict | None = None,
                 strategy: Strategy | None = None, observer=None):
        from dtdl_tpu.obs.observer import NULL_OBSERVER
        self.model_fn = model_fn
        self.model_dir = model_dir
        self.config = config or RunConfig()
        self.params = params or {}
        self.strategy = strategy or SingleDevice()
        self.observer = observer or NULL_OBSERVER
        self.ckpt = Checkpointer(model_dir,
                                 keep=self.config.keep_checkpoint_max)
        self.reporter = Reporter([StdoutSink()])
        # compiled steps are mode+strategy-determined: cache them so each
        # train_and_evaluate leg reuses the XLA executable instead of
        # recompiling (only the *state* round-trips through model_dir)
        self._compiled: dict[str, Any] = {}

    # -- state plumbing -------------------------------------------------------

    def _build_state(self, spec: EstimatorSpec, example):
        # the checkpoint always holds the TRAIN graph's variables (params +
        # optimizer slots), TF1-style — so the restore template uses the
        # TRAIN-mode optimizer even when evaluating/predicting
        tx = spec.tx
        if tx is None:
            tx = self.model_fn(ModeKeys.TRAIN, self.params).tx
        if tx is None:
            import optax
            tx = optax.sgd(0.01)
        key = jax.random.PRNGKey(self.config.tf_random_seed)
        return self.strategy.replicate(init_state(
            spec.model, key, jnp.zeros((1,) + example.shape[1:]), tx))

    def _restore_or_init(self, spec: EstimatorSpec, example):
        state = self._build_state(spec, example)
        restored, step = self.ckpt.restore(state)
        if restored is not None:
            return restored, int(step)
        return state, 0

    def latest_global_step(self) -> int:
        """Step of the latest checkpoint in model_dir (0 if none)."""
        return self.ckpt.latest_step() or 0

    # -- the three verbs ------------------------------------------------------

    def train(self, input_fn: Callable, steps: int | None = None,
              max_steps: int | None = None) -> "Estimator":
        """Advance training; restores latest checkpoint first (TF1 contract).

        ``steps`` = additional steps from wherever the checkpoint left off;
        ``max_steps`` = absolute global-step ceiling (no-op if reached);
        neither = one full pass over input_fn's data (TF1 trains until the
        input is exhausted).
        """
        spec = self.model_fn(ModeKeys.TRAIN, self.params)
        loader = _as_loader(input_fn())
        sample = next(iter(loader))
        state, global_step = self._restore_or_init(spec, sample["image"])
        target = (max_steps if max_steps is not None
                  else global_step + (steps if steps is not None
                                      else len(loader)))
        if global_step >= target:
            return self

        if "train" not in self._compiled:
            self._compiled["train"] = make_train_step(
                self.strategy, **({"loss_fn": spec.loss_fn} if spec.loss_fn
                                  else {}),
                seed=self.config.tf_random_seed)
        train_step = self.observer.watch(self._compiled["train"],
                                         "estimator.train_step")
        cfg = self.config
        # async dispatch discipline (SCALING.md): the loop dispatches
        # back-to-back and syncs ONCE per log_step_count_steps — the drain
        # at the log boundary both fetches the loss and closes the timing
        # window (so global_step/sec covers finished work, not enqueued
        # work).  The queue's lag bounds how far the host may run ahead.
        queue = MetricsQueue(max(cfg.log_step_count_steps, 1))
        t0, logged_at = time.time(), global_step
        # the shuffle order is deterministic in (seed, epoch): resume at the
        # epoch/offset the restored global_step corresponds to, so successive
        # train_and_evaluate legs walk the dataset instead of retraining on
        # the same leading batches each leg
        steps_per_epoch = len(loader)
        epoch = global_step // steps_per_epoch
        skip = global_step % steps_per_epoch
        last_saved = global_step
        try:
            while global_step < target:
                loader.set_epoch(epoch)
                raw = resume_iter(loader, skip)
                skip = 0
                it = prefetch_to_device(raw, self.strategy.shard_batch, 2)
                for batch in it:
                    if global_step >= target:
                        break
                    with self.observer.span("dispatch",
                                            global_step=global_step):
                        state, metrics = train_step(state, batch)
                    global_step += 1
                    queue.push(metrics)
                    if (cfg.log_step_count_steps
                            and global_step % cfg.log_step_count_steps == 0):
                        with self.observer.span("drain"):
                            drained = queue.drain()  # blocks on current step
                        dt = time.time() - t0
                        rate = (global_step - logged_at) / max(dt, 1e-9)
                        goodput = self.observer.window(
                            global_step - logged_at, dt)
                        t0, logged_at = time.time(), global_step
                        self.reporter.report({
                            "global_step": global_step,
                            "loss": drained[-1]["loss"] if drained
                            else float(metrics["loss"]),
                            "global_step/sec": round(rate, 2),
                            **goodput,
                        })
                    if (cfg.save_checkpoints_steps
                            and global_step % cfg.save_checkpoints_steps == 0):
                        self.ckpt.save(global_step, state)
                        last_saved = global_step
                epoch += 1
            if global_step != last_saved:
                self.ckpt.save(global_step, state)
        finally:
            # async saves durable before return — including on an exception
            # mid-train, so a --max-restarts relaunch sees the newest snapshot
            self.ckpt.wait_until_finished()
        return self

    def evaluate(self, input_fn: Callable, steps: int | None = None) -> dict:
        """Exact metrics at the latest checkpoint (padded ragged tails)."""
        spec = self.model_fn(ModeKeys.EVAL, self.params)
        loader = _as_loader(input_fn())
        sample = next(iter(loader))
        state, global_step = self._restore_or_init(spec, sample["image"])
        if steps:
            from dtdl_tpu.data.loader import LimitBatches
            loader = LimitBatches(loader, steps)
        if "eval" not in self._compiled:
            self._compiled["eval"] = make_eval_step(
                self.strategy, **({"loss_fn": spec.loss_fn} if spec.loss_fn
                                  else {}))
        means = _evaluate(self._compiled["eval"], state, loader,
                          self.strategy)
        result = {**means, "global_step": global_step}
        self.reporter.report({"split": "eval", **result})
        return result

    def predict(self, input_fn: Callable):
        """Generator of per-example prediction dicts (TF1 predict shape).

        Ragged tail batches are padded to the loader's batch size (mesh
        strategies shard the batch dim) and the padding rows dropped from
        the yielded stream.
        """
        from dtdl_tpu.train.loop import _pad_and_mask
        spec = self.model_fn(ModeKeys.PREDICT, self.params)
        loader = _as_loader(input_fn())
        sample = next(iter(loader))
        state, _ = self._restore_or_init(spec, sample["image"])
        if "predict" not in self._compiled:
            self._compiled["predict"] = make_predict_step(self.strategy)
        predict_step = self._compiled["predict"]
        for batch in iter(loader):
            n = len(next(iter(batch.values())))
            padded = _pad_and_mask(batch, loader.batch_size)
            padded.pop("mask")
            # audit: ok[host-sync] predict() yields host rows by contract — the drain point of the predict loop
            logits = np.asarray(jax.device_get(predict_step(
                state, self.strategy.shard_batch(padded))))[:n]
            for row in logits:
                yield {"logits": row, "class_ids": int(np.argmax(row)),
                       "probabilities": _softmax(row)}


def _softmax(row: np.ndarray) -> np.ndarray:
    e = np.exp(row - row.max())
    return e / e.sum()


def train_and_evaluate(estimator: Estimator, train_spec: TrainSpec,
                       eval_spec: EvalSpec) -> dict:
    """tf.estimator.train_and_evaluate: train in checkpoint-sized legs,
    evaluating after each new checkpoint, until max_steps."""
    leg = max(1, estimator.config.save_checkpoints_steps)
    result: dict = {}
    while True:
        at = estimator.latest_global_step()
        if at >= train_spec.max_steps:
            break
        estimator.train(train_spec.input_fn,
                        max_steps=min(at + leg, train_spec.max_steps))
        result = estimator.evaluate(eval_spec.input_fn, eval_spec.steps)
    return result
