"""Train state: params + optimizer state + BN statistics + step counter.

One immutable pytree replacing the reference's scattered mutable state (model
parameters inside ``nn.Module``, optimizer slots inside ``torch.optim.SGD``,
BN running stats as module buffers).  Being a pytree, the whole state is
shardable, donatable, and checkpointable as a unit — full trainer-state resume
(the Chainer snapshot shape, reference chainer/train_mnist.py:91-93,120-122)
is just serializing this object.
"""

from __future__ import annotations

from typing import Any, Callable

import flax
import jax
import optax
from flax import core


class TrainState(flax.struct.PyTreeNode):
    step: jax.Array
    params: core.FrozenDict[str, Any]
    opt_state: optax.OptState
    batch_stats: core.FrozenDict[str, Any] | None
    apply_fn: Callable = flax.struct.field(pytree_node=False)
    tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)

    def apply_gradients(self, *, grads, batch_stats=None):
        updates, new_opt_state = self.tx.update(
            grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(
            step=self.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            batch_stats=batch_stats if batch_stats is not None
            else self.batch_stats,
        )

    @classmethod
    def create(cls, *, apply_fn, params, tx, batch_stats=None):
        import jax.numpy as jnp
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            batch_stats=batch_stats,
            apply_fn=apply_fn,
            tx=tx,
        )


def init_state(model, rng, example_input, tx) -> TrainState:
    """Initialize model variables and wrap them in a TrainState.

    The whole initialization (flax init + optimizer slot init) runs under one
    jit: eager init would dispatch thousands of tiny ops one by one, which is
    pathologically slow on remote/tunneled TPU backends (minutes for a
    110-layer model vs seconds jitted).
    """
    def build(rng):
        variables = model.init(rng, example_input, train=False)
        params = variables["params"]
        return TrainState.create(
            apply_fn=model.apply, params=params, tx=tx,
            batch_stats=variables.get("batch_stats"))

    return jax.jit(build)(rng)
