"""Caffe-style Solver — the prototxt-driven training engine.

The reference's Caffe track is declared but empty (reference caffe/README.md,
zero-byte; README.md:4-20), so its *capability* surface is Caffe's canonical
one: ``caffe train --solver=solver.prototxt`` where the solver prototxt names
a net prototxt and the optimization schedule.  This module implements that
surface TPU-natively: the net compiles to a single XLA program (see
dtdl_tpu/models/netspec.py), the lr policy becomes an optax schedule (a
closed-form function of the iteration — no Python control flow in the hot
loop), and multi-device runs ride the framework's strategy layer the way
Caffe's multi-GPU ``-gpu all`` ran tree-reduction data parallelism.

Solver fields honored (Caffe SolverParameter semantics):
  net / train_net / test_net, test_iter, test_interval, test_initialization,
  base_lr, lr_policy (fixed | step | exp | inv | multistep | poly | sigmoid),
  gamma, power, stepsize, stepvalue (repeated), max_iter, iter_size,
  momentum, weight_decay, type (SGD | Nesterov | Adam | AdaGrad | RMSProp |
  AdaDelta), delta, momentum2, rms_decay, display, snapshot, snapshot_prefix,
  random_seed.

Iteration-based semantics throughout (Caffe has no epochs): display/test/
snapshot cadences count iterations.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dtdl_tpu.ckpt.checkpoint import Checkpointer
from dtdl_tpu.data.loader import LimitBatches, prefetch_to_device, resume_iter
from dtdl_tpu.metrics.device import MetricsQueue
from dtdl_tpu.metrics.report import Reporter, StdoutSink
from dtdl_tpu.train.loop import evaluate as _evaluate
from dtdl_tpu.models.netspec import build_net
from dtdl_tpu.parallel.strategy import SingleDevice, Strategy
from dtdl_tpu.train.state import init_state
from dtdl_tpu.train.step import make_eval_step, make_train_step
from dtdl_tpu.utils.prototxt import Message, parse_file


def lr_schedule(sp: Message):
    """SolverParameter → optax schedule implementing Caffe's lr_policy."""
    base = float(sp.get_scalar("base_lr", 0.01))
    policy = str(sp.get_scalar("lr_policy", "fixed"))
    gamma = float(sp.get_scalar("gamma", 0.1))
    power = float(sp.get_scalar("power", 0.75))
    stepsize = int(sp.get_scalar("stepsize", 100000))
    max_iter = int(sp.get_scalar("max_iter", 10000))
    stepvalues = [int(v) for v in sp.getlist("stepvalue")]

    if policy == "fixed":
        return lambda it: jnp.full((), base)
    if policy == "step":
        return lambda it: base * gamma ** jnp.floor(it / stepsize)
    if policy == "exp":
        return lambda it: base * gamma ** it
    if policy == "inv":
        return lambda it: base * (1.0 + gamma * it) ** (-power)
    if policy == "multistep":
        bounds = jnp.asarray(stepvalues or [max_iter], jnp.int32)
        return lambda it: base * gamma ** jnp.sum(it >= bounds)
    if policy == "poly":
        return lambda it: base * (1.0 - jnp.minimum(it, max_iter)
                                  / max_iter) ** power
    if policy == "sigmoid":
        return lambda it: base / (1.0 + jnp.exp(-gamma * (it - stepsize)))
    raise ValueError(f"unknown lr_policy {policy!r}")


def make_optimizer(sp: Message):
    """SolverParameter → optax chain matching Caffe's solver types.

    Caffe applies weight_decay as L2 regularization added to gradients
    before the update — ``optax.add_decayed_weights`` does exactly that.
    """
    schedule = lr_schedule(sp)
    # distinguish "momentum: 0.0" (explicit, honored) from absent (defaults)
    momentum = sp.get_scalar("momentum", None)
    momentum = float(momentum) if momentum is not None else None
    decay = float(sp.get_scalar("weight_decay", 0.0))
    delta = float(sp.get_scalar("delta", 1e-8))
    kind = str(sp.get_scalar("type", "SGD"))

    if kind in ("SGD", "Nesterov"):
        opt = optax.sgd(schedule, momentum=momentum or None,
                        nesterov=kind == "Nesterov")
    elif kind == "Adam":
        opt = optax.adam(schedule,
                         b1=momentum if momentum is not None else 0.9,
                         b2=float(sp.get_scalar("momentum2", 0.999)),
                         eps=delta)
    elif kind == "AdaGrad":
        opt = optax.adagrad(schedule, eps=delta)
    elif kind == "RMSProp":
        opt = optax.rmsprop(schedule,
                            decay=float(sp.get_scalar("rms_decay", 0.99)),
                            eps=delta)
    elif kind == "AdaDelta":
        opt = optax.adadelta(schedule,
                             rho=momentum if momentum is not None else 0.95,
                             eps=delta)
    else:
        raise ValueError(f"unknown solver type {kind!r}")
    if decay:
        opt = optax.chain(optax.add_decayed_weights(decay), opt)
    if int(sp.get_scalar("iter_size", 1)) > 1:
        # Caffe's gradient accumulation across iter_size forward/backwards
        opt = optax.MultiSteps(opt, int(sp.get_scalar("iter_size")))
    return opt


class Solver:
    """``caffe train`` equivalent over the jitted step engine.

    train()/test() run against loaders of {'image', 'label'} batches from
    the framework's data pipeline (a data-layer prototxt names the dataset
    but IO goes through dtdl_tpu.data — the TPU-correct split of concerns).
    """

    def __init__(self, solver_path_or_msg, train_loader, test_loader=None,
                 strategy: Strategy | None = None, dtype=jnp.float32,
                 out: str | None = None, overrides: dict | None = None,
                 observer=None):
        from dtdl_tpu.obs.observer import NULL_OBSERVER
        self.observer = observer or NULL_OBSERVER
        sp = (parse_file(solver_path_or_msg)
              if isinstance(solver_path_or_msg, str) else solver_path_or_msg)
        # overrides must land BEFORE the optimizer is built: lr policies
        # like poly/multistep close over max_iter/stepvalue at construction
        if overrides:
            sp = Message({**sp, **overrides})
        self.param = sp
        self.strategy = strategy or SingleDevice()
        self.train_loader = train_loader
        self.test_loader = test_loader

        base = os.path.dirname(solver_path_or_msg) if isinstance(
            solver_path_or_msg, str) else "."

        def _resolve(p):
            return p if os.path.isabs(p) else os.path.join(base, p)

        net_path = sp.get_scalar("net") or sp.get_scalar("train_net")
        if net_path is None:
            raise ValueError("solver prototxt names no net/train_net")
        self.net = build_net(_resolve(net_path), dtype=dtype)
        # split-file layout: a separate test_net shares weights by layer
        # name (Caffe's weight-sharing rule); same-named layers must have
        # matching shapes or apply() raises.
        test_net_path = sp.get_scalar("test_net")
        self.test_net = (build_net(_resolve(test_net_path), dtype=dtype)
                         if test_net_path else self.net)

        seed = int(sp.get_scalar("random_seed", 0))
        sample = next(iter(train_loader))
        self.tx = make_optimizer(sp)
        self.state = self.strategy.replicate(init_state(
            self.net, jax.random.PRNGKey(seed),
            # audit: ok[host-sync-asarray] shape probe of one host sample at solver build time
            jnp.zeros((1,) + np.asarray(sample["image"]).shape[1:]),
            self.tx))
        self.train_step = make_train_step(self.strategy, seed=seed)
        self.eval_step = make_eval_step(self.strategy)

        # the full prefix is the snapshot namespace (caffe writes
        # <prefix>_iter_N; here <prefix>/snapshot_N) so two solvers with
        # different prefixes in one directory never clobber each other
        prefix = str(sp.get_scalar("snapshot_prefix", "./result/caffe_model"))
        self.out = out or prefix
        self.ckpt = Checkpointer(self.out)
        self.reporter = Reporter([StdoutSink()])
        self.iteration = 0

    @property
    def max_iter(self) -> int:
        return int(self.param.get_scalar("max_iter", 10000))

    def test(self) -> dict:
        """One test pass: test_iter batches (0 = full set), exact means.

        Delegates to dtdl_tpu.train.loop.evaluate, which pads ragged tail
        batches with masked rows so shard_map sharding stays divisible and
        every real example counts exactly once.
        """
        test_iter = int(self.param.get_scalar("test_iter", 0))
        loader = (LimitBatches(self.test_loader, test_iter) if test_iter
                  else self.test_loader)
        # evaluate through the test net (== train net unless test_net given)
        state = self.state.replace(apply_fn=self.test_net.apply)
        means = _evaluate(self.eval_step, state, loader, self.strategy)
        return {f"test_{k}": v for k, v in means.items()}

    def snapshot(self) -> str:
        path = self.ckpt.save(self.iteration, self.state)
        return path

    def restore(self, step: int | None = None) -> bool:
        state, it = self.ckpt.restore(self.state, step)
        if state is None:
            return False
        self.state, self.iteration = state, int(it)
        return True

    def solve(self) -> dict:
        """Run to max_iter with display/test/snapshot cadence.

        Caffe iteration semantics: one iteration = ``iter_size`` forward/
        backward passes followed by ONE parameter update (the optimizer is
        an optax.MultiSteps when iter_size > 1), so max_iter counts updates
        and consumes max_iter * iter_size batches.

        Resume is replay-exact: the batch stream is a deterministic function
        of the batch counter (pass index = batches // len(loader) keys the
        shuffle, offset = batches % len(loader) is skipped at the index
        level via ``resume_iter``), and snapshots land on update boundaries,
        so restore() + solve() replays the identical remaining stream an
        uninterrupted run would have seen — the same contract Trainer and
        Estimator resume have.
        """
        sp = self.param
        display = int(sp.get_scalar("display", 0))
        test_interval = int(sp.get_scalar("test_interval", 0))
        snap = int(sp.get_scalar("snapshot", 0))
        iter_size = int(sp.get_scalar("iter_size", 1))
        if (self.test_loader is not None and test_interval
                and bool(sp.get_scalar("test_initialization", True))):
            self.reporter.report({"iter": self.iteration, **self.test()})
        last: dict = {}
        metrics = None
        # async dispatch discipline (SCALING.md): per-update metrics stay
        # on device in a bounded queue; the ONE drain per display boundary
        # (or at the end) converts them, so the hot loop never blocks on
        # the iteration it just dispatched
        queue = MetricsQueue(max(display, 1) if display else 8)
        newest: dict = {}
        step_fn = self.observer.watch(self.train_step, "solver.train_step")
        import time as _time
        t_disp, iters_at_disp = _time.perf_counter(), self.iteration
        try:
            steps_per_pass = len(self.train_loader)
        except TypeError:
            # unsized (generator-style) loader: replay-exact resume isn't
            # possible — keep the legacy per-pass keying, resume restarts
            # the interrupted pass at its head
            steps_per_pass = None
        # snapshots only happen on iteration (= update) boundaries, so the
        # restored stream position is exactly iteration * iter_size batches
        batches = self.iteration * iter_size
        try:
            while self.iteration < self.max_iter:
                if steps_per_pass:
                    pass_idx, skip = divmod(batches, steps_per_pass)
                else:
                    pass_idx, skip = self.iteration, 0
                self.train_loader.set_epoch(pass_idx)
                it = prefetch_to_device(resume_iter(self.train_loader, skip),
                                        self.strategy.shard_batch, 2)
                for batch in it:
                    if self.iteration >= self.max_iter:
                        break
                    with self.observer.span("dispatch",
                                            iteration=self.iteration):
                        self.state, metrics = step_fn(self.state, batch)
                    batches += 1
                    if batches % iter_size:
                        continue  # mid-accumulation: not an iteration yet
                    self.iteration += 1
                    popped = queue.push(metrics)
                    if popped:
                        newest = popped[-1]
                    if display and self.iteration % display == 0:
                        with self.observer.span("drain"):
                            drained = queue.drain()  # the window's one sync
                        if drained:
                            newest = drained[-1]
                        last = newest
                        goodput = self.observer.window(
                            self.iteration - iters_at_disp,
                            _time.perf_counter() - t_disp)
                        t_disp = _time.perf_counter()
                        iters_at_disp = self.iteration
                        self.reporter.report({"iter": self.iteration, **last,
                                              **goodput})
                    if (test_interval and self.test_loader is not None
                            and self.iteration % test_interval == 0):
                        last = self.test()
                        self.reporter.report({"iter": self.iteration, **last})
                    if snap and self.iteration % snap == 0:
                        self.snapshot()
            if not last and metrics is not None:
                drained = queue.drain()
                last = drained[-1] if drained else newest
            if snap:
                self.snapshot()
        finally:
            # async saves durable before return — also on a mid-run crash,
            # so a restarted solver restores the newest snapshot
            self.ckpt.wait_until_finished()
        return last
