"""Trainer + extensions — the Chainer-style API flavor.

Capability parity with the reference's Chainer track (reference
chainer/train_mnist.py:80-125): a `Trainer` drives the compiled train step
until a stop trigger, firing `extensions` on (n, 'iteration'|'epoch')
triggers.  Provided extensions mirror the ones the reference uses:

* `Evaluator`       — full val-set metrics, allreduced (reference :86-88;
                      multi-node variant chainer/train_mnist_multi.py:101-104)
* `LogReport`       — JSON log of per-period means (reference :103)
* `PrintReport`     — column table on stdout (reference :107-115)
* `snapshot`        — full trainer snapshot, resumable (reference :91-93)
* `dump_graph`      — computation-graph dump; the JAX analogue writes the
                      jaxpr + optimized HLO of the train step (reference :89)

`--resume` restores params, optimizer state, BN stats, iteration/epoch and
RNG epoch for the sampler (reference chainer/train_mnist.py:120-122).
Extensions run on every process but output is leader-gated via the Reporter
(ChainerMN gates on rank 0, reference chainer/train_mnist_multi.py:106-114).
"""

from __future__ import annotations

import json
import os
import time

from dtdl_tpu.ckpt.checkpoint import Checkpointer
from dtdl_tpu.data.loader import prefetch_to_device, resume_iter
from dtdl_tpu.metrics.device import MetricsQueue
from dtdl_tpu.metrics.report import Accumulator, JsonlSink, Reporter, StdoutSink
from dtdl_tpu.obs.observer import NULL_OBSERVER
from dtdl_tpu.parallel.strategy import Strategy
from dtdl_tpu.resil.guard import GuardEscalationError, GuardRollback
from dtdl_tpu.runtime.bootstrap import is_leader
from dtdl_tpu.utils.timing import StepTimer


class Trigger:
    """Fires every n iterations or epochs."""

    def __init__(self, period: int, unit: str):
        if unit not in ("iteration", "epoch"):
            raise ValueError(f"trigger unit {unit!r}")
        self.period = period
        self.unit = unit

    @classmethod
    def of(cls, spec) -> "Trigger":
        if isinstance(spec, Trigger):
            return spec
        period, unit = spec
        return cls(period, unit)

    def should_fire(self, trainer: "Trainer", boundary: str) -> bool:
        if boundary != self.unit:
            return False
        count = trainer.iteration if self.unit == "iteration" else trainer.epoch
        return count > 0 and count % self.period == 0


class Extension:
    """Base extension; subclasses override __call__(trainer)."""

    default_trigger = (1, "epoch")
    priority = 100

    def __call__(self, trainer: "Trainer") -> None:
        raise NotImplementedError

    def serialize(self) -> dict:
        return {}

    def deserialize(self, data: dict) -> None:
        pass


class Trainer:
    """Drives (state, batch) -> (state, metrics) until the stop trigger."""

    def __init__(self, state, train_step, train_loader, strategy: Strategy,
                 stop_trigger=(20, "epoch"), out: str = "./result",
                 prefetch: int = 2, metrics_lag: int = 20, observer=None,
                 guard=None, preempt=None, exporter=None, watchdog=None):
        self.state = state
        self.train_step = train_step
        # obs facade (dtdl_tpu.obs): spans + recompile sentinel + goodput;
        # the default NULL_OBSERVER no-ops every hook
        self.observer = observer or NULL_OBSERVER
        # resil wiring: ``guard`` must be the instance folded into
        # train_step (make_train_step(..., guard=)) — the Trainer feeds it
        # every drained step and handles its rollback policy by restoring
        # the last good snapshot; ``preempt`` is a PreemptionWatcher whose
        # flag is polled at iteration boundaries — on SIGTERM the run
        # snapshots and returns with ``self.preempted`` set, and a fresh
        # Trainer's resume() continues exactly (mid-epoch included)
        self.guard = guard
        self.preempt = preempt
        self.preempted = False
        # continuous-export wiring (round 17): a MetricsExporter is
        # sampled at the drain boundary — the one boundary this loop
        # already owns — so training series/SLOs (default_train_slos
        # over GoodputMeter.export_window / StepGuard.window sources)
        # cost zero added syncs, exactly like the serve pipeline
        self.exporter = exporter
        # elastic step watchdog (round 17): a resil.elastic.StepWatchdog
        # bounds the drain's host↔device wait — a dead peer inside a
        # shard_map collective surfaces as a named PeerLostError at the
        # next drain instead of hanging this host forever
        self.watchdog = watchdog
        self.train_loader = train_loader
        self.strategy = strategy
        self.stop = Trigger.of(stop_trigger)
        self.out = out
        self.prefetch = prefetch

        self.iteration = 0
        self.epoch = 0
        self.iteration_in_epoch = 0
        self._skip_batches = 0  # fast-forward after a mid-epoch resume
        self.observation: dict[str, float] = {}
        self.accumulator = Accumulator()
        # async dispatch discipline (SCALING.md): metrics stay on device in
        # a bounded queue; they are drained — ONE host sync — right before
        # any extension actually fires, so back-to-back iterations never
        # block on the step they just dispatched
        self.metrics_queue = MetricsQueue(metrics_lag)
        self.timer = StepTimer(blocking=False)
        self.start_time = time.time()
        self._extensions: list[tuple[str, Extension, Trigger]] = []
        self.ckpt = Checkpointer(out)  # creates out/ (leader-gated)

    # -- extension management -------------------------------------------------

    def extend(self, extension: Extension, trigger=None,
               name: str | None = None) -> "Trainer":
        trig = Trigger.of(trigger or extension.default_trigger)
        name = name or type(extension).__name__
        self._extensions.append((name, extension, trig))
        self._extensions.sort(key=lambda e: -getattr(e[1], "priority", 100))
        return self

    def _fire(self, boundary: str) -> None:
        for _, ext, trig in self._extensions:
            if trig.should_fire(self, boundary):
                ext(self)

    def _will_fire(self, boundary: str) -> bool:
        return any(trig.should_fire(self, boundary)
                   for _, _, trig in self._extensions)

    def _drain_metrics(self) -> None:
        """Settle pending device metrics into observation/accumulator.

        The drained floats land in dispatch order, so the accumulator's
        per-period means and the final ``observation`` are bitwise what the
        old sync-every-iteration loop produced.
        """
        with self.observer.span("drain"):
            drained = (self.watchdog.run(self.metrics_queue.drain)
                       if self.watchdog is not None
                       else self.metrics_queue.drain())
        for vals in drained:
            if self.guard is not None:
                self.guard.observe(vals)
            self.observation = vals
            self.accumulator.add(vals)
        if drained:
            self.timer.sync()
            # settled window = exactly the drained steps; goodput fields
            # land in observation so LogReport/PrintReport can select them
            self.observation.update(self.observer.window(
                len(drained), self.timer.last_step_s * len(drained)))
        if self.exporter is not None:
            self.exporter.sample()

    # -- run loop -------------------------------------------------------------

    @property
    def _done(self) -> bool:
        count = self.iteration if self.stop.unit == "iteration" else self.epoch
        return count >= self.stop.period

    def run(self) -> None:
        try:
            self._run()
        finally:
            # snapshots save asynchronously; make them durable before the
            # process moves on (a fresh Trainer may resume immediately)
            self.ckpt.wait_until_finished()
            if self.exporter is not None:
                # the forced final point closes the window-delta
                # telescope even on an exception path
                self.exporter.sample(force=True)

    def _run(self) -> None:
        step_fn = self.observer.watch(self.train_step, "trainer.train_step")
        while not self._done:
            try:
                if self._run_epoch(step_fn):
                    return
            except GuardRollback:
                # the guard's rollback policy escalated: restore the last
                # good snapshot and continue from there (mid-epoch exact,
                # via the same resume path as a restart)
                self._rollback()

    def _rollback(self) -> None:
        # in-flight metrics belong to the abandoned timeline — settle and
        # discard them (the queued device work is harmless: the guard's
        # in-jit select already kept any bad update out of the state)
        self.metrics_queue.drain()
        self.accumulator.reset()
        self.observer.event("trainer_rollback", iteration=self.iteration)
        if not self.resume():
            raise GuardEscalationError(
                f"guard requested rollback-to-last-good but no snapshot "
                f"exists in {self.out} — add the snapshot extension (or "
                f"use policy='skip')")

    def _check_preempt(self) -> bool:
        """SIGTERM received: snapshot at this (consistent) boundary and
        stop; run()'s finally makes it durable + committed.  Resume in a
        fresh process continues exactly."""
        if self.preempt is None or not self.preempt.requested:
            return False
        self.observer.event("trainer_preempted", iteration=self.iteration)
        self.save_snapshot()
        self.preempted = True
        return True

    def _run_epoch(self, step_fn) -> bool:
        """One epoch (or the remainder of one after resume/rollback);
        True when the run should stop (done or preempted)."""
        self.train_loader.set_epoch(self.epoch)
        self.timer.reset_epoch()
        if self._skip_batches:
            # mid-epoch resume: the sampler's (seed, epoch) order and
            # the per-batch-keyed transform rng are deterministic, so
            # starting at the consumed prefix replays the exact
            # remainder of the interrupted epoch (Chainer resume parity
            # — its snapshot serializes the iterator position, reference
            # chainer/train_mnist.py:120-122).  O(1) via iter_from.
            skip = self._skip_batches
            self._skip_batches = 0
            raw = resume_iter(self.train_loader, skip)
        else:
            raw = iter(self.train_loader)
            self.iteration_in_epoch = 0
        it = prefetch_to_device(raw, self.strategy.shard_batch,
                                self.prefetch)
        for batch in it:
            with self.observer.span("dispatch", iteration=self.iteration):
                self.state, metrics = step_fn(self.state, batch)
            self.iteration += 1
            self.iteration_in_epoch += 1
            self.timer.step()
            for vals in self.metrics_queue.push(metrics):
                if self.guard is not None:
                    self.guard.observe(vals)
                self.observation = vals
                self.accumulator.add(vals)
            done = self._done and self.stop.unit == "iteration"
            if done or self._will_fire("iteration"):
                self._drain_metrics()
            self._fire("iteration")
            if done or self._check_preempt():
                return True
        self.epoch += 1
        self.iteration_in_epoch = 0
        self._drain_metrics()
        self._fire("epoch")
        return self._check_preempt()

    # -- snapshot / resume ----------------------------------------------------

    def save_snapshot(self) -> str:
        # the state snapshot is asynchronous (overlaps training); the meta
        # sidecar lives NEXT TO the snapshot dir (snapshot_N.meta.json), not
        # inside it — the dir keeps its orbax tmp name until the background
        # write finalizes
        path = self.ckpt.save(self.iteration, self.state)
        meta = {
            "iteration": self.iteration,
            "epoch": self.epoch,
            "iteration_in_epoch": self.iteration_in_epoch,
            "extensions": {name: ext.serialize()
                           for name, ext, _ in self._extensions},
        }
        if is_leader():
            with open(path + ".meta.json", "w") as f:
                json.dump(meta, f)
        return path

    def resume(self, path: str = "") -> bool:
        """Restore trainer state; empty path = latest snapshot in out/."""
        if path:
            state = self.ckpt.restore_path(self.state, path)
            meta_path = path.rstrip("/") + ".meta.json"
            legacy = os.path.join(path, "trainer_meta.json")
        else:
            state, step = self.ckpt.restore(self.state)
            if state is None:
                return False
            meta_path = os.path.join(self.out, f"snapshot_{step}.meta.json")
            legacy = os.path.join(self.out, f"snapshot_{step}",
                                  "trainer_meta.json")
        if not os.path.exists(meta_path) and os.path.exists(legacy):
            meta_path = legacy   # snapshots written before the sidecar move
        self.state = state
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
            self.iteration = meta["iteration"]
            self.epoch = meta["epoch"]
            self.iteration_in_epoch = meta.get("iteration_in_epoch", 0)
            self._skip_batches = self.iteration_in_epoch
            for name, ext, _ in self._extensions:
                if name in meta.get("extensions", {}):
                    ext.deserialize(meta["extensions"][name])
        return True

    @property
    def elapsed_time(self) -> float:
        return time.time() - self.start_time


# ---- extensions -------------------------------------------------------------

class Evaluator(Extension):
    """Full validation pass; reports val/<metric> (reference
    chainer/train_mnist.py:86-88).  Under a mesh strategy the metrics are
    already allreduced inside the eval step — the multi-node evaluator shape
    (reference chainer/train_mnist_multi.py:101-104)."""

    priority = 200  # run before reporting extensions

    def __init__(self, eval_step, val_loader, strategy: Strategy,
                 prefetch: int = 2):
        self.eval_step = eval_step
        self.val_loader = val_loader
        self.strategy = strategy
        self.prefetch = prefetch
        self.last: dict[str, float] = {}

    def __call__(self, trainer: Trainer) -> None:
        from dtdl_tpu.train.loop import evaluate as _evaluate
        means = _evaluate(self.eval_step, trainer.state, self.val_loader,
                          self.strategy, prefetch=self.prefetch)
        self.last = {f"val_{k}": v for k, v in means.items()}
        trainer.observation.update(self.last)


class LogReport(Extension):
    """Collect per-period means into a JSON log (reference
    chainer/train_mnist.py:103).  Keeps the records list in memory and
    appends to ``out/log.jsonl`` on the leader."""

    priority = 150

    def __init__(self):
        self.records: list[dict] = []
        self._sink: JsonlSink | None = None

    def __call__(self, trainer: Trainer) -> None:
        rec = {
            "epoch": trainer.epoch,
            "iteration": trainer.iteration,
            **trainer.accumulator.means(),
            **{k: v for k, v in trainer.observation.items()
               if k.startswith("val_")},
            "elapsed_time": round(trainer.elapsed_time, 3),
        }
        self.records.append(rec)
        if is_leader():
            if self._sink is None:
                self._sink = JsonlSink(os.path.join(trainer.out, "log.jsonl"))
            self._sink.write(rec)
        trainer.accumulator.reset()

    def serialize(self) -> dict:
        return {"records": self.records}

    def deserialize(self, data: dict) -> None:
        self.records = data.get("records", [])


class PrintReport(Extension):
    """Column table of selected entries (reference chainer/train_mnist.py:107-112)."""

    priority = 140

    def __init__(self, entries: list[str], log_report: LogReport):
        self.entries = entries
        self.log_report = log_report
        self._header_printed = False

    def __call__(self, trainer: Trainer) -> None:
        if not is_leader() or not self.log_report.records:
            return
        rec = self.log_report.records[-1]
        if not self._header_printed:
            print("  ".join(f"{e:>14}" for e in self.entries), flush=True)
            self._header_printed = True
        cells = []
        for e in self.entries:
            v = rec.get(e, "")
            cells.append(f"{v:14.5g}" if isinstance(v, float) else f"{v!s:>14}")
        print("  ".join(cells), flush=True)


class snapshot(Extension):  # noqa: N801 - chainer-style lowercase name
    """Full trainer snapshot at each trigger (reference chainer/train_mnist.py:91-93)."""

    def __call__(self, trainer: Trainer) -> None:
        trainer.save_snapshot()


class dump_graph(Extension):  # noqa: N801
    """Dump the train step's jaxpr + lowered HLO once (reference
    chainer/train_mnist.py:89 dumps the loss graph as graphviz).  The JAX
    equivalent of the computation graph is the jaxpr / StableHLO text."""

    default_trigger = (1, "epoch")

    def __init__(self, example_batch):
        self.example_batch = example_batch
        self._dumped = False

    def __call__(self, trainer: Trainer) -> None:
        if self._dumped or not is_leader():
            return
        self._dumped = True
        try:
            lowered = trainer.train_step.lower(
                trainer.state, trainer.strategy.shard_batch(self.example_batch))
            with open(os.path.join(trainer.out, "train_step.hlo.txt"), "w") as f:
                f.write(lowered.as_text())
        except Exception as e:  # graph dump must never kill training
            import logging
            logging.getLogger("dtdl_tpu").warning("dump_graph failed: %s", e)


class ProgressSummary(Extension):
    """Per-epoch one-liner with epoch time — the torch loops' epoch print
    (reference pytorch/distributed_data_parallel.py:150-152)."""

    priority = 130

    def __init__(self, reporter: Reporter | None = None):
        self.reporter = reporter or Reporter([StdoutSink()])

    def __call__(self, trainer: Trainer) -> None:
        self.reporter.report({
            "epoch": trainer.epoch,
            **trainer.observation,
            "epoch_time": trainer.timer.epoch_elapsed_s,
            "avg_batch_time": trainer.timer.avg_step_s,
        })
