"""compile()/fit() — the Keras-style API flavor.

Capability parity with the TF2 track (reference
tensorflow2/mnist_single.py:65-92): build+compile a model (under a strategy —
the reference does it inside ``strategy.scope()``,
mnist_mirror_strategy.py:68-73), ``fit(x, y, batch_size, epochs,
validation_data, callbacks)`` with a History, per-epoch `ModelCheckpoint`,
`TensorBoard` callback, and restore-latest + evaluate (reference
mnist_single.py:88-92).  In JAX the "scope" is the strategy object itself —
pass it at construction; parameters are created replicated/sharded per the
strategy, no context manager needed.
"""

from __future__ import annotations

import os
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dtdl_tpu.ckpt.checkpoint import Checkpointer, load_weights, save_weights
from dtdl_tpu.data.loader import DataLoader, prefetch_to_device
from dtdl_tpu.metrics.device import MetricsQueue
from dtdl_tpu.metrics.report import Accumulator, Reporter, StdoutSink, TensorBoardSink
from dtdl_tpu.parallel.strategy import SingleDevice, Strategy
from dtdl_tpu.train.state import init_state
from dtdl_tpu.train.step import make_eval_step, make_predict_step, make_train_step


class Callback:
    def set_model(self, model: "Model") -> None:
        self.model = model

    def on_train_begin(self) -> None: ...
    def on_epoch_begin(self, epoch: int) -> None: ...
    def on_epoch_end(self, epoch: int, logs: dict) -> None: ...
    def on_train_end(self) -> None: ...


class History(Callback):
    def on_train_begin(self) -> None:
        self.history: dict[str, list] = {}

    def on_epoch_end(self, epoch: int, logs: dict) -> None:
        for k, v in logs.items():
            self.history.setdefault(k, []).append(v)


class ModelCheckpoint(Callback):
    """Per-epoch checkpoints (reference tensorflow2/mnist_single.py:66-76
    saves ``ckpt_{epoch}`` weights every epoch).

    ``save_weights_only=False`` snapshots the full TrainState (optimizer
    slots, BN stats, step) instead of just the params.
    """

    def __init__(self, directory: str, save_weights_only: bool = True,
                 keep: int | None = None):
        self.ckpt = Checkpointer(directory, keep=keep)
        self.save_weights_only = save_weights_only

    def on_epoch_end(self, epoch: int, logs: dict) -> None:
        if self.save_weights_only:
            self.ckpt.save_weights_epoch(epoch, self.model.state.params)
        else:
            self.ckpt.save(epoch, self.model.state)

    def on_train_end(self) -> None:
        # full-state saves are async; block so restore-latest-then-evaluate
        # (reference tensorflow2/mnist_single.py:88-92) sees the snapshot
        self.ckpt.wait_until_finished()


class TensorBoard(Callback):
    """TensorBoard events when available (reference mnist_single.py:72-73)."""

    def __init__(self, log_dir: str):
        self.sink = TensorBoardSink(log_dir)

    def on_epoch_end(self, epoch: int, logs: dict) -> None:
        self.sink.write({"step": epoch, "split": "epoch", **logs})

    def on_train_end(self) -> None:
        self.sink.close()


class PrintLR(Callback):
    """Parity with the reference's (unused) PrintLR callback
    (tensorflow2/mnist_single.py:50-56)."""

    def __init__(self, schedule_or_value):
        self.lr = schedule_or_value

    def on_epoch_end(self, epoch: int, logs: dict) -> None:
        lr = self.lr(self.model.state.step) if callable(self.lr) else self.lr
        print(f"\nLearning rate for epoch {epoch + 1} is {float(lr)}",
              flush=True)


_OPTIMIZERS = {
    "adam": lambda: optax.adam(1e-3),
    "sgd": lambda: optax.sgd(1e-2),
    "rmsprop": lambda: optax.rmsprop(1e-3),
}


class Model:
    """Keras-flavored wrapper around a flax module + strategy."""

    def __init__(self, module, strategy: Strategy | None = None):
        self.module = module
        self.strategy = strategy or SingleDevice()
        self.state = None
        self._train_step = None
        self._eval_step = None
        self._predict_step = None

    def compile(self, optimizer="adam", loss: str | None = None,
                metrics: Sequence[str] = ("accuracy",), seed: int = 0,
                example_input=None) -> "Model":
        """Build params (replicated per strategy) and the compiled steps.

        ``loss`` accepts 'sparse_categorical_crossentropy' (the reference's
        choice, tensorflow2/mnist_single.py:86-87) or None for the same.
        """
        if loss not in (None, "sparse_categorical_crossentropy"):
            raise ValueError(f"unsupported loss {loss!r}")
        if isinstance(optimizer, str):
            tx = _OPTIMIZERS[optimizer.lower()]()
        else:
            tx = optimizer
        self._tx = tx
        self._seed = seed
        self._example_input = example_input
        self._train_step = make_train_step(self.strategy)
        self._eval_step = make_eval_step(self.strategy)
        self._predict_step = make_predict_step(self.strategy,
                                               probabilities=True)
        return self

    def _ensure_state(self, x) -> None:
        if self.state is not None:
            return
        example = self._example_input
        if example is None:
            example = jnp.zeros((1,) + tuple(x.shape[1:]), jnp.float32)
        self.state = self.strategy.replicate(init_state(
            self.module, jax.random.PRNGKey(self._seed), example, self._tx))

    def _loader(self, x, y, batch_size: int, shuffle: bool, seed: int,
                drop_last: bool = True) -> DataLoader:
        """Per-host loader: under multi-process each host reads only its
        stripe of the global permutation and feeds ``batch_size/num_hosts``
        rows — the strategy assembles the global batch.  Without this every
        host would feed identical rows and the global batch would duplicate
        each example process_count times."""
        nproc = jax.process_count()
        if batch_size % max(nproc, 1):
            raise ValueError(
                f"batch_size {batch_size} not divisible by {nproc} processes")
        from dtdl_tpu.data.sharding import ShardedSampler
        sampler = ShardedSampler(len(y), nproc, jax.process_index(),
                                 shuffle=shuffle, seed=seed)
        return DataLoader({"image": x, "label": y}, batch_size // nproc,
                          sampler=sampler, drop_last=drop_last)

    def fit(self, x, y, batch_size: int = 32, epochs: int = 1,
            validation_data=None, callbacks: Sequence[Callback] = (),
            shuffle: bool = True, seed: int = 0, verbose: int = 1,
            observer=None) -> History:
        from dtdl_tpu.obs.observer import NULL_OBSERVER
        import time as _time
        obs = observer or NULL_OBSERVER
        # audit: ok[host-sync-asarray] fit() entry: caller-supplied host arrays
        x = np.asarray(x)
        # audit: ok[host-sync-asarray] fit() entry: caller-supplied host arrays
        y = np.asarray(y)
        self._ensure_state(x)
        history = History()
        cbs = [history, *callbacks]
        for cb in cbs:
            cb.set_model(self)
            cb.on_train_begin()
        reporter = Reporter([StdoutSink()]) if verbose else None
        loader = self._loader(x, y, batch_size, shuffle, seed)
        step_fn = obs.watch(self._train_step, "fit.train_step")
        try:
            for epoch in range(epochs):
                for cb in cbs:
                    cb.on_epoch_begin(epoch)
                loader.set_epoch(epoch)
                acc = Accumulator()
                # async dispatch discipline (SCALING.md): steps dispatch
                # back-to-back; the bounded queue converts metrics `lag`
                # steps behind the dispatch front and the epoch boundary
                # drains the rest — same floats, same order, no per-step
                # host↔device stall
                queue = MetricsQueue()
                it = prefetch_to_device(iter(loader),
                                        self.strategy.shard_batch)
                n_steps, t0 = 0, _time.perf_counter()
                for batch in it:
                    with obs.span("dispatch", epoch=epoch):
                        self.state, metrics = step_fn(self.state, batch)
                    n_steps += 1
                    for vals in queue.push(metrics):
                        acc.add(vals)
                with obs.span("drain", epoch=epoch):
                    for vals in queue.drain():
                        acc.add(vals)
                logs = acc.means()
                # the drain settled every dispatched step: the epoch's
                # train section is an honest goodput window
                logs.update(obs.window(n_steps,
                                       _time.perf_counter() - t0))
                if validation_data is not None:
                    vx, vy = validation_data
                    val = self.evaluate(vx, vy, batch_size=batch_size,
                                        verbose=0)
                    logs.update({f"val_{k}": v for k, v in val.items()})
                if reporter is not None:
                    reporter.report({"epoch": epoch, **logs})
                for cb in cbs:
                    cb.on_epoch_end(epoch, logs)
        finally:
            # on_train_end also flushes pending async checkpoints — run it
            # on a mid-train crash too, so restarts see the newest snapshot
            for cb in cbs:
                cb.on_train_end()
        return history

    def evaluate(self, x, y, batch_size: int = 32, verbose: int = 1) -> dict:
        """Exact full-dataset metrics (ragged tails masked, never dropped)."""
        from dtdl_tpu.train.loop import evaluate as _evaluate
        # audit: ok[host-sync-asarray] evaluate() entry: caller-supplied host arrays
        x = np.asarray(x)
        # audit: ok[host-sync-asarray] evaluate() entry: caller-supplied host arrays
        y = np.asarray(y)
        self._ensure_state(x)
        loader = self._loader(x, y, batch_size, shuffle=False, seed=0,
                              drop_last=False)
        means = _evaluate(self._eval_step, self.state, loader, self.strategy)
        if verbose:
            print(" - ".join(f"{k}: {v:.4f}" for k, v in means.items()),
                  flush=True)
        return means

    def predict(self, x, batch_size: int = 32) -> np.ndarray:
        """Class probabilities (the reference model ends in softmax).

        Multi-process: each host computes its stripe; results are
        all-gathered so every host returns the full, ordered output.
        """
        # audit: ok[host-sync-asarray] predict() entry: caller-supplied host arrays
        x = np.asarray(x)
        self._ensure_state(x)
        n = len(x)
        nproc = jax.process_count()
        if nproc > 1:
            # contiguous equal stripes (padded at the end), gathered below
            stripe = -(-n // nproc)
            lo = jax.process_index() * stripe
            local = x[lo:lo + stripe]
            if len(local) < stripe:  # tail host pads
                pad_rows = np.repeat(x[-1:], stripe - len(local), axis=0)
                local = np.concatenate([local, pad_rows]) if len(local) \
                    else pad_rows
        else:
            local = x
        outs = []
        per_host_bs = max(batch_size // max(nproc, 1), 1)
        for start in range(0, len(local), per_host_bs):
            xb = local[start:start + per_host_bs]
            pad = 0
            if len(xb) < per_host_bs:
                pad = per_host_bs - len(xb)
                xb = np.concatenate([xb, xb[-1:].repeat(pad, axis=0)])
            batch = self.strategy.shard_batch(
                {"image": jnp.asarray(xb),
                 "label": jnp.zeros((len(xb),), jnp.int32)})
            probs = self._predict_step(self.state, batch)
            if nproc > 1:
                probs = np.concatenate(
                    # audit: ok[host-sync-asarray] multi-host predict gathers its stripe to host by contract
                    [np.asarray(s.data) for s in sorted(
                        probs.addressable_shards,
                        key=lambda s: s.index[0].start
                        if s.index and s.index[0].start is not None
                        else 0)])
            else:
                # audit: ok[host-sync-asarray] predict() returns host arrays by contract — the output drain
                probs = np.asarray(probs)
            outs.append(probs[:per_host_bs - pad] if pad else probs)
        local_out = np.concatenate(outs)
        if nproc > 1:
            from jax.experimental import multihost_utils
            gathered = multihost_utils.process_allgather(local_out)
            return gathered.reshape(-1, gathered.shape[-1])[:n]
        return local_out[:n]

    # -- weights io -----------------------------------------------------------

    def save_weights(self, path: str) -> None:
        save_weights(path, self.state.params)

    def load_weights(self, path: str) -> None:
        if self.state is None:
            raise ValueError("call fit/evaluate once (or compile with "
                             "example_input) before load_weights")
        # audit: ok[host-sync-get] weights IO — checkpoint restore is a cold path
        params = load_weights(path, jax.device_get(self.state.params))
        self.state = self.state.replace(
            params=self.strategy.replicate(params))

    def load_latest(self, directory: str) -> bool:
        """Restore-latest-then-evaluate flow (reference mnist_single.py:88-92)."""
        ckpt = Checkpointer(directory)
        if self.state is None:
            raise ValueError("state not initialized yet")
        # audit: ok[host-sync-get] weights IO — checkpoint restore is a cold path
        params, epoch = ckpt.latest_weights(jax.device_get(self.state.params))
        if params is None:
            return False
        self.state = self.state.replace(
            params=self.strategy.replicate(params))
        return True
