from dtdl_tpu.train.state import TrainState, init_state  # noqa: F401
from dtdl_tpu.train.step import (  # noqa: F401
    make_train_step, make_eval_step, make_predict_step,
)
