from dtdl_tpu.train.state import TrainState, init_state  # noqa: F401
from dtdl_tpu.train.step import (  # noqa: F401
    make_train_step, make_eval_step, make_predict_step, make_lm_train_step,
)
from dtdl_tpu.train.loop import train_epoch, evaluate  # noqa: F401
from dtdl_tpu.train.trainer import (  # noqa: F401
    Trainer, Trigger, Extension, Evaluator, LogReport, PrintReport,
    ProgressSummary, snapshot, dump_graph,
)
from dtdl_tpu.train.fit import (  # noqa: F401
    Model, Callback, History, ModelCheckpoint, TensorBoard, PrintLR,
)
from dtdl_tpu.train.solver import Solver  # noqa: F401
from dtdl_tpu.train.estimator import (  # noqa: F401
    Estimator, EstimatorSpec, EvalSpec, ModeKeys, RunConfig, TrainSpec,
    train_and_evaluate,
)
