"""Imperative training loop — the torch-style API flavor.

The reference's hand-written epoch/step loops (reference
pytorch/single_gpu.py:88-120 and pytorch/distributed_data_parallel.py:118-152:
forward, loss, backward, step, log every 20 batches with loss / running acc /
batch time).  Here the per-step math lives in the compiled step function;
this module is the thin host loop around it: feed sharded batches (with
prefetch), tick the timer honestly (blocking on a metric), and report.

Users who want full control write this loop themselves — these helpers are
the canonical version the examples share.
"""

from __future__ import annotations

from dtdl_tpu.data.loader import prefetch_to_device
from dtdl_tpu.metrics.report import Accumulator, Reporter
from dtdl_tpu.parallel.strategy import Strategy
from dtdl_tpu.utils.timing import StepTimer


def train_epoch(train_step, state, loader, strategy: Strategy,
                reporter: Reporter | None = None, epoch: int = 0,
                log_interval: int = 20, timer: StepTimer | None = None,
                prefetch: int = 2, profile_dir: str | None = None):
    """Run one epoch; returns (state, epoch_mean_metrics).

    ``profile_dir`` captures a jax.profiler (XLA op-level) trace of the
    epoch — the device-side observability the reference lacked (SURVEY §5.1).
    """
    from dtdl_tpu.utils.profiling import maybe_trace, step_annotation
    timer = timer or StepTimer()
    timer.reset_epoch()
    acc = Accumulator()
    loader.set_epoch(epoch)
    steps_per_epoch = len(loader)
    it = prefetch_to_device(iter(loader), strategy.shard_batch, prefetch)
    with maybe_trace(profile_dir):
        for i, batch in enumerate(it):
            with step_annotation(i):
                state, metrics = train_step(state, batch)
            timer.step(metrics["loss"])
            acc.add({k: float(v) for k, v in metrics.items()})
            if reporter is not None and (i % log_interval) == 0:
                reporter.report({
                    "epoch": epoch, "step": i,
                    "steps_per_epoch": steps_per_epoch,
                    **{k: float(v) for k, v in metrics.items()},
                    "batch_time": timer.last_step_s,
                })
    if reporter is not None:
        reporter.report({
            "epoch": epoch, "split": "train_epoch",
            **acc.means(),
            "epoch_time": timer.epoch_elapsed_s,
            "avg_batch_time": timer.avg_step_s,
        })
    return state, acc.means()


def _pad_and_mask(batch, target: int):
    """Pad a ragged tail batch to ``target`` rows, masking the padding.

    Keeps batch shapes static (one compiled eval program) and keeps metrics
    exact: the eval step ignores mask=0 rows.
    """
    import numpy as np
    n = len(next(iter(batch.values())))
    mask = np.ones(n, np.float32)
    if n == target:
        return {**batch, "mask": mask}
    pad = target - n
    out = {k: np.concatenate([v, np.repeat(v[-1:], pad, axis=0)])
           for k, v in batch.items()}
    out["mask"] = np.concatenate([mask, np.zeros(pad, np.float32)])
    return out


def evaluate(eval_step, state, loader, strategy: Strategy,
             reporter: Reporter | None = None, epoch: int = 0,
             prefetch: int = 2):
    """Full-dataset evaluation; returns exact global mean metrics.

    Handles ragged tail batches (DataLoader(drop_last=False)) by padding to
    the loader's batch size with masked rows — every real example counts
    exactly once, unlike the reference's silently-dropped or double-counted
    tails.
    """
    target = loader.batch_size
    it = prefetch_to_device(
        (_pad_and_mask(b, target) for b in iter(loader)),
        strategy.shard_batch, prefetch)
    sums = {"loss_sum": 0.0, "correct_sum": 0.0, "count": 0.0}
    for batch in it:
        metrics = eval_step(state, batch)
        for k in sums:
            sums[k] += float(metrics[k])
    if sums["count"] == 0:
        return {"loss": float("nan"), "accuracy": float("nan")}
    means = {"loss": sums["loss_sum"] / sums["count"],
             "accuracy": sums["correct_sum"] / sums["count"]}
    if reporter is not None:
        reporter.report({"epoch": epoch, "split": "val",
                         **{f"val_{k}": v for k, v in means.items()}})
    return means
