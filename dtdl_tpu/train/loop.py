"""Imperative training loop — the torch-style API flavor.

The reference's hand-written epoch/step loops (reference
pytorch/single_gpu.py:88-120 and pytorch/distributed_data_parallel.py:118-152:
forward, loss, backward, step, log every 20 batches with loss / running acc /
batch time).  Here the per-step math lives in the compiled step function;
this module is the thin host loop around it: feed sharded batches (with
prefetch), dispatch back-to-back, and report.

**Async dispatch discipline** (SCALING.md): the loop never reads a metric on
the step it just dispatched.  Device metric pytrees go into a bounded
:class:`~dtdl_tpu.metrics.device.MetricsQueue`; conversion to Python floats
happens only at log/epoch boundaries (or by the queue's bounded
backpressure), so between boundaries the host's only job is enqueueing the
next step.  Pass ``sync_every_step=True`` to get the legacy blocking loop —
the values are bitwise identical either way; only *when* the host blocks
changes.

``unroll=k`` goes further: k prefetched batches are stacked and executed as
ONE ``lax.scan``-of-k-steps XLA program (state donated, metrics stacked and
drained once), cutting per-step dispatch overhead by k.

Users who want full control write this loop themselves — these helpers are
the canonical version the examples share.
"""

from __future__ import annotations

import itertools
from functools import partial

from dtdl_tpu.data.loader import prefetch_to_device
from dtdl_tpu.metrics.device import MetricsQueue
from dtdl_tpu.metrics.report import Accumulator, Reporter
from dtdl_tpu.obs.observer import NULL_OBSERVER, Observer
from dtdl_tpu.parallel.strategy import Strategy
from dtdl_tpu.utils.timing import StepTimer


# bundled-wrapper cache: a fresh jax.jit object per train_epoch call would
# recompile the scan program every epoch.  A small LRU (not a weak map: the
# wrapper's closure refs the step fn, so weak keys could never collect)
# keyed by (id(step), k), holding the step object so an id is never reused
# while its entry lives; the bound caps pinned executables when a process
# churns through many distinct step functions.
from collections import OrderedDict

_BUNDLED_CACHE: OrderedDict = OrderedDict()
_BUNDLED_CACHE_SIZE = 8


def unroll_steps(train_step, k: int):
    """Bundle ``train_step`` into one XLA program running ``k`` steps.

    Returns ``bundled(state, batches) -> (state, stacked_metrics)`` where
    ``batches`` is a tuple of (up to) ``k`` already-sharded batch pytrees.
    The batches are stacked inside the jit and scanned over, so one dispatch
    covers the whole bundle; ``state`` is donated — its buffers are reused
    across the scan instead of round-tripping through the host between
    steps.  A ragged tail bundle (fewer than ``k`` batches) recompiles once
    for its length.  Wrappers are cached per (train_step, k), so repeated
    epochs reuse the executable.

    Numerics: the scan body is the same traced step, so the math is
    identical — for f32 models the results are bitwise equal to the
    step-at-a-time loop (pinned by test).  XLA may *fuse* the body
    differently inside the scan, so reduced-precision (bf16) models can
    differ in last-bit rounding.  When to use: unroll pays when per-step
    DISPATCH dominates (sub-ms device steps); for compute-bound steps it
    buys nothing and the stacked-batch copies can even cost a little.
    """
    import jax
    import jax.numpy as jnp

    key = (id(train_step), k)
    hit = _BUNDLED_CACHE.get(key)
    if hit is not None and hit[0] is train_step:
        _BUNDLED_CACHE.move_to_end(key)
        return hit[1]

    @partial(jax.jit, donate_argnums=(0,))
    def bundled(state, batches):
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *batches)
        return jax.lax.scan(lambda s, b: train_step(s, b), state, stacked)

    _BUNDLED_CACHE[key] = (train_step, bundled)
    _BUNDLED_CACHE.move_to_end(key)
    while len(_BUNDLED_CACHE) > _BUNDLED_CACHE_SIZE:
        _BUNDLED_CACHE.popitem(last=False)
    return bundled


def bundle_batches(it, k: int):
    """Group an iterator into tuples of ``k`` items (ragged final tuple)."""
    while True:
        bundle = tuple(itertools.islice(it, k))
        if not bundle:
            return
        yield bundle


def train_epoch(train_step, state, loader, strategy: Strategy,
                reporter: Reporter | None = None, epoch: int = 0,
                log_interval: int = 20, timer: StepTimer | None = None,
                prefetch: int = 2, profile_dir: str | None = None,
                sync_every_step: bool = False, lag: int | None = None,
                unroll: int = 1, observer: Observer | None = None,
                guard=None):
    """Run one epoch; returns (state, epoch_mean_metrics).

    Async by default: metrics are drained (one host↔device sync) once per
    ``log_interval`` and at the epoch end; ``lag`` bounds the in-flight
    queue between boundaries (default: ``log_interval``, so backpressure
    never converts mid-window).  ``sync_every_step=True`` restores the
    legacy per-step blocking loop (exact per-step batch_time, one stall per
    step).  ``unroll=k`` dispatches k-step ``lax.scan`` bundles.

    ``profile_dir`` captures a jax.profiler (XLA op-level) trace of the
    epoch — the device-side observability the reference lacked (SURVEY §5.1).

    ``observer`` (dtdl_tpu.obs) adds host-phase spans (data/dispatch/
    drain), a recompile sentinel on the step fn, and per-window goodput
    fields merged into the boundary reports — all host-side, so the
    one-sync-per-window contract is unchanged (pinned by
    tests/test_obs.py's sync-counting test).

    ``guard`` (a :class:`dtdl_tpu.resil.StepGuard`) must be the SAME
    instance folded into ``train_step`` via ``make_train_step(...,
    guard=)``: the step suppresses bad updates on device; this loop
    feeds every drained per-step dict to ``guard.observe`` so the host
    policy (skip-count / raise / escalate) runs at the boundaries it
    already syncs at — the guard adds no syncs of its own.  The
    ``rollback`` policy needs a checkpointer and is therefore a Trainer
    feature; from this loop its GuardRollback propagates to the caller.
    """
    from dtdl_tpu.utils.profiling import maybe_trace, step_annotation
    if unroll < 1:
        raise ValueError(f"unroll must be >= 1, got {unroll}")
    if sync_every_step and unroll > 1:
        raise ValueError("unroll > 1 dispatches one program per bundle; "
                         "sync_every_step has no per-step value to block on")
    obs = observer or NULL_OBSERVER
    timer = timer or StepTimer(blocking=sync_every_step)
    timer.reset_epoch()
    acc = Accumulator()
    loader.set_epoch(epoch)
    steps_per_epoch = len(loader)
    # a k-step bundle consumes k batches at one dispatch: the prefetch
    # window must cover it or the bundle assembly itself becomes the stall
    prefetch = max(prefetch, unroll)
    it = prefetch_to_device(iter(loader), strategy.shard_batch, prefetch)

    if sync_every_step:
        step_fn = obs.watch(train_step, "train_step")
        with maybe_trace(profile_dir):
            for i, batch in enumerate(it):
                with step_annotation(i), obs.span("dispatch", step=i):
                    state, metrics = step_fn(state, batch)
                timer.step(metrics["loss"])
                # blocking mode: every step is its own settled window
                goodput = obs.window(1, timer.last_step_s)
                vals = {k: float(v) for k, v in metrics.items()}
                if guard is not None:
                    guard.observe(vals)
                acc.add(vals)
                if reporter is not None and (i % log_interval) == 0:
                    reporter.report({
                        "epoch": epoch, "step": i,
                        "steps_per_epoch": steps_per_epoch,
                        **{k: float(v) for k, v in metrics.items()},
                        "batch_time": timer.last_step_s,
                        **goodput,
                    })
        if reporter is not None:
            reporter.report({
                "epoch": epoch, "split": "train_epoch",
                **acc.means(),
                "epoch_time": timer.epoch_elapsed_s,
                "avg_batch_time": timer.avg_step_s,
            })
        return state, acc.means()

    queue = MetricsQueue(lag if lag is not None else max(log_interval, 1))
    if unroll > 1:
        # wrap AFTER the bundled-wrapper cache (its key is the original
        # step fn's id); expected=2 budgets the ragged tail's one
        # legitimate recompile
        step_fn = obs.watch(unroll_steps(train_step, unroll),
                            "train_step_bundle", expected=2)
        it = bundle_batches(it, unroll)
    else:
        step_fn = obs.watch(train_step, "train_step")
    latest: dict | None = None
    next_log = 0
    step0 = 0
    window_start = 0          # first step of the current obs/goodput window
    it = iter(it)
    _END = object()
    with maybe_trace(profile_dir):
        while True:
            with obs.span("data"):
                batch = next(it, _END)
            if batch is _END:
                break
            with step_annotation(step0), obs.span("dispatch", step=step0):
                state, metrics = step_fn(state, batch)
                n = len(batch) if unroll > 1 else 1
            for _ in range(n):
                timer.step()
            popped = queue.push(metrics, count=n)
            for vals in popped:
                if guard is not None:
                    guard.observe(vals)
                acc.add(vals)
            if popped:
                latest = popped[-1]
            if reporter is not None and step0 >= next_log:
                # boundary: ONE drain converts the whole window (blocks on
                # the just-dispatched step) — the only sync in the window
                with obs.span("drain", steps=step0 + n - window_start):
                    drained = queue.drain()
                for vals in drained:
                    if guard is not None:
                        guard.observe(vals)
                    acc.add(vals)
                if drained:
                    latest = drained[-1]
                timer.sync()
                w = step0 + n - window_start
                window_start = step0 + n
                reporter.report({
                    "epoch": epoch, "step": step0 + n - 1,
                    "steps_per_epoch": steps_per_epoch,
                    **(latest or {}),
                    "batch_time": timer.last_step_s,
                    # settled-window goodput (host floats only — the drain
                    # above was the window's one sync)
                    **obs.window(w, timer.last_step_s * w),
                })
                next_log = (step0 // log_interval + 1) * log_interval
            step0 += n
    with obs.span("drain", steps=step0 - window_start):
        for vals in queue.drain():
            if guard is not None:
                guard.observe(vals)
            acc.add(vals)
    timer.sync()
    if step0 > window_start:
        obs.window(step0 - window_start, timer.last_step_s
                   * (step0 - window_start))
    if reporter is not None:
        reporter.report({
            "epoch": epoch, "split": "train_epoch",
            **acc.means(),
            "epoch_time": timer.epoch_elapsed_s,
            "avg_batch_time": timer.avg_step_s,
        })
    return state, acc.means()


def _pad_and_mask(batch, target: int):
    """Pad a ragged tail batch to ``target`` rows, masking the padding.

    Keeps batch shapes static (one compiled eval program) and keeps metrics
    exact: the eval step ignores mask=0 rows.
    """
    import numpy as np
    n = len(next(iter(batch.values())))
    mask = np.ones(n, np.float32)
    if n == target:
        return {**batch, "mask": mask}
    pad = target - n
    out = {k: np.concatenate([v, np.repeat(v[-1:], pad, axis=0)])
           for k, v in batch.items()}
    out["mask"] = np.concatenate([mask, np.zeros(pad, np.float32)])
    return out


def evaluate(eval_step, state, loader, strategy: Strategy,
             reporter: Reporter | None = None, epoch: int = 0,
             prefetch: int = 2, lag: int = 8,
             observer: Observer | None = None):
    """Full-dataset evaluation; returns exact global mean metrics.

    Handles ragged tail batches (DataLoader(drop_last=False)) by padding to
    the loader's batch size with masked rows — every real example counts
    exactly once, unlike the reference's silently-dropped or double-counted
    tails.  Batches dispatch back-to-back; per-batch sums convert on the
    queue's bounded backpressure (``lag`` batches behind the dispatch
    front) and at the final drain, summing in batch order — identical to
    the synchronous loop's totals.
    """
    target = loader.batch_size
    it = prefetch_to_device(
        (_pad_and_mask(b, target) for b in iter(loader)),
        strategy.shard_batch, prefetch)
    queue = MetricsQueue(lag)
    sums = {"loss_sum": 0.0, "correct_sum": 0.0, "count": 0.0}

    def absorb(entries):
        for vals in entries:
            for k in sums:
                sums[k] += vals[k]

    obs = observer or NULL_OBSERVER
    eval_fn = obs.watch(eval_step, "eval_step")
    for batch in it:
        with obs.span("dispatch", phase="eval"):
            metrics = eval_fn(state, batch)
        absorb(queue.push(metrics))
    with obs.span("drain", phase="eval"):
        absorb(queue.drain())
    if sums["count"] == 0:
        return {"loss": float("nan"), "accuracy": float("nan")}
    means = {"loss": sums["loss_sum"] / sums["count"],
             "accuracy": sums["correct_sum"] / sums["count"]}
    if reporter is not None:
        reporter.report({"epoch": epoch, "split": "val",
                         **{f"val_{k}": v for k, v in means.items()}})
    return means
