"""The jitted train-step engine.

One factory builds the compiled SPMD step for any (model, optimizer, strategy)
triple.  The step is the hot loop the reference hand-writes per script
(reference pytorch/distributed_data_parallel.py:118-152): forward, loss,
backward, gradient sync, optimizer update, metrics — except here the whole
thing is a single traced function: XLA fuses the elementwise work into the
matmuls and overlaps the gradient AllReduce with the remaining backward
computation, the way DDP's bucketed NCCL hooks do.

The strategy object injects the parallelism semantics (see
dtdl_tpu/parallel/strategy.py): `grad_sync` is `lax.pmean` under
`DataParallel`, identity under `SingleDevice`, and implicit-compiler-inserted
under `AutoSharded`.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from dtdl_tpu.ops import accuracy, softmax_cross_entropy
from dtdl_tpu.parallel.strategy import Strategy, SingleDevice
from dtdl_tpu.train.state import TrainState


def _forward(state: TrainState, params, batch, train: bool, rngs=None):
    """Run the model, handling BatchNorm mutability uniformly."""
    x = batch["image"]
    if state.batch_stats is not None:
        variables = {"params": params, "batch_stats": state.batch_stats}
        if train:
            logits, updates = state.apply_fn(
                variables, x, train=True, mutable=["batch_stats"],
                rngs=rngs)
            return logits, updates["batch_stats"]
        return state.apply_fn(variables, x, train=False), None
    logits = state.apply_fn({"params": params}, x, train=train, rngs=rngs)
    return logits, None


def _dropout_rngs(state: TrainState, strategy: Strategy, seed: int):
    """Per-step, per-replica dropout rng (flax ignores it if unused).

    Deterministic in (seed, step); `fold_rank` decorrelates replicas the way
    each DDP rank draws its own dropout mask.
    """
    key = jax.random.fold_in(jax.random.PRNGKey(seed), state.step)
    return {"dropout": strategy.fold_rank(key)}


def make_train_step(strategy: Strategy | None = None,
                    loss_fn: Callable = softmax_cross_entropy,
                    seed: int = 0, guard=None):
    """Build the compiled step ``(state, batch) -> (state, metrics)``.

    ``batch`` is a dict with ``image`` (global batch, leading dim sharded on
    the data axis by the strategy) and integer ``label``.  Metrics come back
    as globally averaged scalars (loss, accuracy) — what the reference prints
    every 20 steps (pytorch/distributed_data_parallel.py:144-148).
    ``seed`` feeds the per-step dropout rng (for models that use dropout).

    ``guard`` (a :class:`dtdl_tpu.resil.StepGuard`) folds the on-device
    anomaly check into this same program: a non-finite loss/grad-norm
    step keeps the old state (``where`` select — bitwise identical to
    unguarded when no fault fires) and the ``bad_step``/``grad_norm``
    metrics ride the async queue, zero added syncs.  The select runs on
    the metric-synced loss and post-``grad_sync`` grads so every replica
    takes the same branch.
    """
    strategy = strategy or SingleDevice()

    def step(state: TrainState, batch):
        rngs = _dropout_rngs(state, strategy, seed)

        def compute_loss(params):
            logits, new_stats = _forward(state, params, batch, train=True,
                                         rngs=rngs)
            return loss_fn(logits, batch["label"]), (logits, new_stats)

        # Under DataParallel, localize() marks params per-replica so the
        # gradients below are local and grad_sync is a true mean-allreduce
        # (see dtdl_tpu/parallel/collectives.py:localize).
        (loss, (logits, new_stats)), grads = jax.value_and_grad(
            compute_loss, has_aux=True)(strategy.localize(state.params))
        grads = strategy.grad_sync(grads)
        if new_stats is not None:
            new_stats = strategy.stats_sync(new_stats)
        new_state = state.apply_gradients(grads=grads, batch_stats=new_stats)
        metrics = strategy.metric_sync({
            "loss": loss,
            "accuracy": accuracy(logits, batch["label"]),
        })
        if guard is not None:
            new_state, gm = guard.select(state, new_state,
                                         metrics["loss"], grads)
            metrics.update(gm)
        return new_state, metrics

    return strategy.compile(step)


def make_eval_step(strategy: Strategy | None = None,
                   loss_fn: Callable = softmax_cross_entropy):
    """Build the compiled eval step ``(state, batch) -> summed metrics``.

    Uses running BN statistics (train=False).  Returns **sums**, not means:
    ``{"loss_sum", "correct_sum", "count"}``, sum-allreduced across the mesh —
    the multi-node evaluator shape (reference chainer/train_mnist_multi.py:101-104
    allreduces eval metrics the same way).  Sum semantics make ragged tail
    batches exact: callers pad the batch to a shardable size and mark padding
    with ``batch["mask"] = 0``; masked examples contribute nothing.  Divide by
    ``count`` at the end (`dtdl_tpu.train.loop.evaluate` does this).
    """
    strategy = strategy or SingleDevice()

    def evaluate(state: TrainState, batch):
        logits, _ = _forward(state, state.params, batch, train=False)
        labels = batch["label"]
        mask = batch.get("mask")
        if mask is None:
            mask = jnp.ones(labels.shape, jnp.float32)
        mask = mask.astype(jnp.float32)
        losses = loss_fn(logits, labels, reduction="none")
        correct = (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
        return strategy.sum_sync({
            "loss_sum": (losses * mask).sum(),
            "correct_sum": (correct * mask).sum(),
            "count": mask.sum(),
        })

    return strategy.compile_eval(evaluate)


def make_lm_train_step(strategy: Strategy | None = None, seed: int = 0,
                       vocab_chunk_size: int = 0,
                       moe_aux_weight: float = 0.01, guard=None):
    """Compiled causal-LM step ``(state, batch) -> (state, metrics)``.

    ``batch``: {'tokens': int32 [B, S]} (optionally 'mask' f32 [B, S-1] over
    *target* positions).  Next-token cross entropy with shift; metrics are
    globally averaged {'loss', 'accuracy'} like the classifier step.

    ``vocab_chunk_size > 0`` switches the head to the vocab-chunked loss
    (dtdl_tpu/ops/cross_entropy.py:chunked_lm_loss, tiles of
    ``vocab_chunk_size`` vocab columns): the [B, S, V] logits are never materialized
    — fwd and bwd stream [tokens, chunk] tiles — so large-vocab models fit
    at long sequence.  Requires a model whose ``__call__`` accepts
    ``return_hidden=True`` (TransformerLM does) with a tied ``embed``
    parameter at the top of its param tree.

    MoE models (``n_experts > 0``) sow a Switch load-balance value per MoE
    layer under the 'aux_loss' collection; the step collects it and ADDS
    ``moe_aux_weight`` times the layer-mean to the training loss (the
    megatron path does the same — parallel/megatron.py).  Without this the
    sow is silently dropped and capacity routing collapses onto few
    experts.  Reported as the ``moe_aux_loss`` metric; 0 disables.

    ``guard`` folds the resil anomaly check into the program, exactly as
    in :func:`make_train_step`.
    """
    strategy = strategy or SingleDevice()

    def step(state: TrainState, batch):
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        mask = batch.get("mask")
        if mask is None:
            mask = jnp.ones(targets.shape, jnp.float32)
        # Global token count, so shards with sparser masks weigh less —
        # keeping the sharded loss/grads identical to single-device.  Each
        # replica's loss is scaled by num_replicas so grad_sync's *mean*
        # reconstructs the global sum/N exactly.
        total = strategy.sum_sync(mask.sum())
        scale = strategy.num_replicas / jnp.maximum(total, 1.0)

        rngs = _dropout_rngs(state, strategy, seed)

        def aux_term(variables):
            """Weighted layer-mean of the sow'd Switch balance values.

            Per-shard statistic: under DataParallel the loss mean across
            replicas makes this the mean of per-replica aux — each
            replica's router sees its own tokens, which is the standard
            per-device aux formulation."""
            leaves = jax.tree.leaves(variables.get("aux_loss", {}))
            if not leaves:      # static at trace time: model has no MoE
                return None, None
            aux = sum(leaves) / len(leaves)
            return moe_aux_weight * aux, aux

        if vocab_chunk_size:
            from dtdl_tpu.ops.cross_entropy import chunked_lm_loss

            def compute_loss(params):
                h, muts = state.apply_fn({"params": params}, inputs,
                                         train=True, rngs=rngs,
                                         return_hidden=True,
                                         mutable=["aux_loss"])
                b, s, d = h.shape
                emb = params["embed"]
                if hasattr(emb, "unbox"):   # flax logical-partitioning box
                    emb = emb.unbox()
                loss_sum, correct = chunked_lm_loss(
                    h.reshape(b * s, d), emb,
                    targets.reshape(b * s), mask.reshape(b * s),
                    vocab_chunk_size)
                loss = loss_sum * scale
                term, aux = aux_term(muts)
                if term is not None:
                    loss = loss + term
                return loss, (correct * scale, aux)
        else:
            def compute_loss(params):
                logits, muts = state.apply_fn({"params": params}, inputs,
                                              train=True, rngs=rngs,
                                              mutable=["aux_loss"])
                logits = logits.astype(jnp.float32)
                lse = jax.nn.logsumexp(logits, axis=-1)
                true = jnp.take_along_axis(
                    logits, targets[..., None].astype(jnp.int32), -1)[..., 0]
                loss = jnp.sum((lse - true) * mask) * scale
                correct = (jnp.argmax(logits, -1) == targets)
                term, aux = aux_term(muts)
                if term is not None:
                    loss = loss + term
                return loss, (jnp.sum(correct * mask) * scale, aux)

        (loss, (acc, aux)), grads = jax.value_and_grad(
            compute_loss, has_aux=True)(strategy.localize(state.params))
        grads = strategy.grad_sync(grads)
        new_state = state.apply_gradients(grads=grads, batch_stats=None)
        metrics = {"loss": loss, "accuracy": acc}
        if aux is not None:
            metrics["moe_aux_loss"] = aux
        metrics = strategy.metric_sync(metrics)
        if guard is not None:
            new_state, gm = guard.select(state, new_state,
                                         metrics["loss"], grads)
            metrics.update(gm)
        return new_state, metrics

    return strategy.compile(step)


def make_predict_step(strategy: Strategy | None = None,
                      probabilities: bool = False):
    """Compiled inference step ``(state, batch) -> logits/probs``.

    Outputs stay aligned with the input batch (sharded on the data axis under
    mesh strategies); call ``jax.device_get`` / ``np.asarray`` to gather.
    """
    strategy = strategy or SingleDevice()

    def predict(state: TrainState, batch):
        logits, _ = _forward(state, state.params, batch, train=False)
        if probabilities:
            logits = jax.nn.softmax(logits, axis=-1)
        return logits

    return strategy.compile_predict(predict)
