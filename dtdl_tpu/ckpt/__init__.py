from dtdl_tpu.ckpt.checkpoint import (  # noqa: F401
    CheckpointCorruptError, save_weights, load_weights, Checkpointer,
)
