"""Checkpoint / resume subsystem.

One subsystem covering the reference's three checkpoint shapes (SURVEY §5.4):

1. **final-weights export** — ``torch.save(net.module.state_dict())`` at end
   of training (reference pytorch/single_gpu.py:77-85; per-rank DDP variant
   pytorch/distributed_data_parallel.py:103-115) → `save_weights` /
   `load_weights` (msgpack of the params pytree);
2. **per-epoch weight checkpoints + restore-latest** — Keras ``ModelCheckpoint``
   + ``tf.train.latest_checkpoint`` (reference tensorflow2/mnist_single.py:66-76,
   88-92) → `Checkpointer.save_weights_epoch` / `Checkpointer.latest_weights`;
3. **full trainer-state snapshot with resume** — Chainer
   ``extensions.snapshot()`` + ``serializers.load_npz`` restoring optimizer
   and iterator state (reference chainer/train_mnist.py:91-93,120-122) →
   `Checkpointer.save` / `Checkpointer.restore` of the whole `TrainState`
   (params + opt_state + batch_stats + step) via orbax, which handles
   sharded/distributed arrays.

Writes are **leader-gated** (process 0) — standardizing the reference's
inconsistency where every DDP rank wrote a file (the rank-0 guard is
commented out at reference pytorch/distributed_data_parallel.py:107) while
ChainerMN gated on rank 0.  Under multi-host sharded states, orbax coordinates
a distributed write instead (every host writes its shards).

**Integrity (ISSUE 5)**: nothing here assumes a write finished.  Each
msgpack blob carries a checksummed manifest sidecar
(``<path>.manifest.json``: byte length + sha256) verified at load; a
torn or truncated blob raises a named :class:`CheckpointCorruptError`
(path + byte length) instead of an opaque flax deserialization error.
Orbax snapshots gain a **commit marker** (a file written inside the
snapshot dir only after ``wait_until_finished`` proves durability): a
durable-looking dir without its marker is a write the process died
inside, and restore-latest **quarantines** it (renamed ``*.corrupt``,
kept for inspection, invisible to the snapshot regex) and falls back to
the previous good snapshot.  The torn-write windows themselves are
covered by the fault-injection sites ``ckpt.pre_rename`` /
``ckpt.pre_commit`` (dtdl_tpu/resil/faults.py) and pinned by
tests/test_resil.py.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re

import jax
import numpy as np
from flax import serialization

from dtdl_tpu.resil.faults import fire as _fault
from dtdl_tpu.runtime.bootstrap import barrier, is_leader

log = logging.getLogger("dtdl_tpu")

# commit marker written inside a snapshot dir once it is durable; a dir
# without it is torn (the process died between orbax finalize and here)
_COMMIT_MARKER = "_DTDL_COMMIT"


class CheckpointCorruptError(RuntimeError):
    """A checkpoint artifact is torn, truncated, or fails its checksum.

    Distinct from the architecture-mismatch ``ValueError`` (a *valid*
    checkpoint for a different model): corruption is quarantined and
    fallen back from; a mismatch is a caller error that must propagate.
    """

    def __init__(self, path: str, nbytes: int | None, reason: str):
        self.path = path
        self.nbytes = nbytes
        size = "unknown size" if nbytes is None else f"{nbytes} bytes"
        super().__init__(f"corrupt checkpoint {path} ({size}): {reason}")


def _manifest_path(path: str) -> str:
    return path + ".manifest.json"


def save_weights(path: str, tree) -> str:
    """Serialize a (replicated or host-local) pytree of weights to msgpack.

    Atomic per artifact: blob to ``.tmp`` then rename, then the manifest
    (byte length + sha256) the same way.  A crash between the two
    renames leaves a blob whose manifest describes the *previous* blob —
    `load_weights` reads that as corrupt and the caller falls back,
    which is the conservative end of the failure model (SCALING.md).
    """
    tree = jax.device_get(tree)
    blob = serialization.to_bytes(tree)
    if is_leader():
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        _fault("ckpt.pre_rename")   # the torn-write window, injectable
        os.replace(tmp, path)
        manifest = {"bytes": len(blob),
                    "sha256": hashlib.sha256(blob).hexdigest()}
        mtmp = _manifest_path(path) + ".tmp"
        with open(mtmp, "w") as f:
            json.dump(manifest, f)
        os.replace(mtmp, _manifest_path(path))
    barrier("save_weights")
    return path


def load_weights(path: str, like):
    """Load weights saved by `save_weights` into the structure of ``like``.

    **Integrity-checked**: when the manifest sidecar exists, the blob's
    byte length and sha256 must match it; any mismatch — and any flax/
    msgpack deserialization failure, which used to surface as an opaque
    internal error — raises :class:`CheckpointCorruptError` naming the
    path and byte length.  A manifest-less blob (external origin) skips
    the checksum but still gets the named wrap on parse failure.

    **Shape-validated**: flax ``from_bytes`` happily returns the *stored*
    array when its shape differs from ``like``'s (verified: a (256,8,32)
    blob restores into a (256,2,128) slot unchanged), which would let a
    checkpoint from a differently-configured model load and then compute a
    different function or crash far from the cause.  Any leaf whose shape
    disagrees with ``like`` fails loudly here instead, naming the paths —
    e.g. snapshots predating a named-config geometry change (the round-3
    head_dim-128 'small'/'base' presets) cannot silently load.  This is
    a ``ValueError``, NOT corruption — it must propagate, never be
    quarantined.
    """
    with open(path, "rb") as f:
        blob = f.read()
    mpath = _manifest_path(path)
    if os.path.exists(mpath):
        with open(mpath) as f:
            manifest = json.load(f)
        if len(blob) != manifest.get("bytes"):
            raise CheckpointCorruptError(
                path, len(blob),
                f"manifest says {manifest.get('bytes')} bytes — truncated "
                f"or torn write")
        digest = hashlib.sha256(blob).hexdigest()
        if digest != manifest.get("sha256"):
            raise CheckpointCorruptError(
                path, len(blob), "sha256 mismatch against manifest")
    try:
        restored = serialization.from_bytes(like, blob)
    except Exception as e:   # flax/msgpack errors are opaque — name them
        raise CheckpointCorruptError(
            path, len(blob), f"{type(e).__name__}: {e}") from e
    _validate_shapes(restored, like, path)
    return restored


def _validate_shapes(restored, like, origin: str) -> None:
    """Raise when any restored leaf's shape or dtype disagrees with
    ``like``'s.

    Neither flax ``from_bytes`` nor orbax ``StandardCheckpointer.restore``
    enforces this (both verified to hand back the *stored* shape when it
    differs from the target), so a checkpoint from a differently-configured
    model would load and then compute a different function or crash far
    from the cause.  Dtype counts too: a same-shape f32 checkpoint loading
    into a bf16 run would silently train in the wrong precision."""
    leaves_r = jax.tree_util.tree_leaves_with_path(restored)
    leaves_l = jax.tree_util.tree_leaves_with_path(like)
    if len(leaves_r) != len(leaves_l):
        # zip() would silently drop the trailing leaves of the longer tree,
        # leaving them unvalidated — structure mismatch is its own error
        raise ValueError(
            f"checkpoint {origin} tree structure does not match the model: "
            f"{len(leaves_r)} restored leaves vs {len(leaves_l)} expected")
    bad = []
    for (path_r, leaf_r), (_, leaf_l) in zip(leaves_r, leaves_l):
        want = getattr(leaf_l, "shape", None)
        got = getattr(leaf_r, "shape", None)
        if want is not None and got is not None and want != got:
            bad.append(f"{jax.tree_util.keystr(path_r)}: "
                       f"checkpoint {got} vs model {want}")
            continue
        want_dt = getattr(leaf_l, "dtype", None)
        got_dt = getattr(leaf_r, "dtype", None)
        if want_dt is not None and got_dt is not None and want_dt != got_dt:
            bad.append(f"{jax.tree_util.keystr(path_r)}: checkpoint dtype "
                       f"{got_dt} vs model {want_dt}")
    if bad:
        raise ValueError(
            f"checkpoint {origin} does not match the model architecture "
            f"({len(bad)} mismatched leaves):\n  " + "\n  ".join(bad[:10]))


class Checkpointer:
    """Directory-managed checkpoints: per-epoch weights + full-state snapshots.

    Layout under ``directory``::

        weights_epoch_0003.msgpack   (shape 2: per-epoch weights)
        snapshot_12/                 (shape 3: orbax full TrainState at step 12)
        final.msgpack                (shape 1: final weights export)
    """

    _WEIGHT_RE = re.compile(r"weights_epoch_(\d+)\.msgpack$")
    _SNAP_RE = re.compile(r"snapshot_(\d+)$")

    def __init__(self, directory: str, keep: int | None = None):
        self.directory = directory
        self.keep = keep
        self._ocp = None   # lazy, persistent AsyncCheckpointer
        self._last_saved_step = None   # protected from gc until superseded
        # rollback detection: _supersede (deleting entries ABOVE a save)
        # only makes sense when this run actually restored from THIS
        # directory's timeline and is rewriting it.  A fresh Checkpointer
        # pointed at an existing directory that saves low ids (step
        # counters start at 0) must NOT delete the previous run's
        # higher-step snapshots — and a warm start from an *external*
        # checkpoint path is not a rollback of this directory either.
        # Tracked per checkpoint shape: restoring a full-state snapshot
        # says nothing about the epoch-weights timeline and vice versa.
        self._restored_snapshot = False
        self._restored_weights = False
        # steps saved async whose commit marker is not yet written; the
        # marker lands at wait_until_finished, once orbax proves the dir
        # durable — a dir without a marker is a torn write
        self._pending_commit: set[int] = set()
        if is_leader():
            os.makedirs(directory, exist_ok=True)
        barrier("ckpt_mkdir")

    # -- lifecycle: `with Checkpointer(...) as ck:` flushes-and-closes on
    # ANY exit, exceptions included — an interrupted run must leave its
    # last staged snapshot durable (and committed) rather than torn

    def __enter__(self) -> "Checkpointer":
        return self

    def __exit__(self, *exc) -> bool:
        try:
            self.wait_until_finished()
        finally:
            self.close()
        return False

    @property
    def _checkpointer(self):
        """One long-lived orbax StandardCheckpointer (an AsyncCheckpointer:
        ``save`` returns after staging device arrays to host; serialization
        and the final directory rename proceed on a background thread).  The
        old per-call ``with StandardCheckpointer()`` made every save
        synchronous — the context exit waits."""
        if self._ocp is None:
            import orbax.checkpoint as ocp
            self._ocp = ocp.StandardCheckpointer()
        return self._ocp

    def wait_until_finished(self) -> None:
        """Block until every in-flight async snapshot is durable on disk,
        then trim to ``keep`` — the just-finalized snapshot is visible now,
        so this is the point where the oldest retained one becomes excess.
        The last-saved step stays protected: after a rollback-restore, a
        re-save of an old step (which sorts below newer snapshots) must not
        be deleted the moment it lands.  Trimming happens ONLY when this
        process actually saved something — read-only paths (restore /
        latest_step in a fresh process) must never delete snapshots, e.g.
        an explicit-step rollback restore of the oldest retained snapshot.
        """
        if self._ocp is not None:
            self._ocp.wait_until_finished()
            self._commit_pending()
            if self._last_saved_step is not None:
                self._gc(self._SNAP_RE, "snapshot_{}",
                         protect=self._last_saved_step)

    def _commit_pending(self) -> None:
        """Write the commit marker of every now-durable snapshot.

        Runs right after orbax's ``wait_until_finished``: the snapshot
        dirs have their final names, so marking them committed is the
        last — and injectable (``ckpt.pre_commit``) — step of the save.
        A crash before the marker leaves a durable-looking dir that
        restore-latest quarantines and falls back from.  Every host
        passes the trailing barrier before any of them can list/restore
        — without it a non-leader racing ahead of the leader's marker
        write would misread a just-committed snapshot as torn."""
        if not self._pending_commit:
            return
        for step in sorted(self._pending_commit):
            path = os.path.join(self.directory, f"snapshot_{step}")
            if is_leader() and os.path.isdir(path):
                _fault("ckpt.pre_commit")   # torn-finalize window
                marker = os.path.join(path, _COMMIT_MARKER)
                tmp = marker + ".tmp"
                with open(tmp, "w") as f:
                    json.dump({"step": step}, f)
                os.replace(tmp, marker)
        self._pending_commit.clear()
        barrier("ckpt_commit")

    def close(self) -> None:
        if self._ocp is not None:
            self._ocp.close()
            self._ocp = None

    # -- shape 2: per-epoch weights ------------------------------------------

    def _quarantine(self, victim: str, err: Exception) -> None:
        """Move a corrupt artifact out of the restore regexes' sight
        (``<name>.corrupt``), keeping it on disk for inspection.  Leader-
        gated like every mutation; the ``.corrupt`` suffix never matches
        the ``$``-anchored snapshot/weights regexes, so the quarantined
        entry can neither be restored nor occupy a ``keep`` slot."""
        log.warning("quarantining corrupt checkpoint %s: %s", victim, err)
        if not is_leader():
            return
        for src in (victim, victim + ".manifest.json"):
            if os.path.exists(src):
                try:
                    os.replace(src, src + ".corrupt")
                except OSError:   # never let cleanup mask the fallback
                    pass

    def save_weights_epoch(self, epoch: int, params) -> str:
        path = os.path.join(self.directory,
                            f"weights_epoch_{epoch:04d}.msgpack")
        save_weights(path, params)
        # same rollback semantics as full snapshots: an epoch saved below
        # existing ones supersedes the abandoned timeline's later epochs,
        # so latest_weights() never restores a stale future — but only when
        # this run restored epoch weights first (see _supersede)
        if self._restored_weights:
            self._supersede(self._WEIGHT_RE, "weights_epoch_{:04d}.msgpack",
                            epoch)
        self._gc(self._WEIGHT_RE, "weights_epoch_{:04d}.msgpack",
                 protect=epoch)
        return path

    def latest_weights(self, like):
        """Restore-latest (``tf.train.latest_checkpoint`` parity).

        **Corruption-tolerant**: a torn/truncated epoch file (named
        :class:`CheckpointCorruptError` from `load_weights`) is
        quarantined and the next-older epoch is tried — restore-latest
        degrades by one epoch instead of crashing the resume.  An
        architecture mismatch (``ValueError``) still propagates: every
        epoch in the directory has the same geometry, so falling back
        would just fail ``keep`` more times and then silently cold-start.
        """
        for epoch in sorted(self._list(self._WEIGHT_RE), reverse=True):
            path = os.path.join(self.directory,
                                f"weights_epoch_{epoch:04d}.msgpack")
            try:
                restored = load_weights(path, like)
            except CheckpointCorruptError as e:
                self._quarantine(path, e)
                continue
            except FileNotFoundError:
                # multi-host race: the leader quarantined (renamed) this
                # epoch between our listing and the open — fall back to
                # the next one, exactly as if we had seen the rename
                continue
            self._restored_weights = True
            return restored, epoch
        return None, None

    # -- shape 3: full trainer-state snapshot --------------------------------

    def save(self, step: int, state, wait: bool = False) -> str:
        """Snapshot the full TrainState (optimizer + BN stats + step).

        **Asynchronous**: returns once device arrays are staged to host
        memory; the write overlaps subsequent training steps (the snapshot
        never blocks the step loop — round-2 verdict weak #3).  The training
        engines call :meth:`wait_until_finished` before they return, and
        every restore path waits first, so readers only ever see durable
        snapshots.  Pass ``wait=True`` to force a synchronous save.
        """
        path = os.path.abspath(
            os.path.join(self.directory, f"snapshot_{step}"))
        self._checkpointer.save(path, state, force=True)
        self._last_saved_step = step
        self._pending_commit.add(step)
        # Saving a step BELOW existing snapshot ids AFTER this run restored
        # an older snapshot means training rolled back, and the higher-step
        # snapshots belong to the abandoned timeline.  They must not
        # survive: they would win restore(step=None)/latest_step() after a
        # crash, silently resuming from the pre-rollback timeline, and
        # they'd permanently occupy `keep` slots so each new-timeline save
        # left only the just-saved snapshot alive.  restore() waits for
        # in-flight writes first, so every stale future is durable and
        # visible here.  Without a prior restore there is no rollback —
        # a fresh run pointed at an existing directory starts its step
        # counter at 0, and deleting the previous run's higher snapshots
        # would be data loss, so _supersede is gated on having restored a
        # snapshot from this directory.
        if self._restored_snapshot:
            self._supersede(self._SNAP_RE, "snapshot_{}", step)
        # The async save is only *staged* here: the snapshot dir still has
        # its orbax tmp name and _list can't see it.  Trimming over the
        # DURABLE list only (never counting the in-flight step as present)
        # keeps `keep` durable snapshots intact through the write window —
        # a crash mid-write can never leave fewer.  The now-excess oldest
        # one is removed at wait_until_finished, once the new snapshot is
        # durable and visible.
        self._gc(self._SNAP_RE, "snapshot_{}", protect=step)
        if wait:
            self.wait_until_finished()
        return path

    def restore(self, like, step: int | None = None):
        """Restore the latest (or given-step) snapshot into ``like``'s shape.

        Returns (state, step) or (None, None) when no snapshot exists — the
        --resume flow (reference chainer/train_mnist.py:120-122).

        **Preemption-safe**: restore-latest walks the snapshots newest
        first, quarantining any torn one — missing commit marker (the
        process died between orbax finalize and commit) or an orbax
        restore failure — and falls back to the previous good snapshot,
        so a crash mid-save costs at most one snapshot interval of work.
        The marker is required only in a **marker-aware** directory (one
        holding at least one committed snapshot): a directory written
        entirely by a pre-marker version has no markers anywhere, and
        condemning it wholesale would silently cold-start over good
        data — legacy snapshots restore normally (orbax's own finalize
        rename is atomic, so a durable-named legacy dir is complete).
        An explicit ``step=`` raises :class:`CheckpointCorruptError`
        instead (the caller asked for that exact snapshot); an
        architecture mismatch (``ValueError``) always propagates.
        """
        self.wait_until_finished()
        steps = self._list(self._SNAP_RE)
        if not steps:
            return None, None
        require_marker = any(self._committed(s) for s in steps)
        if step is not None:
            return self._restore_step(like, step, require_marker)
        for s in sorted(steps, reverse=True):
            try:
                return self._restore_step(like, s, require_marker)
            except CheckpointCorruptError as e:
                self._quarantine(os.path.join(self.directory,
                                              f"snapshot_{s}"), e)
        return None, None

    def _committed(self, step: int) -> bool:
        return os.path.exists(os.path.join(
            self.directory, f"snapshot_{step}", _COMMIT_MARKER))

    def _restore_step(self, like, step: int, require_marker: bool = True):
        """Restore one specific snapshot; CheckpointCorruptError when it
        is torn (no commit marker, in a marker-aware directory) or orbax
        cannot read it."""
        path = os.path.abspath(
            os.path.join(self.directory, f"snapshot_{step}"))
        if not os.path.isdir(path):
            raise CheckpointCorruptError(path, None, "snapshot missing")
        if require_marker and not self._committed(step):
            raise CheckpointCorruptError(
                path, None, "no commit marker — the writing process died "
                "before the snapshot was finalized (torn write)")
        try:
            restored = self._checkpointer.restore(path, like)
        except (ValueError, TypeError):
            raise          # architecture/structure mismatch — caller error
        except Exception as e:
            raise CheckpointCorruptError(
                path, None, f"{type(e).__name__}: {e}") from e
        _validate_shapes(restored, like, path)
        self._restored_snapshot = True
        return restored, step

    def latest_step(self) -> int | None:
        """Step of the newest COMMITTED full-state snapshot (None when
        none exist) — in a marker-aware directory, a durable-looking dir
        without its commit marker is a torn write and never reported as
        resumable (legacy marker-less directories report normally, as in
        :meth:`restore`)."""
        self.wait_until_finished()
        steps = self._list(self._SNAP_RE)
        committed = [s for s in steps if self._committed(s)]
        if committed:
            return max(committed)
        return max(steps) if steps else None

    def restore_path(self, like, path: str):
        """Restore from an explicit snapshot path (--resume <path>).

        No commit-marker requirement — an explicit path is user intent
        (and may point at an external/orbax-native snapshot) — but read
        failures still come back as the named
        :class:`CheckpointCorruptError` rather than orbax internals."""
        self.wait_until_finished()
        abspath = os.path.abspath(path.rstrip("/"))
        try:
            restored = self._checkpointer.restore(abspath, like)
        except (ValueError, TypeError):
            raise          # structure mismatch — caller error
        except Exception as e:
            raise CheckpointCorruptError(
                abspath, None, f"{type(e).__name__}: {e}") from e
        _validate_shapes(restored, like, path)
        # a rollback only rewrites THIS directory's timeline: restoring a
        # snapshot that lives elsewhere (warm start from another run) must
        # not arm _supersede against this directory's snapshots
        if (os.path.dirname(abspath) == os.path.abspath(self.directory)
                and self._SNAP_RE.search(os.path.basename(abspath))):
            self._restored_snapshot = True
        return restored

    # -- shape 1: final weights ----------------------------------------------

    def save_final(self, params) -> str:
        return save_weights(os.path.join(self.directory, "final.msgpack"),
                            params)

    # -- housekeeping ---------------------------------------------------------

    def _list(self, regex) -> list[int]:
        if not os.path.isdir(self.directory):
            return []
        out = []
        for name in os.listdir(self.directory):
            m = regex.search(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def _gc(self, regex, fmt, protect: int | None = None) -> None:
        """Remove all but the ``keep`` newest entries.  ``protect`` (the id
        just saved) is never a victim even when it sorts low — re-saving an
        old step must not delete that step's own snapshot."""
        if self.keep is None or not is_leader():
            return
        ids = self._list(regex)
        for old in ids[:-self.keep]:
            if old == protect:
                continue
            self._delete(fmt, old)

    def _supersede(self, regex, fmt, just_saved: int) -> None:
        """Delete every durable entry with an id ABOVE ``just_saved`` — they
        are stale futures from a timeline abandoned by a rollback restore.
        Runs regardless of ``keep`` (this is a correctness rule for
        restore-latest, not retention policy), leader-gated like all
        deletions.  Callers gate on the per-shape restored flags
        (``_restored_snapshot`` / ``_restored_weights``): only a run that
        actually restored this shape from THIS directory is rewriting its
        timeline; a fresh run saving low ids into an existing directory —
        or one warm-started from an external checkpoint path — is not a
        rollback, and deleting the directory's higher-id entries would
        destroy the previous run's data."""
        if not is_leader():
            return
        for old in self._list(regex):
            if old > just_saved:
                self._delete(fmt, old)

    def _delete(self, fmt, entry_id: int) -> None:
        import shutil
        victim = os.path.join(self.directory, fmt.format(entry_id))
        if os.path.isdir(victim):
            shutil.rmtree(victim)
        elif os.path.exists(victim):
            os.remove(victim)
        meta = victim + ".meta.json"   # Trainer's snapshot sidecar
        if os.path.exists(meta):
            os.remove(meta)
