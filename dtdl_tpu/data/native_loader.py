"""NativeDataLoader — C++ producer/consumer batch pipeline behind the same
iterator protocol as :class:`dtdl_tpu.data.loader.DataLoader`.

Shuffle, pad-4 crop/flip augmentation, and normalization run in C++ worker
threads (dtdl_tpu/native/src/dtdl_native.cpp) into a bounded queue, so the
Python step loop only memcpys ready batches — the role torch DataLoader's
``num_workers=4`` processes play for the reference (reference
pytorch/single_gpu.py:60-61), without fork overhead or the GIL.

Falls back transparently: construct with ``NativeDataLoader.or_python(...)``
to get the pure-Python loader when the native toolchain is unavailable.
"""

from __future__ import annotations

import ctypes

import numpy as np

from dtdl_tpu import native
from dtdl_tpu.data.loader import DataLoader

SHUFFLE = 1
AUGMENT_CROP_FLIP = 2
NORMALIZE = 4


class NativeDataLoader:
    """Iterates dict batches {'image': f32 [B,H,W,C], 'label': i32 [B]}."""

    def __init__(self, images: np.ndarray, labels: np.ndarray,
                 batch_size: int, shuffle: bool = True, seed: int = 0,
                 augment: bool = False, mean=None, std=None,
                 depth: int = 4, n_threads: int = 4, sampler=None):
        lib = native.load()
        if lib is None:
            raise RuntimeError("native library unavailable; use "
                               "NativeDataLoader.or_python(...)")
        self._lib = lib
        if images.ndim == 2:   # flattened features -> [N, F, 1, 1]
            images = images[:, :, None, None]
        if images.ndim == 3:
            images = images[..., None]
        # own C-contiguous copies; the C side borrows these pointers
        self._images = np.ascontiguousarray(images, np.float32)
        self._labels = np.ascontiguousarray(labels, np.int32)
        n, h, w, c = self._images.shape
        self.batch_size = batch_size
        self._shape = (h, w, c)
        flags = (SHUFFLE if shuffle else 0) | \
                (AUGMENT_CROP_FLIP if augment else 0) | \
                (NORMALIZE if mean is not None else 0)
        mean_arr = np.ascontiguousarray(
            np.broadcast_to(np.asarray(
                mean if mean is not None else 0.0, np.float32), (c,)))
        std_arr = np.ascontiguousarray(
            np.broadcast_to(np.asarray(
                std if std is not None else 1.0, np.float32), (c,)))
        self._keepalive = (mean_arr, std_arr)
        self._h = lib.dtdl_loader_create(
            self._images.ctypes.data_as(ctypes.c_void_p),
            self._labels.ctypes.data_as(ctypes.c_void_p),
            n, h, w, c, batch_size, depth, n_threads, flags, seed,
            mean_arr.ctypes.data_as(ctypes.c_void_p),
            std_arr.ctypes.data_as(ctypes.c_void_p))
        if not self._h:
            raise RuntimeError("dtdl_loader_create failed")
        self._epoch = 0
        self._n = n
        # a ShardedSampler gives DistributedSampler parity in multi-host
        # runs: every epoch this host feeds its stripe of a globally
        # reshuffled permutation (C++ then only augments/batches).  Without
        # one, the C++ side shuffles the full local array itself.
        self._sampler = sampler

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch
        if self._sampler is not None:
            self._sampler.set_epoch(epoch)

    def __len__(self) -> int:
        if self._sampler is not None:
            return len(self._sampler) // self.batch_size
        return self._n // self.batch_size

    def __iter__(self):
        lib, h = self._lib, self._h
        if self._sampler is not None:
            idx = np.ascontiguousarray(self._sampler.indices(), np.int64)
            rc = lib.dtdl_loader_start_epoch_indices(
                h, self._epoch, idx.ctypes.data_as(ctypes.c_void_p), len(idx))
            if rc != 0:
                raise RuntimeError("dtdl_loader_start_epoch_indices failed "
                                   "(index out of range?)")
        else:
            lib.dtdl_loader_start_epoch(h, self._epoch)
        hh, w, c = self._shape
        img = np.empty((self.batch_size, hh, w, c), np.float32)
        lab = np.empty((self.batch_size,), np.int32)
        while lib.dtdl_loader_next(
                h, img.ctypes.data_as(ctypes.c_void_p),
                lab.ctypes.data_as(ctypes.c_void_p)):
            yield {"image": img.copy(), "label": lab.copy()}

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.dtdl_loader_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    @staticmethod
    def or_python(images, labels, batch_size, shuffle=True, seed=0,
                  augment=False, mean=None, std=None, sampler=None, **kw):
        """Native pipeline when buildable, Python DataLoader otherwise.

        Both paths honor ``sampler`` (per-host stripe of a per-epoch global
        permutation), so switching loader backends never changes which
        examples a host trains on.
        """
        if native.available():
            try:
                return NativeDataLoader(images, labels, batch_size,
                                        shuffle=shuffle, seed=seed,
                                        augment=augment, mean=mean, std=std,
                                        sampler=sampler, **kw)
            except RuntimeError:
                pass
        from dtdl_tpu.data.loader import (cifar10_train_transform,
                                          normalize_transform)
        transform = None
        if augment and mean is not None:
            transform = cifar10_train_transform(mean, std)
        elif mean is not None:
            transform = normalize_transform(mean, std)
        return DataLoader({"image": np.asarray(images, np.float32),
                           "label": np.asarray(labels, np.int32)},
                          batch_size, shuffle=shuffle, seed=seed,
                          transform=transform, sampler=sampler)


def read_idx_native(path: str):
    """IDX(.gz) reader through the native zlib path; None if unavailable.

    Returns images as float32 scaled to [0,1] (u8 payloads) or labels int32.
    """
    lib = native.load()
    if lib is None:
        return None
    is_gz = 1 if path.endswith(".gz") else 0
    dims = (ctypes.c_int64 * 4)()
    ndim = lib.dtdl_idx_header(path.encode(), is_gz, dims)
    if ndim < 0:
        return None
    shape = tuple(int(dims[i]) for i in range(ndim))
    count = int(np.prod(shape))
    if ndim == 1:   # labels
        out = np.empty(shape, np.int32)
        rc = lib.dtdl_idx_read_i32(path.encode(), is_gz,
                                   out.ctypes.data_as(ctypes.c_void_p), count)
    else:
        out = np.empty(shape, np.float32)
        rc = lib.dtdl_idx_read_f32(path.encode(), is_gz,
                                   out.ctypes.data_as(ctypes.c_void_p),
                                   count, 1.0 / 255.0)
    return out if rc == 0 else None
