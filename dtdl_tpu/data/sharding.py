"""Deterministic dataset sharding across processes.

TPU-native restatement of the reference's two sharding mechanisms:

* ``DistributedSampler`` — per-rank index slices of a shared dataset with a
  per-epoch shuffle (reference pytorch/distributed_data_parallel.py:87-91,
  including ``set_epoch`` semantics);
* ``chainermn.scatter_dataset`` — rank 0 loads, shards are scattered over MPI
  (reference chainer/train_mnist_multi.py:87-92).

On TPU hosts every process can read the dataset source directly, so scatter
becomes *deterministic per-host slicing* — same partition, no wire transfer:
every host computes the same global permutation from (seed, epoch) and takes
its own contiguous stripe.  With remainder handling made explicit: ``pad``
wraps indices so all shards are equal (DistributedSampler's behavior), while
``drop`` truncates to the largest even multiple.
"""

from __future__ import annotations

import numpy as np


class ShardedSampler:
    """Per-process view of a globally shuffled index space."""

    def __init__(self, num_examples: int, num_shards: int = 1,
                 shard_id: int = 0, shuffle: bool = True, seed: int = 0,
                 remainder: str = "pad"):
        if not 0 <= shard_id < num_shards:
            raise ValueError(f"shard_id {shard_id} not in [0, {num_shards})")
        if remainder not in ("pad", "drop"):
            raise ValueError("remainder must be 'pad' or 'drop'")
        self.num_examples = num_examples
        self.num_shards = num_shards
        self.shard_id = shard_id
        self.shuffle = shuffle
        self.seed = seed
        self.remainder = remainder
        self.epoch = 0
        if remainder == "pad":
            self.shard_size = -(-num_examples // num_shards)
        else:
            self.shard_size = num_examples // num_shards

    def set_epoch(self, epoch: int) -> None:
        """Reshuffle deterministically per epoch (DistributedSampler parity:
        the reference calls train_sampler.set_epoch implicitly by epoch count)."""
        self.epoch = epoch

    def global_permutation(self) -> np.ndarray:
        if self.shuffle:
            rng = np.random.default_rng((self.seed, self.epoch))
            perm = rng.permutation(self.num_examples)
        else:
            perm = np.arange(self.num_examples)
        total = self.shard_size * self.num_shards
        if self.remainder == "pad" and total > self.num_examples:
            perm = np.concatenate([perm, perm[: total - self.num_examples]])
        else:
            perm = perm[:total]
        return perm

    def indices(self) -> np.ndarray:
        """This shard's indices for the current epoch (contiguous stripe)."""
        perm = self.global_permutation()
        start = self.shard_id * self.shard_size
        return perm[start:start + self.shard_size]

    def __iter__(self):
        return iter(self.indices())

    def __len__(self) -> int:
        return self.shard_size


def scatter_arrays(arrays: dict, num_shards: int, shard_id: int,
                   shuffle: bool = True, seed: int = 0) -> dict:
    """Materialize this process's shard of a dict of arrays.

    Functional equivalent of ``chainermn.scatter_dataset(..., shuffle=True)``
    (reference chainer/train_mnist_multi.py:91-92) without the wire transfer:
    all hosts derive the same permutation, each keeps only its stripe.
    """
    n = len(next(iter(arrays.values())))
    sampler = ShardedSampler(n, num_shards, shard_id, shuffle=shuffle,
                             seed=seed, remainder="drop")
    idx = sampler.indices()
    return {k: v[idx] for k, v in arrays.items()}


def assert_no_overlap(samplers) -> None:
    """Test helper: shards must partition the index space (no overlap)."""
    seen = set()
    for s in samplers:
        ix = set(int(i) for i in s.indices())
        if seen & ix and s.remainder == "drop":
            raise AssertionError("overlapping shards")
        seen |= ix
