"""Deterministic dataset sharding across processes.

TPU-native restatement of the reference's two sharding mechanisms:

* ``DistributedSampler`` — per-rank index slices of a shared dataset with a
  per-epoch shuffle (reference pytorch/distributed_data_parallel.py:87-91,
  including ``set_epoch`` semantics);
* ``chainermn.scatter_dataset`` — rank 0 loads, shards are scattered over MPI
  (reference chainer/train_mnist_multi.py:87-92).

On TPU hosts every process can read the dataset source directly, so scatter
becomes *deterministic per-host slicing* — same partition, no wire transfer:
every host computes the same global permutation from (seed, epoch) and takes
its own contiguous stripe.  With remainder handling made explicit: ``pad``
wraps indices so all shards are equal (DistributedSampler's behavior), while
``drop`` truncates to the largest even multiple.
"""

from __future__ import annotations

import numpy as np


class ShardedSampler:
    """Per-process view of a globally shuffled index space."""

    def __init__(self, num_examples: int, num_shards: int = 1,
                 shard_id: int = 0, shuffle: bool = True, seed: int = 0,
                 remainder: str = "pad"):
        if not 0 <= shard_id < num_shards:
            raise ValueError(f"shard_id {shard_id} not in [0, {num_shards})")
        if remainder not in ("pad", "drop"):
            raise ValueError("remainder must be 'pad' or 'drop'")
        self.num_examples = num_examples
        self.num_shards = num_shards
        self.shard_id = shard_id
        self.shuffle = shuffle
        self.seed = seed
        self.remainder = remainder
        self.epoch = 0
        if remainder == "pad":
            self.shard_size = -(-num_examples // num_shards)
        else:
            self.shard_size = num_examples // num_shards

    def set_epoch(self, epoch: int) -> None:
        """Reshuffle deterministically per epoch (DistributedSampler parity:
        the reference calls train_sampler.set_epoch implicitly by epoch count)."""
        self.epoch = epoch

    def global_permutation(self) -> np.ndarray:
        if self.shuffle:
            rng = np.random.default_rng((self.seed, self.epoch))
            perm = rng.permutation(self.num_examples)
        else:
            perm = np.arange(self.num_examples)
        total = self.shard_size * self.num_shards
        if self.remainder == "pad" and total > self.num_examples:
            perm = np.concatenate([perm, perm[: total - self.num_examples]])
        else:
            perm = perm[:total]
        return perm

    def indices(self) -> np.ndarray:
        """This shard's indices for the current epoch (contiguous stripe)."""
        perm = self.global_permutation()
        start = self.shard_id * self.shard_size
        return perm[start:start + self.shard_size]

    def __iter__(self):
        return iter(self.indices())

    def __len__(self) -> int:
        return self.shard_size


def scatter_arrays(arrays: dict, num_shards: int, shard_id: int,
                   shuffle: bool = True, seed: int = 0) -> dict:
    """Materialize this process's shard of a dict of arrays.

    Functional equivalent of ``chainermn.scatter_dataset(..., shuffle=True)``
    (reference chainer/train_mnist_multi.py:91-92) without the wire transfer:
    all hosts derive the same permutation, each keeps only its stripe.
    """
    n = len(next(iter(arrays.values())))
    sampler = ShardedSampler(n, num_shards, shard_id, shuffle=shuffle,
                             seed=seed, remainder="drop")
    idx = sampler.indices()
    return {k: v[idx] for k, v in arrays.items()}


class GlobalBatchSampler:
    """World-size-*agnostic* batch order for elastic training (ISSUE 12).

    :class:`ShardedSampler` partitions each epoch's permutation into
    per-worker stripes, so the sample→step mapping changes with the
    world size — a mid-epoch shrink would re-deal the remaining stream
    and silently drop or double-count samples.  This sampler removes
    world size from the *order*: global step ``i`` always consumes the
    same ``global_batch`` indices of the same per-epoch permutation
    (same ``(seed, epoch)`` derivation as :class:`ShardedSampler`),
    regardless of how many workers exist.  Workers take contiguous
    equal slices of each global batch (:meth:`shard`), so after a
    shrink the survivors re-slice the *identical* remaining stream:
    every global step's samples are consumed exactly once across the
    whole elastic timeline — zero lost, zero double-counted.

    ``global_batch`` must divide by every world size the run can shrink
    to; :meth:`check_world` enforces it by name at rendezvous time
    instead of letting a ragged split corrupt the stream later.
    """

    def __init__(self, num_examples: int, global_batch: int,
                 shuffle: bool = True, seed: int = 0):
        if global_batch < 1 or global_batch > num_examples:
            raise ValueError(
                f"global_batch {global_batch} not in [1, {num_examples}]")
        self.num_examples = num_examples
        self.global_batch = global_batch
        self.shuffle = shuffle
        self.seed = seed
        # drop-last semantics: a partial trailing batch would change
        # width across the epoch boundary and break the equal-slice rule
        self.batches_per_epoch = num_examples // global_batch
        self._perm_cache: tuple | None = None     # (epoch, permutation)

    def check_world(self, world_size: int) -> None:
        if world_size < 1 or self.global_batch % world_size:
            raise ValueError(
                f"global_batch {self.global_batch} does not divide over "
                f"a world of {world_size} worker(s) — pick a global "
                f"batch divisible by every world size the run may "
                f"shrink to")

    def batch_indices(self, step: int) -> np.ndarray:
        """The global batch consumed at global step ``step`` — a pure
        function of (seed, step), never of the world.

        The epoch permutation is cached (keyed by epoch), so the O(N)
        shuffle is paid once per epoch, not once per step — the hot
        loop's cost is the O(global_batch) slice.  The cache is one
        atomically-swapped (epoch, perm) tuple, so thread-hosted
        workers sharing a sampler can never read a torn pair (worst
        case across an epoch boundary is a redundant recompute of the
        same deterministic permutation)."""
        epoch, within = divmod(step, self.batches_per_epoch)
        cached = self._perm_cache
        if cached is None or cached[0] != epoch:
            if self.shuffle:
                rng = np.random.default_rng((self.seed, epoch))
                perm = rng.permutation(self.num_examples)
            else:
                perm = np.arange(self.num_examples)
            cached = (epoch, perm)
            self._perm_cache = cached
        start = within * self.global_batch
        return cached[1][start:start + self.global_batch]

    def shard(self, step: int, index: int, world_size: int) -> np.ndarray:
        """Worker ``index``-of-``world_size``'s slice of step ``step``'s
        global batch (contiguous, equal; the slices concatenate back to
        exactly :meth:`batch_indices`)."""
        self.check_world(world_size)
        if not 0 <= index < world_size:
            raise ValueError(f"index {index} not in [0, {world_size})")
        per = self.global_batch // world_size
        batch = self.batch_indices(step)
        return batch[index * per:(index + 1) * per]


def elastic_global_batch(max_world: int, per_worker: int = 1) -> int:
    """Smallest global batch divisible by EVERY world size the run can
    shrink to (1..max_world), scaled by ``per_worker`` — lcm(1..W), the
    divisibility :meth:`GlobalBatchSampler.check_world` demands."""
    lcm = 1
    for w in range(2, max_world + 1):
        lcm = lcm * w // np.gcd(lcm, w)
    return int(lcm) * per_worker


def assert_no_overlap(samplers) -> None:
    """Test helper: shards must partition the index space (no overlap)."""
    seen = set()
    for s in samplers:
        ix = set(int(i) for i in s.indices())
        if seen & ix and s.remainder == "drop":
            raise AssertionError("overlapping shards")
        seen |= ix
