"""Deterministic synthetic datasets.

This build environment has zero network egress, and the reference assumed
pre-downloaded files in a sibling ``datasets/`` tree (reference
tensorflow2/mnist_single.py:36-39, chainer/mnist_dataset.py:21-31).  When real
files are absent the registry falls back to these generators: class-conditional
patterns with additive noise, deterministic in (seed, split), and actually
*learnable* — integration tests can assert loss decrease and >90% train
accuracy, which all-noise data would not allow.
"""

from __future__ import annotations

import numpy as np


def class_pattern_images(n: int, shape: tuple[int, ...], num_classes: int,
                         seed: int, noise: float = 0.25,
                         noise_seed: int | None = None):
    """Images = fixed per-class pattern + gaussian noise; labels balanced.

    ``seed`` determines the class patterns (the *task*); ``noise_seed`` the
    sample draw.  Train/test splits of one dataset must share ``seed`` and
    differ in ``noise_seed`` — otherwise they are different tasks and a model
    can never generalize between them.
    """
    patterns = np.random.default_rng(seed).normal(
        size=(num_classes,) + shape).astype(np.float32)
    rng = np.random.default_rng(seed if noise_seed is None else noise_seed)
    labels = np.arange(n, dtype=np.int32) % num_classes
    rng.shuffle(labels)
    images = patterns[labels] + noise * rng.normal(
        size=(n,) + shape).astype(np.float32)
    # squash into [0, 1] like pixel data so normalization code paths are real
    images = 1.0 / (1.0 + np.exp(-images))
    return images.astype(np.float32), labels


def synthetic_mnist(n_train: int = 60000, n_test: int = 10000, seed: int = 1234):
    tr = class_pattern_images(n_train, (28, 28, 1), 10, seed, noise_seed=seed + 10)
    te = class_pattern_images(n_test, (28, 28, 1), 10, seed, noise_seed=seed + 11)
    return tr, te


def synthetic_cifar10(n_train: int = 50000, n_test: int = 10000,
                      seed: int = 4321):
    tr = class_pattern_images(n_train, (32, 32, 3), 10, seed, noise_seed=seed + 10)
    te = class_pattern_images(n_test, (32, 32, 3), 10, seed, noise_seed=seed + 11)
    return tr, te


def markov_tokens(n_seqs: int, seq_len: int, vocab_size: int = 256,
                  seed: int = 7, branch: int = 4,
                  noise_seed: int | None = None):
    """Token sequences from a sparse first-order Markov chain.

    Each token has only ``branch`` plausible successors (fixed by ``seed``),
    so the distribution is genuinely learnable: a trained LM's cross-entropy
    approaches log(branch) < log(vocab), which integration tests can assert.
    """
    chain_rng = np.random.default_rng(seed)
    successors = chain_rng.integers(
        0, vocab_size, size=(vocab_size, branch)).astype(np.int32)
    rng = np.random.default_rng(seed if noise_seed is None else noise_seed)
    tokens = np.empty((n_seqs, seq_len), np.int32)
    tokens[:, 0] = rng.integers(0, vocab_size, n_seqs)
    choices = rng.integers(0, branch, size=(n_seqs, seq_len))
    for t in range(1, seq_len):
        tokens[:, t] = successors[tokens[:, t - 1], choices[:, t]]
    return tokens


def synthetic_lm(n_train: int = 4096, n_test: int = 512, seq_len: int = 128,
                 vocab_size: int = 256, seed: int = 7):
    tr = markov_tokens(n_train, seq_len, vocab_size, seed, noise_seed=seed + 10)
    te = markov_tokens(n_test, seq_len, vocab_size, seed, noise_seed=seed + 11)
    return tr, te
