"""Host-side batch loader with device prefetch.

The reference feeds devices with torch ``DataLoader(num_workers=4)`` (reference
pytorch/single_gpu.py:60-61) / Chainer ``SerialIterator`` / Keras ``fit``'s
internal pipeline.  On TPU the host must keep sub-second steps fed (SURVEY
§7.3): this loader yields numpy batches from in-memory arrays (optionally
through a `ShardedSampler`), applies vectorized augmentation on the host, and
`prefetch_to_device` pipelines H2D transfer so the next global batch is
already on device when the step finishes.
"""

from __future__ import annotations

from collections import deque
from typing import Callable

import numpy as np

from dtdl_tpu.data.sharding import ShardedSampler


class DataLoader:
    """Minibatch iterator over a dict of equal-length arrays.

    ``batch_size`` is the size of the batches this loader emits — per-host
    under multi-process DDP (the strategy assembles the global batch), global
    otherwise.  Deterministic: shuffling derives from (seed, epoch) via the
    sampler.  ``transform(rng, batch) -> batch`` runs vectorized per batch
    (augmentation, normalization).
    """

    def __init__(self, arrays: dict, batch_size: int,
                 sampler: ShardedSampler | None = None, shuffle: bool = True,
                 seed: int = 0, drop_last: bool = True,
                 transform: Callable | None = None):
        n = len(next(iter(arrays.values())))
        for k, v in arrays.items():
            if len(v) != n:
                raise ValueError(f"array {k!r} length {len(v)} != {n}")
        self.arrays = arrays
        self.batch_size = batch_size
        self.sampler = sampler or ShardedSampler(n, shuffle=shuffle, seed=seed)
        self.drop_last = drop_last
        self.transform = transform
        self._epoch = 0
        if len(self) == 0:
            raise ValueError(
                f"0 batches: {len(self.sampler)} examples with batch_size "
                f"{batch_size}" + (" and drop_last=True" if drop_last else ""))

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch
        self.sampler.set_epoch(epoch)

    def __len__(self) -> int:
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def __iter__(self):
        yield from self.iter_from(0)

    def iter_from(self, start_batch: int):
        """Iterate beginning at batch ``start_batch`` of this epoch.

        The skip happens at the index level — O(1), no skipped batch is
        materialized — which is what makes mid-epoch resume cheap
        (Trainer/Estimator restore at ``global_step % steps_per_epoch``).

        The transform rng is keyed by (seed, epoch, batch index), NOT drawn
        sequentially, so batch k gets bitwise-identical augmentations
        whether the epoch ran straight through or resumed at k — the
        replay-exact property mid-epoch resume relies on.
        """
        idx = np.asarray(self.sampler.indices())
        n_full = len(idx) // self.batch_size
        stop = n_full * self.batch_size if self.drop_last else len(idx)
        for b, start in enumerate(range(start_batch * self.batch_size, stop,
                                        self.batch_size),
                                  start=start_batch):
            take = idx[start:start + self.batch_size]
            batch = {k: v[take] for k, v in self.arrays.items()}
            if self.transform is not None:
                rng = np.random.default_rng(
                    (self.sampler.seed, self._epoch, b, 7))
                batch = self.transform(rng, batch)
            yield batch


def resume_iter(loader, skip: int):
    """Iterator over ``loader`` starting at batch ``skip`` of the current
    epoch — O(1) via ``iter_from`` when the loader supports it, else an
    enumerate-filter fallback (still consumes the skipped batches).  The
    single implementation of mid-epoch resume used by Trainer and
    Estimator."""
    if not skip:
        return iter(loader)
    if hasattr(loader, "iter_from"):
        return loader.iter_from(skip)
    return (b for j, b in enumerate(iter(loader)) if j >= skip)


class LimitBatches:
    """First-n-batches view of a loader (e.g. Caffe's test_iter, TF1's
    evaluate(steps=N)).  ``n=0`` means no limit."""

    def __init__(self, loader, n: int):
        self.loader, self.n = loader, n

    @property
    def batch_size(self):
        return self.loader.batch_size

    def __iter__(self):
        import itertools
        it = iter(self.loader)
        return itertools.islice(it, self.n) if self.n else it


def prefetch_to_device(iterator, put: Callable, depth: int = 2):
    """Pipeline ``put`` (e.g. ``strategy.shard_batch``) ahead of consumption.

    JAX dispatch is async, so issuing the H2D transfer for batch N+1 before
    batch N's step completes overlaps transfer with compute — the role of
    torch's ``num_workers`` prefetch (reference pytorch/single_gpu.py:21).
    """
    buf = deque()
    for item in iterator:
        buf.append(put(item))
        if len(buf) >= depth:
            yield buf.popleft()
    while buf:
        yield buf.popleft()


# ---- augmentation (vectorized host-side transforms) -------------------------

def cifar10_train_transform(mean, std):
    """Random crop (pad 4) + horizontal flip + normalize, vectorized.

    The reference's torchvision transform stack (reference
    pytorch/single_gpu.py:51-55: RandomCrop(32, padding=4),
    RandomHorizontalFlip, ToTensor, Normalize).
    """
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)

    def transform(rng, batch):
        x = batch["image"]
        b, h, w, c = x.shape
        padded = np.pad(x, ((0, 0), (4, 4), (4, 4), (0, 0)), mode="constant")
        ys = rng.integers(0, 9, b)
        xs = rng.integers(0, 9, b)
        # gather-based vectorized crop
        row_idx = ys[:, None] + np.arange(h)[None, :]
        col_idx = xs[:, None] + np.arange(w)[None, :]
        out = padded[np.arange(b)[:, None, None], row_idx[:, :, None],
                     col_idx[:, None, :], :]
        flip = rng.random(b) < 0.5
        out[flip] = out[flip, :, ::-1, :]
        out = (out - mean) / std
        return {**batch, "image": out.astype(np.float32)}

    return transform


def normalize_transform(mean, std):
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)

    def transform(rng, batch):
        del rng
        x = (batch["image"] - mean) / std
        return {**batch, "image": x.astype(np.float32)}

    return transform
