from dtdl_tpu.data.datasets import (  # noqa: F401
    load_dataset, load_mnist, load_cifar10, normalize_cifar10,
    CIFAR10_MEAN, CIFAR10_STD,
)
from dtdl_tpu.data.sharding import ShardedSampler, scatter_arrays  # noqa: F401
from dtdl_tpu.data.loader import (  # noqa: F401
    DataLoader, prefetch_to_device, cifar10_train_transform,
    normalize_transform,
)
from dtdl_tpu.data.idx import read_idx, load_idx_pair  # noqa: F401
