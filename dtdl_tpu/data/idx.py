"""Vectorized IDX (MNIST) file parser.

The reference parses IDX files one byte at a time in pure Python —
``ord(f.read(1))`` over N×784 bytes (reference chainer/mnist_helper.py:24-27),
which takes minutes for MNIST.  This is the vectorized replacement: one
``np.frombuffer`` over the whole payload, ~1000x faster, same npz caching
shape as the reference's ``download.cache_or_load_file`` flow (reference
chainer/mnist_dataset.py:33-38).
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

_IDX_DTYPES = {
    0x08: np.uint8, 0x09: np.int8, 0x0B: np.dtype(">i2"),
    0x0C: np.dtype(">i4"), 0x0D: np.dtype(">f4"), 0x0E: np.dtype(">f8"),
}


def read_idx(path: str) -> np.ndarray:
    """Parse one IDX file (optionally gzipped) into a numpy array."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        zero, dtype_code, ndim = struct.unpack(">HBB", f.read(4))
        if zero != 0 or dtype_code not in _IDX_DTYPES:
            raise ValueError(f"{path}: not an IDX file (magic {zero:#x} "
                             f"dtype {dtype_code:#x})")
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=_IDX_DTYPES[dtype_code])
    if data.size != int(np.prod(shape)):
        raise ValueError(f"{path}: payload {data.size} != shape {shape}")
    return data.reshape(shape)


def load_idx_pair(images_path: str, labels_path: str):
    """Load an (images, labels) IDX pair, validated to match in length."""
    images = read_idx(images_path)
    labels = read_idx(labels_path)
    if images.shape[0] != labels.shape[0]:
        raise ValueError(
            f"images {images.shape[0]} != labels {labels.shape[0]}")
    return images, labels.astype(np.int32)


def cache_npz(cache_path: str, maker) -> dict:
    """Parse-once npz caching (shape of reference chainer/mnist_dataset.py:33-38)."""
    if os.path.exists(cache_path):
        with np.load(cache_path) as z:
            return dict(z)
    arrays = maker()
    os.makedirs(os.path.dirname(cache_path) or ".", exist_ok=True)
    np.savez_compressed(cache_path + ".tmp.npz", **arrays)
    os.replace(cache_path + ".tmp.npz", cache_path)
    return arrays
