"""Dataset registry: MNIST, CIFAR-10, synthetic.

One loader covering the reference's three data paths — the Keras npz load
(reference tensorflow2/mnist_single.py:34-47), the Chainer IDX→npz cache
(reference chainer/mnist_dataset.py:8-38), and torchvision CIFAR-10 (reference
pytorch/distributed_data_parallel.py:85-86) — behind a single
``load_dataset(name, root)`` with a dataset-root flag and a deterministic
synthetic fallback when files are missing (this replaces the reference's
hard-coded sibling-path assumptions, SURVEY §2.4).

Returned arrays are always NHWC float32 in [0,1] with int32 labels:
``(train_images, train_labels), (test_images, test_labels)``.
"""

from __future__ import annotations

import logging
import os
import pickle

import numpy as np

from dtdl_tpu.data import idx, synthetic

log = logging.getLogger("dtdl_tpu")

# standard IDX file names (and the reference's variants)
_MNIST_FILES = {
    "train_images": ("train-images-idx3-ubyte.gz", "train-images.idx3-ubyte.gz"),
    "train_labels": ("train-labels-idx1-ubyte.gz", "train-labels.idx1-ubyte.gz"),
    "test_images": ("t10k-images-idx3-ubyte.gz", "t10k-images.idx3-ubyte.gz"),
    "test_labels": ("t10k-labels-idx1-ubyte.gz", "t10k-labels.idx1-ubyte.gz"),
}


def _find(root: str, names) -> str | None:
    for n in names:
        for cand in (os.path.join(root, n), os.path.join(root, n[:-3])):
            if os.path.exists(cand):
                return cand
    return None


def load_mnist(root: str = "./datasets", flatten: bool = False):
    """MNIST from IDX/gz or npz under ``root``/mnist; synthetic fallback."""
    mdir = os.path.join(root, "mnist")
    npz = os.path.join(mdir, "mnist.npz")
    paths = {k: _find(mdir, v) for k, v in _MNIST_FILES.items()}
    if all(paths.values()):
        def maker():
            tr_i, tr_l = idx.load_idx_pair(paths["train_images"],
                                           paths["train_labels"])
            te_i, te_l = idx.load_idx_pair(paths["test_images"],
                                           paths["test_labels"])
            return {"x_train": tr_i, "y_train": tr_l,
                    "x_test": te_i, "y_test": te_l}
        z = idx.cache_npz(os.path.join(mdir, "mnist_cache.npz"), maker)
        train = (z["x_train"], z["y_train"])
        test = (z["x_test"], z["y_test"])
    elif os.path.exists(npz):
        with np.load(npz) as z:  # keras layout (reference mnist_single.py:36-41)
            train = (z["x_train"], z["y_train"])
            test = (z["x_test"], z["y_test"])
    else:
        log.warning(
            "=== SYNTHETIC DATA IN USE === MNIST files not found under %s; "
            "training on DETERMINISTIC SYNTHETIC images. Loss/accuracy are "
            "NOT comparable to real MNIST.", mdir)
        (tr_i, tr_l), (te_i, te_l) = synthetic.synthetic_mnist()
        train, test = (tr_i, tr_l), (te_i, te_l)

    def prep(images, labels):
        images = np.asarray(images, np.float32)
        if images.max() > 1.5:  # raw 0-255 pixels
            images = images / 255.0
        if images.ndim == 3:
            images = images[..., None]
        if flatten:
            images = images.reshape(images.shape[0], -1)
        return images, np.asarray(labels, np.int32)

    return prep(*train), prep(*test)


CIFAR10_URL = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"
CIFAR10_MD5 = "c58f30108f718f92721af3b95e74349a"  # torchvision's tgz_md5


def download_cifar10(root: str, url: str | None = None,
                     md5: str | None = None) -> str:
    """Fetch, checksum-verify, and extract the CIFAR-10 python batches.

    Parity with the reference's ``CIFAR10(root, download=True)``
    (reference pytorch/single_gpu.py:57,
    pytorch/distributed_data_parallel.py:85): idempotent (skips the fetch
    when the verified archive is already present), MD5-checked with the
    same constant torchvision pins, atomic (.part rename).  Returns the
    extracted ``cifar-10-batches-py`` directory.
    """
    import hashlib
    import shutil
    import tarfile
    import urllib.request

    url = url or CIFAR10_URL
    md5 = md5 or CIFAR10_MD5
    os.makedirs(root, exist_ok=True)
    tgz = os.path.join(root, "cifar-10-python.tar.gz")
    if not os.path.exists(tgz):
        log.info("downloading CIFAR-10 from %s to %s", url, tgz)
        tmp = tgz + ".part"
        with urllib.request.urlopen(url, timeout=120) as r, \
                open(tmp, "wb") as f:
            shutil.copyfileobj(r, f)
        os.replace(tmp, tgz)
    h = hashlib.md5()
    with open(tgz, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    if h.hexdigest() != md5:
        os.remove(tgz)
        raise IOError(f"CIFAR-10 archive checksum mismatch: got "
                      f"{h.hexdigest()}, want {md5} — corrupt download "
                      f"removed, retry")
    # atomic extraction: unpack into a scratch dir, verify every batch
    # file, then one os.replace — an interrupted run can never leave a
    # half-extracted cifar-10-batches-py that later loads partially
    scratch = tgz + ".extract"
    shutil.rmtree(scratch, ignore_errors=True)
    with tarfile.open(tgz, "r:gz") as tf:
        tf.extractall(scratch, filter="data")
    src = os.path.join(scratch, "cifar-10-batches-py")
    missing = [n for n in _CIFAR_BATCHES
               if not os.path.exists(os.path.join(src, n))]
    if missing:
        shutil.rmtree(scratch, ignore_errors=True)
        raise IOError(f"archive extracted but missing {missing}")
    out = os.path.join(root, "cifar-10-batches-py")
    shutil.rmtree(out, ignore_errors=True)   # replace any partial leftover
    os.replace(src, out)
    shutil.rmtree(scratch, ignore_errors=True)
    return out


_CIFAR_BATCHES = [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"]


def _download_locked(root: str, heartbeat: float = 15.0,
                     stale_after: float = 120.0) -> None:
    """download_cifar10 guarded by an exclusive lockfile: the winner
    fetches, everyone else sharing this filesystem polls for the result.

    **Liveness, not a wall clock**: the winner touches the lock's mtime
    every ``heartbeat`` seconds from a daemon thread, and pollers wait for
    as long as they keep *observing the mtime change* (judged against a
    local monotonic clock, so cross-host clock skew and NFS attribute-cache
    lag cannot make a live lock look stale) — a live download can
    legitimately run for hours and every rank still converges on the same
    real dataset (no poller ever gives up on a live winner and silently
    trains on synthetic data while the winner trains on real CIFAR-10).
    Only a lock whose heartbeat has stopped for ``stale_after`` of local
    observation (a hard-killed owner) is reaped.  Every poller exit —
    winner finished, lock reaped here or by a peer — loops back into
    acquisition, where the already-downloaded check under the lock decides
    whether any work remains: a transiently-vanished lock can never strand
    one rank on the synthetic fallback while its peers get real data.
    Reap removal goes through rename-then-unlink, which narrows (but does
    not close) the check-to-remove race against a fresh lock re-created at
    the same path; the fallout of losing that race is a duplicate download
    attempt, and the checksum + atomic extract keep the result correct.
    """
    import threading
    import time
    os.makedirs(root, exist_ok=True)
    lock = os.path.join(root, ".cifar10.download.lock")

    def _reap():
        try:
            victim = f"{lock}.stale.{os.getpid()}.{time.time_ns()}"
            os.rename(lock, victim)   # narrows (not closes) the race
            os.unlink(victim)
            log.warning("removed stale dataset download lock %s", lock)
        except OSError:
            pass   # already gone / lost the rename race

    while True:
        try:
            fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            break   # winner
        except FileExistsError:
            pass
        # Loser: poll while the winner's heartbeat keeps the lock's mtime
        # *changing*.  Staleness is judged by locally-observed mtime change
        # against a local monotonic clock — never by (now - mtime), which
        # compares this host's wall clock against an mtime stamped by the
        # winner's host (cross-host clock skew or NFS attribute-cache lag
        # would reap a live lock).  The cost: an orphan lock takes
        # ``stale_after`` of observation before it is reaped.
        last_mtime = None
        last_change = time.monotonic()
        stale = False
        while True:
            try:
                m = os.path.getmtime(lock)
            except OSError:
                break   # lock vanished: winner finished OR another poller
                        # reaped it — re-enter acquisition; a finished
                        # download is caught under the lock (dir re-scan)
            if m != last_mtime:
                last_mtime, last_change = m, time.monotonic()
            elif time.monotonic() - last_change > stale_after:
                stale = True
                break   # heartbeat stopped: hard-killed owner
            time.sleep(1.0)
        if stale:
            _reap()
        continue    # retry acquisition; the dataset check below decides
                    # whether any downloading is actually left to do
    stop = threading.Event()

    def _beat():
        while not stop.wait(heartbeat):
            try:
                os.utime(lock)
            except OSError:
                return
    beater = threading.Thread(target=_beat, daemon=True)
    beater.start()
    try:
        os.close(fd)
        if _find_cifar10_dir(root) is None:
            download_cifar10(root)
    finally:
        stop.set()
        beater.join()
        try:
            os.unlink(lock)
        except OSError:
            pass


def _find_cifar10_dir(root: str) -> str | None:
    """A directory only counts when EVERY batch file is present — a partial
    (interrupted) extraction must trigger re-download, not a late crash."""
    for cand in ("cifar-10-batches-py", "cifar10", "."):
        d = os.path.join(root, cand)
        if all(os.path.exists(os.path.join(d, n)) for n in _CIFAR_BATCHES):
            return d
    return None


def load_cifar10(root: str = "./datasets", download: bool = True):
    """CIFAR-10 from the python pickle batches.

    When the batches are missing and ``download=True`` (the reference's
    default behavior), they are fetched and checksum-verified first —
    **leader-gated**: in a multi-process world only process 0 downloads
    and extracts, everyone else waits at a barrier and re-scans, so ranks
    sharing a dataset root never race on the archive (the same
    is_leader/barrier discipline the checkpointer uses).  Only if the
    download also fails (e.g. no network egress) does the LOUD
    deterministic synthetic fallback engage — it never silently stands in
    for the real dataset.
    """
    from dtdl_tpu.runtime.bootstrap import barrier, is_leader

    if os.environ.get("DTDL_OFFLINE"):
        download = False     # CI / air-gapped: never touch the network
    cdir = _find_cifar10_dir(root)
    if download:
        # every process takes this path (the barrier must be collective
        # even for ranks that already see the extracted directory)
        if cdir is None and is_leader():
            try:
                download_cifar10(root)
            except Exception as e:  # no egress / bad mirror: loud fallback
                log.error("CIFAR-10 download failed (%s: %s)",
                          type(e).__name__, e)
        barrier("cifar10_download")
        cdir = _find_cifar10_dir(root)
        if cdir is None:
            # still missing: either per-host local disks (the leader's
            # download landed on ITS filesystem, not ours) or the leader's
            # fetch failed transiently.  EVERY process — leader included —
            # retries into its own root, serialized per root by an
            # exclusive lockfile, so ranks converge on the same outcome
            # (all real data, or all loudly synthetic).
            try:
                _download_locked(root)
            except Exception as e:
                log.error("CIFAR-10 local download failed (%s: %s)",
                          type(e).__name__, e)
            cdir = _find_cifar10_dir(root)
    if cdir is None:
        log.warning(
            "=== SYNTHETIC DATA IN USE === CIFAR-10 not found under %s and "
            "download failed/disabled; training on DETERMINISTIC SYNTHETIC "
            "images. Loss/accuracy are NOT comparable to real CIFAR-10 — "
            "place cifar-10-python.tar.gz under the dataset root or enable "
            "network access.", root)
        (tr_i, tr_l), (te_i, te_l) = synthetic.synthetic_cifar10()
        return (tr_i, tr_l), (te_i, te_l)

    def read_batch(name):
        with open(os.path.join(cdir, name), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        images = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return images, np.asarray(d[b"labels"], np.int32)

    parts = [read_batch(f"data_batch_{i}") for i in range(1, 6)]
    tr_i = np.concatenate([p[0] for p in parts]).astype(np.float32) / 255.0
    tr_l = np.concatenate([p[1] for p in parts])
    te_i, te_l = read_batch("test_batch")
    te_i = te_i.astype(np.float32) / 255.0
    return (tr_i, tr_l), (te_i, te_l)


CIFAR10_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR10_STD = np.array([0.2470, 0.2435, 0.2616], np.float32)


def normalize_cifar10(images: np.ndarray) -> np.ndarray:
    """Channel normalization (reference pytorch/single_gpu.py:51-55 uses the
    torchvision Normalize transform with the CIFAR-10 statistics)."""
    return (images - CIFAR10_MEAN) / CIFAR10_STD


def load_dataset(name: str, root: str = "./datasets", **kwargs):
    if name == "mnist":
        return load_mnist(root, **kwargs)
    if name == "cifar10":
        return load_cifar10(root, **kwargs)
    if name == "synthetic":
        return synthetic.synthetic_mnist(**kwargs)
    if name == "synthetic_lm":
        return synthetic.synthetic_lm(**kwargs)
    raise ValueError(f"unknown dataset {name!r}")
