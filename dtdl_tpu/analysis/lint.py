"""The repo linter: AST rules + suppression resolution over a file set.

This is the static half of the audit subsystem (the dynamic half —
jaxpr/HLO program audits — lives in jaxpr_audit.py / hlo_audit.py).  It
parses every ``.py`` under the given paths, runs the per-file and
cross-file rules from dtdl_tpu/analysis/rules/, resolves
``# audit: ok[rule] reason`` suppressions, and returns the surviving
findings.  Pure ``ast`` — nothing is imported or executed, so linting
the whole package takes well under a second and runs inside tier-1
(tests/test_analysis_gate.py) and as the CLI gate (scripts/audit.py).
"""

from __future__ import annotations

import ast
import os
import pathlib

from dtdl_tpu.analysis import rules as rules_pkg
from dtdl_tpu.analysis.findings import (Finding, apply_suppressions,
                                        render_report, scan_suppressions)
from dtdl_tpu.analysis.rules import ParsedModule

__all__ = ["lint_paths", "rule_docs", "render_report", "Finding"]

_SKIP_DIRS = {"__pycache__", ".git", ".claude"}

#: rule ids reported by the PROGRAM auditors (jaxpr_audit / hlo_audit /
#: contracts) and by the suppression machinery itself — part of the one
#: documented catalog.  Program-audit findings are keyed by program
#: name, not file:line: they are resolved by fixing the program or an
#: intentional ``--rebase``, never by inline comments.
EXTRA_RULES = {
    "jaxpr-callback": "host callback traced into a program (a "
                      "device->host round-trip every execution)",
    "jaxpr-const-capture": "oversized constant captured by closure "
                           "(defeats donation/sharding)",
    "hlo-undonated": "expected-donated input not aliased in the "
                     "optimized module (copied every call)",
    "hlo-host-transfer": "compiled program talks to the host "
                         "(callback custom-call / infeed / outfeed)",
    "census-drift": "program collective census / donation diverged "
                    "from the checked-in baseline",
    "lint-syntax": "unparseable source file",
    "suppress-no-reason": "suppression without a justification",
    "suppress-stale": "suppression that matches no finding",
    "suppress-unknown": "suppression naming a rule id that does not "
                        "exist",
}


def rule_docs() -> dict:
    """``{rule_id: one-line doc}`` — the full rule catalog (AST rules +
    program-audit + meta rules; README mirrors it,
    ``scripts/audit.py --list-rules`` prints it)."""
    return dict(sorted({**rules_pkg.registry(), **EXTRA_RULES}.items()))


def _iter_files(paths):
    for p in paths:
        p = pathlib.Path(p)
        if p.is_file() and p.suffix == ".py":
            yield p
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if not any(part in _SKIP_DIRS for part in f.parts):
                    yield f


def _rel(path: pathlib.Path, root) -> str:
    try:
        return path.resolve().relative_to(
            pathlib.Path(root).resolve()).as_posix()
    except ValueError:
        return path.as_posix()


def _iter_sources(paths, root=None):
    """Yield ``(repo_relative_path, pathlib.Path)`` for every unique
    .py under ``paths`` — the file census; parsing (and syntax-error
    handling) happens in :func:`lint_paths`."""
    root = root or os.getcwd()
    seen = set()
    for f in _iter_files(paths):
        rel = _rel(f, root)
        if rel in seen:
            continue
        seen.add(rel)
        yield rel, f


def lint_paths(paths, *, root=None, only_rules=None) -> list[Finding]:
    """Lint ``paths`` (files or directories); returns unsuppressed
    findings.  ``only_rules`` restricts to a rule-id subset (prefix
    match, like suppressions) — for tests and targeted CLI runs."""
    findings: list[Finding] = []
    sups = []
    modules = []
    for rel, f in _iter_sources(paths, root=root):
        try:
            source = f.read_text()
            tree = ast.parse(source, filename=rel)
        except (SyntaxError, UnicodeDecodeError) as e:
            findings.append(Finding(
                "lint-syntax", rel, getattr(e, "lineno", 0) or 0,
                f"unparseable: {e.__class__.__name__}: {e}"))
            continue
        mod = ParsedModule(path=rel, tree=tree, source=source)
        modules.append(mod)
        sups.extend(scan_suppressions(rel, source))
    for mod in modules:
        for chk in rules_pkg.file_checks():
            findings.extend(chk(mod))
    for chk in rules_pkg.repo_checks():
        findings.extend(chk(modules))
    out = apply_suppressions(findings, sups,
                             known_rules=set(rule_docs()))
    if only_rules is not None:
        # post-filter: suppression resolution always runs over the full
        # rule set (so staleness is judged against reality), then the
        # caller's rule subset selects what to report
        only = tuple(only_rules)
        out = [f for f in out
               if any(f.rule == r or f.rule.startswith(r + "-")
                      for r in only)]
    return out
