"""Trace hygiene: no wall clocks or host RNG inside traced functions.

A ``time.time()`` or ``np.random`` call inside a function that gets
jitted runs ONCE, at trace time, and bakes its value into the compiled
program as a constant — every subsequent step reuses the stale
timestamp / the same "random" draw.  It never errors; it just silently
measures nothing and decorrelates nothing (the classic jax footgun).
Host timing belongs outside the program; randomness inside one goes
through ``jax.random`` keys threaded as arguments.

The rule finds functions that are jit/shard_map targets in the same
module — ``jax.jit(f)``, ``@jax.jit``, ``@partial(jax.jit, ...)``,
``jax.shard_map(f, ...)``, and the strategy idiom
``*.compile/compile_eval/compile_predict(f)`` — and flags, anywhere in
their bodies (nested defs included):

* ``trace-host-time`` — ``time.time/perf_counter/monotonic/
  process_time`` and ``datetime.now``.
* ``trace-host-rng``  — ``np.random.*`` / ``random.*`` draws.
"""

from __future__ import annotations

import ast

from dtdl_tpu.analysis.findings import Finding
from dtdl_tpu.analysis.rules import dotted

RULES = {
    "trace-host-time": "host clock call inside a traced function "
                       "(bakes a constant at trace time)",
    "trace-host-rng": "host RNG inside a traced function (same draw "
                      "every step; thread a jax.random key instead)",
}

_TIME = ("time.time", "time.perf_counter", "time.monotonic",
         "time.process_time", "datetime.now", "datetime.datetime.now")
_RNG_PREFIXES = ("np.random.", "numpy.random.", "random.")


def _traced_names(tree) -> set[str]:
    """Names of functions this module passes to jit/shard_map/compile."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = dotted(node.func)
            is_wrap = fn in ("jax.jit", "jax.shard_map", "pjit",
                             "jax.pjit", "jax.make_jaxpr")
            is_partial_jit = (fn in ("partial", "functools.partial")
                              and node.args
                              and dotted(node.args[0]) == "jax.jit")
            is_compile = (isinstance(node.func, ast.Attribute)
                          and node.func.attr.startswith("compile"))
            if (is_wrap or is_partial_jit or is_compile):
                args = node.args[1:] if is_partial_jit else node.args
                for a in args:
                    if isinstance(a, ast.Name):
                        names.add(a.id)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if dotted(dec) == "jax.jit" or (
                        isinstance(dec, ast.Call)
                        and dotted(dec.func) in ("partial",
                                                 "functools.partial")
                        and dec.args
                        and dotted(dec.args[0]) == "jax.jit"):
                    names.add(node.name)
    return names


def check(mod) -> list[Finding]:
    traced = _traced_names(mod.tree)
    if not traced:
        return []
    out = []
    for node in ast.walk(mod.tree):
        if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name in traced):
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                name = dotted(sub.func)
                if name in _TIME:
                    out.append(Finding(
                        "trace-host-time", mod.path, sub.lineno,
                        f"{name}() inside traced '{node.name}' is a "
                        f"trace-time constant"))
                elif any(name.startswith(p) for p in _RNG_PREFIXES):
                    out.append(Finding(
                        "trace-host-rng", mod.path, sub.lineno,
                        f"{name}() inside traced '{node.name}' draws "
                        f"once at trace time"))
    return out
