"""Cross-file catalog consistency: emitters vs their single source of
truth.

Two catalogs in this repo exist precisely so names cannot drift — and
both drifted anyway before they were audited (trainer_rollback lagged
EVENT_CATALOG for four PRs).  These rules re-prove the consistency on
every lint run, AST-only:

* ``obs-*`` — every literal name passed to ``.span( / .event( /
  .instant(`` anywhere in the linted tree must appear in
  ``SPAN_CATALOG`` / ``EVENT_CATALOG`` (dtdl_tpu/obs/trace.py), every
  catalog entry must have an emitter, and dynamic (f-string) names are
  banned except the one sanctioned ``f"replica_{state}"`` family.
* ``metrics-window-*`` — in any class that declares a
  ``_WINDOW_COUNTERS`` frozenset next to a ``summary()`` (ServeMetrics,
  FleetMetrics), every summary field that reads a ``+=``-incremented
  attribute is a monotonic counter and MUST be in the frozenset (or the
  exporter's window deltas silently report a cumulative value as a
  rate), and every frozenset entry must still be a summary key.

Both run only when the linted file set contains the defining module
(obs/trace.py, a ``_WINDOW_COUNTERS`` class) — linting a subtree that
lacks the catalog cannot prove anything about it.
"""

from __future__ import annotations

import ast

from dtdl_tpu.analysis.findings import Finding
from dtdl_tpu.analysis.rules import dotted

RULES = {
    "obs-span-uncataloged": "span name emitted but missing from "
                            "SPAN_CATALOG",
    "obs-event-uncataloged": "event name emitted but missing from "
                             "EVENT_CATALOG",
    "obs-catalog-stale": "catalog entry with no emitter anywhere",
    "obs-event-dynamic": "un-auditable dynamic span/event name "
                         "(literal names only)",
    "metrics-window-counter": "monotonic summary counter missing from "
                              "_WINDOW_COUNTERS (window deltas would "
                              "re-report the cumulative value)",
    "metrics-window-stale": "_WINDOW_COUNTERS entry that is not a "
                            "summary field",
}

#: the one sanctioned dynamic emitter: f"replica_{state}" over the
#: health-machine states — covers every replica_* catalog entry
_DYNAMIC_OK = "replica_{state}"


def _frozenset_literal(node) -> set | None:
    """The string members of a ``frozenset({...})`` literal, else None."""
    if (isinstance(node, ast.Call) and dotted(node.func) == "frozenset"
            and node.args and isinstance(node.args[0], ast.Set)):
        elems = node.args[0].elts
        if all(isinstance(e, ast.Constant) and isinstance(e.value, str)
               for e in elems):
            return {e.value for e in elems}
    return None


def _joined_str_template(node: ast.JoinedStr) -> str:
    """f-string reassembled with ``{x}`` placeholders."""
    parts = []
    for v in node.values:
        if isinstance(v, ast.Constant):
            parts.append(str(v.value))
        elif isinstance(v, ast.FormattedValue):
            parts.append("{%s}" % (dotted(v.value) or "?"))
    return "".join(parts)


def _check_obs(modules) -> list[Finding]:
    trace_mod = next((m for m in modules
                      if m.posix.endswith("dtdl_tpu/obs/trace.py")), None)
    if trace_mod is None:
        return []
    catalogs: dict[str, tuple[set, int]] = {}
    for node in ast.walk(trace_mod.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id in ("SPAN_CATALOG",
                                           "EVENT_CATALOG"):
            members = _frozenset_literal(node.value)
            if members is not None:
                catalogs[node.targets[0].id] = (members, node.lineno)
    if len(catalogs) != 2:
        return [Finding("obs-catalog-stale", trace_mod.path, 0,
                        "SPAN_CATALOG/EVENT_CATALOG are no longer "
                        "auditable frozenset literals")]
    span_cat, span_line = catalogs["SPAN_CATALOG"]
    event_cat, event_line = catalogs["EVENT_CATALOG"]
    # the stale direction (catalog entry with no emitter) is only
    # provable over the WHOLE package — emitters live in serve/, train/,
    # resil/ — so it runs only when the package root is in the file set;
    # a subtree lint (scripts/audit.py dtdl_tpu/obs) still proves the
    # uncataloged direction for the emitters it can see
    full_package = any(m.posix.endswith("dtdl_tpu/__init__.py")
                       for m in modules)

    out = []
    spans: dict[str, tuple] = {}
    events: dict[str, tuple] = {}
    for mod in modules:
        if "dtdl_tpu/" not in mod.posix:
            continue            # emitters live in the package only
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("span", "event", "instant")
                    and node.args):
                continue
            arg = node.args[0]
            book = spans if node.func.attr == "span" else events
            if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                            str):
                book[arg.value] = (mod.path, node.lineno)
            elif isinstance(arg, ast.JoinedStr):
                tmpl = _joined_str_template(arg)
                if tmpl == _DYNAMIC_OK:
                    for name in event_cat:
                        if name.startswith("replica_"):
                            book[name] = (mod.path, node.lineno)
                else:
                    out.append(Finding(
                        "obs-event-dynamic", mod.path, node.lineno,
                        f"dynamic {node.func.attr} name {tmpl!r} — "
                        f"use a literal or extend the sanctioned set"))
            # non-literal Name/Attribute first args are API plumbing
            # (Tracer internals forwarding a name), not emitters

    for name, (path, line) in sorted(spans.items()):
        if name not in span_cat:
            out.append(Finding("obs-span-uncataloged", path, line,
                               f"span {name!r} missing from "
                               f"SPAN_CATALOG"))
    for name, (path, line) in sorted(events.items()):
        if name not in event_cat:
            out.append(Finding("obs-event-uncataloged", path, line,
                               f"event {name!r} missing from "
                               f"EVENT_CATALOG"))
    if full_package:
        for name in sorted(span_cat - set(spans)):
            out.append(Finding("obs-catalog-stale", trace_mod.path,
                               span_line,
                               f"SPAN_CATALOG entry {name!r} has no "
                               f"emitter"))
        for name in sorted(event_cat - set(events)):
            out.append(Finding("obs-catalog-stale", trace_mod.path,
                               event_line,
                               f"EVENT_CATALOG entry {name!r} has no "
                               f"emitter"))
    return out


def _unwrap_round(node):
    """``round(x, n)`` -> ``x`` (summary fields often round floats)."""
    if (isinstance(node, ast.Call) and dotted(node.func) == "round"
            and node.args):
        return node.args[0]
    return node


def _self_attr(node) -> str:
    node = _unwrap_round(node)
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return ""


def _check_windows(modules) -> list[Finding]:
    out = []
    for mod in modules:
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            counters = None
            counters_line = 0
            summary = None
            for item in cls.body:
                if isinstance(item, ast.Assign) and len(item.targets) \
                        == 1 and isinstance(item.targets[0], ast.Name) \
                        and item.targets[0].id == "_WINDOW_COUNTERS":
                    counters = _frozenset_literal(item.value)
                    counters_line = item.lineno
                if isinstance(item, ast.FunctionDef) \
                        and item.name == "summary":
                    summary = item
            if counters is None or summary is None:
                continue
            # every `self.x += ...` anywhere in the class is a counter
            incremented = {
                n.target.attr for n in ast.walk(cls)
                if isinstance(n, ast.AugAssign)
                and isinstance(n.op, ast.Add)
                and _self_attr(n.target)}
            keys: dict[str, tuple[int, str]] = {}
            for node in ast.walk(summary):
                if isinstance(node, ast.Dict):
                    for k, v in zip(node.keys, node.values):
                        if isinstance(k, ast.Constant) \
                                and isinstance(k.value, str):
                            keys[k.value] = (k.lineno, _self_attr(v))
            for key, (line, attr) in sorted(keys.items()):
                if attr and attr in incremented and key not in counters:
                    out.append(Finding(
                        "metrics-window-counter", mod.path, line,
                        f"{cls.name}.summary()['{key}'] reads "
                        f"+=-counter self.{attr} but is not in "
                        f"_WINDOW_COUNTERS"))
            for name in sorted(counters - set(keys)):
                out.append(Finding(
                    "metrics-window-stale", mod.path, counters_line,
                    f"{cls.name}._WINDOW_COUNTERS entry {name!r} is "
                    f"not a summary field"))
    return out


def check_repo(modules) -> list[Finding]:
    return _check_obs(modules) + _check_windows(modules)
