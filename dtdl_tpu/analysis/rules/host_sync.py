"""The host-sync ban: no device reads on step/decode dispatch paths.

PR 1 removed every per-step host↔device round-trip from the training
loops and PR 2's scheduler kept decode dispatch sync-free; these rules
make that discipline machine-checked.  In a hot-path module (see
``rules.HOT_PATH_PREFIXES``) each of the following is a finding:

* ``host-sync-get``     — ``jax.device_get(...)``: a blocking transfer.
* ``host-sync-block``   — ``.block_until_ready()``: a pure wait.
* ``host-sync-item``    — ``.item()``: scalar read; the classic hidden
  sync (``float(loss)`` and friends compile down to this).
* ``host-sync-float``   — ``float(...)`` / ``int(...)`` / ``bool(...)``
  applied directly to a ``jnp.``/``jax.`` expression.
* ``host-sync-asarray`` — ``np.asarray(...)`` / ``np.array(...)``: on a
  device array this is a device_get in numpy clothing.  (``jnp.asarray``
  is host→device and dispatches asynchronously — not flagged.)

Sanctioned syncs — the metrics-queue drain, the one deliberate
device_get of the KV handoff, API-entry conversion of caller-supplied
host data — are either drain-point modules (``rules.DRAIN_MODULES``) or
carry an inline ``# audit: ok[...]`` with the justification, so every
exception is visible where it happens.
"""

from __future__ import annotations

import ast

from dtdl_tpu.analysis.findings import Finding
from dtdl_tpu.analysis.rules import dotted, is_hot

RULES = {
    "host-sync-get": "jax.device_get on a hot path (blocking transfer)",
    "host-sync-block": ".block_until_ready() on a hot path",
    "host-sync-item": ".item() scalar read on a hot path",
    "host-sync-float": "float()/int()/bool() of a jax value on a hot "
                       "path (hidden .item())",
    "host-sync-asarray": "np.asarray/np.array on a hot path (device_get "
                         "in numpy clothing)",
}

_ASARRAY = ("np.asarray", "numpy.asarray", "np.array", "numpy.array")
_CASTS = ("float", "int", "bool")


def _is_jax_rooted(node) -> bool:
    """Does this expression chain root at a jax/jnp name (so a host
    cast of it forces a device read)?"""
    while isinstance(node, (ast.Attribute, ast.Call, ast.Subscript)):
        node = (node.func if isinstance(node, ast.Call)
                else node.value)
    return isinstance(node, ast.Name) and node.id in ("jnp", "jax", "lax")


def check(mod) -> list[Finding]:
    if not is_hot(mod):
        return []
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        if name == "jax.device_get":
            out.append(Finding("host-sync-get", mod.path, node.lineno,
                               "jax.device_get on a hot path"))
        elif name in _ASARRAY:
            out.append(Finding("host-sync-asarray", mod.path, node.lineno,
                               f"{name} on a hot path"))
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr == "block_until_ready"):
            out.append(Finding("host-sync-block", mod.path, node.lineno,
                               ".block_until_ready() on a hot path"))
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr == "item" and not node.args
              and not node.keywords):
            out.append(Finding("host-sync-item", mod.path, node.lineno,
                               ".item() on a hot path"))
        elif (isinstance(node.func, ast.Name)
              and node.func.id in _CASTS and len(node.args) == 1
              and _is_jax_rooted(node.args[0])):
            out.append(Finding(
                "host-sync-float", mod.path, node.lineno,
                f"{node.func.id}() of a jax expression on a hot path"))
    return out
