"""Repo-specific lint rules over Python ASTs.

Each rule module exposes ``check(module)`` (per-file) or
``check_repo(modules)`` (cross-file) returning
:class:`~dtdl_tpu.analysis.findings.Finding` lists.  The registry below
is the single list the driver (dtdl_tpu/analysis/lint.py) runs and the
``--list-rules`` catalog is generated from; rule ids live with their
implementations.

Shared configuration — which modules count as *hot paths* (the
step/decode dispatch code where a stray host sync is a per-token stall,
PR 1's async discipline) and which are sanctioned *drain points* — is
here so every rule reads the same map of the repo.
"""

from __future__ import annotations

import ast
import dataclasses


@dataclasses.dataclass
class ParsedModule:
    """One parsed source file handed to every rule."""

    path: str          # repo-relative posix path (stable finding key)
    tree: ast.Module
    source: str

    @property
    def posix(self) -> str:
        return self.path.replace("\\", "/")


# ---------------------------------------------------------------------------
# the hot-path map: modules whose code runs per step / per token.
# Host-sync and trace-hygiene rules apply only here — flagging a
# device_get in the checkpointer would be noise; flagging one in the
# decode loop is the whole point.
# ---------------------------------------------------------------------------

HOT_PATH_PREFIXES = (
    "dtdl_tpu/train/",
    "dtdl_tpu/serve/",
    "dtdl_tpu/parallel/",
    "dtdl_tpu/models/",
    "dtdl_tpu/ops/",
    "dtdl_tpu/quant/",
    "dtdl_tpu/metrics/",
)

# sanctioned drain points: whole modules whose JOB is the host<->device
# boundary under the PR-1 discipline — the bounded metrics queue (one
# device_get per drain, at log boundaries only).  Everything else
# suppresses inline with a justification, so the exception is visible
# at the call site.
DRAIN_MODULES = (
    "dtdl_tpu/metrics/device.py",
)


def is_hot(mod: ParsedModule) -> bool:
    p = mod.posix
    if any(d in p for d in DRAIN_MODULES):
        return False
    return any(h in p for h in HOT_PATH_PREFIXES)


# ---------------------------------------------------------------------------
# small AST helpers every rule shares
# ---------------------------------------------------------------------------

def dotted(node) -> str:
    """The dotted name of a Name/Attribute chain (``jax.device_get``),
    or '' when the expression is not a plain chain."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def walk_with_scope(tree):
    """Yield ``(node, enclosing_function_name)`` over the whole tree —
    the scope is the nearest enclosing FunctionDef name ('' at module
    level), which several rules key allowlists on."""
    def rec(node, scope):
        for child in ast.iter_child_nodes(node):
            child_scope = scope
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_scope = child.name
            yield child, child_scope
            yield from rec(child, child_scope)
    yield tree, ""
    yield from rec(tree, "")


def registry():
    """``{rule_id: one_line_doc}`` over every registered rule."""
    from dtdl_tpu.analysis.rules import (catalogs, compat, donation,
                                         host_sync, trace_hygiene)
    out = {}
    for mod in (host_sync, compat, donation, trace_hygiene, catalogs):
        out.update(mod.RULES)
    return out


def file_checks():
    from dtdl_tpu.analysis.rules import (compat, donation, host_sync,
                                         trace_hygiene)
    return (host_sync.check, compat.check, donation.check,
            trace_hygiene.check)


def repo_checks():
    from dtdl_tpu.analysis.rules import catalogs
    return (catalogs.check_repo,)
