"""The _compat discipline: one forward-compatible spelling per API.

dtdl_tpu/_compat.py patches ``jax.shard_map`` / ``lax.pcast`` /
``jax.typeof`` onto legacy jax at package import, so every call site
keeps the modern spelling.  A call site that reaches around the shim —
``from jax.experimental.shard_map import shard_map`` — works on today's
container and silently breaks (or forks semantics: the shim pins
``check_rep=False``) when either jax bound moves.  These rules keep the
shim the single owner of that compatibility decision.

* ``compat-shard-map`` — any import or attribute reference to
  ``jax.experimental.shard_map`` outside _compat.py itself.
* ``compat-maps``     — the removed ``jax.experimental.maps`` /
  ``xmap`` namespace (predates even the legacy bound this repo shims).
"""

from __future__ import annotations

import ast

from dtdl_tpu.analysis.findings import Finding
from dtdl_tpu.analysis.rules import dotted

RULES = {
    "compat-shard-map": "jax.experimental.shard_map referenced directly "
                        "(use jax.shard_map via dtdl_tpu._compat)",
    "compat-maps": "removed jax.experimental.maps/xmap namespace "
                   "referenced",
}


def check(mod) -> list[Finding]:
    if mod.posix.endswith("dtdl_tpu/_compat.py"):
        return []            # the shim is the one sanctioned reference
    out = []
    for node in ast.walk(mod.tree):
        ref = None
        if isinstance(node, ast.ImportFrom):
            ref = node.module or ""
            if ref == "jax.experimental" and any(
                    a.name == "shard_map" for a in node.names):
                ref = "jax.experimental.shard_map"
        elif isinstance(node, ast.Import):
            hit = next((a.name for a in node.names
                        if a.name.startswith("jax.experimental.shard_map")
                        or a.name.startswith("jax.experimental.maps")),
                       None)
            ref = hit or ""
        elif isinstance(node, ast.Attribute):
            ref = dotted(node)
        if not ref:
            continue
        if ref.startswith("jax.experimental.shard_map"):
            out.append(Finding(
                "compat-shard-map", mod.path, node.lineno,
                "bypasses dtdl_tpu._compat — call jax.shard_map (the "
                "shim owns the legacy-jax fallback + check_rep policy)"))
        elif ref.startswith("jax.experimental.maps"):
            out.append(Finding(
                "compat-maps", mod.path, node.lineno,
                f"{ref} was removed upstream"))
    return out
