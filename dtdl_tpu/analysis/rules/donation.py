"""Donation discipline: state-threading jits must donate their state.

A train/decode step threads a large state pytree (params + optimizer
state, or the KV arena — the largest buffers in the program) through
every call.  ``jax.jit`` without ``donate_argnums`` makes XLA allocate a
fresh output copy per step: correctness intact, HBM footprint doubled
and a copy inserted on the hottest path — exactly the regression that
surfaces months later as a mystery OOM at a bigger batch.  (The
compiled-program side — whether XLA actually aliased the donated
buffers — is the HLO auditor's job, dtdl_tpu/analysis/hlo_audit.py;
this rule catches the *lost annotation* before anything compiles.)

``jit-donate`` flags a ``jax.jit(fn)`` call (or ``@jax.jit`` /
``@partial(jax.jit, ...)`` decoration) with no ``donate_argnums`` /
``donate_argnames`` when the jitted function looks like a
state-threading step: its name (or its factory's name) contains a
step/decode/prefill/verify/inject token.  Eval/predict programs reuse
their params across calls — never donated, never flagged.
"""

from __future__ import annotations

import ast
import re

from dtdl_tpu.analysis.findings import Finding
from dtdl_tpu.analysis.rules import dotted, walk_with_scope

RULES = {
    "jit-donate": "state-threading jax.jit without donate_argnums "
                  "(fresh HBM copy of the state every step)",
}

_STEP_RE = re.compile(r"(^|_)(step|decode|prefill|verify|inject|train)(_|$)")
_FACTORY_RE = re.compile(r"^make_\w*step$|^_build_\w+$")
_EXEMPT_RE = re.compile(r"eval|predict|extract|infer")


def _has_donate(call: ast.Call) -> bool:
    return any(kw.arg in ("donate_argnums", "donate_argnames")
               for kw in call.keywords)


def _target_name(call: ast.Call) -> str:
    if call.args and isinstance(call.args[0], ast.Name):
        return call.args[0].id
    return ""


def _is_jit(node) -> bool:
    return dotted(node) in ("jax.jit", "pjit", "jax.pjit")


def _flag(mod, lineno, fn_name, scope):
    return Finding(
        "jit-donate", mod.path, lineno,
        f"jax.jit of step-like '{fn_name or scope}' without "
        f"donate_argnums — the threaded state is copied every call")


def check(mod) -> list[Finding]:
    out = []
    for node, scope in walk_with_scope(mod.tree):
        # jax.jit(fn, ...) call form
        if isinstance(node, ast.Call) and _is_jit(node.func):
            if _has_donate(node):
                continue
            fn = _target_name(node)
            step_like = (_STEP_RE.search(fn or "")
                         or _FACTORY_RE.match(scope or ""))
            exempt = _EXEMPT_RE.search(fn) or _EXEMPT_RE.search(scope)
            if step_like and not exempt:
                out.append(_flag(mod, node.lineno, fn, scope))
        # decorator forms: @jax.jit / @partial(jax.jit, ...)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                bare = _is_jit(dec)
                part = (isinstance(dec, ast.Call)
                        and dotted(dec.func) in ("partial",
                                                 "functools.partial")
                        and dec.args and _is_jit(dec.args[0]))
                if not (bare or part):
                    continue
                if part and _has_donate(dec):
                    continue
                if (_STEP_RE.search(node.name)
                        and not _EXEMPT_RE.search(node.name)):
                    out.append(_flag(mod, dec.lineno, node.name, scope))
    return out
