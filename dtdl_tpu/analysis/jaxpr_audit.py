"""Program auditor, trace level: walk a jaxpr and report what the
program *actually contains*.

The linter (lint.py) sees spellings; this module sees the traced
program — the ground truth after Python control flow, closures, and
library layers have resolved.  Given any callable + example args it
recursively walks the jaxpr (through pjit/scan/while/cond sub-jaxprs)
and reports:

* ``jaxpr-callback``      — host callbacks inside the program
  (``pure_callback`` / ``io_callback`` / ``debug_callback``): each one
  is a device→host→device round-trip per execution, i.e. exactly the
  per-step sync PR 1 removed.  (``jax.debug.print`` compiles to one.)
* ``jaxpr-const-capture`` — large constants captured by closure instead
  of passed as arguments.  A closed-over params tree is baked into the
  executable: it bloats the program, defeats donation, and silently
  pins stale weights.
* the **collective census** — per-primitive counts and bytes for the
  manual-SPMD collectives (``psum`` / ``all_gather`` / ``ppermute`` /
  ``all_to_all`` / ``psum_scatter``), the shard_map half of the
  program-shape receipt.  GSPMD-inserted collectives do not exist at
  jaxpr level — those come from the compiled HLO
  (dtdl_tpu/analysis/hlo_audit.py); contract tests census both.
* ``bf16_to_f32_casts`` (census field, not a finding) — the count of
  bf16→f32 ``convert_element_type`` ops: a jump against the baseline
  means an implicit weak-type upcast snuck an f32 path into a bf16
  program (the deliberate casts — logits, loss — are in the baseline).
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np

from dtdl_tpu.analysis.findings import Finding

#: manual-SPMD collective primitives (what shard_map code emits);
#: pmean traces to psum + div, so psum covers it
COLLECTIVE_PRIMS = ("psum", "all_gather", "ppermute", "all_to_all",
                    "psum_scatter", "pmax", "pmin")
_CENSUS_PRIMS = frozenset(COLLECTIVE_PRIMS)

CALLBACK_PRIMS = frozenset({"pure_callback", "io_callback",
                            "debug_callback", "callback", "outfeed",
                            "infeed"})

#: closure-captured constants above this are a finding (default 1 MiB —
#: rope tables and masks sit well under it, a params tree well over)
CONST_LIMIT_BYTES = 1 << 20


@dataclasses.dataclass
class JaxprAudit:
    """Findings + census of one traced program."""

    name: str
    findings: list
    census: dict


def _aval_bytes(aval) -> int:
    try:
        return int(np.prod(aval.shape)) * np.dtype(aval.dtype).itemsize
    except Exception:        # tokens / abstract refs carry no bytes
        return 0


def walk_eqns(jaxpr):
    """Every eqn of ``jaxpr`` and all nested sub-jaxprs (pjit bodies,
    scan/while/cond branches, custom_* calls), depth-first, each eqn
    exactly once."""
    yield from _iter_all_eqns(jaxpr)


def _iter_all_eqns(jaxpr):
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _jaxprs_in(v):
                yield from _iter_all_eqns(sub)


def _jaxprs_in(value):
    """Jaxpr objects inside one eqn param value (handles ClosedJaxpr,
    raw Jaxpr, and tuples/lists of either — scan carries 'jaxpr',
    cond carries 'branches', custom_vjp carries callables we skip)."""
    vals = value if isinstance(value, (tuple, list)) else (value,)
    for v in vals:
        if isinstance(v, jax.core.ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, jax.core.Jaxpr):
            yield v


def census_jaxpr(closed) -> dict:
    """Counts/bytes census of a ClosedJaxpr (see module docstring)."""
    coll: dict[str, dict] = {}
    n_callbacks = 0
    n_bf16_f32 = 0
    for eqn in _iter_all_eqns(closed.jaxpr):
        name = eqn.primitive.name
        if name in _CENSUS_PRIMS:
            ent = coll.setdefault(name, {"count": 0, "bytes": 0})
            ent["count"] += 1
            ent["bytes"] += sum(_aval_bytes(v.aval) for v in eqn.outvars)
        elif name in CALLBACK_PRIMS:
            n_callbacks += 1
        elif name == "convert_element_type":
            src = eqn.invars[0].aval
            dst = eqn.outvars[0].aval
            if (getattr(src, "dtype", None) == jax.numpy.bfloat16
                    and getattr(dst, "dtype", None) == np.float32):
                n_bf16_f32 += 1
    const_bytes = sum(_aval_bytes(jax.core.get_aval(c))
                      for c in closed.consts)
    return {"collectives": {k: coll[k] for k in sorted(coll)},
            "callbacks": n_callbacks,
            "bf16_to_f32_casts": n_bf16_f32,
            "const_bytes": int(const_bytes),
            "n_eqns": sum(1 for _ in _iter_all_eqns(closed.jaxpr))}


def audit_jaxpr(fn, *args, name: str = "program",
                const_limit: int = CONST_LIMIT_BYTES,
                **kwargs) -> JaxprAudit:
    """Trace ``fn(*args, **kwargs)`` and audit the jaxpr.

    ``fn`` may be any traceable callable (jitted or not — a jitted
    wrapper is traced through; the audit sees the same program).  Args
    may be concrete arrays or ``jax.ShapeDtypeStruct``s: tracing never
    executes the program.
    """
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    census = census_jaxpr(closed)
    findings = []
    for eqn in _iter_all_eqns(closed.jaxpr):
        if eqn.primitive.name in CALLBACK_PRIMS:
            findings.append(Finding(
                "jaxpr-callback", name, 0,
                f"host callback '{eqn.primitive.name}' inside the "
                f"program — a device->host round-trip every execution"))
    for c in closed.consts:
        nbytes = _aval_bytes(jax.core.get_aval(c))
        if nbytes > const_limit:
            shape = getattr(c, "shape", ())
            dtype = getattr(c, "dtype", "?")
            findings.append(Finding(
                "jaxpr-const-capture", name, 0,
                f"closure captured a {nbytes/2**20:.1f} MiB constant "
                f"({dtype}{list(shape)}) — pass it as an argument so "
                f"it can shard/donate",
                detail={"bytes": int(nbytes)}))
    return JaxprAudit(name=name, findings=findings, census=census)
