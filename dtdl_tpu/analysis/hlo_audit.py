"""Program auditor, compiled level: donation aliasing + collective
census + host transfers out of the lowered/compiled XLA module.

The jaxpr shows what was traced; the compiled HLO shows what XLA made
of it — GSPMD-inserted collectives that exist in no jaxpr, the actual
input→output buffer aliasing behind ``donate_argnums``, and the
custom-calls a host callback compiles into.  This module parses both
artifacts (``lowered.as_text()`` StableHLO for the per-arg donation
attributes, ``compiled.as_text()`` optimized HLO for ops) — text
parsing on purpose: it needs no private jax APIs and the same two
strings are what a human debugging a program dump would read.

Checks:

* ``hlo-undonated``    — a flat input argument the caller expected
  donated (``expect_donated``) that is absent from the optimized
  module's ``input_output_alias`` map: the ``donate_argnums`` was lost,
  or XLA could not pair the buffer with an output — either way that
  buffer is copied every call.
* ``hlo-host-transfer`` — host callback custom-calls
  (``xla_python_cpu_callback`` & friends), infeed/outfeed, host
  send/recv in the *optimized* module: whatever the source looked
  like, the compiled program talks to the host.
* the **collective census** — counts + bytes per collective op
  (all-reduce / all-gather / reduce-scatter / collective-permute /
  all-to-all, sync or async-start form) parsed from the optimized HLO:
  a GSPMD resharding that sneaks an all-gather into the step shows up
  as a named diff against the checked-in baseline.
"""

from __future__ import annotations

import dataclasses
import re

import jax
import numpy as np

from dtdl_tpu.analysis.findings import Finding

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2,
    "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
    "f64": 8, "c64": 8, "c128": 16,
}

#: optimized-HLO collective op names (async forms end in -start; the
#: matching -done is not counted separately)
COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                  "collective-permute", "all-to-all")

_OP_RE = re.compile(
    r"=\s+(?P<shape>\(?[a-z0-9\[\],{}: ]*?\)?)\s+"
    r"(?P<op>" + "|".join(COLLECTIVE_OPS) + r")(?P<start>-start)?\(")

_SHAPE_RE = re.compile(r"(?P<dt>[a-z]+[0-9]*)\[(?P<dims>[0-9,]*)\]")

_HOST_CALL_RE = re.compile(
    r'custom_call_target="(?P<target>[^"]*'
    r'(?:callback|host_callback|HostCallback)[^"]*)"')

_TRANSFER_OPS = ("infeed", "outfeed", "send", "send-done", "recv",
                 "recv-done")

# one entry-arg declaration: '%argN: tensor<...>' with ITS OWN optional
# attribute dict attached — anchored so one arg's attributes can never
# be read as a neighbor's (tensor types contain no '{' or ',').  The
# attrs body allows quoted strings with braces inside: mhlo.sharding
# values look like "{maximal device=0}" and must not truncate the dict
# before a later tf.aliasing_output entry.
_ALIAS_ARG_RE = re.compile(
    r"%arg(?P<idx>\d+):\s*[^{,)]*?"
    r"\{(?P<attrs>(?:[^{}\"]|\"[^\"]*\")*)\}")

_IO_ALIAS_ENTRY_RE = re.compile(r"\(\s*(?P<param>\d+)\s*,")


@dataclasses.dataclass
class HloAudit:
    """Findings + census of one compiled program."""

    name: str
    findings: list
    census: dict


def shape_bytes(shape_str: str) -> int:
    """Bytes of an HLO shape string (``f32[4,8]{1,0}`` or a tuple of
    them); unknown dtypes count zero rather than guessing."""
    total = 0
    for m in _SHAPE_RE.finditer(shape_str):
        dt = _DTYPE_BYTES.get(m.group("dt"))
        if dt is None:
            continue
        dims = m.group("dims")
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * dt
    return total


def collective_census(hlo_text: str) -> dict:
    """``{op: {count, bytes}}`` over the optimized HLO text."""
    out: dict[str, dict] = {}
    for m in _OP_RE.finditer(hlo_text):
        op = m.group("op")
        ent = out.setdefault(op, {"count": 0, "bytes": 0})
        ent["count"] += 1
        ent["bytes"] += shape_bytes(m.group("shape"))
    return {k: out[k] for k in sorted(out)}


def host_transfers(hlo_text: str) -> list[str]:
    """Host-transfer sites in the optimized HLO: callback custom-call
    targets plus infeed/outfeed/send/recv op names, in order."""
    hits = [m.group("target") for m in _HOST_CALL_RE.finditer(hlo_text)]
    op_re = re.compile(r"=\s+\(?[a-z0-9\[\],{}: ]*?\)?\s+"
                       r"(" + "|".join(_TRANSFER_OPS) + r")\(")
    hits += [m.group(1) for m in op_re.finditer(hlo_text)]
    return hits


def donated_args(lowered_text: str) -> set[int]:
    """Flat input-arg indices the trace OFFERED for donation — args
    carrying ``tf.aliasing_output`` (aliasing already proven at
    lowering) or ``jax.buffer_donor`` (left for XLA to pair) in the
    StableHLO entry function."""
    return {int(m.group("idx"))
            for m in _ALIAS_ARG_RE.finditer(lowered_text)
            if "tf.aliasing_output" in m.group("attrs")
            or "jax.buffer_donor" in m.group("attrs")}


def aliased_params(compiled_text: str) -> set[int]:
    """Parameter numbers XLA actually aliased to an output — the
    ``input_output_alias={ {0}: (20, {}, may-alias), ... }`` header of
    the optimized module.  This is the donation ground truth: an
    offered donation the compiler could not pair still copies."""
    start = compiled_text.find("input_output_alias={")
    if start < 0:
        return set()
    # walk the balanced-brace body (entries contain nested {} indices)
    i = start + len("input_output_alias={")
    depth, end = 1, i
    while end < len(compiled_text) and depth:
        depth += {"{": 1, "}": -1}.get(compiled_text[end], 0)
        end += 1
    body = compiled_text[i:end - 1]
    return {int(e.group("param"))
            for e in _IO_ALIAS_ENTRY_RE.finditer(body)}


def arg_leaf_indices(args: tuple, argnums) -> set[int]:
    """The flat input-arg indices covered by positional ``argnums`` —
    what ``expect_donated`` should be for "these whole subtrees are
    donated" (mirrors jax's donate_argnums flattening)."""
    idx, out = 0, set()
    for i, a in enumerate(args):
        n = len(jax.tree_util.tree_leaves(a))
        if i in argnums:
            out.update(range(idx, idx + n))
        idx += n
    return out


def audit_compiled(fn, *args, name: str = "program",
                   expect_donated=None, **kwargs) -> HloAudit:
    """Lower + compile ``fn(*args)`` and audit the XLA module.

    ``fn`` is a jitted callable (anything with ``.lower``); plain
    callables are wrapped in ``jax.jit`` (which donates nothing — pass
    the real jitted program to audit its donation).  ``expect_donated``
    is a set of flat input-arg indices (see :func:`arg_leaf_indices`)
    that MUST be aliased; None skips the donation check.  Compiling is
    the expensive step (~the program's normal first-call cost); nothing
    is executed.
    """
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    lowered = jitted.lower(*args, **kwargs)
    compiled = lowered.compile()
    low_text = lowered.as_text()
    hlo_text = compiled.as_text()
    offered = donated_args(low_text)
    donated = aliased_params(hlo_text)
    mem = None
    try:
        ma = compiled.memory_analysis()
        mem = {"alias_bytes": int(ma.alias_size_in_bytes),
               "argument_bytes": int(ma.argument_size_in_bytes),
               "output_bytes": int(ma.output_size_in_bytes),
               "temp_bytes": int(ma.temp_size_in_bytes)}
    except Exception:       # pragma: no cover - backend without stats
        pass
    transfers = host_transfers(hlo_text)
    findings = []
    if expect_donated is not None:
        missing = sorted(set(expect_donated) - donated)
        if missing:
            findings.append(Finding(
                "hlo-undonated", name, 0,
                f"{len(missing)} expected-donated input buffer(s) not "
                f"aliased to any output (flat arg indices {missing}) — "
                f"each is a fresh copy every call",
                detail={"missing": missing}))
    for t in transfers:
        findings.append(Finding(
            "hlo-host-transfer", name, 0,
            f"compiled program transfers to host via '{t}'"))
    census = {"collectives": collective_census(hlo_text),
              "host_transfers": len(transfers),
              "donated_args": sorted(donated),
              "donor_args": sorted(offered),
              "memory": mem}
    return HloAudit(name=name, findings=findings, census=census)
