"""Findings, rule ids, and the suppression grammar of the audit layer.

Every check in dtdl_tpu/analysis — the AST linter (lint.py), the jaxpr
walker (jaxpr_audit.py) and the HLO/compiled-program auditor
(hlo_audit.py) — reports through one currency: a :class:`Finding` with a
stable kebab-case ``rule`` id, a location, and a one-line message.  Rule
ids are the contract surface: tests assert on them, suppressions name
them, and the gate (scripts/audit.py) exits nonzero on any finding that
no suppression covers.

**Suppression grammar.**  A finding on line N is suppressed by a comment
on line N or line N-1 of the form::

    # audit: ok[rule-id] one-line justification

The justification is mandatory — a suppression without a reason is
itself a finding (``suppress-no-reason``), and a suppression that
matches no finding is flagged stale (``suppress-stale``) so dead
annotations cannot accumulate after the code they excused is gone.
``rule-id`` may be a full id (``host-sync-get``) or a prefix group
(``host-sync``): the prefix form covers every rule in the group, for
lines that trip several sibling patterns at once.
"""

from __future__ import annotations

import dataclasses
import re

#: the one suppression spelling; groups: rule id, justification
SUPPRESS_RE = re.compile(
    r"#\s*audit:\s*ok\[(?P<rule>[a-z0-9-]+)\]\s*(?P<reason>.*)$")

#: rule ids of the suppression machinery itself (never suppressible)
META_RULES = ("suppress-no-reason", "suppress-stale", "suppress-unknown")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one location.

    ``rule`` is the stable id (see lint.RULE_DOCS for the catalog);
    ``path`` is repo-relative where possible; ``line`` is 1-based (0 for
    whole-file/whole-program findings); ``message`` is the one-line
    diagnosis.  ``detail`` carries optional machine-readable context
    (e.g. the census dict a collective diff came from).
    """

    rule: str
    path: str
    line: int
    message: str
    detail: dict | None = dataclasses.field(default=None, compare=False)

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: [{self.rule}] {self.message}"


@dataclasses.dataclass(frozen=True)
class Suppression:
    """One ``# audit: ok[rule] reason`` annotation in a source file."""

    rule: str
    path: str
    line: int
    reason: str

    def covers(self, finding: Finding) -> bool:
        """A suppression covers findings of its rule (or rule-group
        prefix) on its own line or the line directly below it — the
        comment-above-the-statement idiom."""
        if finding.path != self.path:
            return False
        if finding.line not in (self.line, self.line + 1):
            return False
        return (finding.rule == self.rule
                or finding.rule.startswith(self.rule + "-"))


def scan_suppressions(path: str, source: str) -> list[Suppression]:
    """All suppression annotations in ``source`` (1-based lines).

    Tokenizes so only real ``#`` comments count — a docstring that
    *describes* the suppression syntax (this module's does) is not a
    suppression."""
    import io
    import tokenize

    out = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = SUPPRESS_RE.search(tok.string)
            if m:
                out.append(Suppression(
                    rule=m.group("rule"), path=path, line=tok.start[0],
                    reason=m.group("reason").strip()))
    except tokenize.TokenError:    # pragma: no cover - truncated file
        pass
    return out


def apply_suppressions(findings: list[Finding],
                       sups: list[Suppression],
                       known_rules=None) -> list[Finding]:
    """Resolve suppressions against findings.

    Returns the surviving findings: unsuppressed originals, plus the
    meta-findings of the suppression machinery — a reason-less
    suppression, a stale one (covers nothing), and (when
    ``known_rules`` is given) one naming a rule id that does not exist,
    which would otherwise silently suppress nothing forever.
    """
    out = []
    used: set[Suppression] = set()
    for f in findings:
        hit = next((s for s in sups if s.covers(f)), None)
        if hit is None:
            out.append(f)
        else:
            used.add(hit)
    for s in sups:
        if not s.reason:
            out.append(Finding("suppress-no-reason", s.path, s.line,
                               f"suppression of [{s.rule}] carries no "
                               f"justification"))
        if known_rules is not None and s.rule not in known_rules and \
                not any(r.startswith(s.rule + "-") for r in known_rules):
            out.append(Finding("suppress-unknown", s.path, s.line,
                               f"suppression names unknown rule "
                               f"[{s.rule}]"))
        elif s not in used:
            out.append(Finding("suppress-stale", s.path, s.line,
                               f"suppression of [{s.rule}] matches no "
                               f"finding — remove it"))
    return out


def render_report(findings: list[Finding], *, header: str = "") -> str:
    """Human report: findings grouped by rule, stable order."""
    lines = []
    if header:
        lines.append(header)
    by_rule: dict[str, list[Finding]] = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f)
    for rule in sorted(by_rule):
        lines.append(f"[{rule}] x{len(by_rule[rule])}")
        for f in sorted(by_rule[rule], key=lambda f: (f.path, f.line)):
            lines.append("  " + f.render())
    return "\n".join(lines)
