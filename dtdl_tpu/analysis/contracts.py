"""The pinned program contracts: audit the REAL hot-path programs.

Four programs carry this repo's performance story — the strategy train
step, the 4D megatron step, and the serving decode/verify pair.  This
module builds each one at a tiny fixed geometry (the audit is about
program *shape* — which collectives, what aliasing, any host traffic —
never about model quality, so small and fast is correct) and runs both
auditors over it:

* jaxpr level (dtdl_tpu/analysis/jaxpr_audit.py): callbacks, captured
  constants, the manual-SPMD collective census;
* compiled level (dtdl_tpu/analysis/hlo_audit.py): donation aliasing
  (the train step's state and the engines' KV arena MUST be donated),
  host transfers in the optimized module, the GSPMD collective census.

The result is compared against the checked-in baseline
(``dtdl_tpu/analysis/baselines.json``): any drift — a new all-gather
from a changed sharding, a lost ``donate_argnums``, a debug callback
left in a step — fails by name (rule ``census-drift`` or the auditor's
own finding) in tests/test_analysis_contracts.py and in
``scripts/audit.py --programs``.  Regenerate the baseline with
``scripts/audit.py --programs --rebase`` after an *intentional*
program-shape change, and say why in the PR.
"""

from __future__ import annotations

import json
import pathlib

import jax
import jax.numpy as jnp
import numpy as np

from dtdl_tpu.analysis.findings import Finding
from dtdl_tpu.analysis.hlo_audit import arg_leaf_indices, audit_compiled
from dtdl_tpu.analysis.jaxpr_audit import audit_jaxpr

#: program name -> builder; the contract surface of this module
PROGRAMS = ("train_step", "megatron_step", "serve_decode",
            "serve_verify", "serve_lora_decode")

#: devices each pinned geometry needs (train_step adapts to the local
#: mesh; the 4D megatron step is pinned at its (1, 1, 2, 4) mesh)
MIN_DEVICES = {"train_step": 1, "megatron_step": 8, "serve_decode": 1,
               "serve_verify": 1, "serve_lora_decode": 1}


def runnable_programs(names=PROGRAMS) -> tuple[list, list]:
    """Split ``names`` into (runnable, skipped) for THIS process's
    device count — bench.py / scripts/audit.py run outside the test
    harness's forced 8-device CPU platform, where the megatron
    geometry cannot build; skipping it loudly beats an error row."""
    n = jax.device_count()
    run = [p for p in names if MIN_DEVICES[p] <= n]
    return run, [p for p in names if p not in run]

#: census fields compared against the baseline (the rest of a report —
#: memory stats, eqn counts — is receipt, not contract)
BASELINE_FIELDS = ("jaxpr_collectives", "hlo_collectives",
                   "host_transfers", "callbacks", "bf16_to_f32_casts",
                   "donation_ok")


def baseline_path() -> pathlib.Path:
    return pathlib.Path(__file__).with_name("baselines.json")


def load_baseline() -> dict:
    p = baseline_path()
    return json.loads(p.read_text()) if p.exists() else {}


# ---------------------------------------------------------------------------
# program builders: (jitted, args, donate_argnums) at tiny fixed geometry
# ---------------------------------------------------------------------------

def _build_train_step():
    """The strategy train step (make_train_step under DataParallel on
    the full local mesh) — the PR 1 hot loop."""
    import optax

    from dtdl_tpu.models.mlp import MLP
    from dtdl_tpu.parallel.strategy import DataParallel
    from dtdl_tpu.train.state import init_state
    from dtdl_tpu.train.step import make_train_step

    n = jax.device_count()
    model = MLP(n_units=16, n_out=8)
    example = jnp.zeros((n, 12), jnp.float32)
    state = init_state(model, jax.random.PRNGKey(0), example,
                       optax.sgd(0.1))
    strategy = DataParallel()
    step = make_train_step(strategy)
    batch = {"image": jnp.zeros((2 * n, 12), jnp.float32),
             "label": jnp.zeros((2 * n,), jnp.int32)}
    return step, (state, batch), (0,)


def _build_megatron_step():
    """The 4D megatron step on a (1, 1, pipe=2, model=4) mesh — the
    manual-SPMD face, whose psums are hand-placed and must stay put."""
    import optax

    from dtdl_tpu.parallel import megatron as M
    from dtdl_tpu.runtime.mesh import build_mesh

    cfg = M.MegatronConfig(vocab_size=64, d_model=32, n_heads=4,
                           d_ff=64, n_stages=2, layers_per_stage=1,
                           n_microbatches=2, max_seq=32,
                           dtype=jnp.float32)
    mesh = build_mesh(shape=(1, 1, 2, 4), axes=M.AXES,
                      devices=jax.devices()[:8])
    opt = optax.sgd(0.1)
    params = M.place_params(
        mesh, cfg, jax.device_get(
            M.init_params(cfg, jax.random.PRNGKey(0))))
    opt_state = M.init_optimizer(cfg, mesh, opt, params)
    step = M.make_megatron_train_step(cfg, mesh, opt)
    batch = M.shard_lm_batch(mesh, {
        "tokens": np.zeros((2, 16), np.int32),
        "targets": np.zeros((2, 16), np.int32),
        "mask": np.ones((2, 16), np.float32)})
    args = (params, opt_state, batch["tokens"], batch["targets"],
            batch["mask"])
    return step, args, (0, 1)


def _tiny_engine():
    import flax.linen as nn

    from dtdl_tpu.models.transformer import transformer_lm
    from dtdl_tpu.serve.engine import InferenceEngine

    model = transformer_lm("tiny", vocab_size=64, d_model=32,
                           n_layers=2, n_heads=2, d_ff=64, max_seq=32,
                           attn_impl="dense", dtype=jnp.float32)
    params = nn.unbox(model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))["params"])
    return InferenceEngine(model, params, n_slots=2, buckets=(8,))


def _build_serve_decode():
    """The ONE decode program every serving token rides (PR 2):
    zero host transfers is its entire reason to exist."""
    from dtdl_tpu.serve.sampling import SampleParams, pack

    eng = _tiny_engine()
    fn = eng._build_decode()
    args = (eng.params, eng.init_arena(), eng.init_last_tokens(),
            jnp.ones((eng.n_slots,), bool), jnp.zeros((), jnp.int32),
            jax.random.PRNGKey(0), *pack([SampleParams()] * eng.n_slots),
            jnp.ones((eng.n_slots, 64), bool),
            jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32))
    return fn, args, (1,)


def _build_serve_verify():
    """The k-wide verify program (PR 4 spec decode + round-19 chunked
    prefill share it) at k=2."""
    from dtdl_tpu.serve.sampling import SampleParams, pack

    eng = _tiny_engine()
    k = 2
    fn = eng._build_verify(k)
    B = eng.n_slots
    args = (eng.params, eng.init_arena(), eng.init_last_tokens(),
            jnp.zeros((B, k), jnp.int32), jnp.ones((B,), jnp.int32),
            jnp.ones((B,), bool), jnp.zeros((B,), bool),
            jnp.zeros((B,), jnp.int32), jnp.zeros((B,), jnp.int32),
            jnp.zeros((), jnp.int32), jax.random.PRNGKey(0),
            *pack([SampleParams()] * B),
            jnp.ones((B, k + 1, 64), bool),
            jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32))
    return fn, args, (1,)


def _build_serve_lora_decode():
    """The decode program of a multi-LoRA engine (round 22): the bank
    gather must add no collectives and no host transfers — adapter ids
    and the bank itself ride in as data."""
    import flax.linen as nn

    from dtdl_tpu.models.transformer import transformer_lm
    from dtdl_tpu.serve.engine import InferenceEngine
    from dtdl_tpu.serve.sampling import SampleParams, pack

    model = transformer_lm("tiny", vocab_size=64, d_model=32,
                           n_layers=2, n_heads=2, d_ff=64, max_seq=32,
                           attn_impl="dense", dtype=jnp.float32)
    params = nn.unbox(model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))["params"])
    eng = InferenceEngine(model, params, n_slots=2, buckets=(8,),
                          lora_rank=2, lora_adapters=2)
    fn = eng._build_decode()
    B = eng.n_slots
    args = (eng.params, eng.init_arena(), eng.init_last_tokens(),
            jnp.ones((B,), bool), jnp.zeros((), jnp.int32),
            jax.random.PRNGKey(0), *pack([SampleParams()] * B),
            jnp.ones((B, 64), bool),
            jnp.zeros((B,), jnp.int32), eng.adapter_bank.bank)
    return fn, args, (1,)


_BUILDERS = {"train_step": _build_train_step,
             "megatron_step": _build_megatron_step,
             "serve_decode": _build_serve_decode,
             "serve_verify": _build_serve_verify,
             "serve_lora_decode": _build_serve_lora_decode}


# ---------------------------------------------------------------------------
# auditing + baseline comparison
# ---------------------------------------------------------------------------

def audit_one(name: str) -> dict:
    """Build + audit one pinned program; returns the JSON-able report
    (``findings`` rendered, census fields flat)."""
    fn, args, donate = _BUILDERS[name]()
    ja = audit_jaxpr(fn, *args, name=name)
    expect = arg_leaf_indices(args, set(donate))
    ha = audit_compiled(fn, *args, name=name, expect_donated=expect)
    findings = ja.findings + ha.findings
    donation_ok = not any(f.rule == "hlo-undonated" for f in findings)
    mem = ha.census.get("memory") or {}
    return {
        "jaxpr_collectives": ja.census["collectives"],
        "hlo_collectives": ha.census["collectives"],
        "host_transfers": ha.census["host_transfers"],
        "callbacks": ja.census["callbacks"],
        "bf16_to_f32_casts": ja.census["bf16_to_f32_casts"],
        "donation_ok": donation_ok,
        # receipts (not baseline-compared): sizes drift with geometry
        "donated_bytes": mem.get("alias_bytes", 0),
        "const_bytes": ja.census["const_bytes"],
        "n_donated_args": len(ha.census["donated_args"]),
        "n_expected_donated": len(expect),
        "findings": [f.render() for f in findings],
        "_findings": findings,
    }


def audit_programs(names=PROGRAMS) -> dict:
    return {n: audit_one(n) for n in names}


def compare_to_baseline(reports: dict, baseline: dict) -> list[Finding]:
    """Named drift findings: every BASELINE_FIELDS mismatch between a
    report and the checked-in baseline, plus missing baselines."""
    out = []
    for name, rep in reports.items():
        base = baseline.get(name)
        if base is None:
            out.append(Finding(
                "census-drift", name, 0,
                "no checked-in baseline — run scripts/audit.py "
                "--programs --rebase and commit baselines.json"))
            continue
        for field in BASELINE_FIELDS:
            got, want = rep.get(field), base.get(field)
            if got != want:
                out.append(Finding(
                    "census-drift", name, 0,
                    f"{field} drifted from baseline: {want!r} -> "
                    f"{got!r} (intentional? scripts/audit.py "
                    f"--programs --rebase)",
                    detail={"field": field, "baseline": want,
                            "got": got}))
    return out


def save_baseline(reports: dict) -> pathlib.Path:
    """Write the comparable census subset as the new baseline."""
    slim = {name: {f: rep[f] for f in BASELINE_FIELDS}
            for name, rep in sorted(reports.items())}
    p = baseline_path()
    p.write_text(json.dumps(slim, indent=2, sort_keys=True) + "\n")
    return p
