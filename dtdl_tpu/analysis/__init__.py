"""Static analysis & program audits for the dtdl_tpu stack.

Two engines and one gate (ISSUE 15):

* **Repo linter** (:mod:`dtdl_tpu.analysis.lint` +
  :mod:`dtdl_tpu.analysis.rules`) — AST-based, repo-specific rules:
  the hot-path host-sync ban, the _compat shard_map discipline,
  donation on state-threading jits, trace hygiene (wall clocks / host
  RNG inside traced functions), and cross-file catalog consistency
  (ServeMetrics counters vs ``_WINDOW_COUNTERS``, emitted event names
  vs ``EVENT_CATALOG``).  Pure ``ast`` — sub-second over the package.
* **Program auditor** (:mod:`~dtdl_tpu.analysis.jaxpr_audit` /
  :mod:`~dtdl_tpu.analysis.hlo_audit`) — given any jitted callable +
  example args, walk the traced jaxpr and the lowered/compiled XLA
  module: host callbacks and transfers, donation aliasing, oversized
  closure constants, and the collective census (counts + bytes) that
  :mod:`~dtdl_tpu.analysis.contracts` pins for the real train/megatron/
  decode/verify programs against ``baselines.json``.
* **Gate** — ``scripts/audit.py`` (CLI report, nonzero exit on
  unsuppressed findings, inline ``# audit: ok[rule-id] reason``
  suppressions) and tests/test_analysis_gate.py inside tier-1.
"""

from dtdl_tpu.analysis.findings import (Finding, Suppression,  # noqa: F401
                                        apply_suppressions, render_report,
                                        scan_suppressions)
from dtdl_tpu.analysis.lint import lint_paths, rule_docs  # noqa: F401
from dtdl_tpu.analysis.jaxpr_audit import (JaxprAudit,  # noqa: F401
                                           audit_jaxpr, census_jaxpr)
from dtdl_tpu.analysis.hlo_audit import (HloAudit,  # noqa: F401
                                         arg_leaf_indices, audit_compiled,
                                         collective_census, donated_args,
                                         host_transfers)

__all__ = [
    "Finding", "Suppression", "apply_suppressions", "render_report",
    "scan_suppressions", "lint_paths", "rule_docs", "JaxprAudit",
    "audit_jaxpr", "census_jaxpr", "HloAudit", "arg_leaf_indices",
    "audit_compiled", "collective_census", "donated_args",
    "host_transfers",
]
