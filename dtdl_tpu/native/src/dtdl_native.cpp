// dtdl_tpu native runtime: threaded batch pipeline, IDX(.gz) IO, topology.
//
// The reference delegates its host-side runtime to framework internals:
// torch DataLoader worker processes (reference pytorch/single_gpu.py:60-61,
// num_workers=4), Chainer iterators, and TF's C++ input pipeline.  This is
// the framework's own native equivalent: a C++ producer/consumer batch
// pipeline (shuffle, augment, normalize off the Python thread so the TPU
// step loop never waits on the GIL), a zlib IDX reader replacing the
// reference's byte-by-byte Python parse (reference chainer/mnist_helper.py:
// 24-27), and a host topology probe for the slice launcher.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image).
// Determinism contract: batch content depends only on (seed, epoch,
// batch_index) — never on thread scheduling.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

#include <unistd.h>
#include <zlib.h>

extern "C" {

// ---------------------------------------------------------------------------
// deterministic RNG (splitmix64 + xorshift) — stable across platforms
// ---------------------------------------------------------------------------

static inline uint64_t splitmix64(uint64_t& x) {
  x += 0x9E3779B97f4A7C15ULL;
  uint64_t z = x;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed) {}
  uint64_t next() { return splitmix64(s); }
  // unbiased bounded draw (Lemire)
  uint64_t below(uint64_t n) {
    if (n == 0) return 0;
    return next() % n;  // modulo bias negligible for n << 2^64
  }
  float uniform() { return (next() >> 40) * (1.0f / (1ULL << 24)); }
};

// ---------------------------------------------------------------------------
// batch pipeline
// ---------------------------------------------------------------------------

enum Flags {
  DTDL_SHUFFLE = 1,
  DTDL_AUGMENT_CROP_FLIP = 2,  // pad-4 random crop + horizontal flip (NHWC)
  DTDL_NORMALIZE = 4,          // per-channel (x - mean) / std
};

struct Batch {
  std::vector<float> images;
  std::vector<int32_t> labels;
  int64_t index = -1;
  bool ready = false;
};

struct Loader {
  // dataset (borrowed pointers; Python keeps the arrays alive)
  const float* images;
  const int32_t* labels;
  int64_t n;
  int h, w, c, batch;
  int flags;
  uint64_t seed;
  float mean[16], std[16];

  // epoch state
  std::vector<int64_t> perm;
  int64_t n_batches = 0;
  int epoch = -1;

  // pipeline
  int depth;
  int n_threads;
  std::vector<Batch> slots;
  std::atomic<int64_t> next_build{0};   // next batch index to build
  int64_t next_emit = 0;                // next batch index to hand out
  std::mutex mu;
  std::condition_variable cv_ready, cv_free;
  std::vector<std::thread> workers;
  std::atomic<bool> stop{false};
  int64_t per_image() const { return (int64_t)h * w * c; }
};

static void build_batch(Loader* L, int64_t bi, Batch* out) {
  const int64_t px = L->per_image();
  out->images.resize((size_t)L->batch * px);
  out->labels.resize(L->batch);
  // per-batch deterministic RNG: content independent of thread schedule
  uint64_t s = L->seed * 0x9E3779B97f4A7C15ULL + (uint64_t)L->epoch * 0x100000001B3ULL +
               (uint64_t)bi + 0x51ED2701;
  Rng rng(s);
  const bool aug = L->flags & DTDL_AUGMENT_CROP_FLIP;
  const bool norm = L->flags & DTDL_NORMALIZE;
  for (int i = 0; i < L->batch; ++i) {
    int64_t src = L->perm[bi * L->batch + i];
    out->labels[i] = L->labels[src];
    const float* im = L->images + src * px;
    float* dst = out->images.data() + (int64_t)i * px;
    if (!aug) {
      std::memcpy(dst, im, px * sizeof(float));
    } else {
      // pad-4 random crop + hflip, matching the torchvision stack the
      // reference applies (RandomCrop(32,4) + RandomHorizontalFlip)
      int dy = (int)rng.below(9) - 4;  // crop offset into padded image
      int dx = (int)rng.below(9) - 4;
      bool flip = rng.uniform() < 0.5f;
      for (int y = 0; y < L->h; ++y) {
        int sy = y + dy;
        for (int x = 0; x < L->w; ++x) {
          int sx = x + dx;
          int tx = flip ? (L->w - 1 - x) : x;
          float* o = dst + ((int64_t)y * L->w + tx) * L->c;
          if (sy < 0 || sy >= L->h || sx < 0 || sx >= L->w) {
            for (int ch = 0; ch < L->c; ++ch) o[ch] = 0.0f;
          } else {
            const float* p = im + ((int64_t)sy * L->w + sx) * L->c;
            for (int ch = 0; ch < L->c; ++ch) o[ch] = p[ch];
          }
        }
      }
    }
    if (norm) {
      for (int64_t j = 0; j < px; ++j)
        dst[j] = (dst[j] - L->mean[j % L->c]) / L->std[j % L->c];
    }
  }
  out->index = bi;
}

static void worker_loop(Loader* L) {
  while (!L->stop.load()) {
    int64_t bi = L->next_build.fetch_add(1);
    if (bi >= L->n_batches) return;
    int slot = (int)(bi % L->depth);
    Batch* B = &L->slots[slot];
    {
      // wait until the consumer has drained this slot's previous occupant
      std::unique_lock<std::mutex> lk(L->mu);
      L->cv_free.wait(lk, [&] {
        return L->stop.load() || (!B->ready && L->next_emit + L->depth > bi);
      });
      if (L->stop.load()) return;
    }
    build_batch(L, bi, B);
    {
      std::lock_guard<std::mutex> lk(L->mu);
      B->ready = true;
    }
    L->cv_ready.notify_all();
  }
}

void* dtdl_loader_create(const float* images, const int32_t* labels,
                         int64_t n, int h, int w, int c, int batch,
                         int depth, int n_threads, int flags, uint64_t seed,
                         const float* mean, const float* stdv) {
  if (!images || !labels || n <= 0 || batch <= 0 || c > 16) return nullptr;
  Loader* L = new Loader();
  L->images = images; L->labels = labels; L->n = n;
  L->h = h; L->w = w; L->c = c; L->batch = batch;
  L->flags = flags; L->seed = seed;
  L->depth = depth > 0 ? depth : 4;
  L->n_threads = n_threads > 0 ? n_threads : 4;
  for (int i = 0; i < c; ++i) {
    L->mean[i] = mean ? mean[i] : 0.0f;
    L->std[i] = stdv ? stdv[i] : 1.0f;
  }
  L->slots.resize(L->depth);
  return L;
}

static void join_workers(Loader* L) {
  L->stop.store(true);
  L->cv_free.notify_all();
  for (auto& t : L->workers) t.join();
  L->workers.clear();
  L->stop.store(false);
}

static void begin_epoch(Loader* L, int epoch, int64_t n_indices) {
  L->epoch = epoch;
  L->n_batches = n_indices / L->batch;  // drop_last semantics
  L->next_build.store(0);
  L->next_emit = 0;
  for (auto& B : L->slots) { B.ready = false; B.index = -1; }
  for (int i = 0; i < L->n_threads; ++i)
    L->workers.emplace_back(worker_loop, L);
}

void dtdl_loader_start_epoch(void* h, int epoch) {
  Loader* L = (Loader*)h;
  join_workers(L);
  L->perm.resize(L->n);
  for (int64_t i = 0; i < L->n; ++i) L->perm[i] = i;
  if (L->flags & DTDL_SHUFFLE) {
    Rng rng(L->seed * 0xD1B54A32D192ED03ULL + (uint64_t)epoch + 1);
    for (int64_t i = L->n - 1; i > 0; --i) {  // Fisher-Yates
      int64_t j = (int64_t)rng.below((uint64_t)i + 1);
      std::swap(L->perm[i], L->perm[j]);
    }
  }
  begin_epoch(L, epoch, L->n);
}

// Start an epoch over caller-provided sample indices (e.g. a sharded
// sampler's per-epoch stripe of a globally reshuffled permutation —
// DistributedSampler parity in multi-host runs).  Indices are copied;
// values must lie in [0, n).  Returns 0, or -1 on invalid input.
int dtdl_loader_start_epoch_indices(void* h, int epoch,
                                    const int64_t* indices, int64_t count) {
  Loader* L = (Loader*)h;
  if (!indices || count <= 0) return -1;
  for (int64_t i = 0; i < count; ++i)
    if (indices[i] < 0 || indices[i] >= L->n) return -1;
  join_workers(L);
  L->perm.assign(indices, indices + count);
  begin_epoch(L, epoch, count);
  return 0;
}

// returns 1 and fills outputs, or 0 at end of epoch
int dtdl_loader_next(void* h, float* out_images, int32_t* out_labels) {
  Loader* L = (Loader*)h;
  if (L->next_emit >= L->n_batches) return 0;
  int slot = (int)(L->next_emit % L->depth);
  Batch* B = &L->slots[slot];
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->cv_ready.wait(lk, [&] { return B->ready && B->index == L->next_emit; });
  }
  std::memcpy(out_images, B->images.data(), B->images.size() * sizeof(float));
  std::memcpy(out_labels, B->labels.data(), B->labels.size() * sizeof(int32_t));
  {
    std::lock_guard<std::mutex> lk(L->mu);
    B->ready = false;
    L->next_emit++;
  }
  L->cv_free.notify_all();
  return 1;
}

int64_t dtdl_loader_n_batches(void* h) { return ((Loader*)h)->n_batches; }

void dtdl_loader_destroy(void* h) {
  Loader* L = (Loader*)h;
  L->stop.store(true);
  L->cv_free.notify_all();
  for (auto& t : L->workers) t.join();
  delete L;
}

// ---------------------------------------------------------------------------
// IDX(.gz) reader (zlib) — native replacement for the byte-loop parse
// ---------------------------------------------------------------------------

static std::vector<uint8_t> read_file_maybe_gz(const char* path, bool gz) {
  std::vector<uint8_t> out;
  if (gz) {
    gzFile f = gzopen(path, "rb");
    if (!f) return out;
    uint8_t buf[1 << 16];
    int got;
    while ((got = gzread(f, buf, sizeof(buf))) > 0)
      out.insert(out.end(), buf, buf + got);
    gzclose(f);
  } else {
    FILE* f = fopen(path, "rb");
    if (!f) return out;
    fseek(f, 0, SEEK_END);
    long sz = ftell(f);
    fseek(f, 0, SEEK_SET);
    out.resize(sz);
    if (fread(out.data(), 1, sz, f) != (size_t)sz) out.clear();
    fclose(f);
  }
  return out;
}

static inline uint32_t be32(const uint8_t* p) {
  return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
         ((uint32_t)p[2] << 8) | (uint32_t)p[3];
}

// Parse header: returns ndim (<=4) and fills dims; -1 on error.
int dtdl_idx_header(const char* path, int is_gz, int64_t* dims) {
  auto buf = read_file_maybe_gz(path, is_gz != 0);
  if (buf.size() < 4 || buf[0] != 0 || buf[1] != 0 || buf[2] != 0x08)
    return -1;  // only u8 payloads (MNIST) handled natively
  int ndim = buf[3];
  if (ndim < 1 || ndim > 4 || buf.size() < 4 + 4 * (size_t)ndim) return -1;
  for (int i = 0; i < ndim; ++i) dims[i] = be32(buf.data() + 4 + 4 * i);
  return ndim;
}

// Read payload as float32 scaled by 1/255 (images) into out (caller-sized).
int dtdl_idx_read_f32(const char* path, int is_gz, float* out, int64_t count,
                      float scale) {
  auto buf = read_file_maybe_gz(path, is_gz != 0);
  if (buf.size() < 4) return -1;
  int ndim = buf[3];
  size_t off = 4 + 4 * (size_t)ndim;
  if (buf.size() - off < (size_t)count) return -1;
  const uint8_t* p = buf.data() + off;
  for (int64_t i = 0; i < count; ++i) out[i] = p[i] * scale;
  return 0;
}

int dtdl_idx_read_i32(const char* path, int is_gz, int32_t* out,
                      int64_t count) {
  auto buf = read_file_maybe_gz(path, is_gz != 0);
  if (buf.size() < 4) return -1;
  int ndim = buf[3];
  size_t off = 4 + 4 * (size_t)ndim;
  if (buf.size() - off < (size_t)count) return -1;
  const uint8_t* p = buf.data() + off;
  for (int64_t i = 0; i < count; ++i) out[i] = p[i];
  return 0;
}

// ---------------------------------------------------------------------------
// host topology probe (for the slice launcher / runtime bootstrap)
// ---------------------------------------------------------------------------

int dtdl_topology(char* out, int cap) {
  long cpus = sysconf(_SC_NPROCESSORS_ONLN);
  long pages = sysconf(_SC_PHYS_PAGES);
  long page_sz = sysconf(_SC_PAGE_SIZE);
  char host[256] = {0};
  gethostname(host, sizeof(host) - 1);
  double mem_gb = (double)pages * page_sz / (1024.0 * 1024.0 * 1024.0);
  int n = snprintf(out, cap,
                   "{\"host\":\"%s\",\"cpus\":%ld,\"mem_gb\":%.1f}",
                   host, cpus, mem_gb);
  return (n > 0 && n < cap) ? n : -1;
}

}  // extern "C"
