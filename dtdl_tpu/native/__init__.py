"""Native (C++) runtime components, bound via ctypes.

The reference's host runtime is native code it borrows from its frameworks
(torch DataLoader workers at reference pytorch/single_gpu.py:60-61, TF's C++
input executor, ChainerMN's MPI glue — SURVEY §2.3).  This package is the
framework's own: ``dtdl_native.cpp`` compiled on first use with the system
toolchain (g++ -O3 -pthread -lz) into a cached shared library.  Everything
has a pure-Python fallback — ``available()`` gates all call sites.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile

log = logging.getLogger("dtdl_tpu")

_SRC = os.path.join(os.path.dirname(__file__), "src", "dtdl_native.cpp")
_LIB = None
_TRIED = False


def _build_dir() -> str:
    d = os.environ.get("DTDL_NATIVE_CACHE")
    if not d:
        d = os.path.join(tempfile.gettempdir(),
                         f"dtdl_native_{os.getuid()}")
    os.makedirs(d, exist_ok=True)
    return d


def _lib_path() -> str:
    with open(_SRC, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:16]
    return os.path.join(_build_dir(), f"libdtdl_native_{tag}.so")


def _compile(out: str) -> bool:
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           _SRC, "-o", out + ".tmp", "-lz"]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        log.warning("native build failed to run: %s", e)
        return False
    if r.returncode != 0:
        log.warning("native build failed:\n%s", r.stderr[-2000:])
        return False
    os.replace(out + ".tmp", out)
    return True


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    lib.dtdl_loader_create.restype = c.c_void_p
    lib.dtdl_loader_create.argtypes = [
        c.c_void_p, c.c_void_p, c.c_int64, c.c_int, c.c_int, c.c_int,
        c.c_int, c.c_int, c.c_int, c.c_int, c.c_uint64,
        c.c_void_p, c.c_void_p]
    lib.dtdl_loader_start_epoch.argtypes = [c.c_void_p, c.c_int]
    lib.dtdl_loader_start_epoch_indices.restype = c.c_int
    lib.dtdl_loader_start_epoch_indices.argtypes = [
        c.c_void_p, c.c_int, c.c_void_p, c.c_int64]
    lib.dtdl_loader_next.restype = c.c_int
    lib.dtdl_loader_next.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p]
    lib.dtdl_loader_n_batches.restype = c.c_int64
    lib.dtdl_loader_n_batches.argtypes = [c.c_void_p]
    lib.dtdl_loader_destroy.argtypes = [c.c_void_p]
    lib.dtdl_idx_header.restype = c.c_int
    lib.dtdl_idx_header.argtypes = [c.c_char_p, c.c_int, c.c_void_p]
    lib.dtdl_idx_read_f32.restype = c.c_int
    lib.dtdl_idx_read_f32.argtypes = [c.c_char_p, c.c_int, c.c_void_p,
                                      c.c_int64, c.c_float]
    lib.dtdl_idx_read_i32.restype = c.c_int
    lib.dtdl_idx_read_i32.argtypes = [c.c_char_p, c.c_int, c.c_void_p,
                                      c.c_int64]
    lib.dtdl_topology.restype = c.c_int
    lib.dtdl_topology.argtypes = [c.c_char_p, c.c_int]
    return lib


def load() -> ctypes.CDLL | None:
    """Compile (once) and load the native library; None if unavailable."""
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    if os.environ.get("DTDL_DISABLE_NATIVE"):
        return None
    path = _lib_path()
    if not os.path.exists(path) and not _compile(path):
        return None
    try:
        _LIB = _bind(ctypes.CDLL(path))
    except OSError as e:
        log.warning("native library load failed: %s", e)
        _LIB = None
    return _LIB


def available() -> bool:
    return load() is not None


def topology() -> dict:
    """Host topology probe (cpus, memory, hostname) for the launcher."""
    lib = load()
    if lib is None:
        import multiprocessing
        import socket
        return {"host": socket.gethostname(),
                "cpus": multiprocessing.cpu_count(), "mem_gb": None,
                "native": False}
    buf = ctypes.create_string_buffer(512)
    n = lib.dtdl_topology(buf, len(buf))
    if n < 0:
        return {"native": False}
    import json
    d = json.loads(buf.value.decode())
    d["native"] = True
    return d
