"""Manual-SPMD 4D-parallel transformer train step (dp × sp × pp × tp + ep).

The reference's only parallelism is batch data-parallel over NCCL (SURVEY
§2.2); this module is the framework's scale path beyond it: one
``shard_map`` over a 4-axis mesh ``('data', 'seq', 'pipe', 'model')``
composing every distributed-training dimension, with all collectives
explicit so they can be audited and scheduled:

* **dp**  — batch sharded over 'data'; gradient reduction falls out of the
  VMA-typed autodiff (the loss psum over 'data' transposes to the allreduce
  DDP fires from its grad hooks, reference
  pytorch/distributed_data_parallel.py:74,132).
* **sp**  — sequence sharded over 'seq' in the **zigzag layout** (each
  shard holds one low + one high chunk, so causal masking is
  load-balanced); **ring attention** rotates K/V via ``lax.ppermute``
  (dtdl_tpu/parallel/sequence.py) — one ICI hop per step, half a block of
  matmul per device per step.
* **pp**  — layers stacked ``[n_stages, layers_per_stage, ...]`` and sharded
  over 'pipe'.  Default schedule is **1F1B** (`_value_and_grad_1f1b`): an
  explicit forward+backward pipeline in one ``lax.scan``, remat per stage,
  vocab-parallel loss head used only on the last stage, activations capped
  at ``min(M, 2S-1)`` microbatch inputs.  ``schedule='gpipe'`` keeps the
  autodiff-through-scan GPipe schedule (`_loss_fn`).
* **tp**  — Megatron column→row parallel attention/MLP over 'model':
  QKV/up projections column-sharded, out/down projections row-sharded, one
  ``psum`` after attention-out and one after MLP-down per block.
* **ep**  — MoE experts sharded over 'model' (expert-parallel on the tensor
  axis).  Default dispatch is **routed**: capacity-factor top-1 routing with
  a token ``lax.all_to_all`` over 'model' to the expert's owner and back
  (dispatch FLOPs linear in tokens; dropped-token fraction reported in the
  step metrics).  ``moe_dispatch='dense'`` keeps the one-hot
  every-local-expert oracle.

Parameters are a plain pytree whose leaves carry global shapes; shard_map's
``in_specs`` (from ``param_specs``) place them.  Everything here is pure
JAX — the flax TransformerLM (dtdl_tpu/models/transformer.py) is the
single-device/GSPMD face of the same architecture.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dtdl_tpu import _compat
from dtdl_tpu.ops.attention import flash_attention
from dtdl_tpu.ops.rope import apply_rope, rope_frequencies
from dtdl_tpu.parallel.sequence import (
    ring_attention, zigzag_order, zigzag_positions,
)

DATA, SEQ, PIPE, MODEL = "data", "seq", "pipe", "model"
AXES = (DATA, SEQ, PIPE, MODEL)


@dataclasses.dataclass(frozen=True)
class MegatronConfig:
    vocab_size: int = 256
    d_model: int = 64
    n_heads: int = 4
    d_ff: int = 128
    n_stages: int = 2             # pipeline stages  (== mesh 'pipe' size)
    layers_per_stage: int = 1
    n_experts: int = 0            # 0 = dense MLP; else experts over 'model'
    max_seq: int = 128
    n_microbatches: int = 2
    schedule: str = "1f1b"        # '1f1b' (default) or 'gpipe'
    virtual_stages: int = 1       # v chunks/device: interleaved 1F1B when >1
    moe_dispatch: str = "routed"  # 'routed' (capacity + all-to-all) | 'dense'
    capacity_factor: float = 1.25  # per-expert slots = cf * tokens*k / E
    moe_top_k: int = 1            # experts per token (1 = Switch, 2 = GShard)
    # Switch-style load-balance aux loss weight, ADDED TO THE TRAINING LOSS
    # (not just a metric): capacity-factor routing with no balance pressure
    # collapses onto few experts and drops a growing token fraction — the
    # 0.01 default is the Switch Transformer setting.  0 disables.
    moe_aux_weight: float = 0.01
    dtype: jnp.dtype = jnp.bfloat16
    # fused-rope attend (round 19; ring-fused in kernel round 2): when
    # the 'seq' mesh axis is 1 (TP/PP-only meshes — no ring hops), the
    # local attend IS the whole sequence and rides the Pallas flash
    # kernel with the rotary embedding folded into its tile loads
    # (flash_attention(rope_positions=)), killing the last apply_rope
    # HBM round-trip (8·L·B·H·S·D bytes/step — SCALING.md round 13).
    # Sequence-parallel meshes (seq > 1) fuse through the ring instead:
    # ring_attention(rope=(cos, sin)) rotates each K block *inside* the
    # ppermute schedule at its owner's reconstructed zigzag positions,
    # so the pre-ring apply_rope of K never materializes and the ring
    # carries unrotated blocks — f32-exact vs the unfused path
    # (dtdl_tpu/parallel/sequence.py).  'auto' fuses only on real TPU
    # backends (the CPU fallback runs the flash kernel under the Pallas
    # interpreter, where fusion saves no bytes and costs interpret
    # overhead); True forces it anywhere (the parity tests), False
    # keeps the unfused apply_rope paths.
    fuse_rope: object = "auto"

    def __post_init__(self):
        if self.n_experts and not (1 <= self.moe_top_k <= self.n_experts):
            raise ValueError(
                f"moe_top_k={self.moe_top_k} must be in [1, n_experts="
                f"{self.n_experts}]")

    @property
    def head_dim(self):
        return self.d_model // self.n_heads

    @property
    def n_layers(self):
        return self.n_stages * self.layers_per_stage


def factor_mesh(n_devices: int) -> tuple[int, int, int, int]:
    """Cost-aware (data, seq, pipe, model) sizes for ``n_devices``.

    Two regimes:

    * **bootstrap (n <= 8)**: one doubling per axis in model -> pipe -> seq
      order, so small dev/test meshes exercise every parallelism axis
      (8 devices -> the canonical {data 1, seq 2, pipe 2, model 2} the
      test suite runs on).
    * **growth (n > 8)**: extra factors of two go to the axes in
      communication-cost order.  Tensor parallel first, up to 8 — its
      per-layer activation allreduces are the chattiest traffic and must
      stay inside one ICI domain (8 is the per-host chip count on v5e,
      the Megatron-LM default).  Pipeline next, up to 4 — per-hop traffic
      is one activation tensor and latency-tolerant, but the 1F1B bubble
      grows with stage count so it is capped, not greedy.  Sequence
      parallel stays at 2 by default (long-context runs that want more
      pass ``--mesh``).  Data parallelism absorbs everything left,
      including any odd factor — its one grad allreduce per step overlaps
      with the backward pass and is the axis that scales over DCN.

    16 -> (1,2,2,4), 32 -> (1,2,2,8), 64 -> (1,2,4,8), 128 -> (2,2,4,8).
    """
    shape = {"data": 1, "seq": 1, "pipe": 1, "model": 1}
    rem = n_devices
    for ax in ("model", "pipe", "seq"):          # bootstrap doublings
        if rem % 2 == 0:
            shape[ax] *= 2
            rem //= 2
    while rem % 2 == 0 and shape["model"] < 8:   # tp within ICI first
        shape["model"] *= 2
        rem //= 2
    while rem % 2 == 0 and shape["pipe"] < 4:    # then pp
        shape["pipe"] *= 2
        rem //= 2
    shape["data"] *= rem                         # dp takes the rest
    return (shape["data"], shape["seq"], shape["pipe"], shape["model"])


def build_4d_mesh(devices=None) -> Mesh:
    from dtdl_tpu.runtime.mesh import build_mesh
    if devices is None:
        devices = jax.devices()
    return build_mesh(shape=factor_mesh(len(devices)), axes=AXES,
                      devices=devices)


# ---------------------------------------------------------------------------
# parameters
# ---------------------------------------------------------------------------

def param_specs(cfg: MegatronConfig) -> dict:
    """PartitionSpec per parameter (global-shape view).

    Stacked block params lead with a [n_stages, layers_per_stage, ...]
    prefix sharded on 'pipe'; TP shards the head/ff dims on 'model'; expert
    weights shard the expert dim on 'model' (ep-on-tp).
    """
    specs = {
        "embed": P(None, None),            # [V, D] replicated
        "ln_f": P(),                       # [D]
        "blocks": {
            "ln_attn": P(PIPE),            # [st, L, D]
            "wq": P(PIPE, None, None, MODEL),   # [st, L, D, H*hd] col-parallel
            "wk": P(PIPE, None, None, MODEL),
            "wv": P(PIPE, None, None, MODEL),
            "wo": P(PIPE, None, MODEL, None),   # [st, L, H*hd, D] row-parallel
            "ln_mlp": P(PIPE),
        },
    }
    if cfg.n_experts:
        specs["blocks"].update({
            "router": P(PIPE, None, None, None),     # [st, L, D, E]
            "wi": P(PIPE, None, MODEL, None, None),  # [st, L, E, D, F]
            "wg": P(PIPE, None, MODEL, None, None),
            "wo_mlp": P(PIPE, None, MODEL, None, None),  # [st, L, E, F, D]
        })
    else:
        specs["blocks"].update({
            "wi": P(PIPE, None, None, MODEL),   # [st, L, D, F] col-parallel
            "wg": P(PIPE, None, None, MODEL),
            "wo_mlp": P(PIPE, None, MODEL, None),  # [st, L, F, D] row-parallel
        })
    return specs


def init_params(cfg: MegatronConfig, key) -> dict:
    """Global-shape parameter pytree (host-side init, then device_put)."""
    st, L, D = cfg.n_stages, cfg.layers_per_stage, cfg.d_model
    H, F, E = cfg.n_heads * cfg.head_dim, cfg.d_ff, cfg.n_experts
    keys = iter(jax.random.split(key, 16))

    def dense(k, shape):
        fan_in = shape[-2]
        return (jax.random.normal(k, shape, jnp.float32) /
                math.sqrt(fan_in)).astype(jnp.float32)

    blocks = {
        "ln_attn": jnp.ones((st, L, D)),
        "wq": dense(next(keys), (st, L, D, H)),
        "wk": dense(next(keys), (st, L, D, H)),
        "wv": dense(next(keys), (st, L, D, H)),
        "wo": dense(next(keys), (st, L, H, D)),
        "ln_mlp": jnp.ones((st, L, D)),
    }
    if E:
        blocks.update({
            "router": dense(next(keys), (st, L, D, E)),
            "wi": dense(next(keys), (st, L, E, D, F)),
            "wg": dense(next(keys), (st, L, E, D, F)),
            "wo_mlp": dense(next(keys), (st, L, E, F, D)),
        })
    else:
        blocks.update({
            "wi": dense(next(keys), (st, L, D, F)),
            "wg": dense(next(keys), (st, L, D, F)),
            "wo_mlp": dense(next(keys), (st, L, F, D)),
        })
    return {
        "embed": jax.random.normal(next(keys), (cfg.vocab_size, D)) * 0.02,
        "ln_f": jnp.ones((D,)),
        "blocks": blocks,
    }


# ---------------------------------------------------------------------------
# per-stage forward (runs on local shards inside shard_map)
# ---------------------------------------------------------------------------

def _rms(x, scale, eps=1e-6):
    x32 = x.astype(jnp.float32)
    return (x32 * lax.rsqrt(jnp.mean(x32 * x32, -1, keepdims=True) + eps)
            * scale).astype(x.dtype)


def _attention(cfg, p, x, cos, sin):
    """TP column→row attention with ring attention over 'seq'.

    ``p`` holds one layer's weights (wq/wk/wv [D, H/tp·hd], wo [H/tp·hd, D]).
    """
    b, s_loc, _ = x.shape
    h_loc = p["wq"].shape[-1] // cfg.head_dim    # local heads (H / tp)

    def proj(w):
        y = jnp.einsum("bsd,dh->bsh", x, w.astype(cfg.dtype))
        return y.reshape(b, s_loc, h_loc, cfg.head_dim).transpose(0, 2, 1, 3)

    q, k, v = proj(p["wq"]), proj(p["wk"]), proj(p["wv"])
    # zigzag layout: each 'seq' shard holds one low and one high chunk so
    # causal ring attention is load-balanced; RoPE uses true global
    # positions of the zigzag rows (shard_lm_batch lays the batch out).
    pos = zigzag_positions(SEQ, s_loc)
    sp = lax.axis_size(SEQ)               # static: the mesh is known
    fuse = cfg.fuse_rope
    if fuse == "auto":
        fuse = jax.default_backend() == "tpu"
    if fuse and sp == 1:
        # seq axis of 1: no ring hops — the local attend IS the whole
        # sequence, so the rotary embedding rides the flash kernel's
        # HBM→VMEM tile loads (round 13) instead of a per-layer
        # apply_rope round-trip.  zigzag positions are the identity at
        # n=1, so the kernel's index-causal mask == position-causal.
        o = flash_attention(q, k, v, causal=True, rope=(cos, sin),
                            rope_positions=(pos, pos))
    elif fuse:
        # seq axis > 1 (kernel round 2): the rotation rides the ring —
        # q/k go in unrotated and ring_attention rotates each K block
        # at its owner's zigzag positions inside the ppermute schedule,
        # skipping the pre-ring apply_rope materialization of K.
        o = ring_attention(q, k, v, axis_name=SEQ, causal=True,
                           layout="zigzag", rope=(cos, sin))
    else:
        q = apply_rope(q, cos, sin, positions=pos)
        k = apply_rope(k, cos, sin, positions=pos)
        o = ring_attention(q, k, v, axis_name=SEQ, causal=True,
                           layout="zigzag")
    o = o.transpose(0, 2, 1, 3).reshape(b, s_loc, h_loc * cfg.head_dim)
    y = jnp.einsum("bsh,hd->bsd", o, p["wo"].astype(cfg.dtype))
    return lax.psum(y, MODEL)                    # row-parallel combine


def _mlp_dense(cfg, p, x):
    wi = p["wi"].astype(cfg.dtype)
    wg = p["wg"].astype(cfg.dtype)
    wo = p["wo_mlp"].astype(cfg.dtype)
    h = jax.nn.silu(jnp.einsum("bsd,df->bsf", x, wg)) * \
        jnp.einsum("bsd,df->bsf", x, wi)
    return lax.psum(jnp.einsum("bsf,fd->bsd", h, wo), MODEL)


def _aux_balance_loss(first_choice_cnt, prob_sum, n_tok_global, n_experts):
    """Switch-Transformer load-balance loss E * <f, p> from GLOBAL stats.

    ``first_choice_cnt``/``prob_sum``/``n_tok_global`` must already be
    summed over every axis that partitions tokens, so the value (and its
    gradient through ``prob_sum``) is identical on every shard — which is
    what lets the dense-dispatch oracle and the routed path compute the
    same number, and the unsharded test oracle reproduce it.  ``f`` (the
    dispatch fractions) comes from argmax counts and is a constant under
    autodiff; the gradient pushes the *probabilities* toward balance.
    Matches the flax MoE module's sow'd aux (models/transformer.py).
    """
    denom = jnp.maximum(n_tok_global, 1.0)
    f = first_choice_cnt / denom
    pbar = prob_sum / denom
    return n_experts * jnp.sum(f * pbar)


def _mlp_moe_routed(cfg, p, x):
    """Capacity-factor top-k routed MoE: token all-to-all over 'model'.

    Real expert parallelism (the dense one-hot path below is the oracle):
    dispatch FLOPs are linear in tokens, not tokens x experts.

    Inside shard_map, ``x`` is MODEL-invariant (every tp shard holds the
    same tokens), so dispatch starts by *partitioning* the token set over
    'model' — each shard routes its T/tp slice (Megatron sequence-parallel
    MoE shape).  Routing takes the top ``cfg.moe_top_k`` experts per token
    (k=1: Switch, gate = raw top prob; k=2: GShard, gates renormalized over
    the chosen pair).  Per (source shard, expert) capacity ``C = ceil(cf *
    T_loc * k / E)`` slots, filled first-choices-first so a second choice
    never evicts a first choice; overflow assignments are *dropped*
    (Switch semantics).  One ``lax.all_to_all`` delivers every expert's
    tokens to the shard that owns it, the expert FFNs run batched over
    [e_loc, tp*C*k, D], and a second all-to-all returns outputs to the
    token's source shard, where they are gathered back to token order,
    gate-combined, and psum-restored to the MODEL-invariant layout every
    block ends with.

    Returns ``(y, (n_dropped, n_assign, aux))``: dropped/total *assignment*
    accounting (psummed over 'model'; the step reports their ratio as
    ``moe_dropped_frac``) and the load-balance aux loss from global router
    stats (`_aux_balance_loss`), which the train step adds to the loss
    with weight ``cfg.moe_aux_weight``.
    """
    e_loc = p["wi"].shape[0]                     # local experts (E / tp)
    tp = lax.axis_size(MODEL)
    my = lax.axis_index(MODEL)
    E = e_loc * tp
    K = cfg.moe_top_k
    b, s, D = x.shape
    T = b * s
    xf = x.reshape(T, D)
    Tp = -(-T // tp) * tp                        # pad to a tp multiple
    if Tp != T:
        xf = jnp.pad(xf, ((0, Tp - T), (0, 0)))
    T_loc = Tp // tp
    xs = lax.dynamic_slice_in_dim(xf, my * T_loc, T_loc, 0)  # my slice
    valid = (my * T_loc + jnp.arange(T_loc)) < T

    logits = jnp.einsum("td,de->te", xs.astype(jnp.float32), p["router"])
    probs = jax.nn.softmax(logits, -1)
    topv, topi = lax.top_k(probs, K)             # [T_loc, K]
    if K == 1:
        gate_w = topv                            # Switch: raw top-1 prob
    else:                                        # GShard: renormalized pair
        gate_w = topv / jnp.maximum(jnp.sum(topv, -1, keepdims=True), 1e-9)
    eid = jnp.where(valid[:, None], topi, E)     # padding routes nowhere

    # load-balance stats over the GLOBAL batch: sum over the 'model' token
    # partition AND the data/seq shards, so every shard holds the same aux
    cnt1 = jnp.sum(jax.nn.one_hot(eid[:, 0], E, dtype=jnp.float32), 0)
    prob_sum = jnp.sum(probs * valid[:, None].astype(jnp.float32), 0)
    n_tok_g = jnp.sum(valid.astype(jnp.float32))
    # pcast to one varying set first: n_tok_g is shape-derived (invariant
    # over data/seq) while cnt1/prob_sum vary — psum rejects mixed states
    cnt1, prob_sum, n_tok_g = lax.psum(
        tuple(_vary(a, (DATA, SEQ, MODEL))
              for a in (cnt1, prob_sum, n_tok_g)),
        (DATA, SEQ, MODEL))
    aux = _aux_balance_loss(cnt1, prob_sum, n_tok_g, E)

    # choice-major flattening: ALL first choices take slots before any
    # second choice, so k=1 behavior is unchanged and a 2nd choice never
    # displaces a 1st
    eidf = eid.T.reshape(K * T_loc)
    validf = jnp.tile(valid, K)
    C = max(1, math.ceil(cfg.capacity_factor * T_loc * K / E))
    oh = jax.nn.one_hot(eidf, E, dtype=jnp.int32)  # zero row for eid == E
    pos = jnp.take_along_axis(jnp.cumsum(oh, 0) - 1,
                              jnp.clip(eidf, 0, E - 1)[:, None], 1)[:, 0]
    kept = (eidf < E) & (pos < C)
    n_drop = jnp.sum((validf & ~kept).astype(jnp.float32))
    n_assign = jnp.sum(validf.astype(jnp.float32))

    # scatter assignments into per-expert slots; out-of-capacity rows drop
    xsk = jnp.tile(xs.astype(cfg.dtype), (K, 1))   # choice-major copies
    send = jnp.zeros((E, C, D), cfg.dtype).at[eidf, pos].set(
        xsk, mode="drop")
    # a2a #1: expert-major chunks -> the shard owning those experts
    recv = lax.all_to_all(send, MODEL, 0, 0, tiled=True)  # [tp*e_loc, C, D]
    toks = recv.reshape(tp, e_loc, C, D).transpose(1, 0, 2, 3)
    toks = toks.reshape(e_loc, tp * C, D)
    wi = p["wi"].astype(cfg.dtype)
    wg = p["wg"].astype(cfg.dtype)
    wo = p["wo_mlp"].astype(cfg.dtype)
    h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", toks, wg)) * \
        jnp.einsum("ecd,edf->ecf", toks, wi)
    out = jnp.einsum("ecf,efd->ecd", h, wo)      # [e_loc, tp*C, D]
    # a2a #2: back to each token's source shard, global-expert-id order
    back = out.reshape(e_loc, tp, C, D).transpose(1, 0, 2, 3)
    back = back.reshape(tp * e_loc, C, D)
    ybuf = lax.all_to_all(back, MODEL, 0, 0, tiled=True)  # [E, C, D]
    yk = ybuf.at[eidf, pos].get(mode="fill", fill_value=0)  # [K*T_loc, D]
    w_k = (gate_w.T.reshape(K * T_loc) * kept.astype(jnp.float32))
    y = jnp.sum((yk * w_k.astype(cfg.dtype)[:, None]).reshape(K, T_loc, D),
                axis=0)

    # restore the full MODEL-invariant token set (each shard contributes
    # its slice; the psum is the same row-parallel combine the dense MLP
    # block ends with)
    yfull = jnp.zeros((tp, T_loc, D), cfg.dtype).at[my].set(y)
    yfull = lax.psum(yfull, MODEL).reshape(Tp, D)[:T]
    stats = (lax.psum(n_drop, MODEL), lax.psum(n_assign, MODEL), aux)
    return yfull.reshape(b, s, D), stats


def _mlp_moe(cfg, p, x):
    """Expert-parallel switch MLP: local experts, one-hot dispatch, psum.

    O(tokens x experts) compute — kept as the *oracle* for the routed path
    (``moe_dispatch='dense'``); with ample capacity the two compute the
    identical function, at any ``moe_top_k`` (tests/test_megatron.py).

    Returns ``(y, (0, 0, aux))``: dense dispatch never drops, and the
    load-balance aux uses the same global-stats formula as the routed
    path — here tokens are MODEL-replicated, so the stat psum spans only
    the data/seq shards."""
    e_loc = p["wi"].shape[0]                     # [E/tp, D, F] local experts
    my = lax.axis_index(MODEL)
    E = e_loc * lax.axis_size(MODEL)
    K = cfg.moe_top_k
    router = p["router"]                         # [D, E] replicated
    logits = jnp.einsum("bsd,de->bse", x.astype(jnp.float32), router)
    probs = jax.nn.softmax(logits, -1)
    topv, topi = lax.top_k(probs, K)             # [b, s, K]
    if K == 1:
        gate_w = topv
    else:
        gate_w = topv / jnp.maximum(jnp.sum(topv, -1, keepdims=True), 1e-9)

    cnt1 = jnp.sum(jax.nn.one_hot(topi[..., 0], E, dtype=jnp.float32),
                   axis=(0, 1))
    prob_sum = jnp.sum(probs, axis=(0, 1))
    n_tok = jnp.float32(probs.shape[0] * probs.shape[1])
    cnt1, prob_sum, n_tok = lax.psum(
        tuple(_vary(a, (DATA, SEQ)) for a in (cnt1, prob_sum, n_tok)),
        (DATA, SEQ))
    aux = _aux_balance_loss(cnt1, prob_sum, n_tok, E)

    wi = p["wi"].astype(cfg.dtype)               # [e_loc, D, F]
    wg = p["wg"].astype(cfg.dtype)
    wo = p["wo_mlp"].astype(cfg.dtype)
    y = jnp.zeros(x.shape, cfg.dtype)
    for k in range(K):
        local_id = topi[..., k] - my * e_loc     # position among my experts
        onehot = jax.nn.one_hot(local_id, e_loc, dtype=jnp.float32)
        xe = jnp.einsum("bse,bsd->ebsd", onehot.astype(cfg.dtype), x)
        h = jax.nn.silu(jnp.einsum("ebsd,edf->ebsf", xe, wg)) * \
            jnp.einsum("ebsd,edf->ebsf", xe, wi)
        yk = jnp.einsum("ebsf,efd->bsd", h, wo)
        y = y + lax.psum(yk, MODEL) * gate_w[..., k:k + 1].astype(cfg.dtype)
    zero = jnp.zeros((), jnp.float32)
    return y, (zero, zero, aux)


def _stage_forward(cfg, stage_params, x, cos, sin):
    """Apply this stage's blocks: lax.scan over the stacked layer dim.

    Returns ``(x, (n_dropped, n_assign, aux))`` — per-stage MoE
    dropped-assignment sums (zeros for dense MLP) and the summed
    load-balance aux over this stage's layers, stacked by the scan and
    summed here so the schedules can thread one scalar triple."""
    def block(x, p):
        h = _rms(x, p["ln_attn"])
        x = x + _attention(cfg, p, h, cos, sin)
        h = _rms(x, p["ln_mlp"])
        zero = jnp.zeros((), jnp.float32)
        stats = (zero, zero, zero)
        if cfg.n_experts and cfg.moe_dispatch == "routed":
            y, stats = _mlp_moe_routed(cfg, p, h)
            x = x + y
        elif cfg.n_experts:
            y, stats = _mlp_moe(cfg, p, h)
            x = x + y
        else:
            x = x + _mlp_dense(cfg, p, h)
        return x, stats

    x, stats = lax.scan(block, x, stage_params)
    return x, jax.tree.map(jnp.sum, stats)


# ---------------------------------------------------------------------------
# the GPipe schedule + loss (inside shard_map)
# ---------------------------------------------------------------------------

def _pipeline(cfg, params, x_micro, cos, sin):
    """Run microbatches through the pipe; returns stacked outputs.

    ``x_micro``: [n_micro, mb, s_loc, D] local embedded microbatches.
    Stage s processes tick t's buffer if ``0 <= t - s < n_micro``; a
    ``ppermute`` shifts buffers to the next stage each tick.  Output
    microbatch m leaves the last stage at tick ``m + n_stages - 1``.
    """
    stage = lax.axis_index(PIPE)
    n_stages, n_micro = cfg.n_stages, cfg.n_microbatches
    stage_params = jax.tree.map(lambda a: a[0], params["blocks"])
    # NB: shard_map has already sliced the [n_stages, ...] dim to size 1.

    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    mb_shape = x_micro.shape[1:]
    n_ticks = n_micro + n_stages - 1

    def tick(carry, t):
        buf, outputs, drop, tot, auxs = carry
        # stage 0 injects microbatch t (garbage after n_micro ticks, masked)
        inject = lax.dynamic_index_in_dim(
            x_micro, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
        buf = jnp.where(stage == 0, inject, buf)
        y, st = _stage_forward(cfg, stage_params, buf, cos, sin)
        # this stage holds real (not garbage/masked) data for tick t iff
        # microbatch t - stage is in range — gate the MoE accounting (the
        # where also zeroes aux-loss cotangents into garbage ticks)
        active = ((t - stage) >= 0) & ((t - stage) < n_micro)
        drop = drop + jnp.where(active, st[0], 0.0)
        tot = tot + jnp.where(active, st[1], 0.0)
        auxs = auxs + jnp.where(active, st[2], 0.0)
        # last stage collects output microbatch t - (n_stages - 1)
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        collect = (stage == n_stages - 1) & (t >= n_stages - 1)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(collect,
                               y.astype(outputs.dtype),
                               lax.dynamic_index_in_dim(
                                   outputs, out_idx, 0, keepdims=False)),
            out_idx, 0)
        buf = lax.ppermute(y, PIPE, perm)
        return (buf, outputs, drop, tot, auxs), None

    # Carry vma: activations vary over the batch axes and (once stage params
    # touch them) 'pipe'; they stay *invariant* over 'model' because every
    # block ends in a psum(MODEL).  Pre-cast the injected microbatches and the
    # zero-init carries to exactly that set so the scan types close.
    vary_axes = tuple(sorted(
        set(jax.typeof(x_micro).vma or ()) | {PIPE}))
    x_micro = lax.pcast(
        x_micro, tuple(a for a in vary_axes
                       if a not in (jax.typeof(x_micro).vma or ())),
        to="varying")
    buf0 = lax.pcast(jnp.zeros(mb_shape, cfg.dtype), vary_axes, to="varying")
    outs0 = lax.pcast(jnp.zeros((n_micro,) + mb_shape, cfg.dtype),
                      vary_axes, to="varying")
    stat0 = lax.pcast(jnp.zeros((), jnp.float32), vary_axes, to="varying")
    (_, outputs, drop, tot, auxs), _ = lax.scan(
        tick, (buf0, outs0, stat0, stat0, stat0), jnp.arange(n_ticks))
    # broadcast last stage's outputs to every stage (head/loss replicated)
    outputs = lax.psum(
        jnp.where(stage == n_stages - 1, outputs,
                  jnp.zeros_like(outputs)), PIPE)
    return outputs, (drop, tot, auxs)


def _loss_fn(cfg: MegatronConfig, params, tokens, targets, mask):
    """Global-mean causal LM loss on local shards. Inside shard_map.

    tokens/targets/mask: [b_loc, s_loc] int32 / int32 / f32.
    """
    b_loc, s_loc = tokens.shape
    n_micro = cfg.n_microbatches
    emb = params["embed"]
    x = jnp.take(emb, tokens, axis=0).astype(cfg.dtype)   # [b, s, D]
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq)

    mb = b_loc // n_micro
    x_micro = x.reshape(n_micro, mb, s_loc, cfg.d_model)
    y, (drop, tot, auxs) = _pipeline(cfg, params, x_micro, cos, sin)
    y = y.reshape(b_loc, s_loc, cfg.d_model)

    y = _rms(y, params["ln_f"])
    logits = jnp.einsum("bsd,vd->bsv", y.astype(jnp.float32),
                        emb.astype(jnp.float32))
    lse = jax.nn.logsumexp(logits, -1)
    true_logit = jnp.take_along_axis(
        logits, targets[..., None], axis=-1)[..., 0]
    local_sum = jnp.sum((lse - true_logit) * mask)
    total = lax.psum(jnp.sum(mask), (DATA, SEQ))
    loss = lax.psum(local_sum, (DATA, SEQ)) / jnp.maximum(total, 1.0)
    # per-(layer, microbatch) aux values are GLOBAL (psummed over
    # data/seq/model inside the MoE op), so every data/seq shard
    # accumulated the same sums: pmean is the value-preserving demotion,
    # psum would multiply by the shard count.  psum(PIPE) sums the stages'
    # disjoint layer contributions.
    aux_mean = jnp.zeros((), jnp.float32)
    if cfg.n_experts:
        aux_mean = lax.pmean(lax.psum(auxs, PIPE), (DATA, SEQ)) \
            / (cfg.n_layers * n_micro)
        loss = loss + cfg.moe_aux_weight * aux_mean
    aux = (lax.psum(drop, (DATA, SEQ, PIPE)),
           lax.psum(tot, (DATA, SEQ, PIPE)), aux_mean)
    return loss, aux


# ---------------------------------------------------------------------------
# the 1F1B schedule (explicit-VJP pipeline, inside shard_map)
# ---------------------------------------------------------------------------

def n_pipeline_ticks(cfg: MegatronConfig) -> int:
    """Combined fwd+bwd tick count of the (interleaved) 1F1B scan.

    General formula for v = ``virtual_stages`` chunks per device: the last
    microbatch's chunk-0 backward lands at
    ``(vS-1) + (S-1) + g*vS + (v-1)S + j`` where ``(g, j) = divmod(M-1, S)``.
    v=1 reduces to the classic ``M + 2(S-1)``.
    """
    S, M, v = cfg.n_stages, cfg.n_microbatches, cfg.virtual_stages
    g, j = divmod(M - 1, S)
    return (v * S - 1) + (S - 1) + g * v * S + (v - 1) * S + j + 1


def bubble_fraction(cfg: MegatronConfig) -> float:
    """Idle TIME fraction of the segmented (interleaved) 1F1B schedule.

    The scan is split into three segments (see `_value_and_grad_1f1b`):
    ``vS-1`` forward-only warmup ticks (cost tf/v each), ``T - 2(vS-1)``
    two-lane steady ticks ((tf+tb)/v), and ``vS-1`` backward-only
    cooldown ticks (tb/v).  Useful work per device is ``M(tf+tb)``; the
    excess idle time is exactly ``(S-1)(tf+tb)/v`` when M is a multiple
    of S — **the Megatron interleaved-1F1B bubble bound** (v=1 reduces
    to the classic 1F1B ``(S-1)/(M+S-1)`` fraction).  The earlier
    two-lane lockstep scan paid (tf+tb)/v on every tick including warmup
    and cooldown, capping at ~S(v+1)/(2v) chunk-times of idle;
    segmenting removed that structural penalty without touching the
    per-tick math.

    The *fraction* is independent of the tf:tb ratio by construction:
    warmup and cooldown have equal tick counts, so their combined cost
    is ``(vS-1)(tf+tb)/v`` and the ``(tf+tb)`` factor cancels —
    ``1 - Mv / (T - (vS-1))``.

    Relative to the GPipe path (`_loss_fn`): GPipe's scan runs M + S - 1
    forward ticks and lets autodiff replay them backward; its peak memory
    holds all M microbatch activations, while this schedule saves only
    ``min(k_span, 2vS-1)`` chunk inputs (k_span = M*v when M % S == 0)
    and needs no cross-stage broadcast.
    """
    S, m, v = cfg.n_stages, cfg.n_microbatches, cfg.virtual_stages
    return 1.0 - m * v / (n_pipeline_ticks(cfg) - (v * S - 1))


def _vary(x, axes):
    """pcast ``x`` to additionally vary over ``axes`` (no-op where it does)."""
    have = jax.typeof(x).vma or ()
    add = tuple(a for a in axes if a not in have)
    return lax.pcast(x, add, to="varying") if add else x


def _head_loss(cfg, emb, ln_f, y, targets, mask, inv_total):
    """Vocab-parallel LM head: scaled loss-sum of one microbatch.

    The vocab dim is sharded over 'model' (Megatron-style vocab-parallel
    cross entropy): each tp shard computes logits for its V/tp slice, the
    logsumexp and true-logit gather are combined with one scalar-per-token
    psum('model') each — the full [.., V] logits never materialize per
    device when tp > 1.
    """
    v = cfg.vocab_size
    tp = lax.axis_size(MODEL)
    h = _rms(y, ln_f).astype(jnp.float32)
    if tp > 1 and v % tp == 0:
        v_loc = v // tp
        off = lax.axis_index(MODEL) * v_loc
        emb_slice = lax.dynamic_slice_in_dim(emb, off, v_loc, 0)
        logits = jnp.einsum("bsd,vd->bsv", h, emb_slice.astype(jnp.float32))
        mx = lax.pmax(lax.stop_gradient(jnp.max(logits, -1)), MODEL)
        se = lax.psum(jnp.sum(jnp.exp(logits - mx[..., None]), -1), MODEL)
        lse = mx + jnp.log(se)
        in_range = (targets >= off) & (targets < off + v_loc)
        idx = jnp.clip(targets - off, 0, v_loc - 1)
        true_logit = lax.psum(
            jnp.where(in_range,
                      jnp.take_along_axis(logits, idx[..., None], -1)[..., 0],
                      0.0), MODEL)
    else:
        logits = jnp.einsum("bsd,vd->bsv", h, emb.astype(jnp.float32))
        lse = jax.nn.logsumexp(logits, -1)
        true_logit = jnp.take_along_axis(
            logits, targets[..., None], -1)[..., 0]
    loss = jnp.sum((lse - true_logit) * mask) * inv_total
    if MODEL in (jax.typeof(loss).vma or ()):
        # replicated-head branch: every tp shard computed the same value;
        # pmean is a value-preserving demotion to MODEL-unvarying, keeping
        # the scan carry types identical across both branches
        loss = lax.pmean(loss, MODEL)
    return loss


def _value_and_grad_1f1b(cfg: MegatronConfig, params, tokens, targets, mask):
    """(loss, grads) via an explicit (interleaved) 1F1B schedule.  Inside
    shard_map.

    Three ``lax.scan`` segments totalling :func:`n_pipeline_ticks` ticks:
    a forward-only warmup (vS-1 ticks), a two-lane steady phase, and a
    backward-only cooldown (vS-1 ticks) — per steady tick, every device
    runs one forward *chunk* and one backward *chunk* (rematerialized
    ``jax.vjp``), where a chunk is ``layers_per_stage / virtual_stages`` of
    its layers.  Segmenting prunes the provably-idle lane from the ramp
    ticks, landing the schedule on the Megatron interleaved bubble bound
    ``(S-1)(tf+tb)/v`` (`bubble_fraction`).  With ``v = virtual_stages``
    chunks per device the model is
    a virtual pipeline of depth ``V = v*S`` whose hops always target the
    next/prev device on the 'pipe' ring (chunk c on device S-1 wraps to
    chunk c+1 on device 0), so the two ``ppermute``s per tick are unchanged
    from the plain schedule.  Forward index math at tick ``t`` on device
    ``s``: ``t' = t - s``, group ``g = t' // (vS)``, chunk
    ``c = (t' mod vS) // S``, microbatch ``m = g*S + (t' mod S)`` — v=1
    reduces to the classic ``m = t - s``.  The backward lane mirrors it
    shifted by ``(vS-1) + (S-1-s)``, so the last device backprops a
    microbatch's final chunk the same tick it finishes its forward — the
    1F1B steady state, at any v.

    Compared with autodiff through the GPipe scan (`_loss_fn`), this (a)
    caps live activations at ``min(k_span, 2vS-1)`` chunk *inputs* (remat
    recomputes the rest), (b) never psum-broadcasts stage outputs — only
    scalar loss + per-microbatch dy leave the last device, and (c) shards
    the head's vocab dim over 'model'.  SPMD lockstep means every device
    still *executes* the head each tick (results masked off-stage).

    Gradient reductions that fall out of VMA-typed autodiff in `_loss_fn`
    are explicit here: chunk/embed/ln_f cotangents are accumulated locally
    (params pcast varying) and psummed once after the scan.  The head and
    input-embedding cotangents share ONE [V, D] accumulator (the head's
    contribution is MODEL-sharded by the vocab-parallel head; the input
    side is pre-divided by tp so the single psum over all axes is exact).
    """
    S, M, v = cfg.n_stages, cfg.n_microbatches, cfg.virtual_stages
    if cfg.layers_per_stage % v:
        raise ValueError(f"virtual_stages={v} must divide "
                         f"layers_per_stage={cfg.layers_per_stage}")
    Lc = cfg.layers_per_stage // v           # layers per chunk
    b_loc, s_loc = tokens.shape
    mb = b_loc // M
    D = cfg.d_model
    stage = lax.axis_index(PIPE)
    tp = lax.axis_size(MODEL)
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq)

    inv_total = 1.0 / jnp.maximum(
        lax.psum(jnp.sum(mask), (DATA, SEQ)), 1.0)
    tok_micro = _vary(tokens.reshape(M, mb, s_loc), (PIPE,))
    tgt_micro = _vary(targets.reshape(M, mb, s_loc), (PIPE,))
    msk_micro = _vary(mask.reshape(M, mb, s_loc), (PIPE,))

    # localized (per-device cotangent) copies of everything we differentiate
    p_stage = jax.tree.map(lambda a: _vary(a[0], (DATA, SEQ)),
                           params["blocks"])
    emb_v = _vary(params["embed"], (DATA, SEQ, PIPE, MODEL))
    lnf_v = _vary(params["ln_f"], (DATA, SEQ, PIPE))

    def chunk_params(c):
        return jax.tree.map(
            lambda a: lax.dynamic_slice_in_dim(a, c * Lc, Lc, 0), p_stage)

    def chunk_fn(p, x):
        """(activations, load-balance aux) of one chunk — the aux output is
        part of the differentiated function so the backward lane can inject
        its loss cotangent (``aux_cot``) through the same rematerialized
        vjp that produces dx/dw."""
        y, st = _stage_forward(cfg, p, x, cos, sin)
        return y, _vary(st[2], (PIPE,))

    # d(loss)/d(chunk aux output): the aux objective is the mean over all
    # (layer, microbatch) pairs, weighted by moe_aux_weight — each chunk's
    # aux is a plain sum term, so its cotangent is the constant norm
    aux_cot_w = (cfg.moe_aux_weight / (cfg.n_layers * M)
                 if cfg.n_experts else 0.0)

    perm_up = [(i, (i + 1) % S) for i in range(S)]
    perm_down = [(i, (i - 1) % S) for i in range(S)]
    # ring-buffer slots for saved chunk inputs, keyed by the dense fwd-order
    # index k = g*vS + cS + j.  With a partial last group (M % S != 0) k is
    # not dense, so the small-M cap is the k-range, not M*v.
    g_last, j_last = divmod(M - 1, S)
    k_span = g_last * v * S + (v - 1) * S + j_last + 1
    n_slots = min(k_span, 2 * v * S - 1)
    n_ticks = n_pipeline_ticks(cfg)

    act_axes = tuple(sorted(set(jax.typeof(tok_micro).vma or ())))
    zeros_act = lambda shape: _vary(jnp.zeros(shape, cfg.dtype), act_axes)
    carry0 = dict(
        buf_f=zeros_act((mb, s_loc, D)),
        buf_b=zeros_act((mb, s_loc, D)),
        x_saved=zeros_act((n_slots, mb, s_loc, D)),
        dw=jax.tree.map(lambda a: jnp.zeros_like(a, jnp.float32), p_stage),
        demb=jnp.zeros_like(emb_v, jnp.float32),
        dlnf=jnp.zeros_like(lnf_v, jnp.float32),
        loss=_vary(jnp.zeros((), jnp.float32), act_axes),
        drop=_vary(jnp.zeros((), jnp.float32), act_axes),
        tot=_vary(jnp.zeros((), jnp.float32), act_axes),
        auxs=_vary(jnp.zeros((), jnp.float32), act_axes),
    )

    def fwd_indices(t):
        """(active, chunk, microbatch, dense-order k) of this device's
        forward lane at tick t."""
        tp_ = t - stage
        g = jnp.floor_divide(tp_, v * S)
        w = jnp.mod(tp_, v * S)
        c = jnp.floor_divide(w, S)
        m = g * S + jnp.mod(w, S)
        active = (tp_ >= 0) & (m < M)
        return active, c, jnp.clip(m, 0, M - 1), jnp.maximum(tp_, 0)

    def bwd_indices(t):
        """Mirror of fwd_indices, shifted by (vS-1) + (S-1-stage); the
        chunk counter runs top-down (chunk = v-1 - c')."""
        tb = t - (v * S - 1) - (S - 1 - stage)
        g = jnp.floor_divide(tb, v * S)
        w = jnp.mod(tb, v * S)
        cprime = jnp.floor_divide(w, S)
        j = jnp.mod(w, S)
        m = g * S + j
        active = (tb >= 0) & (m < M)
        chunk = v - 1 - cprime
        # dense fwd-order index of the entry being backproped (its slot)
        k = g * (v * S) + chunk * S + j
        return active, chunk, jnp.clip(m, 0, M - 1), jnp.maximum(k, 0)

    def make_tick(do_fwd: bool, do_bwd: bool):
        """One scan body specialized (at trace time) to its schedule
        segment.  The two-lane lockstep body used to run for ALL ticks,
        paying forward+backward chunk cost even through the warmup
        (where every device's backward lane is provably idle: tb <=
        t-(vS-1) < 0) and the cooldown (symmetrically, no forward lane
        and no head anywhere).  Splitting the scan into fwd-only /
        two-lane / bwd-only segments removes exactly that waste: per-tick
        cost (tf+tb)/v only in the steady segment, tf/v in warmup, tb/v
        in cooldown — total bubble (S-1)(tf+tb)/v, the Megatron
        interleaved 1F1B bound (see `bubble_fraction`)."""

        def tick(carry, t):
            x_saved = carry["x_saved"]
            loss, demb, dlnf = carry["loss"], carry["demb"], carry["dlnf"]
            drop, tot, auxs = carry["drop"], carry["tot"], carry["auxs"]
            y = dy_head = None
            if do_fwd:
                # ---- forward lane: chunk c_f of microbatch m_f ----------
                f_active, c_f, m_idx, k_f = fwd_indices(t)
                tok_f = lax.dynamic_index_in_dim(tok_micro, m_idx, 0,
                                                 keepdims=False)
                inject = jnp.take(params["embed"], tok_f,
                                  axis=0).astype(cfg.dtype)
                x_in = jnp.where((stage == 0) & (c_f == 0), inject,
                                 carry["buf_f"])
                slot_f = jnp.mod(k_f, n_slots)
                old = lax.dynamic_index_in_dim(x_saved, slot_f, 0,
                                               keepdims=False)
                x_saved = lax.dynamic_update_index_in_dim(
                    x_saved, jnp.where(f_active, x_in, old), slot_f, 0)
                p_f = chunk_params(c_f)
                y, st = _stage_forward(cfg, p_f, x_in, cos, sin)
                drop = drop + jnp.where(f_active, st[0], 0.0)
                tot = tot + jnp.where(f_active, st[1], 0.0)
                auxs = auxs + jnp.where(f_active, st[2], 0.0)

            if do_fwd and do_bwd:
                # ---- head on the final chunk's output (last device) ----
                # only the steady segment needs it: the first head fires
                # at t = vS-1 (after warmup) and its dy is consumed by the
                # SAME tick's backward lane, never later
                tgt = lax.dynamic_index_in_dim(tgt_micro, m_idx, 0,
                                               keepdims=False)
                msk = lax.dynamic_index_in_dim(msk_micro, m_idx, 0,
                                               keepdims=False)
                loss_m, head_vjp = jax.vjp(
                    lambda e, lf, yy: _head_loss(cfg, e, lf, yy, tgt, msk,
                                                 inv_total),
                    emb_v, lnf_v, y)
                demb_m, dlnf_m, dy_head = head_vjp(
                    _vary(jnp.float32(1.0), jax.typeof(loss_m).vma or ()))
                head_active = (stage == S - 1) & (c_f == v - 1) & f_active
                loss = loss + jnp.where(head_active, loss_m, 0.0)
                demb = demb + jnp.where(head_active, demb_m, 0.0)
                dlnf = dlnf + jnp.where(head_active, dlnf_m, 0.0)

            dw, dx = carry["dw"], None
            if do_bwd:
                # ---- backward lane: chunk c_b of microbatch u_b ---------
                b_active, c_b, u_idx, k_b = bwd_indices(t)
                x_b = lax.dynamic_index_in_dim(
                    x_saved, jnp.mod(k_b, n_slots), 0, keepdims=False)
                dy = carry["buf_b"]
                if dy_head is not None:
                    dy = jnp.where((stage == S - 1) & (c_b == v - 1),
                                   dy_head, dy)
                p_b = chunk_params(c_b)
                (_, aux_b), chunk_vjp = jax.vjp(chunk_fn, p_b, x_b)
                # the aux-loss cotangent rides the same rematerialized
                # chunk vjp as the activation cotangent; inactive backward
                # lanes get zero
                aux_cot = jnp.where(b_active, jnp.float32(aux_cot_w), 0.0)
                dw_m, dx = chunk_vjp((dy, _vary(aux_cot,
                                                jax.typeof(aux_b).vma
                                                or ())))

                def acc_chunk(a, d):
                    cur = lax.dynamic_slice_in_dim(a, c_b * Lc, Lc, 0)
                    return lax.dynamic_update_slice_in_dim(
                        a, cur + jnp.where(b_active, d, 0.0), c_b * Lc, 0)

                dw = jax.tree.map(acc_chunk, dw, dw_m)
                # input-embedding cotangent (scatter-add), device 0 chunk
                # 0 only; pre-divided by tp so it can share the
                # MODEL-psummed accumulator
                tok_b = lax.dynamic_index_in_dim(tok_micro, u_idx, 0,
                                                 keepdims=False)
                _, embed_vjp = jax.vjp(
                    lambda e: jnp.take(e, tok_b, axis=0).astype(cfg.dtype),
                    emb_v)
                (demb_u,) = embed_vjp(_vary(dx, (MODEL,)))
                demb = demb + jnp.where(
                    b_active & (stage == 0) & (c_b == 0), demb_u / tp, 0.0)

            # ---- ring handoffs (only the lanes that ran) ---------------
            new_carry = dict(
                buf_f=lax.ppermute(y, PIPE, perm_up)
                if do_fwd else carry["buf_f"],
                buf_b=lax.ppermute(dx, PIPE, perm_down)
                if do_bwd else carry["buf_b"],
                x_saved=x_saved, dw=dw, demb=demb,
                dlnf=dlnf, loss=loss, drop=drop, tot=tot, auxs=auxs)
            return new_carry, None

        return tick

    # schedule segments: warmup [0, vS-1) has no backward anywhere
    # (tb = t-(vS-1)-(S-1-s) < 0 for every s), cooldown [fwd_end, T) has
    # no forward anywhere (every device past its last microbatch) and no
    # head (a head's dy is consumed the same tick it is produced) —
    # n_pipeline_ticks = fwd_end + (vS-1), so the segments partition it
    warm_end = v * S - 1
    fwd_end = n_ticks - warm_end
    carry = carry0
    if warm_end:
        carry, _ = lax.scan(make_tick(True, False), carry,
                            jnp.arange(0, warm_end))
    carry, _ = lax.scan(make_tick(True, True), carry,
                        jnp.arange(warm_end, fwd_end))
    if warm_end:
        carry, _ = lax.scan(make_tick(False, True), carry,
                            jnp.arange(fwd_end, n_ticks))

    # ---- combine cotangents into global-layout grads ---------------------
    demb = lax.psum(carry["demb"], (DATA, SEQ, PIPE, MODEL))
    dlnf = lax.psum(carry["dlnf"], (DATA, SEQ, PIPE))
    dblocks = jax.tree.map(lambda a: lax.psum(a, (DATA, SEQ))[None],
                           carry["dw"])
    loss = lax.psum(carry["loss"], (DATA, SEQ, PIPE))
    grads = {"embed": demb, "ln_f": dlnf, "blocks": dblocks}
    aux_mean = jnp.zeros((), jnp.float32)
    if cfg.n_experts:
        # per-(layer, microbatch) aux values are global sums (see
        # _loss_fn): pmean demotes, psum(PIPE) adds the stages' layers
        aux_mean = lax.pmean(lax.psum(carry["auxs"], PIPE), (DATA, SEQ)) \
            / (cfg.n_layers * M)
        loss = loss + cfg.moe_aux_weight * aux_mean
    aux = (lax.psum(carry["drop"], (DATA, SEQ, PIPE)),
           lax.psum(carry["tot"], (DATA, SEQ, PIPE)), aux_mean)
    return loss, grads, aux


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def opt_state_specs(cfg: MegatronConfig, optimizer):
    """PartitionSpecs for the optimizer state: param-like leaves (momentum,
    second moments) shard exactly like their parameters; scalar bookkeeping
    (step counts) is replicated."""
    import optax
    specs = param_specs(cfg)
    shapes = jax.eval_shape(partial(init_params, cfg), jax.random.PRNGKey(0))
    state_shape = jax.eval_shape(optimizer.init, shapes)
    return optax.tree_map_params(
        optimizer, lambda _, s: s, state_shape, specs,
        transform_non_params=lambda _: P())


def make_megatron_train_step(cfg: MegatronConfig, mesh: Mesh, optimizer):
    """Compiled 4D-parallel train step ``(params, opt_state, batch) -> ...``.

    ``batch``: dict of global arrays — 'tokens'/'targets' int32
    [global_batch, global_seq], 'mask' float32 — sharded
    P('data', 'seq') by :func:`shard_lm_batch`.  Gradient reductions over
    every axis fall out of VMA-typed autodiff: params enter unvarying, the
    loss psums make them exact (no hand-written grad allreduce to get wrong).
    """
    if cfg.n_stages != mesh.shape[PIPE]:
        raise ValueError(
            f"cfg.n_stages={cfg.n_stages} must equal mesh 'pipe' size "
            f"{mesh.shape[PIPE]}")
    specs = param_specs(cfg)
    o_specs = opt_state_specs(cfg, optimizer)

    if cfg.schedule not in ("1f1b", "gpipe"):
        raise ValueError(f"unknown pipeline schedule {cfg.schedule!r}")
    if cfg.schedule == "gpipe" and cfg.virtual_stages != 1:
        raise ValueError("virtual_stages (interleaved schedule) requires "
                         "schedule='1f1b'")
    if cfg.schedule == "gpipe" and _compat.SHIMMED:
        # the GPipe schedule is jax.value_and_grad THROUGH shard_map; that
        # is only correct under vma-typed autodiff (current jax).  The
        # legacy check_rep=False shard_map transposes psum to psum and
        # skips the pbroadcast-transposes for replicated params, so the
        # loss comes out right but the GRADS come out shard-local and
        # mis-scaled (up to ~10% on the embedding in the oracle tests,
        # structurally — not fp drift).  Refuse loudly instead of
        # training garbage; 1f1b (the default) is the same math through
        # a hand-written VJP and is verified against the oracle on this
        # jax.  Forward-only GPipe (make_megatron_eval_step) is fine.
        raise ValueError(
            "schedule='gpipe' differentiates through shard_map "
            "collectives, which legacy jax (no vma-typed autodiff; see "
            "dtdl_tpu/_compat.py SHIMMED) gets wrong — use the default "
            "schedule='1f1b' on this jax version")

    def step(params, opt_state, tokens, targets, mask):
        if cfg.schedule == "1f1b":
            loss, grads, aux = _value_and_grad_1f1b(cfg, params, tokens,
                                                    targets, mask)
        else:
            (loss, aux), grads = jax.value_and_grad(
                partial(_loss_fn, cfg), has_aux=True)(
                    params, tokens, targets, mask)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        metrics = {}
        if cfg.n_experts:
            drop, tot, aux_mean = aux
            metrics["moe_aux_loss"] = aux_mean
            if cfg.moe_dispatch == "routed":
                metrics["moe_dropped_frac"] = drop / jnp.maximum(tot, 1.0)
        return params, opt_state, loss, metrics

    metric_spec = {}
    if cfg.n_experts:
        metric_spec["moe_aux_loss"] = P()
        if cfg.moe_dispatch == "routed":
            metric_spec["moe_dropped_frac"] = P()
    batch_spec = P(DATA, SEQ)
    mapped = jax.shard_map(
        step, mesh=mesh,
        in_specs=(specs, o_specs, batch_spec, batch_spec, batch_spec),
        out_specs=(specs, o_specs, P(), metric_spec),
    )
    return jax.jit(mapped, donate_argnums=(0, 1))


def make_megatron_eval_step(cfg: MegatronConfig, mesh: Mesh):
    """Compiled 4D-parallel eval step: forward + metrics, no optimizer.

    ``(params, tokens, targets, mask) -> {'loss', 'accuracy', 'n_tokens'}``
    with the same ``P('data','seq')`` batch placement as training
    (:func:`shard_lm_batch`).  Parity target: every reference script
    evaluates — restore-then-evaluate (reference
    tensorflow2/mnist_single.py:88-92) and the allreduced multi-node
    evaluator (reference chainer/train_mnist_multi.py:101-104); this is the
    4D engine's equivalent, so validation never needs an optimizer update
    (the train step donates params/opt_state, which makes "step but ignore
    the update" unusable for eval).

    Runs the GPipe forward scan regardless of ``cfg.schedule`` — with no
    backward pass 1F1B's interleaving buys nothing, and the forward-only
    scan holds no activation stash.  The LM head is vocab-parallel like
    training's (`_head_loss`): per-shard logits over the V/tp slice,
    logsumexp/true-logit/argmax combined with one psum/pmax/pmin('model')
    each, so full [.., V] logits never materialize when tp > 1.  Loss and
    accuracy are masked global sums over ('data','seq') divided by the
    psummed mask total — ragged tails (mask=0 padding) are exact, matching
    the DP engines' sum-synced metrics.  The eval loss is the plain LM
    cross entropy: the MoE balance aux is a *training* regularizer and is
    deliberately not added to validation loss.
    """
    if cfg.n_stages != mesh.shape[PIPE]:
        raise ValueError(
            f"cfg.n_stages={cfg.n_stages} must equal mesh 'pipe' size "
            f"{mesh.shape[PIPE]}")
    specs = param_specs(cfg)
    batch_spec = P(DATA, SEQ)

    def eval_fn(params, tokens, targets, mask):
        b_loc, s_loc = tokens.shape
        n_micro = cfg.n_microbatches
        emb = params["embed"]
        x = jnp.take(emb, tokens, axis=0).astype(cfg.dtype)
        cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq)
        x_micro = x.reshape(n_micro, b_loc // n_micro, s_loc, cfg.d_model)
        y, _ = _pipeline(cfg, params, x_micro, cos, sin)
        y = y.reshape(b_loc, s_loc, cfg.d_model)
        h = _rms(y, params["ln_f"]).astype(jnp.float32)

        v = cfg.vocab_size
        tp = lax.axis_size(MODEL)
        if tp > 1 and v % tp == 0:
            v_loc = v // tp
            off = lax.axis_index(MODEL) * v_loc
            emb_slice = lax.dynamic_slice_in_dim(emb, off, v_loc, 0)
            logits = jnp.einsum("bsd,vd->bsv", h,
                                emb_slice.astype(jnp.float32))
            loc_max = jnp.max(logits, -1)
            mx = lax.pmax(loc_max, MODEL)
            se = lax.psum(jnp.sum(jnp.exp(logits - mx[..., None]), -1),
                          MODEL)
            lse = mx + jnp.log(se)
            in_range = (targets >= off) & (targets < off + v_loc)
            idx = jnp.clip(targets - off, 0, v_loc - 1)
            true_logit = lax.psum(
                jnp.where(in_range,
                          jnp.take_along_axis(logits, idx[..., None],
                                              -1)[..., 0],
                          0.0), MODEL)
            # global argmax with jnp.argmax's first-occurrence tie-break:
            # shards whose local max hits the global max bid their local
            # argmax (+vocab offset); everyone else bids the out-of-range
            # sentinel V; pmin picks the lowest winning index
            loc_arg = jnp.argmax(logits, -1).astype(jnp.int32) + off
            pred = lax.pmin(jnp.where(loc_max == mx, loc_arg, v), MODEL)
        else:
            logits = jnp.einsum("bsd,vd->bsv", h, emb.astype(jnp.float32))
            lse = jax.nn.logsumexp(logits, -1)
            true_logit = jnp.take_along_axis(
                logits, targets[..., None], -1)[..., 0]
            pred = jnp.argmax(logits, -1).astype(jnp.int32)

        loss_sum = lax.psum(jnp.sum((lse - true_logit) * mask), (DATA, SEQ))
        correct = lax.psum(
            jnp.sum((pred == targets).astype(jnp.float32) * mask),
            (DATA, SEQ))
        count = lax.psum(jnp.sum(mask), (DATA, SEQ))
        denom = jnp.maximum(count, 1.0)
        out = {"loss": loss_sum / denom, "accuracy": correct / denom,
               "n_tokens": count}
        # the replicated-head branch leaves the scalars MODEL-varying in
        # vma type only (every shard computed the same value); pmean is the
        # value-preserving demotion so out_specs P() is accepted
        return {k: lax.pmean(s, MODEL)
                if MODEL in (jax.typeof(s).vma or ()) else s
                for k, s in out.items()}

    mapped = jax.shard_map(
        eval_fn, mesh=mesh,
        in_specs=(specs, batch_spec, batch_spec, batch_spec),
        out_specs={"loss": P(), "accuracy": P(), "n_tokens": P()},
    )
    jitted = jax.jit(mapped)   # no donation: params are reused for training

    def eval_step(params, tokens, targets, mask):
        # validate the microbatch split HERE: inside shard_map tracing the
        # same mistake surfaces as an opaque reshape error deep in the
        # pipeline scan, far from the caller's batch-size choice
        n_data = mesh.shape[DATA]
        b_glob = tokens.shape[0]
        b_loc = b_glob // n_data
        if b_glob % n_data or b_loc % cfg.n_microbatches:
            raise ValueError(
                f"eval batch size {b_glob} is not splittable: the local "
                f"batch b_loc = {b_glob} / {n_data} ('data' mesh axis) = "
                f"{b_loc} must satisfy b_loc % n_microbatches == 0 "
                f"(n_microbatches={cfg.n_microbatches}); use a global "
                f"batch that is a multiple of "
                f"{n_data * cfg.n_microbatches}")
        return jitted(params, tokens, targets, mask)

    return eval_step


def init_optimizer(cfg: MegatronConfig, mesh: Mesh, optimizer, params):
    """Optimizer state placed with param-aligned shardings."""
    state = optimizer.init(params)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        state, opt_state_specs(cfg, optimizer))


def abstract_state(cfg: MegatronConfig, mesh: Mesh, optimizer):
    """Sharded abstract ``(params, opt_state)`` — the orbax restore target.

    Each leaf is a ShapeDtypeStruct carrying the NamedSharding from
    :func:`param_specs` / :func:`opt_state_specs`, so a snapshot restores
    directly into the 4D layout (every host reads only its shards) without
    materializing the global arrays anywhere.  This is what makes the 4D
    path restartable: checkpoint/resume at scale needs no gather step.
    Mirrors the reference's full trainer-state resume
    (chainer/train_mnist.py:120-122) for the megatron engine.
    """
    p_shapes = jax.eval_shape(partial(init_params, cfg),
                              jax.random.PRNGKey(0))
    o_shapes = jax.eval_shape(optimizer.init, p_shapes)

    def to_sds(leaf, spec):
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                    sharding=NamedSharding(mesh, spec))

    return (jax.tree.map(to_sds, p_shapes, param_specs(cfg)),
            jax.tree.map(to_sds, o_shapes,
                         opt_state_specs(cfg, optimizer)))


def shard_lm_batch(mesh: Mesh, batch: dict) -> dict:
    """Place tokens/targets/mask as [batch@'data', seq@'seq'] global arrays.

    When the mesh has a 'seq' axis > 1 the sequence dim is permuted into the
    **zigzag order** first (dtdl_tpu/parallel/sequence.py zigzag_order) —
    the layout contract of the 4D step's causal ring attention.  The LM loss
    is a masked mean over positions, so the permutation changes nothing
    observable; callers that need position-ordered logits apply
    ``zigzag_inverse``.  (Multi-host note: the permutation is applied to
    each process's local view, which is exact as long as the 'seq' axis
    does not span processes — the standard placement, dp over DCN —
    enforced below.)
    """
    n_sp = mesh.shape[SEQ]
    if n_sp > 1:
        if jax.process_count() > 1:
            # a process-spanning 'seq' axis would make the local-view
            # permutation silently wrong — refuse instead
            seq_axis = mesh.axis_names.index(SEQ)
            rows = np.moveaxis(mesh.devices, seq_axis, -1).reshape(-1, n_sp)
            for row in rows:
                if len({d.process_index for d in row}) != 1:
                    raise ValueError(
                        "zigzag shard_lm_batch requires the 'seq' mesh axis "
                        "to be process-local; lay 'data' over DCN instead")
        order = zigzag_order(n_sp, next(iter(batch.values())).shape[1])
        # audit: ok[host-sync-asarray] host batch reorder before device_put — input is host data by contract
        batch = {k: np.asarray(v)[:, order] for k, v in batch.items()}
    sharding = NamedSharding(mesh, P(DATA, SEQ))
    if jax.process_count() == 1:
        return {k: jax.device_put(v, sharding) for k, v in batch.items()}
    return {k: jax.make_array_from_process_local_data(sharding, v)
            for k, v in batch.items()}


def to_flax_params(cfg: MegatronConfig, params: dict) -> dict:
    """Convert the 4D engine's stacked parameter tree into the flax
    :class:`~dtdl_tpu.models.transformer.TransformerLM` tree — the
    serving bridge: train on the megatron engine, restore a snapshot,
    convert, and decode with ``models.generate`` (single-device,
    DP-batch-sharded, or tensor-parallel — generate propagates whatever
    sharding the converted params carry).

    The stacked ``blocks`` leaves are [n_stages, layers_per_stage, ...];
    execution order is the (interleaved) virtual pipeline's — virtual
    stage ``u = c*S + st`` runs device st's chunk-c rows — so flax
    ``block_j`` takes row ``order[j]``.  Attention kernels reshape
    [D, H*hd] -> [D, H, hd] (flax DenseGeneral layout); both engines
    share the rope/RMSNorm/SwiGLU ops, so the converted model computes
    the identical function (pinned by test).  MoE configs map too
    (router/wi/wg/wo shapes coincide) but require the flax model built
    with ``moe_every=1`` — the megatron engine puts an MoE in *every*
    block.  Pass host (or fully-addressable) arrays; use
    ``jax.device_get`` on a sharded state first.
    """
    S, Lc_total, v = cfg.n_stages, cfg.layers_per_stage, cfg.virtual_stages
    H, hd, D = cfg.n_heads, cfg.head_dim, cfg.d_model
    if Lc_total % v:
        # same guard as the engine (_value_and_grad_1f1b): a silent
        # truncated conversion would fail far away with missing blocks
        raise ValueError(f"virtual_stages={v} must divide "
                         f"layers_per_stage={Lc_total}")
    Lc = Lc_total // v
    order = [(u % S, (u // S) * Lc + i)
             for u in range(v * S) for i in range(Lc)]
    blocks = params["blocks"]
    out = {"embed": params["embed"],
           "ln_f": {"scale": params["ln_f"]}}
    for j, (st, li) in enumerate(order):
        p = {k: a[st, li] for k, a in blocks.items()}
        blk = {
            "ln_attn": {"scale": p["ln_attn"]},
            "ln_mlp": {"scale": p["ln_mlp"]},
            "attn": {
                "q": {"kernel": p["wq"].reshape(D, H, hd)},
                "k": {"kernel": p["wk"].reshape(D, H, hd)},
                "v": {"kernel": p["wv"].reshape(D, H, hd)},
                "out": {"kernel": p["wo"].reshape(H, hd, D)},
            },
        }
        if cfg.n_experts:
            blk["moe"] = {"router": {"kernel": p["router"]},
                          "wi": p["wi"], "wg": p["wg"],
                          "wo": p["wo_mlp"]}
        else:
            blk["mlp"] = {"wi": {"kernel": p["wi"]},
                          "wg": {"kernel": p["wg"]},
                          "wo": {"kernel": p["wo_mlp"]}}
        out[f"block_{j}"] = blk
    return out


def to_flax_model(cfg: MegatronConfig, **overrides):
    """Flax :class:`~dtdl_tpu.models.transformer.TransformerLM` matching
    ``cfg`` — the model half of the serving bridge (:func:`to_flax_params`
    is the weights half).

    This is THE single place that maps MegatronConfig fields onto the flax
    model, so a new config field (say a future ``moe_group_size``) gets
    wired here once instead of silently drifting in every caller that
    hand-builds the serving model.  Bridge-mandated settings: ``moe_every=1``
    (the 4D engine puts an MoE in *every* block), the config's OWN
    ``moe_dispatch`` (decode keeps the TRAINED routing semantics — a
    dense-dispatch-trained MoE must not serve through capacity routing),
    and ``attn_impl='dense'`` / f32 as serving-safe defaults.  ``overrides``
    win last — e.g. ``max_seq=...`` to extend the rope table for decode.
    """
    from dtdl_tpu.models.transformer import TransformerLM
    kw = dict(
        vocab_size=cfg.vocab_size,
        d_model=cfg.d_model,
        n_layers=cfg.n_layers,
        n_heads=cfg.n_heads,
        d_ff=cfg.d_ff,
        max_seq=cfg.max_seq,
        n_experts=cfg.n_experts,
        moe_every=1,
        moe_dispatch=cfg.moe_dispatch if cfg.n_experts else "dense",
        capacity_factor=cfg.capacity_factor,
        moe_top_k=cfg.moe_top_k,
        attn_impl="dense",
        dtype=jnp.float32,
    )
    kw.update(overrides)
    return TransformerLM(**kw)


def place_params(mesh: Mesh, cfg: MegatronConfig, params: dict) -> dict:
    specs = param_specs(cfg)
    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs)


def serve_engine(cfg: MegatronConfig, params: dict, mesh: Mesh = None,
                 n_slots: int = 8, buckets=None, page_size: int = 0,
                 n_pages: int = None, quantize_weights: bool = False,
                 kv_dtype=None, kv_pool_bytes: int = None, rules=None,
                 **overrides):
    """Train on the 4D engine, serve through dtdl_tpu.serve — the full
    bridge in one call: :func:`to_flax_model` (geometry) +
    :func:`to_flax_params` (weights) + an
    :class:`~dtdl_tpu.serve.InferenceEngine` around them.

    With ``mesh`` alone, the converted params are placed **replicated**
    on it (``NamedSharding(mesh, P())``) and the engine's jitted
    prefill/decode programs run under GSPMD on that mesh — the same
    pjit machinery the training step used, so a training pod flips to
    serving without a new runtime.  Replication is the right default at
    serving batch sizes: decode is HBM-bandwidth-bound on the weights
    (SCALING.md "Serving latency model"), and every chip holding all
    weights turns the mesh into throughput-parallel decode capacity.

    ``mesh`` plus ``rules`` (e.g. ``'tp'``) serves **tensor-parallel
    proper** (round 19): this function is now a thin caller — the
    engine itself shards params and the KV arena via the GSPMD presets
    in parallel/tensor.py (``InferenceEngine(mesh=, rules=)``), so a
    model too big to replicate serves with 1/tp of the weight and KV
    bytes per chip, and a serving engine no longer needs the megatron
    training mesh at all.

    ``params`` may be the live sharded training state (``device_get`` is
    applied before conversion).  ``overrides`` reach
    :func:`to_flax_model` — e.g. ``max_seq=4096`` to serve longer than
    the trained context.

    The engine-geometry kwargs pass straight through to
    :class:`~dtdl_tpu.serve.InferenceEngine`: ``page_size``/``n_pages``/
    ``kv_pool_bytes`` build the block-paged arena (prefix caching is
    scheduler policy on top), ``quantize_weights``/``kv_dtype`` the int8
    serving variants (dtdl_tpu/quant) — quantization happens AFTER the
    4D→flax conversion, so a bf16/f32 training snapshot serves int8
    without retraining.
    """
    from dtdl_tpu.serve import InferenceEngine

    if rules is not None and mesh is None:
        # silently dropping the requested sharding would surface as an
        # OOM (or one-chip serving) far from the misconfiguration
        raise ValueError(f"rules={rules!r} requires mesh=: "
                         f"tensor-parallel serving needs the mesh the "
                         f"shards land on")
    model = to_flax_model(cfg, **overrides)
    # audit: ok[host-sync-get] to_flax_model is the cold train->serve bridge, not a step path
    fparams = to_flax_params(cfg, jax.device_get(params))
    if mesh is not None and rules is None:
        # replicated placement (the throughput-parallel default); the
        # tensor-parallel path below lets the ENGINE place the shards
        fparams = jax.tree.map(
            lambda p: jax.device_put(p, NamedSharding(mesh, P())), fparams)
    return InferenceEngine(model, fparams, n_slots=n_slots,
                           buckets=buckets, page_size=page_size,
                           n_pages=n_pages,
                           quantize_weights=quantize_weights,
                           kv_dtype=kv_dtype,
                           kv_pool_bytes=kv_pool_bytes,
                           mesh=mesh if rules is not None else None,
                           rules=rules if rules is not None else "tp")
