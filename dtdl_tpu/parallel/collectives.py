"""Collectives adapter — the XLA replacement for NCCL / MPI / gRPC rings.

Inside a jitted SPMD program these helpers emit XLA collectives
(`AllReduce`, `AllGather`, `CollectivePermute`) that ride ICI within a slice
and DCN across slices, chosen by which mesh axis they name.  They replace the
reference's backend zoo: NCCL bucketed allreduce fired from ``loss.backward()``
(reference pytorch/distributed_data_parallel.py:132 via the DDP grad hooks),
ChainerMN's ``pure_nccl``/``naive`` communicators (reference
chainer/train_mnist_multi.py:49-62), and TF's collective executor driven by
TF_CONFIG (reference tensorflow2/mnist_multi_worker_strategy.py:18-27).

Host-level (outside-jit) utilities cover the reference's process-level
collectives: dataset scatter and cross-host broadcast.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from dtdl_tpu.runtime.mesh import DATA_AXIS


# ---- inside-jit (SPMD) collectives -----------------------------------------

def localize(tree, axis: str = DATA_AXIS):
    """Mark a replicated pytree as per-replica varying inside shard_map.

    JAX's shard_map types values by which manual axes they vary over (VMA).
    Differentiating a per-replica loss w.r.t. *replicated* params would make
    the transpose insert an implicit psum — the gradient would arrive already
    summed and an explicit pmean would silently be an identity.  Casting
    params to 'varying' first keeps gradients per-replica so `grad_sync` below
    is a real mean-allreduce, exactly mirroring DDP's explicit bucketed
    allreduce (reference pytorch/distributed_data_parallel.py:74,132).
    """
    return jax.tree.map(
        lambda x: jax.lax.pcast(x, axis, to="varying"), tree)


def grad_sync(grads, axis: str = DATA_AXIS):
    """Mean-allreduce a gradient pytree across the data axis.

    The TPU equivalent of DDP's bucketed NCCL allreduce (reference
    pytorch/distributed_data_parallel.py:74,132) and ChainerMN's
    multi-node-optimizer allreduce (reference chainer/train_mnist_multi.py:81-83).
    XLA fuses/schedules these AllReduces against the backward pass, giving the
    comm/compute overlap torch gets from grad hooks.
    """
    return lax.pmean(grads, axis_name=axis)


def all_reduce_sum(tree, axis: str = DATA_AXIS):
    return lax.psum(tree, axis_name=axis)


def all_reduce_mean(tree, axis: str = DATA_AXIS):
    return lax.pmean(tree, axis_name=axis)


def all_gather_batch(tree, axis: str = DATA_AXIS):
    """Gather per-replica shards into the full global batch on every replica."""
    return jax.tree.map(
        lambda x: lax.all_gather(x, axis_name=axis, axis=0, tiled=True), tree)


def broadcast_from(tree, root: int = 0, axis: str = DATA_AXIS):
    """Replicate replica ``root``'s value to all replicas on ``axis``."""
    def _bcast(x):
        masked = jnp.where(lax.axis_index(axis) == root, x, jnp.zeros_like(x))
        return lax.psum(masked, axis_name=axis)
    return jax.tree.map(_bcast, tree)


def axis_index(axis: str = DATA_AXIS):
    return lax.axis_index(axis)


def pvary_like(x, *refs):
    """Cast ``x`` to vary over every manual axis any of ``refs`` varies over.

    shard_map's VMA typing requires scan carries to enter with the same
    varying-axis set they leave with; zero-initialized accumulators start
    unvarying, so loops that mix them with sharded activations must pre-cast.
    No-op outside shard_map.
    """
    want = set()
    for r in refs:
        want |= set(getattr(jax.typeof(r), "vma", ()) or ())
    have = set(getattr(jax.typeof(x), "vma", ()) or ())
    missing = tuple(sorted(want - have))
    return lax.pcast(x, missing, to="varying") if missing else x


# ---- host-level (outside-jit) utilities ------------------------------------

def host_broadcast(tree, is_source: bool | None = None):
    """Broadcast host-side data from process 0 to all processes.

    Equivalent of ChainerMN's rank-0-loads-then-scatter pattern's broadcast
    half (reference chainer/train_mnist_multi.py:87-90).  No-op when single
    process.
    """
    if jax.process_count() == 1:
        return tree
    from jax.experimental import multihost_utils
    if is_source is None:
        is_source = jax.process_index() == 0
    return multihost_utils.broadcast_one_to_all(tree, is_source=is_source)


def assert_same_across_hosts(tree, name: str = "value") -> None:
    """Debug-mode cross-host checksum (SURVEY §5.2's race-detection stand-in)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils
    multihost_utils.assert_equal(tree, fail_message=f"{name} diverged across hosts")
