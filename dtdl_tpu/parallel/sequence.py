"""Sequence/context parallelism — ring attention and Ulysses all-to-all.

The reference scales only the batch dimension (SURVEY §5.7: CNN/MLP models,
no sequence dimension at all); long-context training is a first-class
capability here, so the framework ships both canonical TPU sequence-parallel
schemes.  Both are written to be called *inside* ``shard_map`` with
activations sharded on a ``seq`` mesh axis:

* **ring attention** (blockwise, RingAttention-style): K/V shards rotate
  around the mesh axis via ``lax.ppermute`` (one ICI hop per step — exactly
  the neighbor-exchange the TPU torus is built for) while each device
  accumulates its queries' attention over every K/V block with the online
  softmax (running max ``m``, normalizer ``l``).  O(S_local²·ring) compute,
  O(S_local) memory per device; the full S×S score matrix never exists on
  any one chip.  Differentiable by construction (scan + ppermute transpose).

  Two sequence layouts are supported.  ``contiguous`` (device i holds
  positions ``[i·s_loc, (i+1)·s_loc)``) is the simple contract, but under
  causal masking its work is imbalanced: device n-1 attends at every ring
  step while device 0 attends once, so skipping masked blocks saves FLOPs
  without shortening the critical path.  ``zigzag`` splits the sequence
  into ``2n`` chunks and gives device i chunk ``i`` (low) plus chunk
  ``2n-1-i`` (high); every device then does exactly half a block of causal
  work at every ring step — the causal saving becomes ~2× *wall-clock*,
  not just energy.  Use :func:`zigzag_order` to lay a global batch out in
  zigzag shard order (loss terms are position-permutation-invariant, so
  training code only needs the forward permutation).

* **Ulysses** (all-to-all head/sequence transpose): one ``lax.all_to_all``
  re-shards activations from sequence-sharded to head-sharded, local flash
  attention (the Pallas kernel from dtdl_tpu.ops.attention) runs over the
  full sequence on a head subset, and a second all-to-all restores sequence
  sharding.  Cheaper than a ring when heads ≥ axis size and the all-to-all
  fits ICI.

Gradient flow needs no hand-written backward: XLA transposes ``ppermute`` /
``all_to_all`` to their inverses, which *is* the ring/all-to-all backward
pass of the papers.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

SEQ_AXIS = "seq"
NEG_INF = -1e30


def _axis_size(axis_name: str) -> int:
    return lax.axis_size(axis_name)


def zigzag_order(n_shards: int, seq_len: int) -> np.ndarray:
    """Gather indices laying a global sequence out in zigzag shard order.

    ``x[..., zigzag_order(n, S), ...]`` (applied to the sequence dim) is the
    array to feed a ``P(..., 'seq', ...)`` sharding so shard i receives
    chunks ``(i, 2n-1-i)`` of the original order.  Identity when n == 1.
    """
    if n_shards <= 1:
        return np.arange(seq_len)
    if seq_len % (2 * n_shards):
        raise ValueError(
            f"zigzag layout needs seq_len ({seq_len}) divisible by "
            f"2*n_shards ({2 * n_shards})")
    c = seq_len // (2 * n_shards)
    parts = []
    for i in range(n_shards):
        parts.append(np.arange(i * c, (i + 1) * c))
        j = 2 * n_shards - 1 - i
        parts.append(np.arange(j * c, (j + 1) * c))
    return np.concatenate(parts)


def zigzag_inverse(n_shards: int, seq_len: int) -> np.ndarray:
    """Scatter indices undoing :func:`zigzag_order` (for outputs that must
    return to the original position order, e.g. sampled logits)."""
    return np.argsort(zigzag_order(n_shards, seq_len))


def zigzag_positions(axis_name: str, s_loc: int):
    """Global position of each local row under the zigzag layout: [s_loc]."""
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    if n == 1:
        return jnp.arange(s_loc)
    c = s_loc // 2
    low = my * c + jnp.arange(c)
    high = (2 * n - 1 - my) * c + jnp.arange(c)
    return jnp.concatenate([low, high])


def _owner_positions(layout: str, n: int, owner, s_loc: int):
    """Global positions of ``owner``'s local rows under ``layout``: [s_loc].

    ``owner`` may be traced (the reconstructed ring source ``src``).  The
    zigzag case is :func:`zigzag_positions` generalized to any owner; at
    n == 1 both layouts reduce to ``arange(s_loc)``.
    """
    if layout == "zigzag" and n > 1:
        c = s_loc // 2
        low = owner * c + jnp.arange(c)
        high = (2 * n - 1 - owner) * c + jnp.arange(c)
        return jnp.concatenate([low, high])
    return owner * s_loc + jnp.arange(s_loc)


def _rope_block(x, rope, positions):
    """Rotate a K block at its owner's global positions (no-op when
    ``rope`` is None).  The ring carries K **unrotated** and rotates a
    local copy at each use — elementwise the identical f32 arithmetic as
    pre-roping before the ring (apply_rope commutes with the ppermute
    and with chunk slicing), so the fused and unfused paths are exact."""
    if rope is None:
        return x
    from dtdl_tpu.ops.rope import apply_rope
    cos, sin = rope
    return apply_rope(x, cos, sin, positions=positions)


def _online_update(q_rows, k_blk, v_blk, o, m, l, scale, mask=None):
    """One online-softmax accumulation of (o, m, l) rows against a K/V block.

    bf16 (native-dtype) matmul inputs with f32 accumulation — the MXU runs
    bf16 at 2x f32 throughput (same contract as the Pallas flash kernel,
    dtdl_tpu/ops/attention.py).  Shared by both ring schedules.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q_rows, k_blk,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_cur)
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    o_new = o * alpha + jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v_blk.dtype), v_blk,
        preferred_element_type=jnp.float32)
    return o_new, m_new, l_new


def ring_attention(q, k, v, *, axis_name: str = SEQ_AXIS,
                   causal: bool = True, scale: float | None = None,
                   layout: str = "contiguous", rope=None):
    """Blockwise ring attention over a sequence-sharded mesh axis.

    Call inside ``shard_map``; q/k/v are the local shards
    ``[batch, heads, seq_local, head_dim]`` of a global sequence.  With
    ``layout='contiguous'`` device i holds positions
    ``[i*seq_local, (i+1)*seq_local)``; with ``layout='zigzag'`` it holds
    chunks ``i`` and ``2n-1-i`` of a ``2n``-chunk split (build the global
    order with :func:`zigzag_order`) — the layout that load-balances causal
    masking across the ring.  Returns the local output shard (same layout).

    ``rope=(cos, sin)`` fuses the rotary embedding into the ring (kernel
    round 2): q/k arrive **unrotated**, q is rotated once at the local
    shard's layout positions, and every K block is rotated *inside* the
    schedule at its original owner's reconstructed positions — the roped
    K tensor never materializes as a pre-ring HBM round-trip and the
    ppermute carries the compact unrotated block.  f32-exact vs roping
    before the call (see :func:`_rope_block`).
    """
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown layout {layout!r}")
    if layout == "zigzag" and causal and _axis_size(axis_name) > 1:
        return _ring_zigzag_causal(q, k, v, axis_name=axis_name, scale=scale,
                                   rope=rope)
    # non-causal attention touches every block regardless of layout, so the
    # zigzag non-causal case is exactly the contiguous schedule below.
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if rope is not None:
        q = _rope_block(q, rope, _owner_positions(layout, n, my, s_loc))

    pos_q = my * s_loc + lax.broadcasted_iota(jnp.int32, (s_loc, s_loc), 0)

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, t):
        k_blk, v_blk, o, m, l = carry
        src = (my - t) % n                        # original owner of k_blk

        def attend(o, m, l):
            mask = None
            if causal:
                pos_k = src * s_loc + lax.broadcasted_iota(
                    jnp.int32, (s_loc, s_loc), 1)
                mask = pos_q >= pos_k
            k_r = _rope_block(k_blk, rope,
                              _owner_positions(layout, n, src, s_loc))
            return _online_update(q, k_r, v_blk, o, m, l, scale, mask)

        if causal:
            # blocks strictly above the diagonal (src > my) are fully
            # masked: skip their matmuls.  Under the contiguous layout this
            # saves FLOPs but not critical path (device n-1 attends every
            # step); the zigzag layout above is the balanced schedule.
            o, m, l = lax.cond(src <= my, attend,
                               lambda o, m, l: (o, m, l), o, m, l)
        else:
            o, m, l = attend(o, m, l)
        k_blk, v_blk = lax.ppermute((k_blk, v_blk), axis_name, perm)
        return (k_blk, v_blk, o, m, l), None

    from dtdl_tpu.parallel.collectives import pvary_like
    o0 = pvary_like(jnp.zeros((b, h, s_loc, d), jnp.float32), q, k, v)
    m0 = pvary_like(jnp.full((b, h, s_loc, 1), NEG_INF, jnp.float32), q, k, v)
    l0 = pvary_like(jnp.zeros((b, h, s_loc, 1), jnp.float32), q, k, v)
    (k, v, o, m, l), _ = lax.scan(step, (k, v, o0, m0, l0), jnp.arange(n))
    l = jnp.where(l == 0.0, 1.0, l)               # fully-masked rows (non-causal corner)
    return (o / l).astype(q.dtype)


def _ring_zigzag_causal(q, k, v, *, axis_name: str, scale: float | None,
                        rope=None):
    """Causal ring attention over the zigzag layout — balanced schedule.

    Device i holds chunks ``(i, 2n-1-i)`` of a ``2n``-chunk global split.
    For a K/V block owned by ``src``:

    * ``src == my`` — the local diagonal: full block, zigzag causal mask
      (handled once, statically, before the rotation scan).
    * ``src < my`` — both kv chunks of ``src`` relate to my chunks as:
      low→(both my chunks) unmasked, high→(both) fully masked.  So attend
      **all local queries to the kv low chunk only** — half a block, no mask.
    * ``src > my`` — low kv chunk is visible only to my high chunk; high kv
      chunk (= chunk ``2n-1-src`` < ``2n-1-my``) is also visible only to my
      high chunk.  So attend **my high-chunk queries to the full kv block**
      — half a block, no mask.

    Every device therefore does exactly half a block of matmul per ring
    step: the causal FLOP saving is also a critical-path saving, unlike the
    contiguous layout's skip.

    ``rope=(cos, sin)``: q/k arrive unrotated; q and the step-0 diagonal K
    are rotated at the local zigzag positions, ring-arrived K blocks at
    their owner ``src``'s reconstructed zigzag positions — always on the
    chunk actually attended (rope is elementwise, so rotating the slice ==
    slicing the rotation).  The scan carries K unrotated.
    """
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    if s_loc % 2:
        raise ValueError(f"zigzag needs an even local seq, got {s_loc}")
    c = s_loc // 2
    if scale is None:
        scale = 1.0 / math.sqrt(d)

    def attend(q_rows, k_blk, v_blk, o, m, l, mask=None):
        return _online_update(q_rows, k_blk, v_blk, o, m, l, scale, mask)

    from dtdl_tpu.parallel.collectives import pvary_like
    o0 = pvary_like(jnp.zeros((b, h, s_loc, d), jnp.float32), q, k, v)
    m0 = pvary_like(jnp.full((b, h, s_loc, 1), NEG_INF, jnp.float32), q, k, v)
    l0 = pvary_like(jnp.zeros((b, h, s_loc, 1), jnp.float32), q, k, v)

    # step 0: local diagonal, full block under the zigzag causal mask
    pos = zigzag_positions(axis_name, s_loc)
    if rope is not None:
        q = _rope_block(q, rope, pos)
    o, m, l = attend(q, _rope_block(k, rope, pos), v, o0, m0, l0,
                     mask=pos[:, None] >= pos[None, :])
    if n == 1:
        return (o / l).astype(q.dtype)

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, t):
        k_blk, v_blk, o, m, l = carry
        k_blk, v_blk = lax.ppermute((k_blk, v_blk), axis_name, perm)
        src = (my - t) % n

        def from_earlier(o, m, l):           # src < my: q_all vs kv low chunk
            k_low = _rope_block(k_blk[:, :, :c], rope,
                                src * c + jnp.arange(c))
            return attend(q, k_low, v_blk[:, :, :c], o, m, l)

        def from_later(o, m, l):             # src > my: q high chunk vs kv all
            k_full = _rope_block(
                k_blk, rope,
                jnp.concatenate([src * c + jnp.arange(c),
                                 (2 * n - 1 - src) * c + jnp.arange(c)]))
            o_hi, m_hi, l_hi = attend(
                q[:, :, c:], k_full, v_blk,
                o[:, :, c:], m[:, :, c:], l[:, :, c:])
            return (jnp.concatenate([o[:, :, :c], o_hi], axis=2),
                    jnp.concatenate([m[:, :, :c], m_hi], axis=2),
                    jnp.concatenate([l[:, :, :c], l_hi], axis=2))

        o, m, l = lax.cond(src < my, from_earlier, from_later, o, m, l)
        return (k_blk, v_blk, o, m, l), None

    (k, v, o, m, l), _ = lax.scan(step, (k, v, o, m, l), jnp.arange(1, n))
    return (o / l).astype(q.dtype)


def ulysses_attention(q, k, v, *, axis_name: str = SEQ_AXIS,
                      causal: bool = True, scale: float | None = None,
                      attn_fn=None):
    """DeepSpeed-Ulysses-style SP: all-to-all seq→heads, attend, reverse.

    Requires ``heads %% axis_size == 0``.  ``attn_fn(q, k, v, causal, scale)``
    defaults to the Pallas flash kernel over the full gathered sequence.
    """
    from dtdl_tpu.ops.attention import flash_attention
    n = _axis_size(axis_name)
    if q.shape[1] % n:
        raise ValueError(
            f"ulysses needs heads ({q.shape[1]}) divisible by axis size {n}")
    if attn_fn is None:
        def attn_fn(q, k, v, causal, scale):
            return flash_attention(q, k, v, causal=causal, scale=scale)

    def to_heads(x):   # [B, H, S/n, D] -> [B, H/n, S, D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def to_seq(x):     # [B, H/n, S, D] -> [B, H, S/n, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    o = attn_fn(to_heads(q), to_heads(k), to_heads(v), causal, scale)
    return to_seq(o)
