"""Sequence/context parallelism — ring attention and Ulysses all-to-all.

The reference scales only the batch dimension (SURVEY §5.7: CNN/MLP models,
no sequence dimension at all); long-context training is a first-class
capability here, so the framework ships both canonical TPU sequence-parallel
schemes.  Both are written to be called *inside* ``shard_map`` with
activations sharded on a ``seq`` mesh axis:

* **ring attention** (blockwise, RingAttention-style): K/V shards rotate
  around the mesh axis via ``lax.ppermute`` (one ICI hop per step — exactly
  the neighbor-exchange the TPU torus is built for) while each device
  accumulates its queries' attention over every K/V block with the online
  softmax (running max ``m``, normalizer ``l``).  O(S_local²·ring) compute,
  O(S_local) memory per device; the full S×S score matrix never exists on
  any one chip.  Differentiable by construction (scan + ppermute transpose).

* **Ulysses** (all-to-all head/sequence transpose): one ``lax.all_to_all``
  re-shards activations from sequence-sharded to head-sharded, local flash
  attention (the Pallas kernel from dtdl_tpu.ops.attention) runs over the
  full sequence on a head subset, and a second all-to-all restores sequence
  sharding.  Cheaper than a ring when heads ≥ axis size and the all-to-all
  fits ICI.

Gradient flow needs no hand-written backward: XLA transposes ``ppermute`` /
``all_to_all`` to their inverses, which *is* the ring/all-to-all backward
pass of the papers.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

SEQ_AXIS = "seq"
NEG_INF = -1e30


def _axis_size(axis_name: str) -> int:
    return lax.axis_size(axis_name)


def ring_attention(q, k, v, *, axis_name: str = SEQ_AXIS,
                   causal: bool = True, scale: float | None = None):
    """Blockwise ring attention over a sequence-sharded mesh axis.

    Call inside ``shard_map``; q/k/v are the local shards
    ``[batch, heads, seq_local, head_dim]`` of a global sequence laid out
    contiguously along the axis (device i holds positions
    ``[i*seq_local, (i+1)*seq_local)``).  Returns the local output shard.
    """
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)

    pos_q = my * s_loc + lax.broadcasted_iota(jnp.int32, (s_loc, s_loc), 0)

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, t):
        k_blk, v_blk, o, m, l = carry
        src = (my - t) % n                        # original owner of k_blk

        def attend(o, m, l):
            # native-dtype (bf16) matmul inputs, f32 accumulation — the MXU
            # runs bf16 at 2x f32 throughput (same contract as the Pallas
            # flash kernel, dtdl_tpu/ops/attention.py)
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk,
                           preferred_element_type=jnp.float32) * scale
            if causal:
                pos_k = src * s_loc + lax.broadcasted_iota(
                    jnp.int32, (s_loc, s_loc), 1)
                s = jnp.where(pos_q >= pos_k, s, NEG_INF)
            m_cur = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m, m_cur)
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
            o_new = o * alpha + jnp.einsum(
                "bhqk,bhkd->bhqd", p.astype(v_blk.dtype), v_blk,
                preferred_element_type=jnp.float32)
            return o_new, m_new, l_new

        if causal:
            # blocks strictly above the diagonal (src > my) are fully
            # masked: skip their matmuls.  This halves aggregate FLOPs
            # (energy), but NOT the critical path — with the contiguous
            # layout some device attends at every ring step, so per-step
            # wall time is unchanged; converting the saving into ~2x time
            # needs a zigzag position assignment (each device holding one
            # low and one high block), a layout-contract change left for a
            # later round.
            o, m, l = lax.cond(src <= my, attend,
                               lambda o, m, l: (o, m, l), o, m, l)
        else:
            o, m, l = attend(o, m, l)
        k_blk, v_blk = lax.ppermute((k_blk, v_blk), axis_name, perm)
        return (k_blk, v_blk, o, m, l), None

    from dtdl_tpu.parallel.collectives import pvary_like
    o0 = pvary_like(jnp.zeros((b, h, s_loc, d), jnp.float32), q, k, v)
    m0 = pvary_like(jnp.full((b, h, s_loc, 1), NEG_INF, jnp.float32), q, k, v)
    l0 = pvary_like(jnp.zeros((b, h, s_loc, 1), jnp.float32), q, k, v)
    (k, v, o, m, l), _ = lax.scan(step, (k, v, o0, m0, l0), jnp.arange(n))
    l = jnp.where(l == 0.0, 1.0, l)               # fully-masked rows (non-causal corner)
    return (o / l).astype(q.dtype)


def ulysses_attention(q, k, v, *, axis_name: str = SEQ_AXIS,
                      causal: bool = True, scale: float | None = None,
                      attn_fn=None):
    """DeepSpeed-Ulysses-style SP: all-to-all seq→heads, attend, reverse.

    Requires ``heads %% axis_size == 0``.  ``attn_fn(q, k, v, causal, scale)``
    defaults to the Pallas flash kernel over the full gathered sequence.
    """
    from dtdl_tpu.ops.attention import flash_attention
    n = _axis_size(axis_name)
    if q.shape[1] % n:
        raise ValueError(
            f"ulysses needs heads ({q.shape[1]}) divisible by axis size {n}")
    if attn_fn is None:
        def attn_fn(q, k, v, causal, scale):
            return flash_attention(q, k, v, causal=causal, scale=scale)

    def to_heads(x):   # [B, H, S/n, D] -> [B, H/n, S, D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def to_seq(x):     # [B, H/n, S, D] -> [B, H, S/n, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    o = attn_fn(to_heads(q), to_heads(k), to_heads(v), causal, scale)
    return to_seq(o)
