"""GSPMD tensor-parallel / FSDP sharding for the flax model zoo.

The compiler-partitioned complement to the manual-SPMD megatron step
(dtdl_tpu/parallel/megatron.py): every TransformerLM parameter carries flax
*logical axis* names (dtdl_tpu/models/transformer.py), and this module maps
them onto mesh axes with swappable rule sets, then jits the train step with
those shardings — XLA's SPMD partitioner inserts the collectives (the
all-gathers/reduce-scatters of FSDP, the allreduces of Megatron TP) that
megatron.py writes by hand.

Rule presets:

* ``tp``        — Megatron sharding: attention heads + FFN hidden + vocab on
                  'model'; activations sharded on 'data' (batch).
* ``fsdp``      — ZeRO-3-style: every parameter's 'embed' dim sharded on
                  'data'; XLA all-gathers params per layer and
                  reduce-scatters grads.
* ``tp_fsdp``   — both: 'model' for width, 'data' for the embed dim.
* ``ep``        — expert parallelism for routed-MoE models: the 'expert'
                  dim on 'model' (each shard owns E/tp experts; GSPMD
                  inserts the token all-to-all around the dispatch
                  einsums), attention heads + vocab still on 'model',
                  FFN hidden unsharded — the megatron engine's ep-on-tp
                  layout, compiler-partitioned.  (Under ``tp``, 'expert'
                  and 'mlp' both name 'model' and flax resolves the
                  conflict toward 'mlp': every expert's FFN is
                  tensor-sharded instead — also valid, but EP is what
                  lets E scale past one device's memory.)

The reference has no model parallelism at all (SURVEY §2.2: TP/PP marked
absent); this is part of the framework's beyond-parity scale path.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dtdl_tpu.runtime.mesh import DATA_AXIS, MODEL_AXIS

RULE_PRESETS = {
    "replicated": (
        ("batch", DATA_AXIS),
        ("vocab", None), ("embed", None), ("heads", None),
        ("head_dim", None), ("mlp", None), ("expert", None),
    ),
    "tp": (
        ("batch", DATA_AXIS),
        ("vocab", MODEL_AXIS), ("embed", None), ("heads", MODEL_AXIS),
        ("head_dim", None), ("mlp", MODEL_AXIS), ("expert", MODEL_AXIS),
    ),
    "fsdp": (
        ("batch", DATA_AXIS),
        ("vocab", None), ("embed", DATA_AXIS), ("heads", None),
        ("head_dim", None), ("mlp", None), ("expert", None),
    ),
    "tp_fsdp": (
        ("batch", DATA_AXIS),
        ("vocab", MODEL_AXIS), ("embed", DATA_AXIS), ("heads", MODEL_AXIS),
        ("head_dim", None), ("mlp", MODEL_AXIS), ("expert", MODEL_AXIS),
    ),
    "ep": (
        ("batch", DATA_AXIS),
        ("vocab", MODEL_AXIS), ("embed", None), ("heads", MODEL_AXIS),
        ("head_dim", None), ("mlp", None), ("expert", MODEL_AXIS),
    ),
}


def logical_shardings(mesh: Mesh, tree, rules="tp"):
    """Map a pytree of flax logical-axis metadata to NamedShardings."""
    if isinstance(rules, str):
        rules = RULE_PRESETS[rules]
    specs = nn.get_partition_spec(tree)
    return nn.logical_to_mesh_sharding(specs, mesh, list(rules))


def quant_logical_shardings(mesh: Mesh, model, rules="tp", mode=True):
    """NamedShardings for a ``quantize_params`` tree (round 20 — the
    PR 14 known-remaining TP+quantize composition; ``mode`` picks the
    recipe since kernel round 2: ``True`` int8+f32, ``'w8f'`` fp8+bf16
    — the specs are dtype-independent, so both modes share this map).

    The quantized clone's params carry no flax logical-axis metadata
    (``QuantDenseGeneral`` declares plain placeholders — a quantized
    model is served, never trained), so ``logical_shardings`` cannot
    shard them.  But the layout is fully determined by the f32 tree:

    * every quantized ``kernel`` keeps its f32 twin's module path AND
      shape (dtdl_tpu/quant/core.py), so it inherits the twin's spec
      verbatim — column/row-parallel exactly like the weights it
      replaces;
    * every ``<name>_scale`` sibling is its tensor's shape with the
      contracted dims as keepdims 1s, so its spec is the tensor's spec
      with every size-1 dim unsharded — a 'model'-sharded output
      feature dim keeps its per-channel scales sharded alongside it
      (each TP shard multiplies by exactly its own channels' scales),
      and replicated dims stay replicated;
    * unquantized leaves (embed, norms, router) pass through on their
      own logical spec.

    ``model`` may be the quantized or unquantized module — both clones
    are derived here.  Returns a sharding pytree matching the
    ``quantize_params`` output structure.
    """
    import functools

    from dtdl_tpu.quant import SCALE_SUFFIX

    tokens = jnp.zeros((1, 1), jnp.int32)
    rng = jax.random.PRNGKey(0)
    boxed = jax.eval_shape(
        functools.partial(model.clone(quantize=False).init, rng),
        tokens)["params"]
    f_sh = logical_shardings(mesh, boxed, rules)
    q_abs = nn.unbox(jax.eval_shape(
        functools.partial(model.clone(quantize=mode or True).init, rng),
        tokens)["params"])

    def scale_spec(tensor_sharding, scale_shape):
        spec = tensor_sharding.spec
        return NamedSharding(mesh, P(*[
            spec[i] if i < len(spec) and scale_shape[i] != 1 else None
            for i in range(len(scale_shape))]))

    def conv(q, f):
        out = {}
        for name, sub in q.items():
            base = name[:-len(SCALE_SUFFIX)]
            if name.endswith(SCALE_SUFFIX) and base in q:
                continue                  # emitted with its tensor
            if isinstance(sub, dict):
                out[name] = conv(sub, f[name])
                continue
            out[name] = f[name]
            sname = f"{name}{SCALE_SUFFIX}"
            if sname in q:
                out[sname] = scale_spec(f[name], q[sname].shape)
        return out

    return conv(q_abs, f_sh)


def heads_axis_size(mesh: Mesh, rules="tp") -> int:
    """Size of the mesh axis the 'heads' logical dim shards on under
    ``rules`` (1 when unsharded) — the serving engine's divisibility
    check: a KV arena splits across exactly this many tensor-parallel
    shards."""
    if isinstance(rules, str):
        rules = RULE_PRESETS[rules]
    axis = dict(rules).get("heads")
    return int(mesh.shape[axis]) if axis is not None else 1


def serve_arena_shardings(mesh: Mesh, arena_shapes, rules="tp"):
    """NamedShardings for a serving KV arena (round 19, the
    tensor-parallel engine): the cache is built by the engine's init
    helpers, not a flax init trace, so it carries no logical metadata —
    but its layout is fixed by construction: every K/V payload and
    scale leaf is ``[slots-or-pages, H, ...]`` with the HEADS dim on
    axis 1 (dense rows [B, H, max_seq, D], paged pools
    [n_pages, H, page, D], int8 scale siblings [.., H, ..]), and the
    per-slot ``index`` vectors are tiny host-shaped scalars.  Sharding
    heads on the same mesh axis the 'heads' logical dim uses keeps each
    TP shard's attention entirely local (the Megatron layout: QKV
    column-parallel in, out-projection row-parallel psum — inserted by
    GSPMD), which is what makes the arena split ``1/tp`` of the KV
    bytes per chip.  Everything else (indices) replicates.
    """
    if isinstance(rules, str):
        rules = RULE_PRESETS[rules]
    axis = dict(rules).get("heads")
    repl = NamedSharding(mesh, P())
    heads = NamedSharding(mesh, P(None, axis))

    def one(leaf):
        return heads if getattr(leaf, "ndim", 0) >= 3 else repl

    return jax.tree.map(one, arena_shapes)


def init_sharded_lm(model, mesh: Mesh, tx, example_tokens, rules="tp",
                    rng=None):
    """Initialize TransformerLM params directly into their shards.

    Uses eval_shape + jit-with-out-shardings so each device materializes only
    its own parameter shards (no host-side full copy) — the way a >HBM model
    would be initialized on a pod.  Returns (params, opt_state, shardings).
    """
    import optax
    rng = jax.random.PRNGKey(0) if rng is None else rng

    def boxed_init(rng):
        return model.init(rng, example_tokens)["params"]

    def init_fn(rng):
        return nn.unbox(boxed_init(rng))   # plain array pytree

    # logical specs come from the boxed metadata; the sharding tree then
    # matches the *unboxed* structure (boxes collapse to their leaf spec)
    abs_boxed = jax.eval_shape(boxed_init, rng)
    param_sh = logical_shardings(mesh, abs_boxed, rules)
    params = jax.jit(init_fn, out_shardings=param_sh)(rng)

    abs_params = nn.unbox(abs_boxed)
    abs_opt = jax.eval_shape(tx.init, abs_params)
    opt_sh = optax.tree_map_params(
        tx, lambda _, s: s, abs_opt, param_sh,
        transform_non_params=lambda _: NamedSharding(mesh, P()))
    opt_state = jax.jit(tx.init, out_shardings=opt_sh)(params)
    return params, opt_state, (param_sh, opt_sh)


def make_sharded_lm_train_step(model, mesh: Mesh, tx, shardings,
                               rules="tp"):
    """pjit'd LM step with GSPMD-inserted collectives.

    ``batch`` {'tokens': int32 [B, S]} is sharded P('data') on the batch dim;
    gradients of 'model'-sharded params reduce over 'data' automatically, and
    FSDP rules make XLA all-gather/reduce-scatter parameters around each use.
    Uses dense attention (einsums partition cleanly under GSPMD; the Pallas
    flash kernel pairs with the shard_map strategies instead).

    ``rules`` (same preset/list as :func:`init_sharded_lm` — pass the one
    the params were initialized with) is installed as the flax
    ``logical_axis_rules`` context around the forward, so the model's
    ``nn.with_logical_constraint`` annotations (e.g. the routed MoE's
    [E, B, C, D] expert buffer pinning 'expert' to its mesh axis) bind to
    real mesh axes instead of silently no-opping — without the context,
    intermediate layouts would rely entirely on XLA's propagation from
    the weight shardings.
    """
    if isinstance(rules, str):
        rules = RULE_PRESETS[rules]
    rules = list(rules)
    param_sh, opt_sh = shardings
    batch_sh = NamedSharding(mesh, P(DATA_AXIS))

    def step(params, opt_state, tokens):
        def loss_fn(p):
            loss, _ = _lm_shift_loss(model, rules, p, tokens)
            return loss

        loss, grads = jax.value_and_grad(loss_fn)(params)
        import optax
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return jax.jit(
        step,
        in_shardings=(param_sh, opt_sh, batch_sh),
        out_shardings=(param_sh, opt_sh, NamedSharding(mesh, P())),
        donate_argnums=(0, 1))


def _lm_shift_loss(model, rules, params, tokens):
    """Shared next-token objective of the GSPMD train AND eval steps
    (one definition, so a numerics change cannot drift between them):
    shift, forward under the logical-rules context, mean CE — returns
    ``(loss, accuracy)``."""
    inputs, targets = tokens[:, :-1], tokens[:, 1:].astype(jnp.int32)
    with nn.logical_axis_rules(rules):
        logits = model.apply({"params": params}, inputs).astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, -1)
    true = jnp.take_along_axis(logits, targets[..., None], -1)[..., 0]
    acc = jnp.mean((jnp.argmax(logits, -1) == targets)
                   .astype(jnp.float32))
    return jnp.mean(lse - true), acc


def make_sharded_lm_eval_step(model, mesh: Mesh, shardings, rules="tp"):
    """Forward-only validation for the GSPMD face: mean next-token loss
    and token accuracy, no optimizer, params NOT donated (they are
    reused for training).  Same rule-context contract as
    :func:`make_sharded_lm_train_step`; parity with the strategy
    engines' ``make_eval_step`` and the 4D ``make_megatron_eval_step``
    (reference evaluate-parity: tensorflow2/mnist_single.py:88-92).
    """
    if isinstance(rules, str):
        rules = RULE_PRESETS[rules]
    rules = list(rules)
    param_sh, _ = shardings
    batch_sh = NamedSharding(mesh, P(DATA_AXIS))

    def evaluate(params, tokens):
        loss, acc = _lm_shift_loss(model, rules, params, tokens)
        return {"loss": loss, "accuracy": acc,
                "n_tokens": jnp.float32(tokens.shape[0]
                                        * (tokens.shape[1] - 1))}

    out_sh = {k: NamedSharding(mesh, P())
              for k in ("loss", "accuracy", "n_tokens")}
    return jax.jit(evaluate, in_shardings=(param_sh, batch_sh),
                   out_shardings=out_sh)
