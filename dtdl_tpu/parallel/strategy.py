"""Parallelism strategies.

A `Strategy` owns the device mesh and defines three things the train-step
engine composes with:

* ``grad_sync``   — what happens to gradients before the optimizer update
* ``compile``     — how a per-replica step function becomes a global SPMD step
* ``shard_batch`` / ``replicate`` — where batches and parameters live

Mapping to the reference's strategy layer (SURVEY §2.2):

| reference                                             | here                    |
|-------------------------------------------------------|-------------------------|
| plain single-device loop (pytorch/single_gpu.py)      | `SingleDevice`          |
| nn.DataParallel / MirroredStrategy / ParallelUpdater  | `DataParallel(local_mesh())` |
| DistributedDataParallel / MultiWorkerMirroredStrategy / ChainerMN | `DataParallel(build_mesh())` over a multi-host mesh |
| (future TP/PP/SP axes)                                | `AutoSharded` with custom rules |

`DataParallel` uses `shard_map` with an explicit `lax.pmean` — the literal
SPMD restatement of DDP: every replica computes on its local shard of the
batch with per-replica BatchNorm statistics (matching DDP, which syncs grads
but not BN batches), gradients are mean-allreduced over ICI, and every replica
applies an identical update.  Running BN statistics are also pmean-synced so
the replicated train state stays bitwise identical across replicas (torch DDP
achieves the same end by broadcasting buffers from rank 0 each step).

`AutoSharded` instead gives XLA's SPMD partitioner the whole step with sharded
inputs and replicated params — the compiler inserts the AllReduces.  Under it,
BatchNorm reductions become global-batch (sync-BN semantics).  Both are
provided; `DataParallel` is the DDP-parity default.
"""

from __future__ import annotations

from functools import partial

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dtdl_tpu.runtime.mesh import DATA_AXIS, batch_sharded, build_mesh, local_mesh, replicated
from dtdl_tpu.parallel import collectives


class Strategy:
    """Base: single logical device semantics."""

    mesh: Mesh | None = None
    axis: str | None = None

    def localize(self, tree):
        """Hook: mark replicated values as per-replica before local compute."""
        return tree

    def grad_sync(self, grads):
        return grads

    def metric_sync(self, tree):
        return tree

    def sum_sync(self, tree):
        """Sum-allreduce (for exact count-weighted eval metrics)."""
        return tree

    def stats_sync(self, tree):
        return tree

    def fold_rank(self, key):
        """Decorrelate an rng across replicas (identity off-mesh)."""
        return key

    def compile(self, step_fn, donate_state: bool = True):
        """Jit a step ``(state, batch, ...) -> (state, metrics)``."""
        return jax.jit(step_fn, donate_argnums=(0,) if donate_state else ())

    def compile_eval(self, eval_fn):
        return jax.jit(eval_fn)

    def compile_predict(self, predict_fn):
        """Jit an inference fn ``(state, batch) -> outputs`` (batch-aligned)."""
        return jax.jit(predict_fn)

    def shard_batch(self, batch):
        return jax.device_put(batch)

    def replicate(self, tree):
        return jax.device_put(tree)

    @property
    def num_replicas(self) -> int:
        return 1

    def per_replica_batch(self, global_batch_size: int) -> int:
        """Explicit global-vs-per-replica semantics.

        The reference divides the batch by the *local* device count only
        (reference pytorch/distributed_data_parallel.py:71), which silently
        changes the global batch as nodes are added; we define --batch-size as
        GLOBAL and split by the world replica count (SURVEY §2.4).
        """
        n = self.num_replicas
        if global_batch_size % n:
            raise ValueError(
                f"global batch {global_batch_size} not divisible by "
                f"{n} replicas")
        return global_batch_size // n


class SingleDevice(Strategy):
    """One device, no collectives — reference pytorch/single_gpu.py:43-85."""


class MeshStrategy(Strategy):
    """Shared mesh-bearing behavior: batch/state placement over a mesh.

    ``axis`` may be a tuple of mesh axes for hierarchical data parallelism
    (e.g. ``('dcn', 'data')`` over a `hybrid_mesh`): the batch shards over
    all of them and gradient allreduces name them all, so XLA emits the
    in-slice ICI reduce and the cross-slice DCN reduce as one hierarchy.
    """

    def __init__(self, mesh: Mesh | None = None,
                 axis: str | tuple[str, ...] = DATA_AXIS):
        self.mesh = mesh if mesh is not None else build_mesh()
        self.axis = axis

    def shard_batch(self, batch):
        """Place a host batch as a global array sharded on the data axis.

        Single-process: device_put scatters local data across the mesh.
        Multi-process: each host contributes its local shard of the global
        batch (`make_array_from_process_local_data`) — the deterministic
        per-host sharding that replaces ``DistributedSampler`` wire-level
        scatter (reference chainer/train_mnist_multi.py:91-92).
        """
        sharding = batch_sharded(self.mesh, self.axis)
        if jax.process_count() == 1:
            return jax.device_put(batch, sharding)
        return jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(sharding, x),
            batch)

    def replicate(self, tree):
        return jax.device_put(tree, replicated(self.mesh))

    @property
    def num_replicas(self) -> int:
        if isinstance(self.axis, tuple):
            out = 1
            for a in self.axis:
                out *= self.mesh.shape[a]
            return out
        return self.mesh.shape[self.axis]


class DataParallel(MeshStrategy):
    """shard_map data parallelism over a mesh axis (DP and DDP).

    Single-process over `local_mesh()` ≡ nn.DataParallel/MirroredStrategy;
    multi-process over `build_mesh()` ≡ DDP/MultiWorkerMirroredStrategy/
    ChainerMN — same code, the mesh just spans hosts.
    """

    def localize(self, tree):
        return collectives.localize(tree, self.axis)

    def grad_sync(self, grads):
        return collectives.grad_sync(grads, self.axis)

    def metric_sync(self, tree):
        return collectives.all_reduce_mean(tree, self.axis)

    def sum_sync(self, tree):
        return collectives.all_reduce_sum(tree, self.axis)

    def stats_sync(self, tree):
        return collectives.all_reduce_mean(tree, self.axis)

    def fold_rank(self, key):
        # each replica draws its own dropout mask, like per-rank DDP
        # workers; axis_index flattens tuple axes row-major, matching the
        # P((...)) batch-sharding order
        return jax.random.fold_in(key, jax.lax.axis_index(self.axis))

    def compile(self, step_fn, donate_state: bool = True):
        mapped = jax.shard_map(
            step_fn, mesh=self.mesh,
            in_specs=(P(), P(self.axis)),
            out_specs=(P(), P()),
        )
        return jax.jit(mapped, donate_argnums=(0,) if donate_state else ())

    def compile_eval(self, eval_fn):
        mapped = jax.shard_map(
            eval_fn, mesh=self.mesh,
            in_specs=(P(), P(self.axis)),
            out_specs=P(),
        )
        return jax.jit(mapped)

    def compile_predict(self, predict_fn):
        # outputs stay sharded on the data axis, aligned with the input batch
        mapped = jax.shard_map(
            predict_fn, mesh=self.mesh,
            in_specs=(P(), P(self.axis)),
            out_specs=P(self.axis),
        )
        return jax.jit(mapped)


class AutoSharded(MeshStrategy):
    """Compiler-partitioned strategy (pjit style).

    Params replicated, batch sharded on the data axis; XLA's SPMD partitioner
    inserts the collectives.  The mesh may carry extra axes (model, pipeline,
    sequence) — pass ``param_spec`` to shard the state for model parallelism;
    the data-parallel gradient allreduce still falls out of the partitioner
    automatically.  ``param_spec`` is either one ``PartitionSpec`` applied to
    every state leaf, or a callable ``(path, leaf) -> PartitionSpec``
    evaluated over the TrainState tree (``path`` is the jax key path; switch
    on it / the leaf's shape to shard kernels but replicate biases — the
    optimizer-state leaves mirror the param shapes, so one shape rule shards
    both consistently).
    """

    def __init__(self, mesh: Mesh | None = None, axis: str = DATA_AXIS,
                 param_spec=None):
        super().__init__(mesh, axis)
        self.param_spec = param_spec if param_spec is not None else P()

    @property
    def _per_leaf(self):
        return callable(self.param_spec) and \
            not isinstance(self.param_spec, P)

    def _state_sharding(self, like=None):
        if self._per_leaf:
            if like is None:
                raise ValueError("per-leaf param_spec needs the state tree")
            return jax.tree_util.tree_map_with_path(
                lambda path, leaf: NamedSharding(
                    self.mesh, self.param_spec(path, leaf)), like)
        return NamedSharding(self.mesh, self.param_spec)

    def compile(self, step_fn, donate_state: bool = True):
        batch_s = batch_sharded(self.mesh, self.axis)
        donate = (0,) if donate_state else ()
        if self._per_leaf:
            # The per-leaf sharding tree needs the state's structure, which
            # compile() doesn't have yet — bind it lazily from the first
            # state passed in.  in/out shardings are both EXPLICIT: with
            # out_shardings unspecified the partitioner is free to pick
            # output placements, and any divergence would compound step to
            # step (state feeds back in); pinning both sides makes the
            # placement an invariant instead of a hope.
            return _LazyPerLeafStep(self, step_fn, batch_s, donate)
        state_s = self._state_sharding()
        return jax.jit(
            step_fn,
            in_shardings=(state_s, batch_s),
            out_shardings=(state_s, NamedSharding(self.mesh, P())),
            donate_argnums=donate,
        )

    def compile_eval(self, eval_fn):
        state_s = None if self._per_leaf else self._state_sharding()
        return jax.jit(
            eval_fn,
            in_shardings=(state_s, batch_sharded(self.mesh, self.axis)),
            out_shardings=NamedSharding(self.mesh, P()),
        )

    def compile_predict(self, predict_fn):
        state_s = None if self._per_leaf else self._state_sharding()
        return jax.jit(
            predict_fn,
            in_shardings=(state_s, batch_sharded(self.mesh, self.axis)),
            out_shardings=batch_sharded(self.mesh, self.axis),
        )

    def replicate(self, tree):
        if self._per_leaf:
            # one device_put with a sharding pytree batches the transfers
            return jax.device_put(tree, self._state_sharding(like=tree))
        return jax.device_put(tree, self._state_sharding())


class _LazyPerLeafStep:
    """Jitted step whose state shardings bind on first call.

    AutoSharded(param_spec=<callable>) decides shardings per state leaf,
    but the state tree only exists after ``init_state``/``replicate`` —
    so the jit (with fully explicit in/out shardings, which is what keeps
    leaf placements stable across steps) is created on the first
    invocation and cached.  ``lower`` is forwarded for cost analysis."""

    def __init__(self, strategy: "AutoSharded", step_fn, batch_sharding,
                 donate):
        self._strategy = strategy
        self._step_fn = step_fn
        self._batch_s = batch_sharding
        self._donate = donate
        self._jit = None

    def _bind(self, state):
        state_s = self._strategy._state_sharding(like=state)
        mesh = self._strategy.mesh
        self._jit = jax.jit(
            self._step_fn,
            in_shardings=(state_s, self._batch_s),
            out_shardings=(state_s, NamedSharding(mesh, P())),
            donate_argnums=self._donate)

    def __call__(self, state, batch):
        if self._jit is None:
            self._bind(state)
        return self._jit(state, batch)

    def lower(self, state, batch):
        if self._jit is None:
            self._bind(state)
        return self._jit.lower(state, batch)


def data_parallel_local() -> DataParallel:
    """Single-process multi-device DP (nn.DataParallel equivalent)."""
    return DataParallel(local_mesh())


def distributed_data_parallel() -> DataParallel:
    """Global-mesh allreduce DP (DistributedDataParallel equivalent)."""
    return DataParallel(build_mesh())


def choose_strategy(name: str = "auto", mesh: Mesh | None = None) -> Strategy:
    """Pick a strategy the way the reference picks via script choice.

    'single' | 'dp' | 'ddp' | 'auto' (auto = ddp if >1 device else single).
    """
    if name == "auto":
        name = "ddp" if len(jax.devices()) > 1 else "single"
    if name == "single":
        return SingleDevice()
    if name == "dp":
        return DataParallel(mesh if mesh is not None else local_mesh())
    if name == "ddp":
        return DataParallel(mesh if mesh is not None else build_mesh())
    if name == "pjit":
        return AutoSharded(mesh)
    raise ValueError(f"unknown strategy {name!r}")
