"""KVStore — the MXNet-idiom gradient-aggregation surface over XLA collectives.

The reference declares an ``mxnet/`` track (reference README.md:4-20) that was
never written (``mxnet/README.md`` is empty, SURVEY §2.1).  MXNet's canonical
distributed idiom is the **key-value store**: workers ``push`` gradients keyed
by parameter name, the store aggregates (sums) them — locally across devices
for ``local``/``device`` stores, across machines via parameter servers for
``dist_sync`` — and workers ``pull`` the aggregate back before the optimizer
update.  This module is that capability rebuilt TPU-native:

* ``push``/``pull`` inside a jitted SPMD step stage per-replica values and
  aggregate them with ``lax.psum`` over the mesh's data axis — the XLA
  AllReduce over ICI replaces the parameter-server hop entirely (there is no
  server tier to place; the "store" is the collective).
* ``dist_async`` is accepted and routed to synchronous aggregation, the same
  accept-but-route treatment the reference gives TF's vestigial PS mode
  (reference tensorflow2/mnist_multi_worker_strategy.py:15-16 rejects Ps;
  SURVEY §2.2 says keep the flag surface, route to collective DP) — on a TPU
  mesh the synchronous AllReduce is both faster and deterministic, so async
  staleness buys nothing.
* ``KVStoreStrategy`` plugs the store into the train-step engine as the
  gradient-sync backend, which is exactly the role ``kvstore=`` plays in
  ``mxnet.mod.Module.fit`` — the rest of the step (forward, backward, update)
  is untouched.

Like MXNet, aggregation is a **sum**; normalization is explicit —
``pull(average=True)`` or a constructor ``rescale`` factor — mirroring how
MXNet leaves it to the optimizer's ``rescale_grad=1/batch_size``.
``KVStoreStrategy`` pulls averaged gradients, making it numerically identical
to ``lax.pmean`` DDP.
"""

from __future__ import annotations

import threading
import time

import numpy as np

import jax
from jax import lax

from dtdl_tpu.parallel.strategy import DataParallel, SingleDevice, Strategy
from dtdl_tpu.runtime.bootstrap import BarrierTimeoutError, backoff_delay
from dtdl_tpu.runtime.mesh import DATA_AXIS, build_mesh, local_mesh

VALID_KINDS = ("local", "device", "dist_sync", "dist_device_sync", "dist_async")


# ---------------------------------------------------------------------------
# host-side control-plane store (ISSUE 12)
#
# The jit-side KVStore above is the *data plane* — psum over a mesh axis.
# Elastic training additionally needs a *control plane* the collectives
# cannot provide: a host-side key-value surface for heartbeat leases,
# rendezvous membership, commit markers, and generation fencing, which
# must keep working while the data-plane world is broken (that is its
# whole job).  :class:`HostKVStore` is that surface: one logical store
# per training cluster, consulted by every worker's host loop.  Tests
# and the bench drill host workers as threads sharing one store — the
# PR 9 CPU-testable construction (fleet replicas share one engine); a
# real deployment backs the same five-verb protocol (set / get / wait /
# add / delete, plus store-side age stamps and the generation counter)
# with the coordinator's KV service.  All failure paths are NAMED:
# :class:`StoreTimeoutError` for a bounded wait, `BarrierTimeoutError`
# for a barrier, :class:`StaleGenerationError` for a fenced epoch, and
# :class:`StoreRetriesExhaustedError` when :class:`RetryingStore` burns
# its bounded retry budget on transient faults.
# ---------------------------------------------------------------------------


class StoreError(RuntimeError):
    """Base class for host-store failures (all named, never silent)."""


class TransientStoreError(StoreError):
    """A retryable store failure (connection blip, leader election in
    the backing service).  :class:`RetryingStore` retries exactly this
    class; anything else propagates immediately."""


class StoreTimeoutError(StoreError):
    """A bounded :meth:`HostKVStore.wait` expired without the key."""


class StoreRetriesExhaustedError(StoreError):
    """:class:`RetryingStore` burned its whole retry budget on
    transient faults — the store (or the network to it) is down, not
    blinking.  Carries the last transient error as ``__cause__``."""


class StaleGenerationError(StoreError):
    """A generation-fenced operation arrived with a stale epoch: the
    world has re-formed since this worker last participated.  A stale
    peer waking from a stall gets THIS, by name, instead of silently
    corrupting (or hanging) the new world — the training-plane twin of
    the PR 9 generation-fenced replica restart."""


_MISSING = object()


class HostKVStore:
    """Thread-safe host-side coordination store (see block comment).

    Every ``set`` records a store-side monotonic stamp, so lease ages
    (:meth:`age`) are judged on ONE clock — worker clock skew can never
    fake a live peer.  ``generation`` is the cluster epoch: it only
    moves through :meth:`bump_generation` (compare-and-swap, so N
    survivors proposing concurrently coalesce onto one new epoch) and
    every epoch-carrying op goes through :meth:`check_generation`.
    """

    def __init__(self):
        self._cond = threading.Condition(threading.Lock())
        self._data: dict[str, object] = {}
        self._stamp: dict[str, float] = {}
        self._gen = 0

    # ---- the five verbs ----------------------------------------------

    def set(self, key: str, value) -> None:
        with self._cond:
            self._data[key] = value
            self._stamp[key] = time.monotonic()
            self._cond.notify_all()

    def get(self, key: str, default=_MISSING):
        with self._cond:
            if key in self._data:
                return self._data[key]
        if default is _MISSING:
            raise KeyError(key)
        return default

    def wait(self, key: str, timeout_s: float):
        """Block until ``key`` exists; named timeout instead of a hang."""
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while key not in self._data:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cond.wait(remaining):
                    if key in self._data:      # woke on the final notify
                        break
                    raise StoreTimeoutError(
                        f"store key {key!r} did not appear within "
                        f"{timeout_s}s")
            return self._data[key]

    def add(self, key: str, delta: int = 1) -> int:
        """Atomic integer counter; returns the post-increment value."""
        with self._cond:
            value = int(self._data.get(key, 0)) + delta
            self._data[key] = value
            self._stamp[key] = time.monotonic()
            self._cond.notify_all()
            return value

    def delete(self, key: str) -> None:
        with self._cond:
            self._data.pop(key, None)
            self._stamp.pop(key, None)

    # ---- queries ------------------------------------------------------

    def keys(self, prefix: str = "") -> list[str]:
        with self._cond:
            return sorted(k for k in self._data if k.startswith(prefix))

    def age(self, key: str):
        """Seconds since ``key`` was last set (store clock), or None if
        the key has never been set — the lease-expiry primitive."""
        with self._cond:
            stamp = self._stamp.get(key)
        return None if stamp is None else time.monotonic() - stamp

    def newest_age(self, prefix: str):
        """Age of the most recently set key under ``prefix`` (None when
        empty) — how long a rendezvous round has been quiet."""
        with self._cond:
            stamps = [s for k, s in self._stamp.items()
                      if k.startswith(prefix)]
        return None if not stamps else time.monotonic() - max(stamps)

    # ---- state transfer (the WAL/snapshot hooks of the TCP server) ---

    def snapshot_state(self) -> tuple[dict, int]:
        """Consistent copy of (data, generation) — what a coordinator
        snapshot must persist.  Stamps are deliberately NOT part of the
        state: lease ages are judged on the live store's clock, and a
        recovered store re-stamps everything at recovery time (see
        :meth:`restore_state`)."""
        with self._cond:
            return dict(self._data), self._gen

    def restore_state(self, data: dict, gen: int) -> None:
        """Install recovered state.  Every key is re-stamped *now*: a
        store cannot judge lease staleness across its own outage, so
        recovery resets every age to zero — strictly conservative (no
        peer is declared dead because the COORDINATOR was down); a peer
        that really died during the outage stops beating and is
        re-detected one watchdog period after recovery."""
        with self._cond:
            self._data = dict(data)
            now = time.monotonic()
            self._stamp = {k: now for k in self._data}
            self._gen = int(gen)
            self._cond.notify_all()

    # ---- generation fencing ------------------------------------------

    @property
    def generation(self) -> int:
        with self._cond:
            return self._gen

    def bump_generation(self, expected: int) -> int:
        """Compare-and-swap epoch bump: advances only if the store is
        still at ``expected`` (so concurrent survivors proposing a
        re-rendezvous coalesce onto ONE new epoch).  Returns the
        current generation either way."""
        with self._cond:
            if self._gen == expected:
                self._gen = expected + 1
                self._cond.notify_all()
            return self._gen

    def check_generation(self, gen: int) -> None:
        with self._cond:
            current = self._gen
        if current != gen:
            raise StaleGenerationError(
                f"generation {gen} is stale: the store is at generation "
                f"{current} — this worker's world has been superseded")


def store_barrier(store, name: str, ranks, rank: int, gen: int = 0,
                  timeout_s: float = 30.0, poll_s: float = 0.01) -> None:
    """Generation-fenced barrier over a host store.

    Arrival keys carry the epoch, and the fence is checked both at
    arrival and while waiting: a stale-epoch arrival (or an epoch that
    advances mid-wait — the world re-formed without us) raises
    :class:`StaleGenerationError` by name, and a dead peer surfaces as
    the same named :class:`~dtdl_tpu.runtime.bootstrap.
    BarrierTimeoutError` the device-plane barrier uses — never a hang.

    The poll is **deadline-sliced**: each sleep is bounded by the
    remaining budget, never a full fixed ``poll_s`` — a sub-watchdog
    ``timeout_s`` must expire ON TIME, not overshoot by a poll period
    (a barrier armed with a 50 ms budget inside a 200 ms watchdog that
    silently waited 1 s would defeat the watchdog arithmetic the
    elastic layer's SCALING.md failure model depends on).
    """
    store.check_generation(gen)
    store.set(f"bar/{gen}/{name}/{rank}", True)
    deadline = time.monotonic() + timeout_s
    while True:
        missing = [r for r in ranks
                   if store.get(f"bar/{gen}/{name}/{r}", None) is None]
        if not missing:
            return
        store.check_generation(gen)
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise BarrierTimeoutError(
                f"store barrier {name!r} (generation {gen}) timed out "
                f"after {timeout_s}s waiting for rank(s) {missing}")
        time.sleep(min(poll_s, remaining))


class RetryingStore:
    """Bounded-retry facade over a host store.

    Every verb is retried on :class:`TransientStoreError` with
    exponential backoff and seeded jitter (deterministic schedules for
    tests; jitter de-synchronizes a thundering herd of survivors
    hammering a recovering store).  The budget is BOUNDED: exhaustion
    raises :class:`StoreRetriesExhaustedError` naming the op and
    attempt count, with the last transient error chained.  Fencing and
    timeout errors are never retried — they are verdicts, not blips.
    """

    def __init__(self, store, retries: int = 5, backoff_s: float = 0.005,
                 max_backoff_s: float = 0.25, jitter: float = 0.5,
                 seed: int = 0):
        if retries < 0:
            raise ValueError(f"retries must be >= 0, got {retries}")
        self.store = store
        self.retries = retries
        self.backoff_s = backoff_s
        self.max_backoff_s = max_backoff_s
        self.jitter = jitter
        self._rng = np.random.default_rng(seed)

    def _call(self, op: str, *args, **kwargs):
        last = None
        for attempt in range(self.retries + 1):
            try:
                return getattr(self.store, op)(*args, **kwargs)
            except TransientStoreError as e:
                last = e
                if attempt < self.retries:
                    time.sleep(backoff_delay(
                        attempt, self.backoff_s, self.max_backoff_s,
                        float(self._rng.random()), self.jitter))
        raise StoreRetriesExhaustedError(
            f"store.{op} failed after {self.retries + 1} attempts; last "
            f"transient error: {last}") from last

    # the verbs + queries, each through the bounded-retry path
    def set(self, key, value):
        return self._call("set", key, value)

    def get(self, key, default=_MISSING):
        if default is _MISSING:
            return self._call("get", key)
        return self._call("get", key, default)

    def wait(self, key, timeout_s):
        return self._call("wait", key, timeout_s)

    def add(self, key, delta=1):
        return self._call("add", key, delta)

    def delete(self, key):
        return self._call("delete", key)

    def keys(self, prefix=""):
        return self._call("keys", prefix)

    def age(self, key):
        return self._call("age", key)

    def newest_age(self, prefix):
        return self._call("newest_age", prefix)

    # fencing delegates un-retried: a verdict must not be re-asked
    @property
    def generation(self):
        return self.store.generation

    def bump_generation(self, expected):
        return self.store.bump_generation(expected)

    def check_generation(self, gen):
        return self.store.check_generation(gen)


class KVStore:
    """MXNet-style key-value store over a mesh axis.

    Inside a traced SPMD step (under ``KVStoreStrategy.compile`` /
    ``DataParallel.compile``), ``push`` stages per-replica pytrees and
    ``pull`` returns the cross-replica sum (times ``rescale``).  Outside jit,
    ``init``/``pull_init`` hold host-level initial values — MXNet's
    ``kv.init(key, value)`` handshake where worker 0's value wins.
    """

    def __init__(self, kind: str = "local", axis: str = DATA_AXIS,
                 mesh=None, rescale: float | None = None):
        if kind not in VALID_KINDS:
            raise ValueError(
                f"unknown kvstore kind {kind!r}; one of {VALID_KINDS}")
        self.kind = kind
        self.axis = axis
        if mesh is None:
            mesh = (build_mesh() if kind.startswith("dist")
                    else local_mesh())
        self.mesh = mesh
        self._staged: dict[str, object] = {}
        self._init: dict[str, object] = {}
        self.rescale = rescale

    # ---- topology (MXNet kv.rank / kv.num_workers) -------------------------

    @property
    def rank(self) -> int:
        """This worker *process*'s rank — MXNet's ``kv.rank`` is a process-
        level id, pairing with ``num_workers`` for host-side data sharding
        (``data[rank::num_workers]``)."""
        return jax.process_index()

    @property
    def num_workers(self) -> int:
        """Number of worker *processes* (MXNet semantics: 1 for local/device
        stores, the dist world size for dist_*).  Distinct from
        ``aggregation_width`` — one TPU process drives many devices."""
        return jax.process_count() if self.kind.startswith("dist") else 1

    @property
    def aggregation_width(self) -> int:
        """Device replicas summed by push/pull: the store's mesh-axis size."""
        return self.mesh.shape[self.axis]

    @property
    def distributed(self) -> bool:
        return self.aggregation_width > 1

    # ---- host-level init (outside jit) -------------------------------------

    def init(self, key: str, value) -> None:
        """Register an initial value; worker 0's copy wins across hosts."""
        from dtdl_tpu.parallel.collectives import host_broadcast
        self._init[key] = host_broadcast(value)

    def pull_init(self, key: str):
        return self._init[key]

    # ---- traced push/pull (inside an SPMD step) ----------------------------

    def push(self, key: str, value) -> None:
        """Stage this replica's contribution for ``key``."""
        self._staged[key] = value

    def pull(self, key: str, average: bool = False):
        """Aggregate the last pushed value across the store's replicas.

        **Sum**-aggregation, the MXNet contract — normalization is the
        caller's job there (optimizer ``rescale_grad``) and here it is the
        constructor's ``rescale`` factor or ``average=True`` (divide by
        ``aggregation_width``).  ``dist_async`` intentionally reaches the
        same synchronous psum (see module docstring).
        """
        value = self._staged.pop(key)
        # width-1 store: the sum is the value itself, but rescale/average
        # must still apply — same numerics on 1 device as on N.
        summed = (lax.psum(value, axis_name=self.axis) if self.distributed
                  else value)
        scale = 1.0 / self.aggregation_width if average else \
            (self.rescale if self.rescale is not None else 1.0)
        if scale == 1.0:
            return summed
        return jax.tree.map(lambda g: g * scale, summed)

    def push_pull(self, key: str, value, average: bool = False):
        """One-shot push+pull (MXNet's fused ``pushpull``)."""
        self.push(key, value)
        return self.pull(key, average=average)

class KVStoreStrategy(DataParallel):
    """DataParallel whose gradient sync routes through a ``KVStore``.

    This is ``kvstore=`` in ``Module.fit``: the store owns aggregation, the
    strategy owns placement/compilation.  With a ``local``/``device`` store
    the mesh is this process's devices (single-process multi-device, MXNet
    ``ctx=[mx.gpu(0), mx.gpu(1)]``); with ``dist_*`` it spans all hosts.
    """

    def __init__(self, kv: KVStore):
        super().__init__(kv.mesh, kv.axis)
        self.kv = kv

    def grad_sync(self, grads):
        return self.kv.push_pull("grad", grads, average=True)


def create(kind: str = "local", mesh=None, axis: str = DATA_AXIS) -> KVStore:
    """``mxnet.kv.create`` equivalent."""
    return KVStore(kind, axis=axis, mesh=mesh)


def kvstore_strategy(kv: KVStore | str = "local", mesh=None) -> Strategy:
    """Strategy for ``Module.fit(kvstore=...)``: SingleDevice when the store
    spans one device, else KVStore-backed data parallelism.  Accepts an
    existing store (the one you printed/initialized) or a kind string."""
    if isinstance(kv, str):
        kv = create(kv, mesh=mesh)
    if kv.aggregation_width == 1:
        return SingleDevice()
    return KVStoreStrategy(kv)
