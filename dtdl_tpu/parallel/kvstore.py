"""KVStore — the MXNet-idiom gradient-aggregation surface over XLA collectives.

The reference declares an ``mxnet/`` track (reference README.md:4-20) that was
never written (``mxnet/README.md`` is empty, SURVEY §2.1).  MXNet's canonical
distributed idiom is the **key-value store**: workers ``push`` gradients keyed
by parameter name, the store aggregates (sums) them — locally across devices
for ``local``/``device`` stores, across machines via parameter servers for
``dist_sync`` — and workers ``pull`` the aggregate back before the optimizer
update.  This module is that capability rebuilt TPU-native:

* ``push``/``pull`` inside a jitted SPMD step stage per-replica values and
  aggregate them with ``lax.psum`` over the mesh's data axis — the XLA
  AllReduce over ICI replaces the parameter-server hop entirely (there is no
  server tier to place; the "store" is the collective).
* ``dist_async`` is accepted and routed to synchronous aggregation, the same
  accept-but-route treatment the reference gives TF's vestigial PS mode
  (reference tensorflow2/mnist_multi_worker_strategy.py:15-16 rejects Ps;
  SURVEY §2.2 says keep the flag surface, route to collective DP) — on a TPU
  mesh the synchronous AllReduce is both faster and deterministic, so async
  staleness buys nothing.
* ``KVStoreStrategy`` plugs the store into the train-step engine as the
  gradient-sync backend, which is exactly the role ``kvstore=`` plays in
  ``mxnet.mod.Module.fit`` — the rest of the step (forward, backward, update)
  is untouched.

Like MXNet, aggregation is a **sum**; normalization is explicit —
``pull(average=True)`` or a constructor ``rescale`` factor — mirroring how
MXNet leaves it to the optimizer's ``rescale_grad=1/batch_size``.
``KVStoreStrategy`` pulls averaged gradients, making it numerically identical
to ``lax.pmean`` DDP.
"""

from __future__ import annotations

import jax
from jax import lax

from dtdl_tpu.parallel.strategy import DataParallel, SingleDevice, Strategy
from dtdl_tpu.runtime.mesh import DATA_AXIS, build_mesh, local_mesh

VALID_KINDS = ("local", "device", "dist_sync", "dist_device_sync", "dist_async")


class KVStore:
    """MXNet-style key-value store over a mesh axis.

    Inside a traced SPMD step (under ``KVStoreStrategy.compile`` /
    ``DataParallel.compile``), ``push`` stages per-replica pytrees and
    ``pull`` returns the cross-replica sum (times ``rescale``).  Outside jit,
    ``init``/``pull_init`` hold host-level initial values — MXNet's
    ``kv.init(key, value)`` handshake where worker 0's value wins.
    """

    def __init__(self, kind: str = "local", axis: str = DATA_AXIS,
                 mesh=None, rescale: float | None = None):
        if kind not in VALID_KINDS:
            raise ValueError(
                f"unknown kvstore kind {kind!r}; one of {VALID_KINDS}")
        self.kind = kind
        self.axis = axis
        if mesh is None:
            mesh = (build_mesh() if kind.startswith("dist")
                    else local_mesh())
        self.mesh = mesh
        self._staged: dict[str, object] = {}
        self._init: dict[str, object] = {}
        self.rescale = rescale

    # ---- topology (MXNet kv.rank / kv.num_workers) -------------------------

    @property
    def rank(self) -> int:
        """This worker *process*'s rank — MXNet's ``kv.rank`` is a process-
        level id, pairing with ``num_workers`` for host-side data sharding
        (``data[rank::num_workers]``)."""
        return jax.process_index()

    @property
    def num_workers(self) -> int:
        """Number of worker *processes* (MXNet semantics: 1 for local/device
        stores, the dist world size for dist_*).  Distinct from
        ``aggregation_width`` — one TPU process drives many devices."""
        return jax.process_count() if self.kind.startswith("dist") else 1

    @property
    def aggregation_width(self) -> int:
        """Device replicas summed by push/pull: the store's mesh-axis size."""
        return self.mesh.shape[self.axis]

    @property
    def distributed(self) -> bool:
        return self.aggregation_width > 1

    # ---- host-level init (outside jit) -------------------------------------

    def init(self, key: str, value) -> None:
        """Register an initial value; worker 0's copy wins across hosts."""
        from dtdl_tpu.parallel.collectives import host_broadcast
        self._init[key] = host_broadcast(value)

    def pull_init(self, key: str):
        return self._init[key]

    # ---- traced push/pull (inside an SPMD step) ----------------------------

    def push(self, key: str, value) -> None:
        """Stage this replica's contribution for ``key``."""
        self._staged[key] = value

    def pull(self, key: str, average: bool = False):
        """Aggregate the last pushed value across the store's replicas.

        **Sum**-aggregation, the MXNet contract — normalization is the
        caller's job there (optimizer ``rescale_grad``) and here it is the
        constructor's ``rescale`` factor or ``average=True`` (divide by
        ``aggregation_width``).  ``dist_async`` intentionally reaches the
        same synchronous psum (see module docstring).
        """
        value = self._staged.pop(key)
        # width-1 store: the sum is the value itself, but rescale/average
        # must still apply — same numerics on 1 device as on N.
        summed = (lax.psum(value, axis_name=self.axis) if self.distributed
                  else value)
        scale = 1.0 / self.aggregation_width if average else \
            (self.rescale if self.rescale is not None else 1.0)
        if scale == 1.0:
            return summed
        return jax.tree.map(lambda g: g * scale, summed)

    def push_pull(self, key: str, value, average: bool = False):
        """One-shot push+pull (MXNet's fused ``pushpull``)."""
        self.push(key, value)
        return self.pull(key, average=average)

class KVStoreStrategy(DataParallel):
    """DataParallel whose gradient sync routes through a ``KVStore``.

    This is ``kvstore=`` in ``Module.fit``: the store owns aggregation, the
    strategy owns placement/compilation.  With a ``local``/``device`` store
    the mesh is this process's devices (single-process multi-device, MXNet
    ``ctx=[mx.gpu(0), mx.gpu(1)]``); with ``dist_*`` it spans all hosts.
    """

    def __init__(self, kv: KVStore):
        super().__init__(kv.mesh, kv.axis)
        self.kv = kv

    def grad_sync(self, grads):
        return self.kv.push_pull("grad", grads, average=True)


def create(kind: str = "local", mesh=None, axis: str = DATA_AXIS) -> KVStore:
    """``mxnet.kv.create`` equivalent."""
    return KVStore(kind, axis=axis, mesh=mesh)


def kvstore_strategy(kv: KVStore | str = "local", mesh=None) -> Strategy:
    """Strategy for ``Module.fit(kvstore=...)``: SingleDevice when the store
    spans one device, else KVStore-backed data parallelism.  Accepts an
    existing store (the one you printed/initialized) or a kind string."""
    if isinstance(kv, str):
        kv = create(kv, mesh=mesh)
    if kv.aggregation_width == 1:
        return SingleDevice()
    return KVStoreStrategy(kv)
