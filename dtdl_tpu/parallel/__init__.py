from dtdl_tpu.parallel.strategy import (  # noqa: F401
    Strategy, SingleDevice, DataParallel, AutoSharded,
    data_parallel_local, distributed_data_parallel, choose_strategy,
)
from dtdl_tpu.parallel import collectives  # noqa: F401
from dtdl_tpu.parallel.kvstore import (  # noqa: F401
    KVStore, KVStoreStrategy, kvstore_strategy,
)
from dtdl_tpu.parallel.sequence import (  # noqa: F401
    ring_attention, ulysses_attention, zigzag_inverse, zigzag_order,
    zigzag_positions,
)
from dtdl_tpu.parallel.megatron import (  # noqa: F401
    MegatronConfig, build_4d_mesh, factor_mesh,
    make_megatron_eval_step, make_megatron_train_step, to_flax_params,
)
from dtdl_tpu.parallel.tensor import (  # noqa: F401
    RULE_PRESETS, init_sharded_lm, logical_shardings,
    make_sharded_lm_eval_step, make_sharded_lm_train_step,
)
