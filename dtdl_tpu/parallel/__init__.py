from dtdl_tpu.parallel.strategy import (  # noqa: F401
    Strategy, SingleDevice, DataParallel, AutoSharded,
    data_parallel_local, distributed_data_parallel, choose_strategy,
)
from dtdl_tpu.parallel import collectives  # noqa: F401
