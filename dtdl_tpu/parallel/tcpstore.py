"""TCP control-plane store: the multi-process backing of the PR 12
five-verb protocol, with a crash-recoverable coordinator.

PR 12's elastic training plane runs over :class:`~dtdl_tpu.parallel.
kvstore.HostKVStore` — threads sharing one Python dict.  Its
known-remaining named the open edge: the five-verb protocol (set / get
/ wait / add / delete + store-side age stamps + generation CAS +
fenced ``store_barrier``) is *the contract a TCP/etcd/coordinator-KV
backing must meet for real multi-host*.  This module is that backing,
built the way the reference's multi-process tracks rendezvous —
PyTorch's ``tcp://`` TCPStore init and the MXNet kvstore ``dist_sync``
parameter-server idiom — but carrying OUR protocol, so
``resil/elastic.py`` runs over it unchanged (pinned by the
cross-backend contract suite in tests/test_store_contract.py):

* **wire protocol** — length-prefixed frames (4-byte big-endian length
  + pickled payload) over plain stdlib sockets.  A short read is a
  *torn frame*, detected and named (:class:`TornFrameError`) — never a
  silent mis-parse.  Pickle is acceptable here for the same reason it
  is in PyTorch's TCPStore: the control plane lives inside the
  training cluster's trust boundary (bind to the cluster-internal
  interface; this is not an internet-facing service).
* **client** (:class:`TCPStoreClient`) — drops in wherever
  ``HostKVStore`` is accepted: the five verbs, the queries, and the
  generation surface, each one RPC.  Every RPC has a connect deadline
  and an IO deadline; connection failures (refused, reset, timed out,
  torn) close the socket, reconnect with bounded jittered backoff
  (:func:`~dtdl_tpu.runtime.bootstrap.backoff_delay` — THE formula),
  and surface only :class:`~dtdl_tpu.parallel.kvstore.
  TransientStoreError`, so the PR 12 :class:`RetryingStore` semantics
  carry over byte-for-byte: transients are retried, verdicts
  (:class:`StoreTimeoutError`, :class:`StaleGenerationError`,
  :class:`ServerEpochError`) never are.  ``wait`` is deadline-sliced:
  the server blocks at most ``wait_slice_s`` per RPC and the client
  re-issues with the *remaining* budget, so a sub-watchdog timeout
  expires on time instead of overshooting by a poll period, and a
  coordinator outage mid-wait surfaces as a transient the caller's
  retry budget absorbs.  Sockets are **per-thread** (a heartbeat
  daemon and the step loop share one client object without locking —
  each thread holds its own connection).
* **server** (:class:`TCPStoreServer`) — a thread-per-connection
  acceptor over one :class:`HostKVStore` (the contract's reference
  implementation IS the server's state), with coordinator crash
  recovery:

  - every mutation (set / add / delete / generation bump) is appended
    to a WAL *before* it is applied; a periodic snapshot compacts the
    log (records carry sequence numbers, so a crash between snapshot
    and truncate never double-applies an ``add``);
  - a restarted server rehydrates keys + generation from snapshot +
    WAL, **re-stamping every lease at recovery time** — the store
    cannot judge staleness across its own outage, so recovery is
    conservative: nobody is declared dead because the *coordinator*
    was down (a peer that really died stops beating and is
    re-detected one watchdog period later);
  - a **server epoch** token is minted at first boot and persisted
    with the state.  A server that comes back *without* its WAL mints
    a fresh epoch; clients pin the epoch at first contact and every
    reconnect re-handshakes it, so an amnesiac coordinator is refused
    by name (:class:`ServerEpochError` — a verdict, never retried)
    instead of silently rejoined with empty state (which would read
    as "every peer is dead and the generation is 0" — the exact
    split-brain this token exists to prevent).

Every socket-level edge is deterministically injectable through
:func:`~dtdl_tpu.resil.faults.store_site` (disconnect at the k-th RPC,
torn reply frame, blackholed request, connect-refused, coordinator
crash mid-reply), and the client keeps RPC latency tails
(obs/hist.py) plus reconnect/timeout/torn counters exportable as a
``MetricsExporter`` window source.  See SCALING.md "Control plane
over real sockets (round 18)" for the latency-vs-heartbeat arithmetic
and the recovery model.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time
import uuid
from typing import Optional

import numpy as np

from dtdl_tpu.obs.hist import LogHistogram
from dtdl_tpu.obs.observer import NULL_OBSERVER
from dtdl_tpu.parallel.kvstore import (
    HostKVStore, RetryingStore, StaleGenerationError, StoreError,
    StoreTimeoutError, TransientStoreError,
)
from dtdl_tpu.resil.faults import (InjectedCrash, InjectedFault, fire,
                                   store_site)
from dtdl_tpu.runtime.bootstrap import backoff_delay

_MISSING = object()

# env var every launcher threads through to its workers (launch/local
# sets it on children, launch/slurm exports it from the sbatch script,
# runtime.initialize(store_addr=...) publishes it) — one spelling, so
# `connect()` below works identically under every launch path
STORE_ADDR_ENV = "DTDL_STORE_ADDR"


class TornFrameError(TransientStoreError):
    """A frame arrived torn: the peer closed (or the connection died)
    mid-frame, leaving a partial length prefix or payload.  Named so a
    half-written reply is never mis-parsed as data — and transient,
    because a reconnect re-establishes framing from a clean boundary."""


class ServerEpochError(StoreError):
    """The server's epoch token does not match the one this client
    pinned at first contact: the coordinator restarted WITHOUT its WAL
    and is running with amnesiac state.  A verdict, never retried —
    rejoining an empty store would read as "all peers dead, generation
    0" and corrupt every survivor's view.  The operator must restore
    the WAL (or restart the world)."""


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------

_LEN = struct.Struct(">I")
MAX_FRAME = 256 * 1024 * 1024        # sanity bound: a corrupt length
                                     # prefix must not allocate the heap


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            if buf:
                raise TornFrameError(
                    f"connection closed mid-frame: got {len(buf)} of "
                    f"{n} bytes")
            raise ConnectionError("connection closed")
        buf += chunk
    return bytes(buf)


def send_frame(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def recv_frame(sock: socket.socket):
    header = _recv_exact(sock, _LEN.size)
    (n,) = _LEN.unpack(header)
    if n > MAX_FRAME:
        raise TornFrameError(
            f"frame length {n} exceeds the {MAX_FRAME}-byte bound — "
            f"corrupt length prefix or desynchronized framing")
    return pickle.loads(_recv_exact(sock, n))


# verdicts crossing the wire: (kind tag on the wire) <-> (named error)
_ERR_TO_WIRE = {
    StoreTimeoutError: "timeout",
    StaleGenerationError: "stale",
    KeyError: "key",
    ValueError: "value",
    ServerEpochError: "epoch",
}
_WIRE_TO_ERR = {v: k for k, v in _ERR_TO_WIRE.items()}


# ---------------------------------------------------------------------------
# client-side metrics (satellite: store observability)
# ---------------------------------------------------------------------------


class StoreClientMetrics:
    """Host-side books of one :class:`TCPStoreClient`: RPC latency
    tails in a fixed-memory :class:`LogHistogram` plus the failure
    counters (reconnects, IO timeouts, torn frames, transient errors,
    epoch refusals).  ``window()`` returns counter *deltas* since the
    last window with the tails as current-value gauges — the same
    delta-vs-gauge split the serve metrics feed a ``MetricsExporter``
    with; ``summary()`` stays cumulative."""

    COUNTERS = ("rpcs", "reconnects", "timeouts", "torn_frames",
                "transient_errors", "epoch_refusals")

    def __init__(self):
        self.hist = LogHistogram()
        self._lock = threading.Lock()
        self._counts = {k: 0 for k in self.COUNTERS}
        self._last = {k: 0 for k in self.COUNTERS}

    def observe(self, seconds: float) -> None:
        with self._lock:
            self._counts["rpcs"] += 1
            self.hist.add(seconds)

    def count(self, name: str) -> None:
        with self._lock:
            self._counts[name] += 1

    def window(self) -> dict:
        with self._lock:
            out = {}
            for k in self.COUNTERS:
                out[f"store_{k}"] = self._counts[k] - self._last[k]
                self._last[k] = self._counts[k]
            if self.hist.n:
                out["store_rpc_p50_ms"] = round(self.hist.p50 * 1e3, 6)
                out["store_rpc_p95_ms"] = round(self.hist.p95 * 1e3, 6)
                out["store_rpc_p99_ms"] = round(self.hist.p99 * 1e3, 6)
            return out

    def summary(self) -> dict:
        with self._lock:
            out = {f"store_{k}": v for k, v in self._counts.items()}
            out.update(self.hist.summary(prefix="store_rpc_", unit=1e3))
            return out


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class TCPStoreClient:
    """Socket client for :class:`TCPStoreServer` — a drop-in for
    :class:`HostKVStore` (module docstring).  Thread-safe via
    per-thread connections; wrap in :class:`RetryingStore` for the
    bounded-retry facade exactly as with the host store."""

    def __init__(self, addr: str, *, connect_timeout_s: float = 2.0,
                 io_timeout_s: float = 5.0, reconnect_attempts: int = 8,
                 backoff_s: float = 0.02, max_backoff_s: float = 0.5,
                 jitter: float = 0.5, seed: int = 0,
                 wait_slice_s: float = 0.25, rpc_retries: int = 2,
                 observer=None,
                 metrics: Optional[StoreClientMetrics] = None):
        host, _, port = addr.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"store address must be host:port, "
                             f"got {addr!r}")
        self.addr = addr
        self._host, self._port = host, int(port)
        self.connect_timeout_s = connect_timeout_s
        self.io_timeout_s = io_timeout_s
        self.reconnect_attempts = reconnect_attempts
        self.backoff_s = backoff_s
        self.max_backoff_s = max_backoff_s
        self.jitter = jitter
        self.wait_slice_s = wait_slice_s
        self.rpc_retries = rpc_retries
        self.observer = observer or NULL_OBSERVER
        self.metrics = metrics or StoreClientMetrics()
        # the jitter rng is shared across threads (per-thread sockets,
        # ONE client) and np.random.Generator is not thread-safe —
        # draws are serialized so concurrent reconnects (hb daemon +
        # step loop after a coordinator restart) can't corrupt the
        # state or break the seeded-determinism contract
        self._rng = np.random.default_rng(seed)
        self._rng_lock = threading.Lock()
        self._local = threading.local()
        # the pinned server epoch: set at first successful handshake,
        # checked on every reconnect (None until first contact)
        self.server_epoch: Optional[str] = None
        self._epoch_lock = threading.Lock()

    # ---- connection management ---------------------------------------

    def _connect(self) -> socket.socket:
        """One connect + epoch handshake, with bounded jittered backoff
        across attempts.  Raises :class:`TransientStoreError` when the
        budget exhausts, :class:`ServerEpochError` (a verdict) when the
        server answers with a foreign epoch."""
        last: Optional[BaseException] = None
        for attempt in range(self.reconnect_attempts + 1):
            sock = None
            try:
                fire(store_site("connect"))
                sock = socket.create_connection(
                    (self._host, self._port),
                    timeout=self.connect_timeout_s)
                sock.settimeout(self.io_timeout_s)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY,
                                1)
                with self._epoch_lock:
                    expected = self.server_epoch
                send_frame(sock, ("hello", (expected,)))
                epoch = self._decode(recv_frame(sock))
                with self._epoch_lock:
                    if self.server_epoch is None:
                        self.server_epoch = epoch
                return sock
            except ServerEpochError:
                if sock is not None:
                    sock.close()
                self.metrics.count("epoch_refusals")
                self.observer.event("store_epoch_refused",
                                    addr=self.addr)
                raise
            except (InjectedFault, OSError, TornFrameError,
                    pickle.UnpicklingError, EOFError) as e:
                if sock is not None:
                    sock.close()
                last = e
                if attempt < self.reconnect_attempts:
                    with self._rng_lock:
                        u = float(self._rng.random())
                    time.sleep(backoff_delay(
                        attempt, self.backoff_s, self.max_backoff_s,
                        u, self.jitter))
        raise TransientStoreError(
            f"could not connect to store at {self.addr} after "
            f"{self.reconnect_attempts + 1} attempts; last error: "
            f"{last}") from last

    def _sock(self) -> socket.socket:
        sock = getattr(self._local, "sock", None)
        if sock is None:
            sock = self._connect()
            self._local.sock = sock
        return sock

    def _drop(self) -> None:
        sock = getattr(self._local, "sock", None)
        if sock is not None:
            try:
                sock.close()
            finally:
                self._local.sock = None

    def close(self) -> None:
        """Close THIS thread's connection (others close on their own
        thread, or with the process)."""
        self._drop()

    # ---- the RPC core -------------------------------------------------

    def _decode(self, resp):
        if not isinstance(resp, tuple) or not resp:
            raise TornFrameError(f"malformed response frame: {resp!r}")
        if resp[0] == "ok":
            return resp[1]
        if resp[0] == "err":
            _, kind, msg = resp
            # NOTE: a server-side StoreTimeoutError here is a VERDICT
            # (a wait slice expiring is normal polling), not an IO
            # failure — the timeouts counter tracks only socket-level
            # deadline expiries
            raise _WIRE_TO_ERR.get(kind, StoreError)(msg)
        raise TornFrameError(f"malformed response frame: {resp!r}")

    def _rpc(self, op: str, *args, deadline_extra: float = 0.0):
        """One RPC with transport-level resilience.  IDEMPOTENT ops
        (everything except ``add`` — ``set``/``delete`` overwrite,
        reads re-read, ``bump_generation`` is a CAS whose re-send is a
        stale-proposal no-op) are transparently re-sent up to
        ``rpc_retries`` times after a successful reconnect, so a
        coordinator blip under a *generation read* — which the outer
        :class:`RetryingStore` deliberately never retries, because the
        verdict an op RETURNS must not be re-asked — does not kill the
        caller.  ``add`` is at-most-once-ambiguous (the reply may have
        died after the increment applied), so it is never re-sent
        here and surfaces the transient to the caller's policy layer,
        which owns the at-least-once decision."""
        retries = self.rpc_retries if op != "add" else 0
        last: Optional[BaseException] = None
        for attempt in range(retries + 1):
            sock = self._sock()
            t0 = time.perf_counter()
            try:
                fault = fire(store_site("rpc"))  # may raise InjectedFault
                blackhole = (fault is not None
                             and fault.kind == "blackhole")
                if deadline_extra:
                    sock.settimeout(self.io_timeout_s + deadline_extra)
                try:
                    if not blackhole:  # injected: the network ate it
                        send_frame(sock, (op, args))
                    resp = recv_frame(sock)
                finally:
                    if deadline_extra:
                        sock.settimeout(self.io_timeout_s)
                # latency is recorded for COMPLETED round trips only —
                # a failed attempt's reconnect/backoff time would smear
                # recovery cost into the RPC tails — and `wait` slices
                # are excluded from the histogram entirely: the server
                # HOLDS a wait on purpose, so its duration measures the
                # caller's polling budget, not transport health (the
                # number the heartbeat-period arithmetic divides by)
                if op == "wait":
                    self.metrics.count("rpcs")
                else:
                    self.metrics.observe(time.perf_counter() - t0)
                return self._decode(resp)
            except (InjectedFault, OSError, TornFrameError,
                    pickle.UnpicklingError, EOFError) as e:
                last = e
                torn = isinstance(e, TornFrameError)
                self._drop()
                self.metrics.count("transient_errors")
                if torn:
                    self.metrics.count("torn_frames")
                    self.observer.event("store_torn_frame", op=op,
                                        addr=self.addr)
                if isinstance(e, socket.timeout):
                    self.metrics.count("timeouts")
                # reconnect NOW (bounded backoff inside): coordinator
                # downtime within the budget stays transparent, and an
                # amnesiac restart surfaces the epoch verdict
                # immediately instead of hiding behind a transient
                try:
                    self._local.sock = self._connect()
                    self.metrics.count("reconnects")
                    self.observer.event("store_reconnect", op=op,
                                        addr=self.addr)
                except TransientStoreError as ce:
                    # could not re-attach within the bounded budget:
                    # no point re-sending, surface the transient
                    raise TransientStoreError(
                        f"store rpc {op!r} to {self.addr} failed and "
                        f"reconnect exhausted: {ce}") from e
        if isinstance(last, TornFrameError):
            raise last                 # named: torn frames stay torn
        raise TransientStoreError(
            f"store rpc {op!r} to {self.addr} failed: {last}") from last

    # ---- the five verbs ----------------------------------------------

    def set(self, key: str, value) -> None:
        self._rpc("set", key, value)

    def get(self, key: str, default=_MISSING):
        try:
            return self._rpc("get", key)
        except KeyError:
            if default is _MISSING:
                raise
            return default

    def wait(self, key: str, timeout_s: float):
        """Deadline-sliced blocking wait (module docstring): the server
        blocks at most ``wait_slice_s`` per RPC, the client re-issues
        with the remaining budget — expiry is on time, never a full
        slice late, and a coordinator blip mid-wait is a transient for
        the caller's retry budget, not a lost wait."""
        deadline = time.monotonic() + timeout_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise StoreTimeoutError(
                    f"store key {key!r} did not appear within "
                    f"{timeout_s}s")
            s = min(remaining, self.wait_slice_s)
            try:
                return self._rpc("wait", key, s, deadline_extra=s)
            except StoreTimeoutError:
                continue               # slice expired; budget may not have

    def add(self, key: str, delta: int = 1) -> int:
        return self._rpc("add", key, delta)

    def delete(self, key: str) -> None:
        self._rpc("delete", key)

    # ---- queries ------------------------------------------------------

    def keys(self, prefix: str = "") -> list:
        return self._rpc("keys", prefix)

    def age(self, key: str):
        return self._rpc("age", key)

    def newest_age(self, prefix: str):
        return self._rpc("newest_age", prefix)

    # ---- generation fencing ------------------------------------------

    @property
    def generation(self) -> int:
        return self._rpc("generation")

    def bump_generation(self, expected: int) -> int:
        return self._rpc("bump_generation", expected)

    def check_generation(self, gen: int) -> None:
        self._rpc("check_generation", gen)


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class TCPStoreServer:
    """Threaded TCP coordinator over one :class:`HostKVStore`, with WAL
    + snapshot crash recovery and the server-epoch token (module
    docstring).  ``wal_dir=None`` runs in-memory only (unit tests, or
    deployments that prefer a fresh world over recovery — note the
    epoch token still protects clients from a silent state wipe across
    a restart)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 wal_dir: Optional[str] = None,
                 snapshot_every: int = 512, wal_fsync: bool = False,
                 wal_exclude_prefixes: tuple = (), observer=None):
        self.host = host
        self.port = port
        self.wal_dir = wal_dir
        self.snapshot_every = snapshot_every
        # flush-per-append survives PROCESS death (the page cache has
        # the bytes — the SIGKILL drills rely on exactly this);
        # wal_fsync=True additionally survives HOST/power loss at a
        # per-mutation fsync cost, for deployments where an acked
        # commit marker must be durable against the machine, not just
        # the process (snapshots are always fsynced either way)
        self.wal_fsync = wal_fsync
        # keys under these prefixes are applied but NOT logged — the
        # write-amplification lever for high-churn step-plane traffic
        # (an elastic world routes full gradient trees through
        # `g/{gen}/{step}/{rank}` sets).  The trade is restart
        # transparency: un-logged keys do not survive a coordinator
        # restart, so excluding "g/" means a crash mid-exchange costs
        # the world one re-form (survivors' waits expire and they
        # re-rendezvous) instead of riding through invisibly.  The
        # DEFAULT logs everything: "hb/" must be recovered or
        # dead_peers reads never-beat-at-all as dead right after a
        # restart, and the drills pin full transparency.
        self.wal_exclude_prefixes = tuple(wal_exclude_prefixes)
        self.observer = observer or NULL_OBSERVER
        self.store = HostKVStore()
        self.epoch: Optional[str] = None
        self.recovered = False
        self.replayed_records = 0
        self.stopped = threading.Event()
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: list[socket.socket] = []
        self._conn_lock = threading.Lock()
        self._wal_lock = threading.Lock()
        self._wal_file = None
        self._seq = 0
        self._since_snapshot = 0

    # ---- WAL + snapshot ----------------------------------------------

    @property
    def _snap_path(self):
        return os.path.join(self.wal_dir, "snapshot.pkl")

    @property
    def _wal_path(self):
        return os.path.join(self.wal_dir, "wal.log")

    def _recover(self) -> None:
        """Rehydrate state: snapshot first, then replay WAL records
        with seq > the snapshot's (so a crash between snapshot and WAL
        truncation never double-applies an ``add`` or a bump).  A torn
        WAL tail — the crash happened mid-append — truncates the replay
        at the last complete record, exactly like a torn frame."""
        if self.wal_dir is None:
            self.epoch = uuid.uuid4().hex
            return
        os.makedirs(self.wal_dir, exist_ok=True)
        snap_seq = 0
        had_state = False
        if os.path.exists(self._snap_path):
            with open(self._snap_path, "rb") as f:
                snap = pickle.load(f)
            self.epoch = snap["epoch"]
            self.store.restore_state(snap["data"], snap["gen"])
            snap_seq = self._seq = snap["seq"]
            had_state = True
        if os.path.exists(self._wal_path):
            with open(self._wal_path, "rb") as f:
                while True:
                    header = f.read(_LEN.size)
                    if len(header) < _LEN.size:
                        break                      # clean EOF / torn tail
                    (n,) = _LEN.unpack(header)
                    payload = f.read(n)
                    if len(payload) < n:
                        break                      # torn tail: stop here
                    try:
                        seq, op, args = pickle.loads(payload)
                    except Exception:
                        break                      # corrupt tail record
                    had_state = True
                    if seq <= snap_seq:
                        continue                   # already in snapshot
                    try:
                        self._apply(op, args)
                    except Exception:
                        # the record is write-ahead: it was logged even
                        # if the LIVE apply then failed (e.g. add() on
                        # a non-integer value — the client got the
                        # error).  Skipping reproduces the live store's
                        # state; crashing here would brick every future
                        # recovery on one poison record.
                        pass
                    self._seq = seq
                    self.replayed_records += 1
        if self.epoch is None:
            self.epoch = uuid.uuid4().hex
        if had_state:
            self.recovered = True
            self.observer.event(
                "store_wal_recovered", epoch=self.epoch,
                generation=self.store.generation,
                n_keys=len(self.store.keys()),
                replayed=self.replayed_records)
        # compact now (persists a fresh epoch on first boot, and makes
        # restart-after-restart recovery start from a dense snapshot);
        # _write_snapshot leaves the truncated WAL open for appends
        self._write_snapshot()

    def _apply(self, op: str, args):
        """The one mutation dispatch — shared by live requests
        (:meth:`_log_and_apply`) and WAL replay, so the two paths can
        never drift on a verb."""
        if op == "set":
            return self.store.set(*args)
        if op == "add":
            return self.store.add(*args)
        if op == "delete":
            return self.store.delete(*args)
        if op == "bump_generation":
            return self.store.bump_generation(*args)
        raise ValueError(f"unknown mutation op {op!r}")

    def _write_snapshot(self) -> None:
        if self.wal_dir is None:
            return
        data, gen = self.store.snapshot_state()
        if self.wal_exclude_prefixes:
            # excluded (transient) keys stay out of snapshots too, so
            # "does not survive a restart" holds whichever durability
            # path recovery takes
            data = {k: v for k, v in data.items()
                    if not (isinstance(k, str)
                            and k.startswith(self.wal_exclude_prefixes))}
        tmp = self._snap_path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump({"epoch": self.epoch, "data": data, "gen": gen,
                         "seq": self._seq}, f,
                        protocol=pickle.HIGHEST_PROTOCOL)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path)
        # truncate the WAL only AFTER the snapshot is durable; a crash
        # in between just replays records the seq filter skips
        if self._wal_file is not None:
            self._wal_file.close()
        self._wal_file = open(self._wal_path, "wb")
        self._since_snapshot = 0

    def _log_and_apply(self, op: str, args):
        """Write-ahead, then apply, then return the op's result —
        serialized so the WAL order IS the apply order."""
        key = args[0] if args else ""
        logged = not (isinstance(key, str)
                      and key.startswith(self.wal_exclude_prefixes)) \
            if self.wal_exclude_prefixes else True
        with self._wal_lock:
            if self._wal_file is not None and logged:
                self._seq += 1
                payload = pickle.dumps(
                    (self._seq, op, args),
                    protocol=pickle.HIGHEST_PROTOCOL)
                self._wal_file.write(_LEN.pack(len(payload)) + payload)
                self._wal_file.flush()
                if self.wal_fsync:
                    os.fsync(self._wal_file.fileno())
            result = self._apply(op, args)
            if logged:
                self._since_snapshot += 1
            if (self._wal_file is not None
                    and self._since_snapshot >= self.snapshot_every):
                self._write_snapshot()
            return result

    # ---- lifecycle ----------------------------------------------------

    def start(self) -> "TCPStoreServer":
        self._recover()
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self.port = self._listener.getsockname()[1]
        self._listener.listen(128)
        self.stopped.clear()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="tcpstore-accept",
            daemon=True)
        self._accept_thread.start()
        return self

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    def stop(self, abort: bool = False) -> None:
        """Shut the server down.  ``abort=True`` is the crash shape
        (the ``store_site('reply', 'crash')`` path lands here): every
        connection is killed mid-whatever, nothing is flushed beyond
        what the WAL already holds — recovery is the WAL's job, which
        is the point."""
        if self.stopped.is_set():
            return
        self.stopped.set()
        listener, self._listener = self._listener, None
        if listener is not None:
            # shutdown BEFORE close: on Linux, close() alone does not
            # wake a thread blocked in accept() — the kernel socket
            # would stay alive inside the syscall and hold the port
            # hostage against the restarted coordinator's bind
            try:
                listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                listener.close()
            except OSError:
                pass
        with self._conn_lock:
            conns, self._conns = list(self._conns), []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        with self._wal_lock:
            if self._wal_file is not None:
                if not abort:
                    self._wal_file.flush()
                self._wal_file.close()
                self._wal_file = None
        t = self._accept_thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)

    def __enter__(self) -> "TCPStoreServer":
        return self.start() if self._listener is None else self

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # ---- serving ------------------------------------------------------

    def _accept_loop(self) -> None:
        listener = self._listener       # stop() nulls the attribute
        while not self.stopped.is_set():
            try:
                conn, _ = listener.accept()
            except OSError:
                return                         # listener closed: done
            conn.settimeout(None)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conn_lock:
                self._conns.append(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="tcpstore-conn", daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            hello_done = False
            while not self.stopped.is_set():
                try:
                    req = recv_frame(conn)
                except (ConnectionError, TornFrameError, OSError,
                        pickle.UnpicklingError, EOFError):
                    return
                resp = self._dispatch(req, hello_done)
                if (not hello_done and isinstance(req, tuple)
                        and len(req) == 2 and req[0] == "hello"
                        and resp[0] == "ok"):
                    hello_done = True
                try:
                    fault = fire(store_site("reply"))
                except InjectedCrash:
                    # the coordinator dies mid-reply: abort the whole
                    # server from this handler thread — nothing else
                    # is sent, every client sees a dead socket
                    self.stop(abort=True)
                    return
                except InjectedFault:
                    return                     # drop just this conn
                if fault is not None and fault.kind == "torn":
                    payload = pickle.dumps(
                        resp, protocol=pickle.HIGHEST_PROTOCOL)
                    frame = _LEN.pack(len(payload)) + payload
                    try:
                        conn.sendall(frame[:max(1, len(frame) // 2)])
                    except OSError:
                        pass
                    return                     # tear: half a frame, EOF
                if fault is not None and fault.kind == "blackhole":
                    continue                   # reply eaten; client
                                               # times out
                try:
                    send_frame(conn, resp)
                except OSError:
                    return
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._conn_lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    def _dispatch(self, req, hello_done: bool):
        try:
            if not isinstance(req, tuple) or len(req) != 2:
                raise ValueError(f"malformed request: {req!r}")
            op, args = req
            if op == "hello":
                (expected,) = args
                if expected is not None and expected != self.epoch:
                    raise ServerEpochError(
                        f"server epoch mismatch at {self.addr}: client "
                        f"pinned {expected}, server is {self.epoch} — "
                        f"the coordinator restarted WITHOUT its WAL; "
                        f"refusing to silently rejoin amnesiac state")
                return ("ok", self.epoch)
            if not hello_done:
                raise ValueError(
                    f"first request must be the hello handshake, "
                    f"got {op!r}")
            if op in ("set", "add", "delete", "bump_generation"):
                return ("ok", self._log_and_apply(op, args))
            if op == "get":
                return ("ok", self.store.get(*args))
            if op == "wait":
                key, timeout_s = args
                return ("ok", self.store.wait(key, timeout_s))
            if op == "keys":
                return ("ok", self.store.keys(*args))
            if op == "age":
                return ("ok", self.store.age(*args))
            if op == "newest_age":
                return ("ok", self.store.newest_age(*args))
            if op == "generation":
                return ("ok", self.store.generation)
            if op == "check_generation":
                return ("ok", self.store.check_generation(*args))
            raise ValueError(f"unknown store op {op!r}")
        except tuple(_ERR_TO_WIRE) as e:
            kind = next(k for cls, k in _ERR_TO_WIRE.items()
                        if isinstance(e, cls))
            msg = e.args[0] if e.args else str(e)
            return ("err", kind, msg)
        except Exception as e:      # never let one request kill a conn
            return ("err", "store", f"{type(e).__name__}: {e}")


# ---------------------------------------------------------------------------
# wiring helpers + the standalone coordinator CLI
# ---------------------------------------------------------------------------


def store_addr(default: str = "") -> str:
    """The store address the launcher threaded through
    (``DTDL_STORE_ADDR``), or ``default``."""
    return os.environ.get(STORE_ADDR_ENV, default)


def connect(addr: Optional[str] = None, retries: int = 5, seed: int = 0,
            observer=None, **client_kw) -> RetryingStore:
    """One-call client wiring: ``TCPStoreClient`` wrapped in the PR 12
    :class:`RetryingStore` facade (bounded retries on transients,
    verdicts pass through) — the store object an ``ElasticWorker``
    takes verbatim.  ``addr`` defaults to ``DTDL_STORE_ADDR``.

    **`add` is at-least-once under this facade.**  The transport layer
    never re-sends an `add` (its reply dying leaves the increment
    ambiguous), but the retry facade re-asks on the surfaced
    transient, so a coordinator blip can double-count.  Build exact
    protocol counters from CAS (``bump_generation``) or overwrites
    (``set``) — the elastic protocol does; treat ``add`` as a
    statistics verb."""
    addr = addr or store_addr()
    if not addr:
        raise ValueError(
            f"no store address: pass addr= or set {STORE_ADDR_ENV} "
            f"(launchers thread it through automatically)")
    client = TCPStoreClient(addr, seed=seed, observer=observer,
                            **client_kw)
    return RetryingStore(client, retries=retries, seed=seed)


def main(argv=None) -> int:
    """Standalone coordinator:  ``python -m dtdl_tpu.parallel.tcpstore
    --port 12801 --wal-dir /path/to/wal``.  Prints ``STORE ready
    addr=...`` once listening (launch scripts wait on that line) and
    serves until SIGTERM/SIGINT."""
    import argparse
    import signal

    p = argparse.ArgumentParser(description=main.__doc__)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--wal-dir", default=None)
    p.add_argument("--snapshot-every", type=int, default=512)
    p.add_argument("--wal-fsync", action="store_true",
                   help="fsync every WAL append (durable against host "
                        "power loss, not just process death)")
    a = p.parse_args(argv)
    server = TCPStoreServer(host=a.host, port=a.port, wal_dir=a.wal_dir,
                            snapshot_every=a.snapshot_every,
                            wal_fsync=a.wal_fsync).start()
    print(f"STORE ready addr={server.addr} epoch={server.epoch} "
          f"recovered={server.recovered}", flush=True)

    def _stop(signum, frame):
        server.stop()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    server.stopped.wait()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
