"""ResNet-50 (v1.5, NHWC) — the north-star throughput model.

The reference has no ResNet, but BASELINE.json sets ResNet-50 samples/sec/chip
as the build's headline metric, so it lives in the zoo alongside the parity
models.  Bottleneck blocks with the stride on the 3x3 conv (v1.5), bfloat16
compute via ``dtype``, float32 BN statistics, zero-init of the final BN scale
in each block (standard large-batch trick).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

conv_init = nn.initializers.variance_scaling(2.0, "fan_out", "truncated_normal")


class BottleneckBlock(nn.Module):
    filters: int
    stride: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        conv = partial(nn.Conv, use_bias=False, kernel_init=conv_init,
                       dtype=self.dtype)
        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3), strides=(self.stride, self.stride),
                 padding=1)(y)
        y = nn.relu(norm()(y))
        y = conv(self.filters * 4, (1, 1))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1),
                            strides=(self.stride, self.stride))(residual)
            residual = norm()(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    num_classes: int = 1000
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding=3, use_bias=False,
                    kernel_init=conv_init, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                stride = 2 if i > 0 and j == 0 else 1
                x = BottleneckBlock(64 * 2 ** i, stride,
                                    dtype=self.dtype)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3))


def resnet50(dtype=jnp.float32, num_classes: int = 1000) -> ResNet:
    return ResNet50(num_classes=num_classes, dtype=dtype)
