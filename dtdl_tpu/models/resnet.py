"""ResNet-50 (v1.5, NHWC) — the north-star throughput model.

The reference has no ResNet, but BASELINE.json sets ResNet-50 samples/sec/chip
as the build's headline metric, so it lives in the zoo alongside the parity
models.  Bottleneck blocks with the stride on the 3x3 conv (v1.5), bfloat16
compute via ``dtype``, float32 BN statistics, zero-init of the final BN scale
in each block (standard large-batch trick).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

conv_init = nn.initializers.variance_scaling(2.0, "fan_out", "truncated_normal")


class BottleneckBlock(nn.Module):
    filters: int
    stride: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        conv = partial(nn.Conv, use_bias=False, kernel_init=conv_init,
                       dtype=self.dtype)
        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3), strides=(self.stride, self.stride),
                 padding=1)(y)
        y = nn.relu(norm()(y))
        y = conv(self.filters * 4, (1, 1))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1),
                            strides=(self.stride, self.stride))(residual)
            residual = norm()(residual)
        return nn.relu(y + residual)


class SpaceToDepthStem(nn.Module):
    """The 7x7/2 stem conv, computed in space-to-depth form (MXU-friendly).

    The standard stem convolves a 3-channel 224x224 image with a 7x7 stride-2
    kernel — on the TPU that contraction (7*7*3 = 147) runs the MXU at ~4%
    utilisation and the f32 image is the single largest tensor the step reads
    (measured: 7.1 ms of a 101 ms ResNet-50 step, see RESNET50_ROOFLINE.md).
    Rewriting it over a 2x2 space-to-depth view of the image — input
    [N,224,224,3] -> [N,112,112,12], kernel [7,7,3,64] zero-padded to 8x8 and
    regrouped to [4,4,12,64], stride 1 — computes the *identical* function
    (verified to exact equality in tests/test_resnet.py) with 4x fewer,
    denser MXU passes.

    The parameter keeps the canonical [7,7,3,64] shape — porting weights
    to/from a standard stem is a value copy (note the param *path* differs:
    ``SpaceToDepthStem_0/kernel`` vs ``Conv_0/kernel``, so checkpoints from
    a ``s2d_stem=False`` model need that one-key rename).  The pad+regroup
    is a constant-time transform inside the forward pass and gradients flow
    through it to the 7x7 weights.
    """
    features: int = 64
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        n, h, w, c = x.shape
        kernel = self.param("kernel", conv_init, (7, 7, c, self.features),
                            jnp.float32)
        # zero-pad the taps to an 8x8 window (offset -4..3 about each output
        # pixel: original offsets -3..3 plus one dead row/col at -4), then
        # regroup (2b+s) -> (block b, subpixel s) to match the s2d input.
        k8 = jnp.pad(kernel, ((1, 0), (1, 0), (0, 0), (0, 0)))
        k = k8.reshape(4, 2, 4, 2, c, self.features)
        k = k.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * c, self.features)
        # space-to-depth: [N,H,W,C] -> [N,H/2,W/2,4C], channel = (s, t, c)
        xs = x.reshape(n, h // 2, 2, w // 2, 2, c)
        xs = xs.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w // 2, 4 * c)
        dtype = self.dtype
        return jax.lax.conv_general_dilated(
            xs.astype(dtype), k.astype(dtype), window_strides=(1, 1),
            padding=((2, 1), (2, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))


class ResNet(nn.Module):
    """``s2d_stem`` is **opt-in** (like PyramidNet's ``channel_align``): it
    renames the stem parameter path (``SpaceToDepthStem_0/kernel`` vs
    ``Conv_0/kernel``), so flipping it silently breaks restore of any
    snapshot taken with the other setting.  The default keeps the canonical
    checkpoint tree interchangeable with reference-format weight ports; the
    bench path enables it explicitly for the HBM win."""
    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    num_classes: int = 1000
    dtype: Any = jnp.float32
    s2d_stem: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        if self.s2d_stem and x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0:
            x = SpaceToDepthStem(64, dtype=self.dtype)(x)
        else:
            x = nn.Conv(64, (7, 7), strides=(2, 2), padding=3, use_bias=False,
                        kernel_init=conv_init, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                stride = 2 if i > 0 and j == 0 else 1
                x = BottleneckBlock(64 * 2 ** i, stride,
                                    dtype=self.dtype)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3))


def resnet50(dtype=jnp.float32, num_classes: int = 1000,
             s2d_stem: bool = False) -> ResNet:
    return ResNet50(num_classes=num_classes, dtype=dtype, s2d_stem=s2d_stem)
