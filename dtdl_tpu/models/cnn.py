"""MNIST 3-conv CNN.

Capability parity with the Keras Sequential CNN duplicated across the three
TF2 scripts (reference tensorflow2/mnist_single.py:14-30 ≡
mnist_mirror_strategy.py and mnist_multi_worker_strategy.py copies): Conv 32
3x3 VALID + ReLU, MaxPool 2, Conv 64 3x3 + ReLU, MaxPool 2, Conv 64 3x3 +
ReLU, Flatten, Dense 64 + ReLU, Dense 10.  The reference ends in a softmax
activation; we return logits and fold the softmax into the loss (numerically
better and fuses on TPU) — predict-time probabilities are exposed by the fit()
API instead.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MnistCNN(nn.Module):
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        del train
        x = x.astype(self.dtype)
        if x.ndim == 3:  # (B, 28, 28) -> add channel dim
            x = x[..., None]
        x = nn.relu(nn.Conv(32, (3, 3), padding="VALID", dtype=self.dtype)(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(64, (3, 3), padding="VALID", dtype=self.dtype)(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(64, (3, 3), padding="VALID", dtype=self.dtype)(x))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(64, dtype=self.dtype)(x))
        return nn.Dense(self.num_classes, dtype=self.dtype)(x).astype(jnp.float32)
