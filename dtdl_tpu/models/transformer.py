"""Decoder-only Transformer language model (the long-context flagship).

The reference tops out at CNNs/MLPs over 784-pixel images (SURVEY §5.7 —
reference pytorch/model.py:53-118, chainer/train_mnist_multi.py:15-28); this
framework treats sequence models and long context as first-class, so the
model zoo gains a modern decoder-only LM:

* pre-norm blocks, RMSNorm, rotary position embeddings, SwiGLU MLP
* causal **flash attention** via the Pallas TPU kernel
  (dtdl_tpu/ops/attention.py); ``attn_impl='dense'`` selects the reference
  einsum path for numerics tests
* optional **mixture-of-experts** MLP — dense top-1 one-hot dispatch (the
  numerics oracle) or GShard-style routed capacity-factor top-k (the
  scale path: static-shape dispatch einsums GSPMD partitions over an
  'expert' mesh axis; see :class:`MoE`)
* every parameter is annotated with flax *logical axes* so the same module
  runs replicated, FSDP, or tensor-parallel under pjit by flipping the
  logical→mesh rules (dtdl_tpu/parallel/tensor.py)
* ``remat`` applies ``jax.checkpoint`` per block — the standard TPU
  memory/FLOPs trade for long sequences

Logical axis names: 'vocab', 'embed', 'heads', 'head_dim' (attention
projections), 'mlp' (FFN hidden), 'expert' (MoE).
"""

from __future__ import annotations

import functools
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from dtdl_tpu.ops.attention import flash_attention, mha_reference
from dtdl_tpu.ops.paged_attention import paged_attention
from dtdl_tpu.ops.rope import apply_rope, rope_frequencies
from dtdl_tpu.quant import (QuantDenseGeneral, canon_kv_dtype, kv_quantize,
                            kv_scale_dtype, weight_dtypes)

Dtype = Any


class CacheOverflowError(ValueError):
    """Decode would write past the KV cache / rope table (``max_seq``).

    Raised eagerly whenever the cache index is a concrete value (plain
    ``model.apply(..., mutable=['cache'])`` outside jit).  Inside a
    compiled program the index is a tracer and cannot be checked here —
    ``generate`` validates ``prompt + max_new_tokens <= max_seq`` before
    tracing, and the serving scheduler (dtdl_tpu/serve/scheduler.py)
    retires a slot the moment its sequence reaches ``cache_max_seq`` —
    without a caller-level guard the cache index would silently clamp
    into the last position and corrupt it.
    """


def cache_max_seq(cache) -> int:
    """The ``max_seq`` a KV cache was built for (its rope-table length).

    Reads the [.., max_seq, head_dim] K/V buffer shape, so it works on a
    live cache pytree, the ``jax.eval_shape`` result, or a serving arena.
    """
    for leaf in jax.tree.leaves(cache):
        if getattr(leaf, "ndim", 0) >= 3:
            return int(leaf.shape[-2])
    raise ValueError("no K/V buffers in cache pytree")


def _part(init, *names):
    return nn.with_logical_partitioning(init, names)


def _required_cache_leaf(name):
    """Init fn for cache leaves the caller must supply (the paged and
    int8 arena layouts are built by the serving engine's init helpers,
    never by an init trace): if flax falls back to initializing one, the
    cache pytree was malformed — fail with the diagnosis instead of
    allocating a silent zero."""
    def init(*_):
        raise ValueError(
            f"KV cache is missing the '{name}' leaf; build the arena "
            f"with TransformerLM.init_cache/init_paged_cache (the "
            f"serving engine inserts any per-call page_table/active "
            f"leaves itself — dtdl_tpu/serve/engine.py)")
    return init


class RMSNorm(nn.Module):
    eps: float = 1e-6
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", _part(nn.initializers.ones, "embed"),
                           (x.shape[-1],))
        x32 = x.astype(jnp.float32)
        norm = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (norm * scale).astype(self.dtype)


class Attention(nn.Module):
    n_heads: int
    head_dim: int
    attn_impl: str = "flash"      # 'flash' | 'dense'
    dtype: Dtype = jnp.bfloat16
    quantize: Any = False         # weight-only projections (serve):
    #                               True/'int8' -> int8, 'w8f' -> fp8
    paged_kernel: bool = False    # Pallas paged attend (kernel round 2)

    @nn.compact
    def __call__(self, x, cos, sin, decode: bool = False):
        d_model = x.shape[-1]
        def proj(name):
            if self.quantize:
                # same module path + 'kernel' param name as the f32
                # layer, so quantize_params maps tree-to-tree
                return QuantDenseGeneral(
                    features=(self.n_heads, self.head_dim), axis=-1,
                    dtype=self.dtype, mode=self.quantize, name=name)
            return nn.DenseGeneral(
                features=(self.n_heads, self.head_dim), axis=-1,
                use_bias=False, dtype=self.dtype,
                kernel_init=_part(nn.initializers.lecun_normal(),
                                  "embed", "heads", "head_dim"),
                name=name)
        q = proj("q")(x)
        k = proj("k")(x)
        v = proj("v")(x)
        # batched multi-LoRA (round 22): when the engine passes a 'lora'
        # collection, every projection gains a low-rank delta gathered
        # from the adapter bank by each row's adapter id — per-slot DATA
        # (dtdl_tpu/serve/tenant/lora.py), so one compiled step serves a
        # mixed-adapter batch.  Absent during the init trace and for
        # engines without a bank: params and programs are unchanged.
        lora = self.has_variable("lora", "q_a")
        if lora:
            aid = self.get_variable("lora", "aid")           # [B] int32

            def lo_delta(name, h):
                a = jnp.take(self.get_variable("lora", f"{name}_a"),
                             aid, axis=0)                    # [B, d, r]
                bb = jnp.take(self.get_variable("lora", f"{name}_b"),
                              aid, axis=0)                   # [B, r, H, D]
                t = jnp.einsum("bsd,bdr->bsr", x.astype(a.dtype), a)
                return h + jnp.einsum("bsr,brhe->bshe", t,
                                      bb).astype(h.dtype)
            q, k, v = lo_delta("q", q), lo_delta("k", k), lo_delta("v", v)
        # [B, S, H, D] -> [B, H, S, D]
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        if decode:
            o = self._decode_attend(q, k, v, cos, sin)
        elif self.attn_impl == "flash":
            # fused rope (round 13): the rotation rides the kernel's Q/K
            # tile loads instead of round-tripping [B, H, S, D] through
            # HBM per layer (ops/attention.py); block shapes resolve from
            # the static autotune table keyed on (head_dim, seq, causal)
            o = flash_attention(q, k, v, causal=True, rope=(cos, sin))
        else:
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
            o = mha_reference(q, k, v, causal=True).astype(self.dtype)
        o = o.transpose(0, 2, 1, 3)
        if self.quantize:
            out = QuantDenseGeneral(
                features=d_model, axis=(-2, -1), dtype=self.dtype,
                mode=self.quantize, name="out")(o)
        else:
            out = nn.DenseGeneral(
                features=d_model, axis=(-2, -1), use_bias=False,
                dtype=self.dtype,
                kernel_init=_part(nn.initializers.lecun_normal(),
                                  "heads", "head_dim", "embed"),
                name="out")(o)
        if lora:
            a = jnp.take(self.get_variable("lora", "out_a"),
                         aid, axis=0)                        # [B, H, D, r]
            bb = jnp.take(self.get_variable("lora", "out_b"),
                          aid, axis=0)                       # [B, r, d]
            t = jnp.einsum("bshe,bher->bsr", o.astype(a.dtype), a)
            out = out + jnp.einsum("bsr,brd->bsd", t, bb).astype(out.dtype)
        return out

    # prefill query rows are processed in blocks of this many: peak
    # attention memory stays O(chunk * max_seq) instead of the
    # O(prompt * max_seq) f32 logits a one-shot dense prefill would
    # materialize per layer — the same memory bound the flash kernel
    # gives training (advisor finding, round 4)
    PREFILL_CHUNK = 256

    def _decode_attend(self, q, k, v, cos, sin):
        """Incremental attention against a KV cache ('cache' collection).

        Serves both prefill (S = prompt length) and stepping (S = 1): the
        new keys/values land at positions [index, index+S) of a
        [B, H, max_seq, D] cache (max_seq = the rope table length), the
        rope rotation uses the true global positions, and each new query
        row attends every cached position up to and including its own.
        Dense masked attention — decode is one query row against a cache,
        which is exactly the memory-light shape the flash kernel's tiling
        is NOT for; long prefills are chunked over query rows
        (``PREFILL_CHUNK``) to keep the same O(seq) memory bound.
        Mutate via ``apply(..., mutable=['cache'])``.

        The cache ``index`` may be a scalar (every row at the same
        position — the ``generate`` path) or a **[B] vector of per-row
        positions** (the serving arena: each batch row is an independent
        slot at its own decode position, so one compiled step serves a
        continuously-batched mix of sequence lengths).  The vector path
        takes S >= 1 tokens per row (:meth:`_verify_attend_slots`): S = 1
        is the decode step, S = k+1 the speculative-decoding verify pass
        — prefill happens per slot at scalar index and is scattered into
        the arena by the engine (dtdl_tpu/serve/engine.py).
        """
        import math
        b, h, s_new, d = q.shape
        max_len = cos.shape[0]
        if s_new > max_len:
            raise CacheOverflowError(
                f"{s_new} new tokens cannot fit a max_seq={max_len} "
                f"KV cache/rope table")
        # block-paged arena (cache built by init_paged_cache, page
        # tables inserted per call by the serving engine): route before
        # the dense declarations below can allocate [B, max_seq] buffers
        if self.has_variable("cache", "pages_key"):
            return self._paged_attend_slots(q, k, v, cos, sin)
        # has_variable BEFORE self.variable: during the init trace the
        # cache does not exist yet, and mutating it there would bake the
        # example input into the returned cache and leave index=1 — every
        # later position would be off by one
        cache_exists = self.has_variable("cache", "key")
        # int8 KV layout (init_cache(kv_dtype='int8')): the cache pytree
        # itself carries the layout — scale leaves present means the K/V
        # buffers are int8 and every write quantizes / every read
        # dequants in-kernel.  Data-driven like the paged routing above,
        # so the SAME module serves both layouts (one compiled program
        # per engine either way; the engine never mixes layouts).
        quant = self.has_variable("cache", "key_scale")
        ck = self.variable("cache", "key", jnp.zeros,
                           (b, h, max_len, d), self.dtype)
        cv = self.variable("cache", "value", jnp.zeros,
                           (b, h, max_len, d), self.dtype)
        cks = cvs = None
        if quant:
            cks = self.variable("cache", "key_scale",
                                _required_cache_leaf("key_scale"))
            cvs = self.variable("cache", "value_scale",
                                _required_cache_leaf("value_scale"))
        ci = self.variable("cache", "index",
                           lambda: jnp.zeros((), jnp.int32))
        if not cache_exists:
            # this IS the init trace: shapes only, no cache mutation
            return jnp.zeros_like(q)
        pos = ci.value
        if not isinstance(pos, jax.core.Tracer):
            # eager decode: the index is concrete, so overflow is
            # checkable HERE instead of silently clamping the write into
            # the last cache row (jitted callers must bound-check before
            # tracing — see CacheOverflowError)
            # audit: ok[host-sync-float] eager-only overflow check — jitted callers never reach this branch
            limit = int(jnp.max(pos)) if pos.ndim else int(pos)
            if limit + s_new > max_len:
                raise CacheOverflowError(
                    f"decode at position {limit} with {s_new} new "
                    f"token(s) exceeds max_seq={max_len}; the cache "
                    f"index would clamp and corrupt the last row")
        if pos.ndim:
            return self._verify_attend_slots(q, k, v, cos, sin,
                                             ck, cv, ci, pos, cks, cvs)
        q = apply_rope(q, cos, sin, offset=pos)
        k = apply_rope(k, cos, sin, offset=pos)
        if quant:
            # quantize-on-scatter: each new position's K/V row is scaled
            # off its own max (write-once — see quant.kv_quantize); the
            # cache leaf's dtype picks the payload (int8 or fp8)
            k8, ks = kv_quantize(k, dtype=ck.value.dtype)
            v8, vs = kv_quantize(v, dtype=cv.value.dtype)
            ck.value = jax.lax.dynamic_update_slice(
                ck.value, k8, (0, 0, pos, 0))
            cv.value = jax.lax.dynamic_update_slice(
                cv.value, v8, (0, 0, pos, 0))
            cks.value = jax.lax.dynamic_update_slice(
                cks.value, ks, (0, 0, pos))
            cvs.value = jax.lax.dynamic_update_slice(
                cvs.value, vs, (0, 0, pos))
        else:
            ck.value = jax.lax.dynamic_update_slice(
                ck.value, k.astype(self.dtype), (0, 0, pos, 0))
            cv.value = jax.lax.dynamic_update_slice(
                cv.value, v.astype(self.dtype), (0, 0, pos, 0))
        ci.value = pos + s_new

        keys, values = ck.value, cv.value
        scale = 1.0 / math.sqrt(d)

        def attend(q_rows, qpos):
            """[B, H, C, D] query rows at global positions qpos [C]."""
            mask = jnp.arange(max_len)[None, :] <= qpos[:, None]
            if quant:
                # dequant-on-gather, fused: the int8→dtype convert rides
                # the einsum's operand read, the per-position key scale
                # multiplies the [.., K] logits (constant along the
                # contracted D, so this IS the dequantized matmul), and
                # the value scale folds into the softmax weights — no
                # dequantized [.., D] copy is ever materialized
                logits = jnp.einsum("bhqd,bhkd->bhqk", q_rows,
                                    keys.astype(self.dtype),
                                    preferred_element_type=jnp.float32)
                logits = logits * cks.value[:, :, None, :]
            else:
                logits = jnp.einsum("bhqd,bhkd->bhqk", q_rows, keys,
                                    preferred_element_type=jnp.float32)
            logits = jnp.where(mask[None, None], logits * scale, -1e30)
            probs = jax.nn.softmax(logits, axis=-1)
            if quant:
                w = (probs * cvs.value[:, :, None, :]).astype(self.dtype)
                return jnp.einsum("bhqk,bhkd->bhqd", w,
                                  values.astype(self.dtype))
            return jnp.einsum("bhqk,bhkd->bhqd",
                              probs.astype(self.dtype), values)

        chunk = self.PREFILL_CHUNK
        if s_new <= chunk:
            return attend(q, pos + jnp.arange(s_new))
        # long prefill: pad the query rows to a chunk multiple and map
        # over [n_chunks, B, H, chunk, D] blocks — the pad rows compute
        # garbage (masked to a uniform softmax) and are sliced away
        pad = -s_new % chunk
        qp = jnp.pad(q, ((0, 0), (0, 0), (0, pad), (0, 0)))
        n_chunks = (s_new + pad) // chunk
        q_blocks = jnp.moveaxis(
            qp.reshape(b, h, n_chunks, chunk, d), 2, 0)
        pos_blocks = (pos + jnp.arange(s_new + pad)).reshape(
            n_chunks, chunk)
        out = jax.lax.map(lambda args: attend(*args),
                          (q_blocks, pos_blocks))
        out = jnp.moveaxis(out, 0, 2).reshape(b, h, s_new + pad, d)
        return out[:, :, :s_new]

    def _verify_attend_slots(self, q, k, v, cos, sin, ck, cv, ci, pos,
                             cks=None, cvs=None):
        """Vector-index cached attention, ``s_new`` tokens per slot: row b
        is an independent slot whose new tokens sit at global positions
        ``pos[b] .. pos[b]+s_new-1``.  Same math as the scalar path per
        row — rope at each token's own global position, K/V scattered
        into the row's cache at ``pos[b]``, causal mask per query row —
        so scoring k candidate positions in one pass is token-identical
        to k sequential single-token decodes (pinned by
        tests/test_spec_decode.py; ``s_new=1`` is exactly the decode step
        the serving engine compiles, pinned by tests/test_serve.py).

        This is the verify half of speculative decoding: one parameter
        sweep scores ``s_new`` candidate tokens per slot against the KV
        arena (dtdl_tpu/serve/engine.py builds the accept/advance logic
        on top).  The index advances by the full ``s_new``; a caller that
        commits fewer tokens (rejected candidates) rolls the index leaves
        back itself — the overwritten-before-attended discipline makes
        the stale K/V rows beyond the committed index harmless, exactly
        like prefill's pad positions.

        Callers must guarantee ``pos[b] + s_new <= max_seq`` for every
        row that matters: the per-row scatter clamps its start index, so
        an overflowing write would land misaligned over live positions
        (jitted callers bound-check before tracing — the serving
        scheduler settles worst-case indices before dispatch; eager
        callers are checked in ``_decode_attend``).
        """
        import math
        b, h, s_new, d = q.shape
        max_len = cos.shape[0]
        quant = cks is not None
        rope_row = jax.vmap(
            lambda xb, p: apply_rope(xb[None], cos, sin, offset=p)[0])
        q = rope_row(q, pos)
        k = rope_row(k, pos)
        scatter_row = jax.vmap(
            lambda buf, new, p: jax.lax.dynamic_update_slice(
                buf, new, (0, p, 0)))
        if quant:
            # quantize-on-scatter, per (row, head, position) — the same
            # write-once discipline as the scalar path (quant.kv_quantize)
            k8, ks = kv_quantize(k, dtype=ck.value.dtype)
            v8, vs = kv_quantize(v, dtype=cv.value.dtype)
            ck.value = scatter_row(ck.value, k8, pos)
            cv.value = scatter_row(cv.value, v8, pos)
            scatter_s = jax.vmap(
                lambda buf, new, p: jax.lax.dynamic_update_slice(
                    buf, new, (0, p)))
            cks.value = scatter_s(cks.value, ks, pos)
            cvs.value = scatter_s(cvs.value, vs, pos)
        else:
            ck.value = scatter_row(ck.value, k.astype(self.dtype), pos)
            cv.value = scatter_row(cv.value, v.astype(self.dtype), pos)
        ci.value = pos + s_new

        scale = 1.0 / math.sqrt(d)
        qpos = pos[:, None] + jnp.arange(s_new)[None, :]        # [B, S]
        mask = (jnp.arange(max_len)[None, None, :]
                <= qpos[:, :, None])                            # [B, S, max]
        if quant:
            # dequant-on-gather, fused exactly like the scalar path: the
            # int8→dtype convert rides the einsum operand read, the key
            # scale multiplies the [.., K] logits, the value scale folds
            # into the softmax weights
            logits = jnp.einsum("bhqd,bhkd->bhqk", q,
                                ck.value.astype(self.dtype),
                                preferred_element_type=jnp.float32)
            logits = logits * cks.value[:, :, None, :]
        else:
            logits = jnp.einsum("bhqd,bhkd->bhqk", q, ck.value,
                                preferred_element_type=jnp.float32)
        logits = jnp.where(mask[:, None], logits * scale, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        if quant:
            w = (probs * cvs.value[:, :, None, :]).astype(self.dtype)
            return jnp.einsum("bhqk,bhkd->bhqd", w,
                              cv.value.astype(self.dtype))
        return jnp.einsum("bhqk,bhkd->bhqd",
                          probs.astype(self.dtype), cv.value)

    def _paged_attend_slots(self, q, k, v, cos, sin):
        """The vector-index cached attend (:meth:`_verify_attend_slots`)
        generalized to a **block-paged** KV arena: instead of row b
        owning a contiguous ``[max_seq]`` cache row, its positions map
        through a per-row page table onto a shared pool of
        ``page_size``-token pages (``pages_key``/``pages_value``
        ``[n_pages, H, page_size, D]``), so a short sequence pins only
        the pages it has reached.  Per row the math is IDENTICAL to the
        dense vector path — rope at each token's true global position,
        K/V scattered at ``pos[b] .. pos[b]+s_new-1`` (now through the
        table), causal mask per query row over the gathered logical view
        — which is what keeps paged decode/verify token-identical to the
        dense arena (tests/test_paged_kv.py).  ``s_new`` spans the same
        three shapes: prefill (B=1, S=suffix bucket, index=#cached
        prefix tokens), decode (S=1), speculative verify (S=k+1).

        Cache leaves: ``pages_key``/``pages_value`` (the pool),
        ``index`` [B] — the arena the engine donates — plus two
        **per-call data leaves** the engine inserts before ``apply`` and
        strips after: ``page_table`` [B, n_ptab] int32 (logical page ->
        physical page; unmapped entries point at the reserved garbage
        page 0) and ``active`` [B] bool.  Page tables are data, never
        shapes: remapping pages or changing occupancy reuses the same
        compiled program.

        The one discipline the dense path did not need: an INACTIVE
        row's write is explicitly routed to the garbage page.  Dense
        slots write garbage into their *own* row (harmless); a paged
        slot's stale table may point at pages long since freed and
        remapped to a live request, so writes gate on ``active``.
        Positions of garbage rows are also clamped before they index
        the rope/page tables — out-of-range stale indices must produce
        discarded garbage, not NaNs that a masked-but-gathered page
        could leak into a live row's softmax·V sum (0 · NaN = NaN).

        Callers guarantee, for every ACTIVE row, ``pos[b] + s_new <=
        max_seq`` and a table mapping every logical page up to that
        bound (the serving scheduler allocates pages from the same
        worst-case index tracking it already settles overflow with).
        """
        import math
        b, h, s_new, d = q.shape
        max_len = cos.shape[0]
        pk = self.variable("cache", "pages_key",
                           _required_cache_leaf("pages_key"))
        pv = self.variable("cache", "pages_value",
                           _required_cache_leaf("pages_value"))
        pt = self.variable("cache", "page_table",
                           _required_cache_leaf("page_table"))
        act = self.variable("cache", "active",
                            _required_cache_leaf("active"))
        ci = self.variable("cache", "index",
                           _required_cache_leaf("index"))
        # int8 pools (init_paged_cache(kv_dtype='int8')): per-(page,
        # head, in-page position) scales ride WITH their page through
        # the same table — layout is data, same compiled program shape
        quant = self.has_variable("cache", "pages_key_scale")
        pks = pvs = None
        if quant:
            pks = self.variable("cache", "pages_key_scale",
                                _required_cache_leaf("pages_key_scale"))
            pvs = self.variable("cache", "pages_value_scale",
                                _required_cache_leaf("pages_value_scale"))
        pos, table, active = ci.value, pt.value, act.value
        n_pages, H, page, D = pk.value.shape
        n_ptab = table.shape[1]
        if not isinstance(pos, jax.core.Tracer):
            # eager misuse check, mirroring the dense vector path (the
            # serving engine always runs this jitted and bound-checks
            # host-side before dispatch)
            live = jnp.where(jnp.asarray(active), jnp.asarray(pos), 0)
            # audit: ok[host-sync-float] eager-only overflow check — jitted callers never reach this branch
            if int(jnp.max(live)) + s_new > max_len:
                raise CacheOverflowError(
                    # audit: ok[host-sync-float] eager-only overflow check — jitted callers never reach this branch
                    f"paged decode at position {int(jnp.max(live))} with "
                    f"{s_new} new token(s) exceeds max_seq={max_len}")
        # clamped positions: identity for active rows (caller contract),
        # keeps stale inactive rows inside every table (see docstring)
        pos_safe = jnp.clip(pos, 0, max_len - s_new)
        rope_row = jax.vmap(
            lambda xb, p: apply_rope(xb[None], cos, sin, offset=p)[0])
        q = rope_row(q, pos_safe)
        k = rope_row(k, pos_safe)

        # (page, offset) scatter coordinates for the S new tokens,
        # computed ONCE per step and shared by every pool leaf — K, V
        # and (int8) their scale siblings (the PR 6 known-remaining:
        # the old path flattened/unflattened the ENTIRE pool around
        # every leaf's scatter — two full-pool transposes per leaf per
        # decode step; scattering straight onto the (page, offset) axes
        # leaves the pool layout untouched, and the gather stays
        # page-granular so XLA moves contiguous [H, page, D] chunks).
        # Token t of row b lands at offset g%page of physical page
        # table[b, g//page]; inactive rows route to garbage page 0.
        g = pos_safe[:, None] + jnp.arange(s_new)[None, :]       # [B, S]
        phys = jnp.take_along_axis(
            table, jnp.clip(g // page, 0, n_ptab - 1), axis=1)   # [B, S]
        page_idx = jnp.where(active[:, None], phys, 0).reshape(-1)
        off_idx = (g % page).reshape(-1)

        def update_and_view(pool, new):
            """Scatter ``new`` [B,H,S,...] onto the shared (page_idx,
            off_idx) coordinates and gather the [B,H,n_ptab*page,...]
            logical view; returns (pool', view)."""
            if pool.ndim == 4:
                upd = new.transpose(0, 2, 1, 3).reshape(b * s_new, H, D)
                pool = pool.at[page_idx, :, off_idx, :].set(
                    upd.astype(pool.dtype))
                pages = jnp.take(pool, table, axis=0)
                gat = pages.transpose(0, 2, 1, 3, 4).reshape(
                    b, H, n_ptab * page, D)
            else:
                upd = new.transpose(0, 2, 1).reshape(b * s_new, H)
                pool = pool.at[page_idx, :, off_idx].set(upd)
                pages = jnp.take(pool, table, axis=0)
                gat = pages.transpose(0, 2, 1, 3).reshape(
                    b, H, n_ptab * page)
            return pool, gat

        if quant:
            # quantize-on-scatter through the SAME (page, offset)
            # coordinates: each new position's K/V row is scaled off its
            # own max, so append-only shared pages never need rescaling
            k, ks = kv_quantize(k, dtype=pk.value.dtype)
            v, vs = kv_quantize(v, dtype=pv.value.dtype)

        scale = 1.0 / math.sqrt(d)
        if self.paged_kernel:
            # kernel round 2: scatter-only pool updates (no gathered
            # [B, H, n_ptab*page, D] view exists), then the Pallas
            # paged-attention kernel walks the table itself — page-
            # granular DMAs with the scale fusion folded into the tile
            # loads (dtdl_tpu/ops/paged_attention.py)
            def scatter(pool, new):
                if pool.ndim == 4:
                    upd = new.transpose(0, 2, 1, 3).reshape(
                        b * s_new, H, D)
                    return pool.at[page_idx, :, off_idx, :].set(
                        upd.astype(pool.dtype))
                upd = new.transpose(0, 2, 1).reshape(b * s_new, H)
                return pool.at[page_idx, :, off_idx].set(
                    upd.astype(pool.dtype))

            if quant:
                pks.value = scatter(pks.value, ks)
                pvs.value = scatter(pvs.value, vs)
            pk.value = scatter(pk.value, k)
            pv.value = scatter(pv.value, v)
            ci.value = pos + s_new   # engine masks/rolls back, as dense
            return paged_attention(
                q, pk.value, pv.value, table, pos_safe, active,
                scale=scale,
                key_scale=pks.value if quant else None,
                value_scale=pvs.value if quant else None)

        if quant:
            pks.value, kss = update_and_view(pks.value, ks)
            pvs.value, vss = update_and_view(pvs.value, vs)
        pk.value, keys = update_and_view(pk.value, k)
        pv.value, values = update_and_view(pv.value, v)
        ci.value = pos + s_new   # engine masks/rolls back, as dense

        qpos = pos_safe[:, None] + jnp.arange(s_new)[None, :]    # [B, S]
        mask = (jnp.arange(n_ptab * page)[None, None, :]
                <= qpos[:, :, None])                     # [B, S, n_ptab*pg]
        if quant:
            # dequant-on-gather, fused as in the dense paths: int8
            # pages convert inside the einsum read, the key scale (the
            # same gathered logical view as the pages, through the same
            # shared offsets) multiplies the [.., K] logits, the value
            # scale folds into the softmax weights — garbage-page
            # positions carry scale 0 or stale finite values, masked
            # exactly like their K/V
            logits = jnp.einsum("bhqd,bhkd->bhqk", q,
                                keys.astype(self.dtype),
                                preferred_element_type=jnp.float32)
            logits = logits * kss[:, :, None, :]
        else:
            logits = jnp.einsum("bhqd,bhkd->bhqk", q, keys,
                                preferred_element_type=jnp.float32)
        logits = jnp.where(mask[:, None], logits * scale, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        if quant:
            w = (probs * vss[:, :, None, :]).astype(self.dtype)
            return jnp.einsum("bhqk,bhkd->bhqd", w,
                              values.astype(self.dtype))
        return jnp.einsum("bhqk,bhkd->bhqd",
                          probs.astype(self.dtype), values)


class SwiGLU(nn.Module):
    d_ff: int
    dtype: Dtype = jnp.bfloat16
    quantize: Any = False         # weight-only wi/wg/wo (serve):
    #                               True/'int8' -> int8, 'w8f' -> fp8

    @nn.compact
    def __call__(self, x):
        d_model = x.shape[-1]
        if self.quantize:
            # same module paths + 'kernel' param names as the f32
            # layers, so quantize_params maps tree-to-tree
            def dense(features, name):
                return QuantDenseGeneral(features=features, axis=-1,
                                         dtype=self.dtype,
                                         mode=self.quantize, name=name)
        else:
            def dense(features, name):
                # wo is the row-parallel projection whatever the
                # geometry — key the partition names off the param,
                # not the feature count (d_ff == d_model would flip it)
                names = (("mlp", "embed") if name == "wo"
                         else ("embed", "mlp"))
                return nn.Dense(
                    features, use_bias=False, dtype=self.dtype,
                    kernel_init=_part(nn.initializers.lecun_normal(),
                                      *names), name=name)
        wi = dense(self.d_ff, "wi")(x)
        wg = dense(self.d_ff, "wg")(x)
        h = nn.silu(wg) * wi
        return dense(d_model, "wo")(h)


class MoE(nn.Module):
    """Mixture-of-experts MLP with two XLA-friendly dispatch modes.

    ``dispatch='dense'`` (the numerics oracle): top-1 routing through a
    one-hot einsum — every device computes every expert's einsum over all
    tokens, O(E · tokens · D · F) FLOPs.  Fine for tests and small E;
    useless at scale.

    ``dispatch='routed'`` (the GSPMD scale path): GShard-style
    capacity-factor top-k.  Tokens are split into routing groups of up
    to ``group_size`` consecutive tokens (1024 default — the measured
    knee; ragged tails padded and masked out of routing), each group
    getting ``C = ceil(cf · g · k / E)`` slots per expert; assignments
    fill choice-major (every first choice before any second choice,
    matching the megatron engine's routed dispatch,
    parallel/megatron.py:286-392), tokens past capacity are dropped
    (their residual passes through).  Dispatch/combine are one-hot
    einsums to a fixed [E, n_groups, C, D] expert buffer — static shapes
    throughout, so under the 'ep' logical rules (parallel/tensor.py) the
    expert dim shards on 'model' and XLA's partitioner inserts the token
    all-to-all; expert FFN FLOPs drop to O(cf · k · tokens · D · F),
    E-independent.

    Both modes share identical parameters (router/wi/wg/wo), so a dense
    checkpoint loads into a routed model and, with ``capacity_factor >=
    n_experts / top_k`` (nothing droppable), routed computes the same
    function as dense top-1 — the oracle-equality contract the tests pin.

    A Switch load-balance aux (E · <f, p>, first-choice counts) is
    stashed via ``self.sow`` under 'aux_loss'; the LM train step adds it
    to the loss (train/step.py:make_lm_train_step).
    """
    n_experts: int
    d_ff: int
    dtype: Dtype = jnp.bfloat16
    dispatch: str = "dense"       # 'dense' | 'routed'
    capacity_factor: float = 1.25
    top_k: int = 1
    # routing-group CAP (tokens): the dispatch/combine one-hot einsums
    # cost O(tokens · E · C · D) with C = cf·g·k/E, i.e. O(tokens · g)
    # per token — groups bound g the way GShard does, instead of paying
    # the whole sequence length.  Groups are g consecutive tokens within
    # a batch row; a ragged tail is padded and the pad tokens are
    # excluded from routing (they take no capacity).  0 = the measured
    # default cap of 1024
    group_size: int = 0
    # weight-only expert wi/wg/wo (serve): per-(expert, output channel)
    # scales, True/'int8' int8 or 'w8f' fp8; the router stays f32 (O(d)
    # bytes, high sensitivity — dtdl_tpu/quant/core.py)
    quantize: Any = False

    @nn.compact
    def __call__(self, x):
        if not 1 <= self.top_k <= self.n_experts:
            # same guard as the megatron engine's MegatronConfig: top_k=0
            # would silently zero every MoE output, top_k > E dies deep in
            # lax.top_k with an opaque trace error
            raise ValueError(f"top_k={self.top_k} must be in "
                             f"[1, n_experts={self.n_experts}]")
        if self.dispatch == "dense" and self.top_k != 1:
            # dense dispatch is top-1 by construction; silently training
            # top-1 when the user asked for top-2 would be invisible
            raise ValueError("dense dispatch is top-1 only; top_k="
                             f"{self.top_k} requires dispatch='routed'")
        b, s, d_model = x.shape
        router = nn.Dense(self.n_experts, use_bias=False, dtype=jnp.float32,
                          kernel_init=_part(nn.initializers.lecun_normal(),
                                            "embed", "expert"),
                          name="router")(x.astype(jnp.float32))
        probs = jax.nn.softmax(router, axis=-1)          # [b, s, E]
        onehot1 = jax.nn.one_hot(jnp.argmax(probs, axis=-1),
                                 self.n_experts, dtype=jnp.float32)

        # load-balance aux loss (Switch Transformer): E * <f, p> over the
        # first choice — identical formula for both dispatch modes
        self.sow("aux_loss", "moe",
                 self.n_experts * jnp.sum(onehot1.mean(axis=(0, 1))
                                          * probs.mean(axis=(0, 1))))

        def expert_param(name, shape, in_ax, out_ax):
            if self.quantize:
                # quantized kernel + per-(expert, output-channel) scale,
                # with the same param name (+ '_scale' sibling) so
                # quantize_params maps tree-to-tree; placeholder values
                # — a quantized model is served, never trained
                payload_dt, scale_dt = weight_dtypes(self.quantize)
                q = self.param(name,
                               lambda *_: jnp.zeros(shape, payload_dt))
                s = self.param(
                    f"{name}_scale",
                    lambda *_: jnp.ones((shape[0], 1, shape[2]),
                                        scale_dt))
                return q.astype(self.dtype), s
            # batch_axis keeps the expert dim out of fan_in so every expert
            # initializes like its dense counterpart
            init = nn.initializers.lecun_normal(batch_axis=(0,))
            return self.param(
                name, _part(init, *(("expert",) + (in_ax, out_ax))),
                shape).astype(self.dtype), None

        w_in = expert_param("wi", (self.n_experts, d_model, self.d_ff),
                            "embed", "mlp")
        w_gate = expert_param("wg", (self.n_experts, d_model, self.d_ff),
                              "embed", "mlp")
        w_out = expert_param("wo", (self.n_experts, self.d_ff, d_model),
                             "mlp", "embed")

        if self.dispatch == "routed":
            return self._routed(x, probs, w_in, w_gate, w_out)
        if self.dispatch != "dense":
            raise ValueError(f"unknown MoE dispatch {self.dispatch!r}")

        gate = jnp.sum(probs * onehot1, axis=-1, keepdims=True)
        # dense dispatch: xe[e, b, s, d] = onehot[b, s, e] * x[b, s, d]
        xe = jnp.einsum("bse,bsd->ebsd", onehot1.astype(self.dtype), x)
        h = nn.silu(self._emm("ebsd,edf->ebsf", xe, w_gate)) * \
            self._emm("ebsd,edf->ebsf", xe, w_in)
        # quantized wo keeps the expert axis through the matmul (each
        # expert has its own output scale, which cannot factor out of a
        # cross-expert contraction) and sums after dequant; unquantized
        # stays the original single contraction bit-for-bit
        y = (jnp.sum(self._emm("ebsf,efd->ebsd", h, w_out), axis=0)
             if self.quantize else
             jnp.einsum("ebsf,efd->bsd", h, w_out[0]))
        return y * gate.astype(self.dtype)

    def _emm(self, spec, x, w):
        """Expert matmul over a ``(kernel, scale-or-None)`` pair: the
        per-(expert, out-channel) scale is constant along the contracted
        dims, so multiplying the e-leading rank-4 OUTPUT is exactly the
        dequantized matmul (same identity as
        dtdl_tpu/quant/layers.py:QuantDenseGeneral)."""
        kernel, scale = w
        y = jnp.einsum(spec, x, kernel)
        if scale is not None:
            y = (y * scale.reshape(scale.shape[0], 1, 1, -1)
                 .astype(jnp.float32)).astype(self.dtype)
        return y

    def _routed(self, x, probs, w_in, w_gate, w_out):
        """Capacity-factor top-k dispatch (see class docstring).

        Tokens are split into routing groups of up to ``group_size``
        consecutive tokens (GShard-style): capacity is per (batch row,
        group), so the [*, g, E, C] dispatch tensors stay O(g) per token
        instead of O(seq) — at seq 4096 / E 8 / cf 1.25 the ungrouped
        dispatch einsum alone would cost ~2x the expert FFN FLOPs.  A
        ragged last group is padded; pad tokens are masked out of the
        routing entirely (no capacity consumed, output sliced away), so
        any sequence length works — including single-token decode, where
        g=1 makes capacity a no-drop identity (inference never drops).
        Measured on the v5e ('base'+E8 forward, bs 8 seq 4096): dense
        dispatch 54.6 ms, routed ungrouped 45.1 ms, g=1024 **38.2 ms**,
        g=256 38.8 ms — the 1024 default cap is the measured knee."""
        import math
        b, s_full, d_model = x.shape
        g = min(self.group_size or 1024, s_full)
        pad = -s_full % g
        if pad:
            x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
            probs = jnp.pad(probs, ((0, 0), (0, pad), (0, 0)))
        n_groups = b * ((s_full + pad) // g)
        # [g] validity per position of each row-group, tiled over rows
        valid = (jnp.arange(s_full + pad) < s_full).astype(jnp.float32)
        valid = jnp.tile(valid.reshape(-1, g), (b, 1))   # [n_groups, g]
        x = x.reshape(n_groups, g, d_model)
        probs = probs.reshape(n_groups, g, self.n_experts)
        b, s = n_groups, g
        E, k = self.n_experts, self.top_k
        C = min(s, int(math.ceil(self.capacity_factor * s * k / E)))

        gates, idx = jax.lax.top_k(probs, k)             # [b, s, k]
        if k > 1:
            # GShard-style renormalization over the chosen k (top-1 keeps
            # the raw softmax prob — Switch semantics, == dense mode)
            gates = gates / jnp.maximum(
                jnp.sum(gates, -1, keepdims=True), 1e-9)

        dispatch = jnp.zeros((b, s, E, C), jnp.float32)
        combine = jnp.zeros((b, s, E, C), jnp.float32)
        taken = jnp.zeros((b, 1, E), jnp.float32)        # slots used so far
        for j in range(k):                               # choice-major fill
            m = jax.nn.one_hot(idx[:, :, j], E,
                               dtype=jnp.float32) * valid[..., None]
            pos = jnp.cumsum(m, axis=1) - m + taken      # [b, s, E]
            keep = m * (pos < C)
            slot = jax.nn.one_hot(pos.astype(jnp.int32), C,
                                  dtype=jnp.float32)     # [b, s, E, C]
            d_j = keep[..., None] * slot
            dispatch = dispatch + d_j
            combine = combine + gates[:, :, j, None, None] * d_j
            taken = taken + jnp.sum(m, axis=1, keepdims=True)

        # [E, B, C, D] expert buffers: 'expert' leads so that, under a
        # caller-installed nn.logical_axis_rules context (e.g. the 'ep'
        # preset via make_sharded_lm_train_step), the constraint pins the
        # buffer's expert dim to its mesh axis and GSPMD inserts the
        # token all-to-all; with no context installed the constraint is
        # a no-op and the layout falls back to propagation from the
        # weight shardings
        xe = jnp.einsum("bsec,bsd->ebcd", dispatch.astype(self.dtype), x)
        xe = nn.with_logical_constraint(
            xe, ("expert", "batch", None, "embed"))
        h = nn.silu(self._emm("ebcd,edf->ebcf", xe, w_gate)) * \
            self._emm("ebcd,edf->ebcf", xe, w_in)
        y = self._emm("ebcf,efd->ebcd", h, w_out)
        y = nn.with_logical_constraint(
            y, ("expert", "batch", None, "embed"))
        out = jnp.einsum("ebcd,bsec->bsd", y,
                         combine.astype(self.dtype))
        return out.reshape(-1, s_full + pad, d_model)[:, :s_full]


class Block(nn.Module):
    n_heads: int
    head_dim: int
    d_ff: int
    n_experts: int = 0
    attn_impl: str = "flash"
    dtype: Dtype = jnp.bfloat16
    moe_dispatch: str = "dense"
    capacity_factor: float = 1.25
    moe_top_k: int = 1
    moe_group_size: int = 0
    quantize: Any = False         # weight-only matmuls (serve):
    #                               True/'int8' -> int8, 'w8f' -> fp8
    paged_kernel: bool = False    # Pallas paged attend (kernel round 2)

    @nn.compact
    def __call__(self, x, cos, sin, decode: bool = False):
        h = RMSNorm(dtype=self.dtype, name="ln_attn")(x)
        x = x + Attention(self.n_heads, self.head_dim, self.attn_impl,
                          self.dtype, quantize=self.quantize,
                          paged_kernel=self.paged_kernel,
                          name="attn")(h, cos, sin, decode=decode)
        h = RMSNorm(dtype=self.dtype, name="ln_mlp")(x)
        if self.n_experts > 0:
            x = x + MoE(self.n_experts, self.d_ff, self.dtype,
                        dispatch=self.moe_dispatch,
                        capacity_factor=self.capacity_factor,
                        top_k=self.moe_top_k,
                        group_size=self.moe_group_size,
                        quantize=self.quantize, name="moe")(h)
        else:
            x = x + SwiGLU(self.d_ff, self.dtype,
                           quantize=self.quantize, name="mlp")(h)
        return x


class TransformerLM(nn.Module):
    """Decoder-only LM; input int32 tokens [batch, seq] -> logits f32."""
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 8
    n_heads: int = 8
    d_ff: int = 1408
    max_seq: int = 2048
    n_experts: int = 0            # 0 = dense SwiGLU MLP
    moe_every: int = 2            # every k-th block is MoE (when n_experts>0)
    moe_dispatch: str = "dense"   # 'dense' oracle | 'routed' capacity top-k
    capacity_factor: float = 1.25  # routed: slots = ceil(cf * g * k / E)
    moe_top_k: int = 1            # routed: experts per token
    moe_group_size: int = 0       # routing group (0 = min(seq, 1024))
    attn_impl: str = "flash"
    remat: bool = False
    dtype: Dtype = jnp.bfloat16
    # weight-only serving: every matmul kernel becomes a quantized
    # tensor + per-output-channel scale with dequant fused into the
    # matmul (dtdl_tpu/quant/) — ``True``/'int8' the int8+f32 recipe,
    # 'w8f' the fp8+bf16 one.  A quantized model is built as
    # ``model.clone(quantize=mode)`` and loaded via
    # ``quant.quantize_params`` — never trained.  Embedding, norms and
    # MoE routers stay f32 (see dtdl_tpu/quant/core.py for why).
    quantize: Any = False
    # Pallas paged-attention decode kernel (kernel round 2): the paged
    # arena's decode/verify attend walks the page table inside the
    # kernel instead of gathering the whole logical view
    # (dtdl_tpu/ops/paged_attention.py).  The serving engine resolves
    # its 'auto' flag to this bool at construction.
    paged_kernel: bool = False

    @property
    def head_dim(self):
        return self.d_model // self.n_heads

    def cache_shapes(self, batch_size: int, per_slot_index: bool = False,
                     kv_dtype=None):
        """Abstract (ShapeDtypeStruct) KV-cache pytree for ``batch_size``
        rows — one [B, H, max_seq, head_dim] K/V buffer pair + position
        index per block, no compute (``jax.eval_shape`` of the decode
        init trace).  ``per_slot_index=True`` widens the index leaves from
        a scalar to [B] — the serving-arena layout where each row is an
        independent slot at its own decode position.

        ``kv_dtype='int8'`` is the **quantized** cache layout
        (dtdl_tpu/quant): the K/V buffers become int8 and each gains a
        per-(row, head, position) f32 ``*_scale`` sibling [B, H,
        max_seq] — :meth:`Attention._decode_attend` quantizes on scatter
        and dequants in the attention einsums on gather, so decode HBM
        traffic per cached byte halves vs bf16 (quarters vs f32) at the
        cost of one scale float per position per head.
        ``kv_dtype='fp8'`` is the same layout with a float8_e4m3fn
        payload and bf16 scales (quant.kv_scale_dtype)."""
        kv_dtype = canon_kv_dtype(kv_dtype)
        shapes = jax.eval_shape(
            functools.partial(self.init, decode=True),
            jax.random.PRNGKey(0),
            jnp.zeros((batch_size, 1), jnp.int32))["cache"]
        if per_slot_index:
            shapes = jax.tree.map(
                lambda s: jax.ShapeDtypeStruct((batch_size,), s.dtype)
                if s.ndim == 0 else s, shapes)
        if kv_dtype is not None:
            def conv(tree):
                if isinstance(tree, dict):
                    if "key" in tree and "index" in tree:
                        kv = tree["key"].shape          # [B, H, S, D]
                        sc = jax.ShapeDtypeStruct(
                            kv[:3], kv_scale_dtype(kv_dtype))
                        return dict(
                            tree,
                            key=jax.ShapeDtypeStruct(kv, kv_dtype),
                            value=jax.ShapeDtypeStruct(kv, kv_dtype),
                            key_scale=sc, value_scale=sc)
                    return {k: conv(v) for k, v in tree.items()}
                return tree
            shapes = conv(shapes)
        return shapes

    def init_cache(self, batch_size: int, per_slot_index: bool = False,
                   kv_dtype=None):
        """Fresh zero KV cache (see :meth:`cache_shapes`); ``max_seq`` of
        the result is recoverable via :func:`cache_max_seq`."""
        return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                            self.cache_shapes(batch_size, per_slot_index,
                                              kv_dtype))

    def paged_cache_shapes(self, n_slots: int, n_pages: int,
                           page_size: int, kv_dtype=None):
        """Abstract pytree of the **block-paged** serving arena: per
        block, a shared ``pages_key``/``pages_value`` pool of
        ``[n_pages, H, page_size, head_dim]`` plus the per-slot
        ``index`` [n_slots] — the layout
        :meth:`Attention._paged_attend_slots` consumes (per-call
        ``page_table``/``active`` leaves are inserted by the serving
        engine, not stored).  Page 0 is reserved as the garbage page,
        hence ``n_pages >= 2``; ``page_size`` must divide ``max_seq`` so
        the gathered logical view covers exactly the rope table.

        ``kv_dtype='int8'`` quantizes the pools: int8
        ``pages_key``/``pages_value`` plus per-(page, head, in-page
        position) f32 ``pages_key_scale``/``pages_value_scale``
        [n_pages, H, page_size] — each K/V page byte halves vs bf16, so
        a fixed HBM pool holds ~2x the pages (the slots-per-byte
        multiplier the serving engine's ``kv_pool_bytes`` sizing and
        compile_stats receipts expose).  Scales ride WITH their page
        (scattered/gathered through the same page table), so prefix-
        cache sharing of int8 pages needs no extra bookkeeping.
        ``kv_dtype='fp8'`` swaps the payload for float8_e4m3fn and the
        scale sidecars for bf16 — the byte win over int8 is entirely
        the 2-vs-4-byte scales (quant.kv_scale_dtype)."""
        kv_dtype = canon_kv_dtype(kv_dtype)
        if page_size < 1 or self.max_seq % page_size:
            raise ValueError(
                f"page_size must be >= 1 and divide max_seq="
                f"{self.max_seq}, got {page_size}")
        if n_pages < 2:
            raise ValueError(f"n_pages must be >= 2 (page 0 is the "
                             f"reserved garbage page), got {n_pages}")

        def conv(tree):
            if isinstance(tree, dict):
                if "key" in tree and "index" in tree:
                    _, H, _, D = tree["key"].shape
                    pool_dt = kv_dtype or tree["key"].dtype
                    out = {
                        "pages_key": jax.ShapeDtypeStruct(
                            (n_pages, H, page_size, D), pool_dt),
                        "pages_value": jax.ShapeDtypeStruct(
                            (n_pages, H, page_size, D), pool_dt),
                        "index": jax.ShapeDtypeStruct(
                            (n_slots,), jnp.int32),
                    }
                    if kv_dtype is not None:
                        sc = jax.ShapeDtypeStruct(
                            (n_pages, H, page_size),
                            kv_scale_dtype(kv_dtype))
                        out["pages_key_scale"] = sc
                        out["pages_value_scale"] = sc
                    return out
                return {k: conv(v) for k, v in tree.items()}
            return tree
        return conv(self.cache_shapes(1))

    def init_paged_cache(self, n_slots: int, n_pages: int,
                         page_size: int, kv_dtype=None):
        """Fresh zeroed paged arena (see :meth:`paged_cache_shapes`)."""
        return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                            self.paged_cache_shapes(n_slots, n_pages,
                                                    page_size, kv_dtype))

    @nn.compact
    def __call__(self, tokens, train: bool = False,
                 return_hidden: bool = False, decode: bool = False):
        """``return_hidden=True`` yields the final normalized hidden states
        [B, S, D] instead of logits — the contract of the vocab-chunked LM
        loss (dtdl_tpu/ops/cross_entropy.py:chunked_lm_loss), which never
        materializes the [B, S, V] logits.

        ``decode=True`` runs incremental attention against per-block KV
        caches (the 'cache' variable collection; create it by tracing
        ``init``/``apply`` with decode=True, mutate with
        ``mutable=['cache']``) — the autoregressive-generation contract of
        :func:`generate`."""
        del train
        emb = self.param(
            "embed", _part(nn.initializers.normal(stddev=0.02),
                           "vocab", "embed"),
            (self.vocab_size, self.d_model))
        x = jnp.take(emb, tokens, axis=0).astype(self.dtype)
        cos, sin = rope_frequencies(self.head_dim, self.max_seq)

        # remat is a training-time memory/FLOPs trade; under decode it
        # would also trace the `decode` flag into a tracer (remat treats
        # every call arg as dynamic) — plain blocks for decode
        block_cls = Block
        if self.remat and not decode:
            block_cls = nn.remat(Block, static_argnums=())
        for i in range(self.n_layers):
            moe = (self.n_experts > 0 and
                   (i + 1) % self.moe_every == 0)
            block = block_cls(
                self.n_heads, self.head_dim, self.d_ff,
                n_experts=self.n_experts if moe else 0,
                attn_impl=self.attn_impl, dtype=self.dtype,
                moe_dispatch=self.moe_dispatch,
                capacity_factor=self.capacity_factor,
                moe_top_k=self.moe_top_k,
                moe_group_size=self.moe_group_size,
                quantize=self.quantize,
                paged_kernel=self.paged_kernel,
                name=f"block_{i}")
            # only pass the flag when set: a kwarg through nn.remat is
            # traced, and Attention branches on it in Python
            x = block(x, cos, sin, decode=True) if decode \
                else block(x, cos, sin)

        x = RMSNorm(dtype=self.dtype, name="ln_f")(x)
        if return_hidden:
            return x
        logits = jnp.einsum("bsd,vd->bsv", x, emb.astype(self.dtype))
        return logits.astype(jnp.float32)


def generate(model: TransformerLM, params, prompt, max_new_tokens: int,
             temperature: float = 0.0, rng=None, strategy=None):
    """Autoregressive generation with per-block KV caches.

    ``prompt``: int32 [B, S0] (S0 + max_new_tokens must fit
    ``model.max_seq``; ``max_new_tokens >= 1``).  One prefill pass embeds
    the whole prompt into the caches, then a ``lax.scan`` of single-token
    steps decodes — the scan keeps the loop inside ONE compiled program
    (no per-token dispatch, static shapes throughout; the cache is a
    fixed [B, H, max_seq, D] buffer indexed by the traced cache
    position), and the compiled program is cached per
    (model, shapes, temperature) so repeated calls don't re-trace.
    ``temperature=0`` is greedy argmax; otherwise samples from
    logits/temperature with ``rng``.

    ``strategy``: a :class:`~dtdl_tpu.parallel.DataParallel` (or any
    mesh strategy) scales decoding like training — the prompt is placed
    batch-sharded on the data axis and XLA propagates that sharding
    through the whole program, so every replica prefils and steps its
    own batch rows with its own cache shards.  Tokens are IDENTICAL to
    the single-device run: the computation is batch-elementwise, and
    JAX's counter-based PRNG makes ``categorical`` draws depend only on
    the global position, not the partitioning.  (jit re-specializes per
    input sharding, so one compiled-program cache entry serves each
    placement.)

    Returns int32 [B, S0 + max_new_tokens].  (The reference has no
    sequence models, let alone inference — SURVEY §5.7; this is part of
    the framework's first-class LM capability.)
    """
    b, s0 = prompt.shape
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got "
                         f"{max_new_tokens}")
    if s0 + max_new_tokens > model.max_seq:
        raise ValueError(
            f"prompt ({s0}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_seq ({model.max_seq})")
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature sampling needs an rng key")
    rng = jax.random.PRNGKey(0) if rng is None else rng
    if strategy is not None:
        prompt = strategy.shard_batch(jnp.asarray(prompt))
    run = _compiled_generate(model, b, s0, max_new_tokens, temperature)
    return run(params, prompt, rng)


@functools.lru_cache(maxsize=64)
def _compiled_generate(model, b, s0, max_new_tokens, temperature):
    """Memoized jitted prefill+scan program for one
    (model, shape, temperature) signature — repeated generate() calls
    with the same signature reuse one compiled program.  (flax Modules
    are frozen dataclasses, so ``model`` is a valid cache key.)"""
    from jax import lax

    # abstract trace only: the cache is zeros of the right shapes, no
    # extra full init of the model inside the compiled program
    cache_shapes = jax.eval_shape(
        functools.partial(model.init, decode=True),
        jax.random.PRNGKey(0), jnp.zeros((b, 1), jnp.int32))["cache"]

    def sample(logits, key):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            key, logits / temperature, axis=-1).astype(jnp.int32)

    @jax.jit
    def run(params, prompt, rng):
        cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                             cache_shapes)
        # prefill via return_hidden: only the LAST position's logits are
        # sampled, so the [B, S0, vocab] logit tensor never materializes
        # (the same never-materialize discipline as chunked_lm_loss)
        hidden, muts = model.apply(
            {"params": params, "cache": cache}, prompt, decode=True,
            return_hidden=True, mutable=["cache"])
        emb = params["embed"]
        if hasattr(emb, "unbox"):       # flax logical-partitioning box
            emb = emb.unbox()
        # EXACTLY the module head's numerics (dtype-matched einsum, f32
        # cast after): a higher-precision prefill einsum could pick a
        # different argmax on near-tied logits than the step path does
        logits_last = jnp.einsum(
            "bd,vd->bv", hidden[:, -1],
            emb.astype(model.dtype)).astype(jnp.float32)
        rng_0, rng_scan = jax.random.split(rng)
        tok = sample(logits_last, rng_0)

        def step(carry, key):
            cache, tok = carry
            logits, muts = model.apply(
                {"params": params, "cache": cache}, tok[:, None],
                decode=True, mutable=["cache"])
            nxt = sample(logits[:, -1], key)
            return (muts["cache"], nxt), tok

        keys = jax.random.split(rng_scan, max_new_tokens)[:-1]
        (_, last), toks = lax.scan(step, (muts["cache"], tok), keys)
        toks = jnp.moveaxis(toks, 0, 1)           # [B, max_new-1]
        return jnp.concatenate([prompt, toks, last[:, None]], axis=1)

    return run


def transformer_lm(size: str = "tiny", **overrides) -> TransformerLM:
    """Named configs; 'tiny' fits the CPU test mesh, 'base' the bench chip.

    'small' and 'base' use **head_dim 128** (the MXU lane width): the Pallas
    flash kernel tiles [block, head_dim] blocks, so head_dim 32 wastes 3/4
    of every matmul lane — measured 2.6x slower end-to-end on a v5e at seq
    4096 (397k vs 1,037k tokens/s for the identical FLOP count).  Fewer,
    wider heads is the TPU-first layout.

    These are the *v2* geometries (the canonical names 'small-hd128' /
    'base-hd128' alias them): pre-hd128 'small'/'base' snapshots carry
    differently-shaped attention kernels, so an old checkpoint cannot
    silently load into the new head split — both the msgpack weight path
    (`ckpt.load_weights`) and the orbax full-state path
    (`Checkpointer.restore`/`restore_path`) run explicit shape validation
    and reject the mismatch (neither flax's nor orbax's own restore does).
    """
    cfgs = {
        "tiny": dict(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                     d_ff=128, max_seq=128),
        "small": dict(vocab_size=8192, d_model=256, n_layers=4, n_heads=2,
                      d_ff=704, max_seq=1024),
        "base": dict(vocab_size=32000, d_model=512, n_layers=8, n_heads=4,
                     d_ff=1408, max_seq=2048),
        # 'large' cashes LM_ROOFLINE.md §5's conclusion that further MFU
        # comes from model shape: d_model 1024 doubles every matmul's
        # contraction depth vs 'base' (same head_dim-128 MXU layout), and
        # ~239M params at seq 4096 need the standard long-seq memory
        # discipline — remat'd blocks plus the vocab-chunked loss
        # (pass vocab_chunk_size to make_lm_train_step; the [B,S,32k] f32
        # logits alone would be 4.2 GB at bs8/seq4096)
        "large": dict(vocab_size=32000, d_model=1024, n_layers=16,
                      n_heads=8, d_ff=2816, max_seq=2048, remat=True),
    }
    # routed-MoE variant of 'base': 8 experts every other block, GShard
    # capacity dispatch (the bench's MoE throughput row — measured 1.48x
    # the dense-dispatch step at identical routing math)
    cfgs["base-moe8"] = dict(cfgs["base"], n_experts=8, moe_every=2,
                             moe_dispatch="routed")
    cfgs["small-hd128"] = cfgs["small"]
    cfgs["base-hd128"] = cfgs["base"]
    cfg = dict(cfgs[size])
    cfg.update(overrides)
    return TransformerLM(**cfg)
