"""3-layer MLP for MNIST.

Capability parity with the reference Chainer MLP (reference
chainer/train_mnist.py:13-26: three Linear layers n_units=1000 with ReLU, input
size inferred, logits out; variant at chainer/train_mnist_multi.py:15-28).
Flax infers the input width at init the same way Chainer's ``L.Linear(None,..)``
does.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    n_units: int = 1000
    n_out: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        del train  # no train-time-only layers; kept for a uniform signature
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        x = nn.relu(nn.Dense(self.n_units, dtype=self.dtype)(x))
        x = nn.relu(nn.Dense(self.n_units, dtype=self.dtype)(x))
        return nn.Dense(self.n_out, dtype=self.dtype)(x).astype(jnp.float32)
