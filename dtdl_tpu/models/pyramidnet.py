"""PyramidNet-110 (alpha=270) for CIFAR-10 — the benchmark model.

Capability parity with the reference PyramidNet (reference
pytorch/model.py:53-118): pre-activation residual blocks
(BN → conv3x3(stride) → BN → ReLU → conv3x3 → BN), identity shortcuts that
zero-pad new channels and 2x2 ceil-mode average-pool on downsampling
(reference pytorch/model.py:6-21), and a linearly growing channel count
addrate = alpha / (3 * num_layers) with per-block rounding of a fractional
running width (reference pytorch/model.py:87-97).  Note the reference builds
``num_layers - 1`` = 17 blocks per stage (the loop at pytorch/model.py:89),
so 51 blocks total — we match that exactly so parameter counts and loss
curves are comparable.

TPU-first choices: NHWC layout (channels-last tiles onto the MXU), bfloat16
compute with float32 params/BN statistics via ``dtype``, kaiming fan-out init
matching the reference's init loop (pytorch/model.py:79-85).  BatchNorm uses
per-replica statistics under data parallelism — the same semantics as the
reference's DDP, which allreduces gradients but not BN stats (SURVEY §7.3).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

conv_init = nn.initializers.variance_scaling(2.0, "fan_out", "truncated_normal")


class IdentityPadding(nn.Module):
    """Parameter-free shortcut: zero-pad channels, avg-pool on stride 2.

    Mirrors reference pytorch/model.py:6-21 (F.pad on the channel dim + 2x2
    ceil-mode AvgPool2d).
    """
    add_channels: int
    stride: int = 1

    @nn.compact
    def __call__(self, x):
        if self.add_channels > 0:
            x = jnp.pad(x, ((0, 0), (0, 0), (0, 0), (0, self.add_channels)))
        if self.stride == 2:
            # ceil_mode=True: pad odd spatial dims so no edge pixel is dropped
            h, w = x.shape[1], x.shape[2]
            x = jnp.pad(x, ((0, 0), (0, h % 2), (0, w % 2), (0, 0)))
            x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        return x


class ResidualBlock(nn.Module):
    """Pre-act pyramid block: BN-conv-BN-ReLU-conv-BN (+ padded identity).

    Mirrors reference pytorch/model.py:24-50.
    """
    in_channels: int
    out_channels: int
    stride: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = lambda: nn.BatchNorm(  # noqa: E731
            use_running_average=not train, momentum=0.9, epsilon=1e-5,
            dtype=self.dtype)
        conv = lambda ch, s: nn.Conv(  # noqa: E731
            ch, (3, 3), strides=(s, s), padding=1, use_bias=False,
            kernel_init=conv_init, dtype=self.dtype)

        shortcut = IdentityPadding(
            self.out_channels - self.in_channels, self.stride)(x)
        out = norm()(x)
        out = conv(self.out_channels, self.stride)(out)
        out = norm()(out)
        out = nn.relu(out)
        out = conv(self.out_channels, 1)(out)
        out = norm()(out)
        return out + shortcut


class PyramidNet(nn.Module):
    """Additive PyramidNet for 32x32 inputs (reference pytorch/model.py:53-112).

    ``channel_align > 1`` rounds every block's channel count UP to that
    multiple (the reference's additive growth yields 8-misaligned widths —
    17, 19, 21, ... 286).  Measured on a v5e at bs=256: alignment does
    **not** change wall-clock (63.8 ms/step both ways) — the MXU already
    pads misaligned channels internally, so aligning only converts hidden
    padding into counted FLOPs (45.4% -> 48.2% nominal MFU at identical
    speed).  The remaining utilization gap is per-op overhead across ~150
    small-spatial convs (conv fusions run at 351 GB/s / 45% MFU — bound by
    neither roofline), not channel padding.  Kept as an opt-in for
    experiments; default 1 is the exact reference-parity model.
    """
    num_layers: int = 18
    alpha: int = 270
    num_classes: int = 10
    channel_align: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        addrate = self.alpha / (3.0 * self.num_layers)
        x = x.astype(self.dtype)
        x = nn.Conv(16, (3, 3), padding=1, use_bias=False,
                    kernel_init=conv_init, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=self.dtype)(x)

        def width(ch: float) -> int:
            a = self.channel_align
            return -(-int(round(ch)) // a) * a

        # fractional running width with per-block rounding, 17 blocks/stage
        in_ch = 16.0
        for stage_stride in (1, 2, 2):
            stride = stage_stride
            for _ in range(self.num_layers - 1):
                out_ch = in_ch + addrate
                x = ResidualBlock(width(in_ch), width(out_ch),
                                  stride, dtype=self.dtype)(x, train=train)
                in_ch = out_ch
                stride = 1

        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))  # global 8x8 avg pool
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


def pyramidnet(dtype=jnp.float32, num_classes: int = 10,
               channel_align: int = 1) -> PyramidNet:
    """Factory matching reference pytorch/model.py:115-118 (110 layers, a=270).

    ``channel_align=8`` selects the TPU-aligned variant (see PyramidNet)."""
    return PyramidNet(num_layers=18, alpha=270, num_classes=num_classes,
                      channel_align=channel_align, dtype=dtype)
