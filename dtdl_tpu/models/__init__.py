"""Model zoo: the reference's three architectures plus ResNet-50.

All models are flax.linen modules in NHWC layout (the TPU-native layout —
convolutions tile directly onto the MXU), with a ``dtype`` knob for bfloat16
compute and float32 parameters.
"""

from dtdl_tpu.models.mlp import MLP  # noqa: F401
from dtdl_tpu.models.cnn import MnistCNN  # noqa: F401
from dtdl_tpu.models.pyramidnet import PyramidNet, pyramidnet  # noqa: F401
from dtdl_tpu.models.resnet import ResNet, ResNet50, resnet50  # noqa: F401
from dtdl_tpu.models.transformer import (  # noqa: F401
    CacheOverflowError, TransformerLM, cache_max_seq, generate,
    transformer_lm,
)
from dtdl_tpu.models.netspec import CaffeNet, build_net  # noqa: F401

_REGISTRY = {
    "mlp": lambda **kw: MLP(**kw),
    "mnist_cnn": lambda **kw: MnistCNN(**kw),
    "pyramidnet": lambda **kw: pyramidnet(**kw),
    "resnet50": lambda **kw: resnet50(**kw),
    "transformer_lm": lambda **kw: transformer_lm(**kw),
}


def get_model(name: str, **kwargs):
    try:
        return _REGISTRY[name](**kwargs)
    except KeyError:
        raise ValueError(
            f"unknown model {name!r}; have {sorted(_REGISTRY)}") from None


def input_spec(name: str) -> tuple[tuple[int, ...], str]:
    """(example input shape without batch dim, dataset name) per model."""
    specs = {
        "mlp": ((784,), "mnist"),
        "mnist_cnn": ((28, 28, 1), "mnist"),
        "pyramidnet": ((32, 32, 3), "cifar10"),
        "resnet50": ((224, 224, 3), "imagenet"),
        "transformer_lm": ((128,), "synthetic_lm"),
    }
    try:
        return specs[name]
    except KeyError:
        raise ValueError(
            f"unknown model {name!r}; have {sorted(specs)}") from None
