"""Caffe NetParameter → flax model builder.

The reference declares a Caffe track but ships no code (reference
caffe/README.md is zero-byte; track declared at README.md:4-20).  Caffe's user
model is declarative: a net is a prototxt list of ``layer { }`` messages wired
by named blobs (bottom/top), trained by a solver prototxt (see
dtdl_tpu/train/solver.py).  This module gives that surface a TPU-native
implementation: the layer graph is parsed once, validated, topologically
walked, and executed as a pure flax module — so the whole net jits into a
single XLA program (NHWC, bfloat16-capable) instead of Caffe's per-layer
CPU/GPU kernel dispatch.

Supported layer types (the LeNet / CIFAR-quick family): Data/Input (shape
declaration only — data comes from the framework's data pipeline),
Convolution, Pooling (MAX/AVE), InnerProduct, ReLU, Sigmoid, TanH, Dropout,
LRN, Softmax, SoftmaxWithLoss, Accuracy, Flatten.  Phase filtering honors
``include { phase: TRAIN|TEST }``.  Loss/Accuracy layers are recorded as
net *outputs* — the train engine computes them fused (softmax folded into
cross-entropy, reference-style logits-out, see dtdl_tpu/models/cnn.py note).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import flax.linen as nn
import jax
import jax.numpy as jnp

from dtdl_tpu.utils.prototxt import Message


@dataclass
class LayerSpec:
    name: str
    type: str
    bottoms: list[str]
    tops: list[str]
    params: Message
    phases: list[str] = field(default_factory=list)  # [] = both

    def in_phase(self, phase: str) -> bool:
        return not self.phases or phase in self.phases


# layer types that only declare data/labels — skipped during execution
_DATA_TYPES = {"Data", "Input", "MemoryData", "HDF5Data", "ImageData"}
# layer types resolved by the training engine, not the forward pass
_LOSS_TYPES = {"SoftmaxWithLoss", "Accuracy"}


def _phases(layer: Message) -> list[str]:
    return [str(inc.get_scalar("phase", "")).upper()
            for inc in layer.getlist("include")]


def parse_net(msg: Message) -> list[LayerSpec]:
    """NetParameter message → ordered LayerSpecs (layer order is execution
    order, as in Caffe's upgraded NetParameter)."""
    specs = []
    for layer in msg.getlist("layer") + msg.getlist("layers"):
        specs.append(LayerSpec(
            name=str(layer.get_scalar("name", f"layer{len(specs)}")),
            type=str(layer.get_scalar("type", "")),
            bottoms=[str(b) for b in layer.getlist("bottom")],
            tops=[str(t) for t in layer.getlist("top")],
            params=layer,
            phases=_phases(layer),
        ))
    return specs


_VARIANCE_MODES = {"FAN_IN": "fan_in", "FAN_OUT": "fan_out",
                   "AVERAGE": "fan_avg"}


def _filler_init(param: Message, key: str):
    """Caffe FillerParameter → flax initializer (or None if absent).

    Caffe seeds every learnable blob from a filler
    (weight_filler/bias_filler in the layer's param message); ignoring them
    makes training trajectories diverge from a real Caffe run of the same
    prototxt.  Types honored: constant, uniform, gaussian, xavier, msra,
    positive_unitball — with Caffe's defaults (constant 0.0, uniform [0,1),
    gaussian std 1, variance_norm FAN_IN).
    """
    f = param.get_scalar(key, None)
    if f is None:
        return None
    t = str(f.get_scalar("type", "constant"))
    if t == "constant":
        return nn.initializers.constant(float(f.get_scalar("value", 0.0)))
    if t == "uniform":
        lo = float(f.get_scalar("min", 0.0))
        hi = float(f.get_scalar("max", 1.0))
        return lambda k, shape, dtype=jnp.float32: jax.random.uniform(
            k, shape, dtype, lo, hi)
    if t == "gaussian":
        mean = float(f.get_scalar("mean", 0.0))
        std = float(f.get_scalar("std", 1.0))
        return lambda k, shape, dtype=jnp.float32: (
            mean + std * jax.random.normal(k, shape, dtype))
    mode = _VARIANCE_MODES.get(
        str(f.get_scalar("variance_norm", "FAN_IN")).upper(), "fan_in")
    if t == "xavier":
        # uniform on [-sqrt(3/n), sqrt(3/n)] — variance_scaling's uniform
        # branch with scale 1 computes exactly that limit
        return nn.initializers.variance_scaling(1.0, mode, "uniform")
    if t == "msra":
        # gaussian with std sqrt(2/n) (He et al.), Caffe uses a plain normal
        return nn.initializers.variance_scaling(2.0, mode, "normal")
    if t == "positive_unitball":
        def init(k, shape, dtype=jnp.float32):
            x = jax.random.uniform(k, shape, dtype)
            flat = x.reshape(-1, shape[-1])
            return (flat / flat.sum(axis=0)).reshape(shape)
        return init
    raise NotImplementedError(f"Caffe filler type {t!r}")


def _filler_kwargs(param: Message) -> dict:
    """kernel_init/bias_init kwargs for a layer's fillers (flax defaults
    stand in when a filler is absent)."""
    kw = {}
    w = _filler_init(param, "weight_filler")
    b = _filler_init(param, "bias_filler")
    if w is not None:
        kw["kernel_init"] = w
    if b is not None:
        kw["bias_init"] = b
    return kw


def _pair(param: Message, key: str, default=0):
    """Caffe's  kernel_size/stride/pad  may be scalar or per-dim (h, w)."""
    vals = param.getlist(key)
    if not vals:
        h = param.get_scalar(key + "_h", default)
        w = param.get_scalar(key + "_w", default)
        return int(h), int(w)
    if len(vals) == 1:
        return int(vals[0]), int(vals[0])
    return int(vals[0]), int(vals[1])


class CaffeNet(nn.Module):
    """Execute a parsed Caffe layer graph as one flax module.

    Blobs flow through a dict keyed by top/bottom names; the final output is
    the bottom blob of the SoftmaxWithLoss/Softmax/Accuracy layer (the
    logits), matching the framework convention of folding softmax into the
    loss.  The TRAIN/TEST phase is picked per call via ``train=``.

    The module's static config is the prototxt *text* (hashable, so jit
    caching works); the layer graph is re-parsed at trace time, which runs
    once per compilation.
    """

    net_text: str
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        from dtdl_tpu.utils.prototxt import parse
        layers = parse_net(parse(self.net_text))
        phase = "TRAIN" if train else "TEST"
        x = x.astype(self.dtype)
        if x.ndim == 3:  # (B, H, W) -> NHWC
            x = x[..., None]
        blobs: dict[str, jnp.ndarray] = {}
        # seed every data-layer top with the input batch
        logits_blob = None
        for spec in layers:
            if not spec.in_phase(phase):
                continue
            if spec.type in _DATA_TYPES:
                for top in spec.tops:
                    if top not in ("label",):
                        blobs[top] = x
                continue
            if spec.type in _LOSS_TYPES or spec.type == "Softmax":
                # record which blob carries the logits; engine computes loss
                if spec.bottoms:
                    logits_blob = spec.bottoms[0]
                continue
            bottom = blobs[spec.bottoms[0]] if spec.bottoms else x
            blobs[spec.tops[0] if spec.tops else spec.name] = \
                self._apply_layer(spec, bottom, train)
        if logits_blob is not None and logits_blob in blobs:
            out = blobs[logits_blob]
        else:  # no loss layer: last computed blob
            out = list(blobs.values())[-1] if blobs else x
        return out.astype(jnp.float32)

    def _apply_layer(self, spec: LayerSpec, x, train: bool):
        t = spec.type
        if t == "Convolution":
            p = spec.params.get_scalar("convolution_param", Message())
            kh, kw = _pair(p, "kernel_size", 3)
            sh, sw = _pair(p, "stride", 1)
            ph, pw = _pair(p, "pad", 0)
            dh, dw = _pair(p, "dilation", 1)
            return nn.Conv(
                int(p.get_scalar("num_output")), (kh, kw),
                strides=(max(sh, 1), max(sw, 1)),
                padding=((ph, ph), (pw, pw)),
                kernel_dilation=(max(dh, 1), max(dw, 1)),
                feature_group_count=int(p.get_scalar("group", 1)),
                use_bias=bool(p.get_scalar("bias_term", True)),
                dtype=self.dtype, name=spec.name, **_filler_kwargs(p))(x)
        if t == "Pooling":
            p = spec.params.get_scalar("pooling_param", Message())
            if bool(p.get_scalar("global_pooling", False)):
                kh, kw = x.shape[1], x.shape[2]
                sh = sw = 1
                ph = pw = 0
            else:
                kh, kw = _pair(p, "kernel_size", 2)
                sh, sw = _pair(p, "stride", 1)
                ph, pw = _pair(p, "pad", 0)
                sh, sw = max(sh, 1), max(sw, 1)
            ave = str(p.get_scalar("pool", "MAX")).upper() == "AVE"
            # Caffe sizes pooling with CEIL: out = ceil((H+2p-k)/s)+1 (with
            # the last window clipped to start inside image+pad); flax pools
            # are floor/VALID.  Pad explicitly to reproduce the geometry:
            # -inf for MAX; zeros for AVE with a divisor that counts only
            # the [-pad, H+pad) extent — Caffe clips each window's divisor
            # to height+pad, so ceil-overhang cells beyond H+pad count in
            # neither numerator nor denominator.
            pads = [(0, 0)]
            for dim, (k, s, pad) in ((1, (kh, sh, ph)), (2, (kw, sw, pw))):
                pads.append(_caffe_pool_pad(x.shape[dim], k, s, pad))
            pads.append((0, 0))
            window, strides = (kh, kw), (sh, sw)
            if ave:
                # divisor mask: 1 over the countable extent [-p, H+p), 0 on
                # the ceil overhang beyond it
                count_h = min(pads[1][1], ph)
                count_w = min(pads[2][1], pw)
                ones = jnp.ones((1,) + x.shape[1:3] + (1,), x.dtype)
                ones = jnp.pad(ones, [(0, 0), (pads[1][0], count_h),
                                      (pads[2][0], count_w), (0, 0)],
                               constant_values=1)
                ones = jnp.pad(ones, [(0, 0), (0, pads[1][1] - count_h),
                                      (0, pads[2][1] - count_w), (0, 0)])
                x = jnp.pad(x, pads)
                num = nn.avg_pool(x, window, strides=strides)
                den = nn.avg_pool(ones, window, strides=strides)
                return num / den
            fill = jnp.finfo(x.dtype).min
            x = jnp.pad(x, pads, constant_values=fill)
            return nn.max_pool(x, window, strides=strides)
        if t == "InnerProduct":
            p = spec.params.get_scalar("inner_product_param", Message())
            if x.ndim > 2:
                x = x.reshape((x.shape[0], -1))
            return nn.Dense(int(p.get_scalar("num_output")),
                            use_bias=bool(p.get_scalar("bias_term", True)),
                            dtype=self.dtype, name=spec.name,
                            **_filler_kwargs(p))(x)
        if t == "ReLU":
            # Caffe ReLU supports leaky slope via negative_slope
            p = spec.params.get_scalar("relu_param", Message())
            slope = float(p.get_scalar("negative_slope", 0.0))
            return nn.leaky_relu(x, slope) if slope else nn.relu(x)
        if t == "Sigmoid":
            return nn.sigmoid(x)
        if t == "TanH":
            return nn.tanh(x)
        if t == "Dropout":
            p = spec.params.get_scalar("dropout_param", Message())
            ratio = float(p.get_scalar("dropout_ratio", 0.5))
            return nn.Dropout(ratio, deterministic=not train,
                              name=spec.name)(x)
        if t == "LRN":
            p = spec.params.get_scalar("lrn_param", Message())
            return _lrn(x,
                        size=int(p.get_scalar("local_size", 5)),
                        alpha=float(p.get_scalar("alpha", 1e-4)),
                        beta=float(p.get_scalar("beta", 0.75)),
                        k=float(p.get_scalar("k", 1.0)))
        if t == "Flatten":
            return x.reshape((x.shape[0], -1))
        raise NotImplementedError(f"Caffe layer type {t!r} ({spec.name})")


def _caffe_pool_pad(H: int, k: int, s: int, p: int) -> tuple[int, int]:
    """(lo, hi) padding reproducing Caffe's ceil-mode pooled output size.

    out = ceil((H + 2p - k) / s) + 1, minus one if the last window would
    start beyond the padded image (Caffe's clip rule); hi-padding extends
    the input exactly to the last window's end.
    """
    out = -(-(H + 2 * p - k) // s) + 1
    if p > 0 and (out - 1) * s >= H + p:
        out -= 1
    hi = max(0, (out - 1) * s + k - H - p)
    return p, hi


def _lrn(x, size: int, alpha: float, beta: float, k: float):
    """Local response normalization across channels (NHWC last axis).

    Implemented as a channel-axis box sum via cumulative sums — static
    shapes, fuses fine on TPU (no data-dependent control flow).
    """
    sq = jnp.square(x)
    half = size // 2
    pad = jnp.pad(sq, [(0, 0)] * (x.ndim - 1) + [(half + 1, half)])
    csum = jnp.cumsum(pad, axis=-1)
    C = x.shape[-1]
    window = csum[..., size:size + C] - csum[..., :C]
    return x / jnp.power(k + alpha / size * window, beta)


def build_net(path_or_text: str, dtype=jnp.float32) -> CaffeNet:
    """Load a net prototxt (file path, or the prototxt text itself) into a
    CaffeNet module.  Raises on an empty/invalid net up front."""
    import os
    if os.path.exists(path_or_text):
        with open(path_or_text) as f:
            text = f.read()
    else:
        text = path_or_text
    from dtdl_tpu.utils.prototxt import parse
    if not parse_net(parse(text)):
        raise ValueError("net prototxt defines no layers")
    return CaffeNet(net_text=text, dtype=dtype)
