"""Continuous metrics export: boundary-sampled time series, pluggable
sinks, and an opt-in Prometheus scrape endpoint.

PR 3 gave every subsystem an end-of-run ``summary()`` dict; a monitor
watching a fleet needs the same numbers *continuously*.  This module is
the bridge, built to the PR-1 discipline: the exporter never touches a
device value and is only ever **sampled at boundaries the loops already
own** (the router's pump tick, a scheduler drain) — it adds zero
syncs by construction, and :meth:`MetricsExporter.sample` throttles
itself to ``interval_s`` so a hot pump loop costs one clock read per
tick, not a snapshot.

The pieces:

* **sources** — named callables returning flat metric dicts.  The serve
  layer feeds ``ServeMetrics.window()`` / ``FleetMetrics.window()``
  (counter *increments* since the last sample, tails/gauges at current
  value — see serve/metrics.py), so a series point reads as "what
  happened this window"; cumulative sources (``GoodputMeter.totals``,
  ``StepGuard.summary``) plug in the same way.
* **sinks** — ``write(point)`` receivers.  :class:`JsonlSeriesSink`
  appends one JSON object per sample (the greppable artifact the
  invariant tests read); :class:`PrometheusSink` holds the latest point
  and renders the text exposition format any Prometheus-compatible
  scraper ingests.
* **scrape endpoint** — :meth:`MetricsExporter.serve_http` starts a
  stdlib ``http.server`` thread answering ``GET /metrics`` with the
  latest point (opt-in; port 0 picks a free port).  Pull-based export
  costs nothing between scrapes.
* **SLO hook** — an attached :class:`~dtdl_tpu.obs.slo.SLOEvaluator`
  runs on every sampled point and its ``slo_*`` fields are merged into
  the same point before the sinks see it, so threshold/burn-rate
  crossings land in the exported series exactly where the triggering
  window does.
"""

from __future__ import annotations

import http.server
import json
import os
import re
import threading
import time
from typing import Callable, Optional

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def prometheus_name(name: str) -> str:
    """Sanitize a field name to the Prometheus metric grammar."""
    name = _NAME_RE.sub("_", name)
    if not name or name[0].isdigit():
        name = "_" + name
    return name


def prometheus_text(point: dict, prefix: str = "dtdl_") -> str:
    """Render one series point as Prometheus text exposition (0.0.4):
    every numeric field becomes a gauge line with the point's timestamp
    in milliseconds.  Window-delta fields are gauges of per-interval
    increments — rate() over them is wrong; sum-over-time is the
    cumulative count (documented in SCALING.md round 16)."""
    ts_ms = int(point.get("t", time.time()) * 1e3)
    lines = []
    for k, v in sorted(point.items()):
        if k in ("t", "t_mono"):
            continue
        if isinstance(v, bool):
            v = int(v)
        elif not isinstance(v, (int, float)):
            continue
        name = prometheus_name(prefix + k)
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {v} {ts_ms}")
    return "\n".join(lines) + ("\n" if lines else "")


class JsonlSeriesSink:
    """One JSON object per sampled point, appended to ``path`` and
    flushed per write (boundary-rate traffic; a crashed run keeps every
    settled point)."""

    def __init__(self, path: str):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self.path = path
        self._f = open(path, "a")

    def write(self, point: dict) -> None:
        self._f.write(json.dumps(point) + "\n")
        self._f.flush()

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()


class PrometheusSink:
    """Holds the latest point; :meth:`render` is the scrape body."""

    def __init__(self, prefix: str = "dtdl_"):
        self.prefix = prefix
        self.last_point: dict = {}

    def write(self, point: dict) -> None:
        self.last_point = point

    def render(self) -> str:
        return prometheus_text(self.last_point, self.prefix)

    def close(self) -> None:
        pass


class _ScrapeHandler(http.server.BaseHTTPRequestHandler):
    render: Callable[[], str]        # bound by serve_http per server

    def do_GET(self):                # noqa: N802 - stdlib naming
        if self.path.split("?", 1)[0] != "/metrics":
            self.send_error(404)
            return
        body = self.render().encode()
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):        # silence per-request stderr spam
        pass


class MetricsExporter:
    """Boundary-sampled metrics pipeline: sources → (SLO eval) → sinks
    (see module docstring).

    ``interval_s`` is the minimum spacing between snapshots — callers
    invoke :meth:`sample` at every boundary they own and the exporter
    decides which ones become points (``force=True`` bypasses the
    throttle, e.g. for the final snapshot at shutdown).  The exporter
    is host-only and lock-free by design: it is sampled from ONE thread
    (the router pump or the scheduler's drain path); sinks that cross
    threads (the scrape server reads ``PrometheusSink.last_point``)
    exchange a single dict reference, which is atomic in CPython.
    """

    def __init__(self, sinks=(), interval_s: float = 0.25,
                 observer=None, prefix: str = "dtdl_"):
        self.sinks = list(sinks)
        self.interval_s = interval_s
        self.observer = observer
        self.prefix = prefix
        self._sources: list[tuple[str, Callable[[], dict]]] = []
        self.slo = None
        self.last_point: dict = {}
        self.n_snapshots = 0
        self.source_errors = 0
        self.sink_errors = 0
        self._last_t = 0.0
        self._http: Optional[http.server.ThreadingHTTPServer] = None
        self._prom: Optional[PrometheusSink] = None

    # ---- configuration ------------------------------------------------

    def add_source(self, name: str,
                   fn: Callable[[], dict]) -> "MetricsExporter":
        """Register a metrics source; ``name`` prefixes its fields
        (pass "" for sources whose fields are already namespaced, like
        the serve summaries)."""
        self._sources.append((name, fn))
        return self

    def add_sink(self, sink) -> "MetricsExporter":
        self.sinks.append(sink)
        return self

    def attach_slo(self, evaluator) -> "MetricsExporter":
        """Run ``evaluator`` (an :class:`~dtdl_tpu.obs.slo.
        SLOEvaluator`) on every sampled point; its ``slo_*`` fields are
        merged into the point before the sinks write it."""
        self.slo = evaluator
        return self

    def serve_http(self, port: int = 0,
                   host: str = "127.0.0.1") -> int:
        """Opt-in scrape endpoint: GET /metrics returns the latest
        point in Prometheus text format.  Returns the bound port
        (``port=0`` picks a free one).  Daemon thread; idle between
        scrapes."""
        if self._http is not None:
            return self._http.server_address[1]
        if self._prom is None:
            self._prom = PrometheusSink(self.prefix)
            self.sinks.append(self._prom)
        prom = self._prom
        handler = type("Handler", (_ScrapeHandler,),
                       {"render": staticmethod(prom.render)})
        self._http = http.server.ThreadingHTTPServer((host, port),
                                                     handler)
        t = threading.Thread(target=self._http.serve_forever,
                             name="metrics-scrape", daemon=True)
        t.start()
        return self._http.server_address[1]

    @property
    def port(self) -> Optional[int]:
        return self._http.server_address[1] if self._http else None

    # ---- sampling ------------------------------------------------------

    def sample(self, force: bool = False) -> Optional[dict]:
        """Take one snapshot if ``interval_s`` has elapsed (or
        ``force``); returns the point written, or None when throttled.
        Call this only from boundaries the owning loop already settles
        at — the exporter reads host counters, never the device."""
        now = time.perf_counter()
        if not force and now - self._last_t < self.interval_s:
            return None
        self._last_t = now
        point = {"t": time.time(), "t_mono": round(now, 6)}
        for name, fn in self._sources:
            try:
                vals = fn()
            except Exception:
                # a broken source must not take the serving loop (or
                # the other sources) down with it; count and move on
                self.source_errors += 1
                continue
            pre = f"{name}_" if name else ""
            for k, v in vals.items():
                if isinstance(v, bool):
                    point[pre + k] = int(v)
                elif isinstance(v, (int, float)):
                    point[pre + k] = v
        if self.slo is not None:
            point.update(self.slo.evaluate(point, now=now))
        for sink in self.sinks:
            try:
                sink.write(point)
            except Exception:
                # same contract as sources: a sick sink (disk full, a
                # file closed under us) must never take the serving
                # loop down — count it and keep the other sinks fed
                self.sink_errors += 1
        self.last_point = point
        self.n_snapshots += 1
        return point

    # ---- lifecycle -----------------------------------------------------

    def close(self) -> None:
        if self._http is not None:
            self._http.shutdown()
            self._http.server_close()
            self._http = None
        for sink in self.sinks:
            try:
                sink.close()
            except Exception:
                pass

    def __enter__(self) -> "MetricsExporter":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
