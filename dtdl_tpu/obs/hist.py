"""Streaming log-bucketed histogram: tail percentiles in fixed memory.

Serving tails (TTFT p99, per-token p99) and step-time tails are the
production numbers the ROADMAP's north star is judged on, but the PR-1/
PR-2 discipline forbids the easy implementation: appending every sample
to a list grows without bound under heavy traffic, and computing exact
percentiles at summary time sorts millions of floats.  This histogram
is the standard fix (HdrHistogram / Prometheus-style): geometric
buckets, O(1) ``add`` with no allocation, percentiles by cumulative
walk, bounded relative error of one bucket ratio
(``10 ** (1 / bins_per_decade)`` = 3.7% bucket width at the default 64
bins/decade, ≤1.8% from the reported geometric midpoint).

Everything is plain host floats: ``add`` never touches a device value,
so wiring this into the serve harvest or a training drain adds zero
syncs (the numbers it sees are already lag-harvested by the queue).
"""

from __future__ import annotations

import math


class LogHistogram:
    """Fixed-memory log-bucketed histogram over (0, +inf).

    ``lo``/``hi`` bound the bucketed range — samples outside clamp into
    the first/last bucket but min/max/mean stay exact, so a clamped p99
    is still never reported beyond the observed extremes.  Defaults
    cover 1 microsecond to 1000 seconds, the whole latency range a
    training step or a serve token can plausibly occupy.
    """

    __slots__ = ("lo", "hi", "bins_per_decade", "_ratio_log", "_n_bins",
                 "_counts", "n", "total", "_min", "_max")

    def __init__(self, lo: float = 1e-6, hi: float = 1e3,
                 bins_per_decade: int = 64):
        if not (0 < lo < hi):
            raise ValueError(f"need 0 < lo < hi, got lo={lo} hi={hi}")
        if bins_per_decade < 1:
            raise ValueError(f"bins_per_decade must be >= 1, got "
                             f"{bins_per_decade}")
        self.lo = lo
        self.hi = hi
        self.bins_per_decade = bins_per_decade
        self._ratio_log = 1.0 / bins_per_decade          # log10 per bucket
        self._n_bins = int(math.ceil(
            (math.log10(hi) - math.log10(lo)) * bins_per_decade)) + 1
        self._counts = [0] * self._n_bins
        self.n = 0
        self.total = 0.0
        self._min = math.inf
        self._max = -math.inf

    # ---- ingest -------------------------------------------------------

    def _index(self, x: float) -> int:
        if x <= self.lo:
            return 0
        i = int((math.log10(x) - math.log10(self.lo)) / self._ratio_log)
        return min(i, self._n_bins - 1)

    def add(self, x: float) -> None:
        """O(1), allocation-free; non-positive samples clamp to ``lo``."""
        x = float(x)
        self._counts[self._index(x)] += 1
        self.n += 1
        self.total += x
        if x < self._min:
            self._min = x
        if x > self._max:
            self._max = x

    def extend(self, xs) -> None:
        for x in xs:
            self.add(x)

    def merge(self, other: "LogHistogram") -> "LogHistogram":
        """In-place merge of an identically-bucketed histogram."""
        if (other.lo, other.hi, other.bins_per_decade) != (
                self.lo, self.hi, self.bins_per_decade):
            raise ValueError("cannot merge histograms with different "
                             "bucket layouts")
        for i, c in enumerate(other._counts):
            self._counts[i] += c
        self.n += other.n
        self.total += other.total
        self._min = min(self._min, other._min)
        self._max = max(self._max, other._max)
        return self

    # ---- read ---------------------------------------------------------

    @property
    def mean(self) -> float:
        return self.total / self.n if self.n else 0.0

    @property
    def min(self) -> float:
        return self._min if self.n else 0.0

    @property
    def max(self) -> float:
        return self._max if self.n else 0.0

    def percentile(self, p: float) -> float:
        """The p-th percentile (p in [0, 100]); the bucket's geometric
        midpoint, clamped to the observed min/max so the extremes are
        exact whatever the bucket width."""
        if not 0 <= p <= 100:
            raise ValueError(f"percentile must be in [0, 100], got {p}")
        if self.n == 0:
            return 0.0
        rank = p / 100.0 * self.n
        seen = 0
        for i, c in enumerate(self._counts):
            seen += c
            if seen >= rank and c:
                lo_edge = self.lo * 10 ** (i * self._ratio_log)
                hi_edge = lo_edge * 10 ** self._ratio_log
                mid = math.sqrt(lo_edge * hi_edge)
                return min(max(mid, self._min), self._max)
        return self._max          # pragma: no cover - rank <= n always hits

    @property
    def p50(self) -> float:
        return self.percentile(50)

    @property
    def p95(self) -> float:
        return self.percentile(95)

    @property
    def p99(self) -> float:
        return self.percentile(99)

    def summary(self, prefix: str = "", unit: float = 1.0,
                digits: int = 6) -> dict:
        """Flat dict of the standard fields (``unit`` rescales, e.g.
        1e3 for ms); empty when nothing was recorded."""
        if self.n == 0:
            return {}
        r = lambda v: round(v * unit, digits)  # noqa: E731
        return {f"{prefix}count": self.n,
                f"{prefix}mean": r(self.mean),
                f"{prefix}p50": r(self.p50),
                f"{prefix}p95": r(self.p95),
                f"{prefix}p99": r(self.p99),
                f"{prefix}max": r(self.max)}
