"""SLO layer: declarative targets, error budgets, burn-rate alerts.

The obs stack measures (tails, counters, goodput); nothing *judges*.
This module closes that gap with the standard SRE construction: a
declarative objective, a rolling evaluation window, and an **error
budget** — the fraction of badness the target tolerates — whose
consumption rate ("burn rate") is the alert signal, because a raw
breach count cannot distinguish "one bad second" from "burning a
month's budget in an hour" (SCALING.md "Fleet observability", round
16).

Two objective shapes, both evaluated on the exported series points the
:class:`~dtdl_tpu.obs.export.MetricsExporter` feeds through
:class:`SLOEvaluator` (so evaluation happens exactly at the sampling
boundaries, never adds a sync, and its verdict fields land in the same
exported point as the window that triggered them):

* **gauge SLOs** — a threshold on an exported field, e.g. TTFT p99
  ≤ 0.5 s from the existing fixed-memory
  :class:`~dtdl_tpu.obs.hist.LogHistogram` tails, or an
  acceptance-rate floor.  ``burn = value / target`` (inverted for
  ``>=`` objectives) — 1.0 is the line.
* **ratio SLOs** — good/bad *counter increments* (the
  ``window()`` delta fields from serve/metrics.py) accumulated over a
  rolling ``window_s``, e.g. availability ≥ 99.9% with bad =
  failed + expired (the :data:`~dtdl_tpu.serve.metrics.
  UNAVAILABLE_KINDS` classification — load-shedding rejections are
  deliberate and do not burn the budget).  ``burn = error_rate /
  (1 - target)`` — burn 1.0 means the budget is being consumed exactly
  at the rate that exhausts it at the window's end; a 100%-outage
  window at target 99.9% burns at 1000x.

Crossings are emitted twice, by design: as trace events
(``slo_breach`` / ``slo_burn_rate`` / ``slo_recovered`` — they land on
the timeline next to the evictions/retries that caused them) and as
``slo_*`` exported series fields (a monitor needs no trace parser).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Optional, Sequence

# burn rates are clamped here: a zero denominator (a >= objective
# collapsing to value 0, a <= objective with target 0) reads "maximal
# burn", and a finite cap keeps every exported point strict-JSON
# (json.dumps would otherwise emit the literal `Infinity`, which RFC
# 8259 consumers reject)
BURN_CAP = 1e6


class SLO:
    """One declarative objective (see module docstring).

    Gauge mode: ``SLO("ttft_p99", metric="fleet_ttft_s_p99", op="<=",
    target=0.5)`` — judged on the exported field's current value.
    Ratio mode: ``SLO("availability", good="fleet_requests_finished",
    bad=("fleet_requests_failed", "fleet_requests_expired"),
    target=0.999)`` — judged on counter increments over a rolling
    ``window_s``.  ``burn_alert`` is the burn-rate crossing threshold
    (1.0 = budget consumed exactly as fast as it accrues).
    """

    def __init__(self, name: str, metric: Optional[str] = None,
                 op: str = "<=", target: float = None,
                 good: Optional[str] = None,
                 bad: Optional[Sequence[str] | str] = None,
                 window_s: float = 10.0, burn_alert: float = 1.0,
                 gate: Optional[str] = None):
        if target is None:
            raise ValueError(f"SLO {name!r} needs a target")
        gauge = metric is not None
        ratio = good is not None or bad is not None
        if gauge == ratio:
            raise ValueError(
                f"SLO {name!r}: pass exactly one of metric= (gauge "
                f"threshold) or good=/bad= (rolling ratio)")
        if gauge and op not in ("<=", ">="):
            raise ValueError(f"SLO {name!r}: op must be '<=' or '>=', "
                             f"got {op!r}")
        if ratio:
            if not (good and bad):
                raise ValueError(f"SLO {name!r}: ratio mode needs both "
                                 f"good= and bad= fields")
            if not 0.0 < target < 1.0:
                raise ValueError(f"SLO {name!r}: a ratio target must be "
                                 f"in (0, 1), got {target}")
        self.name = name
        self.metric = metric
        self.op = op
        self.target = float(target)
        self.good = good
        self.bad = ((bad,) if isinstance(bad, str) else tuple(bad or ()))
        self.window_s = window_s
        self.burn_alert = burn_alert
        # gate: skip judgment on points where this field is absent or
        # zero — for objectives over rates whose input field is ALWAYS
        # exported (e.g. spec_acceptance_rate is 0.0 in every window
        # even with speculation off; gating on spec_drafted_tokens
        # judges only windows that actually drafted)
        self.gate = gate
        self.ok: Optional[bool] = None      # None until first verdict
        self.alerting = False               # burn-rate crossing latch
        self.breaches = 0
        self.burn_crossings = 0
        self._events: deque = deque()       # ratio mode: (t, good, bad)

    # ---- evaluation ----------------------------------------------------

    def _verdict(self, point: dict, now: float):
        """(value-ish fields, ok, burn) for this point, or None when
        the input field(s) are absent (no traffic yet) or the gate
        field says the objective does not apply to this window."""
        if self.gate is not None and not point.get(self.gate):
            return None
        if self.metric is not None:
            v = point.get(self.metric)
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                return None
            if self.op == "<=":
                ok = v <= self.target
                burn = (v / self.target if self.target > 0
                        else BURN_CAP if v > 0 else 0.0)
            else:
                ok = v >= self.target
                burn = (self.target / v if v > 0
                        else BURN_CAP if self.target > 0 else 0.0)
            return {"value": round(float(v), 6)}, ok, min(burn, BURN_CAP)
        g = point.get(self.good, 0)
        b = sum(point.get(f, 0) or 0 for f in self.bad)
        if not isinstance(g, (int, float)):
            g = 0
        self._events.append((now, g, b))
        while self._events and now - self._events[0][0] > self.window_s:
            self._events.popleft()
        G = sum(e[1] for e in self._events)
        B = sum(e[2] for e in self._events)
        if G + B <= 0:
            return None                 # no terminal traffic in window
        sli = G / (G + B)
        budget = 1.0 - self.target
        burn = min((1.0 - sli) / budget, BURN_CAP)
        return ({"sli": round(sli, 6), "good": G, "bad": B},
                sli >= self.target, burn)

    def evaluate(self, point: dict, now: float, observer=None) -> dict:
        """Judge one exported point; returns the ``slo_<name>_*``
        fields to merge into it and emits crossing events on the
        observer (ok↔breach transitions and burn-rate latch edges)."""
        verdict = self._verdict(point, now)
        if verdict is None:
            return {}
        fields, ok, burn = verdict
        pre = f"slo_{self.name}_"
        out = {pre + k: v for k, v in fields.items()}
        out[pre + "ok"] = int(ok)
        out[pre + "burn"] = round(burn, 4)
        out[pre + "target"] = self.target
        # state transitions and crossing counters advance UNCONDITIONALLY
        # — an evaluator without an observer still keeps honest books
        # (summary() is the bench/monitor rollup); the observer only
        # decides whether the crossing also lands on a trace
        prev_ok = self.ok
        self.ok = ok
        breached = not ok and prev_ok is not False
        recovered = ok and prev_ok is False
        if breached:
            self.breaches += 1
        crossed = burn >= self.burn_alert and not self.alerting
        if crossed:
            self.alerting = True
            self.burn_crossings += 1
        elif burn < self.burn_alert and self.alerting:
            self.alerting = False
        if observer is not None:
            if breached:
                observer.event("slo_breach", slo=self.name,
                               target=self.target,
                               burn=out[pre + "burn"], **fields)
            elif recovered:
                observer.event("slo_recovered", slo=self.name,
                               target=self.target, **fields)
            if crossed:
                observer.event("slo_burn_rate", slo=self.name,
                               burn=out[pre + "burn"],
                               alert=self.burn_alert, **fields)
        return out


def default_train_slos(step_time_s: Optional[float] = None,
                       bad_step_ratio: Optional[float] = None,
                       window_s: float = 10.0) -> list:
    """The standard *training* objectives — the twin of
    ``serve.fleet.default_fleet_slos`` — declared over the fields a
    trainer-attached exporter samples from ``GoodputMeter.
    export_window()`` (source name ``"goodput"``) and ``StepGuard.
    window()`` (source name ``"guard"``):

    * ``step_time_s`` — mean settled step time ≤ the target, judged on
      ``goodput_step_time_s`` and gated on ``goodput_steps`` so idle
      windows are skipped;
    * ``bad_step_ratio`` — the anomalous-step budget: a rolling
      good/bad ratio over ``guard_good_steps`` / ``guard_bad_steps``
      with target ``1 - bad_step_ratio`` (e.g. 0.01 tolerates 1% bad
      steps; a NaN burst burns the budget at the same burn-rate math
      the serving availability SLO uses).
    """
    slos = []
    if step_time_s is not None:
        slos.append(SLO("step_time", metric="goodput_step_time_s",
                        op="<=", target=step_time_s,
                        gate="goodput_steps"))
    if bad_step_ratio is not None:
        if not 0.0 < bad_step_ratio < 1.0:
            raise ValueError(f"bad_step_ratio must be in (0, 1), got "
                             f"{bad_step_ratio}")
        slos.append(SLO("bad_steps", good="guard_good_steps",
                        bad="guard_bad_steps",
                        target=1.0 - bad_step_ratio, window_s=window_s))
    return slos


class SLOEvaluator:
    """Evaluates a set of :class:`SLO` objectives on each exported
    series point (attach via :meth:`~dtdl_tpu.obs.export.
    MetricsExporter.attach_slo`); crossings go to ``observer`` as trace
    events, verdicts into the point as ``slo_*`` fields."""

    def __init__(self, slos: Sequence[SLO], observer=None):
        names = [s.name for s in slos]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self.slos = list(slos)
        self.observer = observer

    def evaluate(self, point: dict, now: Optional[float] = None) -> dict:
        now = time.perf_counter() if now is None else now
        out = {}
        for slo in self.slos:
            out.update(slo.evaluate(point, now, self.observer))
        return out

    def summary(self) -> dict:
        """Flat rollup: per-SLO last verdict + fleet-wide crossing
        counts (the ``slo_*`` bench summary fields)."""
        out = {"slo_breach_events": sum(s.breaches for s in self.slos),
               "slo_burn_crossings": sum(s.burn_crossings
                                         for s in self.slos)}
        for s in self.slos:
            if s.ok is not None:
                out[f"slo_{s.name}_ok"] = int(s.ok)
        return out
