"""Goodput / MFU accounting: analytic FLOPs in, roofline fractions out.

LM_ROOFLINE.md / RESNET50_ROOFLINE.md derived MFU by hand once per
round; this module is that math as a library, fed per drained window so
every loop can report ``mfu`` / ``tokens_per_sec`` / achieved-vs-
roofline continuously instead of in one-off docs.  Three pieces:

* **analytic model FLOPs** — :func:`lm_train_flops` (TransformerLM from
  its config; moved here from bench.py, which re-exports it) and
  :func:`netspec_flops` (Caffe-style CNNs from their parsed LayerSpecs).
  Analytic counts are the honest MFU numerator on TPU: XLA's
  ``cost_analysis()`` cannot see inside Pallas custom-calls and misses
  the flash-attention FLOPs entirely (LM_ROOFLINE.md §1).  The
  convention is matmul-only model FLOPs — causal attention at the
  computed half, backward at 2x forward, recompute never credited, and
  elementwise work (rope — fused into the kernels since round 13 —
  norms, activations) never counted (:func:`lm_rope_hbm_bytes` carries
  the BYTE side of the rope-fusion story instead).
* **chip peaks** — :func:`peak_flops_per_chip` (public bf16 figures by
  device_kind; None on CPU and unknown chips).
* :class:`GoodputMeter` — turns (steps, seconds) windows into the
  metric fields, using only numbers the drain already produced: no
  device syncs, per the PR-1 discipline.
"""

from __future__ import annotations

from typing import Optional


# Dense bf16 peak FLOP/s per chip, by device_kind substring (longest match
# wins, so "TPU v5 lite" beats "TPU v5").  Public figures: v2 45T, v3 123T,
# v4 275T, v5e 197T, v5p 459T, v6e (Trillium) 918T.
_PEAK_BF16 = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
    "TPU v6": 918e12,
}


def peak_flops_per_chip() -> Optional[float]:
    """bf16 peak for the local chip, or None if unknown (e.g. CPU)."""
    import jax
    kind = getattr(jax.devices()[0], "device_kind", "") or ""
    best = None
    for k, v in _PEAK_BF16.items():
        if k in kind and (best is None or len(k) > len(best[0])):
            best = (k, v)
    return best[1] if best else None


def lm_forward_flops(cfg, batch: int, seq: int) -> float:
    """Matmul-only FLOPs of one LM forward over ``seq`` positions.

    ``cfg`` is a TransformerLM (or anything with d_model / n_heads /
    head_dim / d_ff / n_layers / vocab_size).  Causal attention is
    counted at the *computed half* (the flash kernel skips
    above-diagonal tiles) — conservative vs quoting dense S² work.
    MoE layers count ACTIVATED expert compute (top_k x the dense MLP);
    router/dispatch/capacity overhead is deliberately not credited.
    """
    t = seq
    qkvo = 4 * 2 * batch * t * cfg.d_model * (cfg.n_heads * cfg.head_dim)
    attn = 2 * 2 * batch * cfg.n_heads * t * t * cfg.head_dim * 0.5
    mlp = 3 * 2 * batch * t * cfg.d_model * cfg.d_ff
    head = 2 * batch * t * cfg.d_model * cfg.vocab_size
    n_moe = 0
    if getattr(cfg, "n_experts", 0) and hasattr(cfg, "moe_every"):
        n_moe = cfg.n_layers // cfg.moe_every
    return (cfg.n_layers * (qkvo + attn) + (cfg.n_layers - n_moe) * mlp
            + n_moe * getattr(cfg, "moe_top_k", 1) * mlp + head)


def lm_train_flops(cfg, batch: int, seq: int) -> float:
    """Matmul-only model FLOPs for one LM *train* step (fwd + 2x bwd).

    The train step predicts ``seq - 1`` next tokens, so the forward is
    counted over seq-1 positions; backward at the standard 2x forward
    (the kernel's recompute overhead is NOT credited).  This is the
    number bench.py's ``mfu`` uses (see LM_ROOFLINE.md §1 for the
    measured gap vs XLA's cost_analysis).
    """
    return 3.0 * lm_forward_flops(cfg, batch, seq - 1)


def lm_decode_flops(cfg, batch: int, context: int) -> float:
    """Matmul-only FLOPs of ONE batched decode step at KV length
    ``context``: every weight matmul at seq=1 plus the attention reads
    against the cache.  The per-token serving MFU numerator (decode is
    HBM-bound, so this fraction is honest about how far below peak the
    phase must sit — SCALING.md "Serving latency model")."""
    qkvo = 4 * 2 * batch * cfg.d_model * (cfg.n_heads * cfg.head_dim)
    attn = 2 * 2 * batch * cfg.n_heads * context * cfg.head_dim
    mlp = 3 * 2 * batch * cfg.d_model * cfg.d_ff
    head = 2 * batch * cfg.d_model * cfg.vocab_size
    n_moe = 0
    if getattr(cfg, "n_experts", 0) and hasattr(cfg, "moe_every"):
        n_moe = cfg.n_layers // cfg.moe_every
    return (cfg.n_layers * (qkvo + attn) + (cfg.n_layers - n_moe) * mlp
            + n_moe * getattr(cfg, "moe_top_k", 1) * mlp + head)


def lm_prefill_flops(cfg, prompt_len: int) -> float:
    """Forward-only FLOPs of prefilling one prompt (batch 1)."""
    return lm_forward_flops(cfg, 1, prompt_len)


def lm_verify_flops(cfg, batch: int, context: int, k: int) -> float:
    """Matmul-only FLOPs of ONE speculative verify pass scoring k drafts
    (k+1 query positions) per slot at KV length ``context``.

    Essentially ``(k+1) x lm_decode_flops`` — verify stays bandwidth-
    bound on TPU (the same full parameter read as decode) but amortizes
    it over up to k+1 accepted tokens, which is the whole speculative-
    decoding trade (SCALING.md "Speculative decoding arithmetic").
    Goodput itself needs no new field: accepted tokens flow through the
    serve metrics' delivered-token count, so ``decode tokens/sec``
    already counts real tokens, never drafts.
    """
    return (k + 1) * lm_decode_flops(cfg, batch, context)


def lm_rope_hbm_bytes(cfg, batch: int, seq: int,
                      dtype_bytes: int = 2) -> float:
    """HBM bytes per train step an UNFUSED rope implementation
    round-trips — the traffic the fused-rope attention kernels
    (ops/attention.py, round 13) eliminate.

    Per layer, a standalone ``apply_rope`` reads and writes both
    [B, H, S, D] Q and K tensors once in the forward, and the backward
    inverse-rotates dQ/dK the same way: 2 phases × 2 tensors × (read +
    write) = 8 × B·H·S·D·bytes per layer.  Fused, the rotation runs on
    tiles already in VMEM and only the [S, D]-shaped table rows move —
    ~1/(2·B·H) of this, counted as zero here.  NOTE the analytic FLOP
    numerator (:func:`lm_forward_flops`) is matmul-only by convention
    and never counted rope's elementwise work, so fusing rope changes
    measured step TIME, not the model-FLOP accounting — mfu rises
    because the denominator seconds shrink, with no numerator edit.
    """
    qk = batch * cfg.n_heads * cfg.head_dim * seq * dtype_bytes
    return cfg.n_layers * 8.0 * qk


# ---------------------------------------------------------------------------
# CNN FLOPs from a Caffe netspec
# ---------------------------------------------------------------------------

def _pair(param, key: str, default: int) -> tuple:
    v = param.get_scalar(key, None)
    if v is None:
        return (int(param.get_scalar(key + "_h", default)),
                int(param.get_scalar(key + "_w", default)))
    return int(v), int(v)


def _caffe_pool_out(size: int, k: int, s: int, pad: int) -> int:
    # Caffe sizes pooling with CEIL (netspec.py mirrors this in padding)
    out = -(-(size + 2 * pad - k) // s) + 1
    if pad and (out - 1) * s >= size + pad:
        out -= 1
    return max(out, 1)


def netspec_flops(specs, input_shape, phase: str = "TRAIN",
                  backward: bool = False) -> float:
    """Matmul/conv-only analytic FLOPs of one forward pass through a
    parsed Caffe net (``dtdl_tpu.models.netspec.parse_net`` LayerSpecs,
    or a prototxt path / Message).

    ``input_shape`` is one example's (H, W, C).  Elementwise layers
    (ReLU/LRN/Dropout/Softmax) and pooling count 0 — the MFU-numerator
    convention credits only the dense math.  ``backward=True`` adds the
    standard 2x for the backward pass (one train step = 3x forward).
    Multiply by the batch size for a step's total.
    """
    from dtdl_tpu.models.netspec import parse_net
    from dtdl_tpu.utils.prototxt import Message, parse_file

    if isinstance(specs, str):
        specs = parse_net(parse_file(specs))
    elif isinstance(specs, Message):
        specs = parse_net(specs)

    h, w, c = (int(x) for x in input_shape)
    flat = None                      # set once an InnerProduct flattens
    total = 0.0
    for spec in specs:
        if not spec.in_phase(phase):
            continue
        p = spec.params
        if spec.type == "Convolution":
            cp = p.get_scalar("convolution_param", Message())
            kh, kw = _pair(cp, "kernel_size", 3)
            sh, sw = _pair(cp, "stride", 1)
            ph, pw = _pair(cp, "pad", 0)
            cout = int(cp.get_scalar("num_output"))
            group = int(cp.get_scalar("group", 1))
            oh = (h + 2 * ph - kh) // max(sh, 1) + 1
            ow = (w + 2 * pw - kw) // max(sw, 1) + 1
            total += 2.0 * kh * kw * (c // group) * cout * oh * ow
            if bool(cp.get_scalar("bias_term", True)):
                total += float(cout * oh * ow)
            h, w, c, flat = oh, ow, cout, None
        elif spec.type == "Pooling":
            pp = p.get_scalar("pooling_param", Message())
            if bool(pp.get_scalar("global_pooling", False)):
                h = w = 1
                continue
            kh, kw = _pair(pp, "kernel_size", 2)
            sh, sw = _pair(pp, "stride", 1)
            ph, pw = _pair(pp, "pad", 0)
            h = _caffe_pool_out(h, kh, max(sh, 1), ph)
            w = _caffe_pool_out(w, kw, max(sw, 1), pw)
        elif spec.type == "InnerProduct":
            ip = p.get_scalar("inner_product_param", Message())
            nin = flat if flat is not None else h * w * c
            nout = int(ip.get_scalar("num_output"))
            total += 2.0 * nin * nout
            if bool(ip.get_scalar("bias_term", True)):
                total += float(nout)
            flat = nout
        elif spec.type == "Flatten":
            flat = h * w * c
        # Data/ReLU/LRN/Dropout/Softmax/losses: 0 by convention
    return total * (3.0 if backward else 1.0)


# ---------------------------------------------------------------------------
# the meter
# ---------------------------------------------------------------------------

class GoodputMeter:
    """Per-window goodput fields from numbers the drain already has.

    Configure once with the workload's analytic per-step FLOPs (and
    per-step token count for LMs); each :meth:`window` call converts a
    settled (steps, seconds) window into reporter-ready fields.

    Denominator convention: ``peak_flops="auto"`` (the default) detects
    ONE chip's peak; ``None`` disables MFU outright (throughput fields
    only).  When ``flops_per_step`` covers a step sharded across several
    local devices, pass ``peak_flops=peak_flops_per_chip() * n_devices``
    explicitly — the auto single-chip default would inflate mfu by the
    device count (bench.py avoids this by using XLA's per-device
    partitioned FLOP count).  A
    ``roofline_mfu`` target (e.g. the 0.46 measured in LM_ROOFLINE.md)
    adds ``vs_roofline`` — the achieved fraction of what this chip has
    *demonstrated*, which is the regression signal ``mfu`` alone (a
    fraction of an unreachable dense peak) is too noisy to give.
    """

    def __init__(self, flops_per_step: Optional[float] = None,
                 tokens_per_step: Optional[float] = None,
                 samples_per_step: Optional[float] = None,
                 peak_flops="auto",
                 roofline_mfu: Optional[float] = None):
        self.flops_per_step = flops_per_step
        self.tokens_per_step = tokens_per_step
        self.samples_per_step = samples_per_step
        self.peak_flops = (peak_flops_per_chip() if peak_flops == "auto"
                           else peak_flops)
        self.roofline_mfu = roofline_mfu
        self.total_steps = 0
        self.total_seconds = 0.0
        self._exp_steps = 0
        self._exp_seconds = 0.0

    def window(self, steps: int, seconds: float) -> dict:
        """Goodput fields for one settled window (empty if degenerate)."""
        if steps <= 0 or seconds <= 0:
            return {}
        self.total_steps += steps
        self.total_seconds += seconds
        return self._fields(steps, seconds)

    def _fields(self, steps: int, seconds: float) -> dict:
        out = {"steps_per_sec": round(steps / seconds, 3)}
        if self.tokens_per_step:
            out["tokens_per_sec"] = round(
                self.tokens_per_step * steps / seconds, 1)
        if self.samples_per_step:
            out["samples_per_sec"] = round(
                self.samples_per_step * steps / seconds, 2)
        if self.flops_per_step:
            achieved = self.flops_per_step * steps / seconds
            out["achieved_tflops"] = round(achieved / 1e12, 4)
            if self.peak_flops:
                mfu = achieved / self.peak_flops
                out["mfu"] = round(mfu, 4)
                if self.roofline_mfu:
                    out["vs_roofline"] = round(mfu / self.roofline_mfu, 3)
        return out

    def totals(self) -> dict:
        """Whole-run goodput (same fields over the summed windows)."""
        if self.total_steps <= 0 or self.total_seconds <= 0:
            return {}
        return self._fields(self.total_steps, self.total_seconds)

    def export_window(self) -> dict:
        """Delta since the last :meth:`export_window` call — the no-arg
        source a :class:`~dtdl_tpu.obs.export.MetricsExporter` samples
        at drain boundaries (register as ``exporter.add_source(
        "goodput", meter.export_window)``; keys are bare, the source
        name prefixes them).  Fields cover the steps the loops settled
        via :meth:`window` in the interval: the per-window goodput set
        plus ``steps`` and the mean ``step_time_s`` — the gauge
        ``default_train_slos()`` judges step-time SLOs on.  Empty on an
        idle interval (the SLO layer's gate skips those)."""
        dsteps = self.total_steps - self._exp_steps
        dsecs = self.total_seconds - self._exp_seconds
        self._exp_steps = self.total_steps
        self._exp_seconds = self.total_seconds
        if dsteps <= 0 or dsecs <= 0:
            return {}
        out = self._fields(dsteps, dsecs)
        out["steps"] = dsteps
        out["step_time_s"] = round(dsecs / dsteps, 6)
        return out
