"""Span tracer: host-side phase timing as Chrome trace events.

The PR-1 async discipline made the loops opaque on purpose — between
boundaries the host only enqueues work, so wall-clock prints no longer
say where host time goes (data? dispatch? the drain?).  This tracer is
the host-side complement of ``jax.profiler`` (which sees the *device*
ops): lightweight ``span("data") / span("dispatch") / span("drain")``
context managers record complete ('X') events on the calling thread
(the serve scheduler adds ``admit`` / ``harvest`` and, under
speculative decoding, ``draft`` — host time inside the DraftSource —
and ``verify`` — the k-wide verify dispatch, args carrying the step's
draft width; the fleet Router adds ``route`` around its dispatch
round).  The resil layer marks its recoveries as zero-duration
:meth:`Tracer.instant` events (``guard_bad_step`` / ``guard_rollback``
/ ``trainer_preempted`` / ``request_expired`` / ``request_cancelled``
/ ``engine_failure`` / ``scheduler_shutdown``, via ``Observer.event``),
and the fleet layer its health/lifecycle edges (``replica_suspect`` /
``replica_evicted`` / ``replica_draining`` / ``replica_restarted`` /
``request_retry`` / ``request_hedged`` / ``hedge_won`` /
``router_shutdown``), so a trace shows exactly where a run skipped,
rolled back, shed load, or failed over.  Everything is
thread-safe for the serve scheduler, exported as Chrome-trace-event JSON
that Perfetto / ``chrome://tracing`` loads directly — the same format
the XLA profiler emits, so the two traces read with the same tools
(:func:`xla_events` below parses either).

Two honesty rules, inherited from SCALING.md "Async dispatch
discipline":

* a span measures **host phases only** — entering/leaving a span never
  touches the device, so tracing cannot add a sync (pinned by the
  sync-counting test in tests/test_obs.py);
* device time appears only as **window-settled** spans
  (:meth:`Tracer.device_window`): once a drain has settled a log window,
  the window's wall time is recorded on a synthetic "device" track —
  late by one window, exact in total, never a per-step round-trip.

When a ``jax.profiler`` capture is active, each span also opens a
``TraceAnnotation`` (via :mod:`dtdl_tpu._compat` — never a hard dep) so
host phases line up with XLA ops inside one Perfetto view.

**Request correlation (round 16).**  Fleet-era serving spreads one user
request over many threads — router intake, a pump dispatch, one worker
per attempt (retries and hedges are *sibling* attempts) — and anonymous
spans cannot be joined back into the request's story.  Every
request-scoped event therefore carries correlation args: ``rid`` (the
USER request id, stable across attempts), ``arid`` (the replica-local
attempt id), and on dispatch a ``lineage`` field (``primary`` /
``retry:N`` after N burned retries / ``requeue`` for a free
backpressure re-dispatch / ``hedge``).  :meth:`Tracer.flow` adds Chrome-trace flow
events (``ph`` s/t/f sharing ``id=rid``) so Perfetto draws the arrows
from submit through every attempt to the winning completion, and
:meth:`Tracer.request_timeline` reconstructs the same story
programmatically — the ordered list of every recorded event correlated
with one rid, whichever thread emitted it.

The span/event catalogs below (:data:`SPAN_CATALOG` /
:data:`EVENT_CATALOG`) are the single source of truth for names emitted
anywhere in dtdl_tpu; tests/test_obs_export.py audits the source tree
against them, so the catalog can no longer silently lag a new emitter
(it did twice between PR 5 and PR 9).
"""

from __future__ import annotations

import contextlib
import gzip
import json
import os
import threading
import time

from dtdl_tpu import _compat

# synthetic track ids inside the exported trace: host spans carry the
# real thread id; settled device windows live on their own track
DEVICE_TID = 1

# ---------------------------------------------------------------------------
# the span/event catalog — every name emitted through Observer.span /
# Observer.event / Tracer.instant anywhere in dtdl_tpu/.  Audited against
# the source tree by tests/test_obs_export.py: add the name HERE when you
# add an emitter, or the audit fails by name.
# ---------------------------------------------------------------------------

SPAN_CATALOG = frozenset({
    # training loops (PR 3)
    "data", "dispatch", "drain",
    # serve scheduler (PR 2/4): admission, drafting, the k-wide verify
    # dispatch, the lag harvest, and the per-admission prefill call
    "admit", "draft", "verify", "harvest", "prefill",
    # fleet router (PR 9)
    "route",
})

EVENT_CATALOG = frozenset({
    # resil (PR 5); trainer_rollback was emitted since PR 5 but missing
    # from the documented catalog until the round-16 audit pinned it —
    # exactly the drift the audit test exists to stop
    "guard_bad_step", "guard_rollback", "trainer_preempted",
    "trainer_rollback",
    # serve scheduler containment + lifecycle (PR 5/6)
    "request_expired", "request_cancelled", "engine_failure",
    "scheduler_shutdown", "page_pool_shed",
    # fleet health/lifecycle edges (PR 9); replica_* names are emitted as
    # f"replica_{state}" over the health-machine states
    "replica_suspect", "replica_evicted", "replica_draining",
    "replica_healthy", "replica_restarted", "replica_drain_timeout",
    "request_retry", "request_hedged", "hedge_won", "router_shutdown",
    "router_drain_timeout", "router_pump_error",
    # request-correlated lifecycle (round 16): intake → dispatch →
    # admit → first token → terminal, every one carrying rid/arid
    "request_submitted", "request_dispatched", "request_admitted",
    "request_first_token", "request_finished", "request_done",
    # SLO layer (round 16)
    "slo_breach", "slo_recovered", "slo_burn_rate",
    # chunked prefill / disaggregation (round 19): the page-granular
    # KV migration (side=extract on the prefill replica, side=inject
    # on the decode one) and the Router's stage transition between them
    "kv_handoff", "request_migrated",
    # elastic training plane (round 17): peer detection, world
    # re-formation, shrink-to-survivors restore, generation fencing —
    # every abort/fence/shed on the failure path surfaces here, never
    # as a silent hang
    "elastic_peer_lost", "elastic_rendezvous", "elastic_restore",
    "elastic_snapshot", "elastic_stale_fenced", "elastic_step_timeout",
    # TCP control-plane store (round 18): every socket-level recovery
    # edge of the coordinator protocol — a reconnect after a dead
    # socket, a torn reply frame detected by name, an amnesiac
    # coordinator refused by epoch, and a WAL rehydration on the
    # server side
    "store_reconnect", "store_torn_frame", "store_epoch_refused",
    "store_wal_recovered",
    # multi-tenant serving (round 22): LoRA adapter-bank residency
    # edges, grammar-constraint outcomes (reason=illegal is a contained
    # failure, reason=incomplete a budget truncation mid-structure),
    # and incremental TokenStream deliveries at harvest boundaries
    "adapter_loaded", "adapter_evicted", "grammar_violation",
    "stream_delivery",
    # hierarchical KV cache (round 23): a batch of evicted pages
    # spilled to the host/disk tiers, a prefix-miss served back out of
    # them, and the fleet prefix directory's routing/consistency edges
    # (a hit = affinity beat least-loaded; an invalidation = a replica
    # eviction/drain/containment delisted its advertised pages)
    "page_spilled", "page_restored", "prefix_directory_hit",
    "prefix_directory_invalidated",
})


# ---------------------------------------------------------------------------
# correlation ids (round 17): rids are prefixed with a process tag so
# multi-host traces (and elastic-training events from many workers)
# merge into one Perfetto view without id collisions — process A's
# request 7 ("p0/7") can never chain into process B's ("p1/7").
# ---------------------------------------------------------------------------

_PROC_TAG: str | None = None


def proc_tag() -> str:
    """This process's correlation-id prefix: ``DTDL_PROC_TAG`` when set
    (a router/launcher naming its workers), else ``p{process_index}``.
    Cached on first use; override early via :func:`set_proc_tag`."""
    global _PROC_TAG
    if _PROC_TAG is None:
        tag = os.environ.get("DTDL_PROC_TAG")
        if not tag:
            import jax
            tag = f"p{jax.process_index()}"
        _PROC_TAG = tag
    return _PROC_TAG


def set_proc_tag(tag: str | None) -> None:
    """Set (or with None, reset) the process tag — call before any
    correlated event is emitted; changing it mid-trace splits chains."""
    global _PROC_TAG
    _PROC_TAG = tag


def corr_rid(n) -> str:
    """The wire form of a correlation id: ``f"{proc_tag}/{n}"``.  Every
    emitter of a ``rid``/``arid`` arg or a request-flow id goes through
    here; already-prefixed strings pass through unchanged (the Router
    stamps attempt clones whose user rid was prefixed at intake)."""
    return n if isinstance(n, str) else f"{proc_tag()}/{n}"


class _Span:
    """One open span; records the 'X' event on exit."""

    __slots__ = ("tracer", "name", "args", "t0", "_ann")

    def __init__(self, tracer: "Tracer", name: str, args: dict):
        self.tracer = tracer
        self.name = name
        self.args = args
        self._ann = None

    def __enter__(self):
        self._ann = _compat.trace_annotation(self.name)
        if self._ann is not None:
            self._ann.__enter__()
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        if self._ann is not None:
            self._ann.__exit__(*exc)
        self.tracer._record(self.name, self.t0, t1 - self.t0,
                            threading.get_ident(), self.args)
        return False


class Tracer:
    """Thread-safe span recorder with Chrome-trace-event export.

    ``max_events`` bounds memory: the buffer is a ring in spirit — once
    full, new events are dropped and ``dropped`` counts them (a trace
    that silently ate the heap would violate the observability budget
    it exists to enforce).
    """

    def __init__(self, max_events: int = 200_000):
        self.max_events = max_events
        self.dropped = 0
        self._events: list[dict] = []
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._meta: dict = {"pid": os.getpid()}

    # ---- recording ----------------------------------------------------

    def span(self, name: str, **args) -> _Span:
        """Context manager timing one host phase on the calling thread."""
        return _Span(self, name, args)

    def instant(self, name: str, **args) -> None:
        """A zero-duration marker event."""
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
                return
            self._events.append({
                "name": name, "ph": "i", "s": "t",
                "ts": (time.perf_counter() - self._t0) * 1e6,
                "pid": self._meta["pid"],
                "tid": threading.get_ident(),
                **({"args": args} if args else {})})

    def counter(self, name: str, value: float) -> None:
        """A counter sample (Perfetto renders these as a line track)."""
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
                return
            self._events.append({
                "name": name, "ph": "C",
                "ts": (time.perf_counter() - self._t0) * 1e6,
                "pid": self._meta["pid"], "tid": 0,
                "args": {"value": value}})

    _FLOW_PH = {"start": "s", "step": "t", "end": "f"}

    def flow(self, name: str, fid, phase: str = "step",
             **args) -> None:
        """A Chrome-trace flow event: ``phase`` is ``start`` / ``step``
        / ``end`` and every event sharing (``name``, ``fid``) is joined
        into one arrow chain across threads — the Perfetto rendering of
        a request's path through router intake, dispatch, and each
        attempt's replica thread.  ``fid`` is the correlation id (the
        fleet uses the USER request rid in its proc-tagged
        :func:`corr_rid` wire form)."""
        ph = self._FLOW_PH.get(phase)
        if ph is None:
            raise ValueError(f"flow phase must be one of "
                             f"{sorted(self._FLOW_PH)}, got {phase!r}")
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
                return
            ev = {"name": name, "cat": "request", "ph": ph, "id": fid,
                  "ts": (time.perf_counter() - self._t0) * 1e6,
                  "pid": self._meta["pid"],
                  "tid": threading.get_ident()}
            if ph == "f":
                ev["bp"] = "e"     # bind the arrowhead to the enclosing
            if args:               # slice's end, the Perfetto convention
                ev["args"] = args
            self._events.append(ev)

    def request_timeline(self, rid) -> list[dict]:
        """Every recorded event correlated with USER request ``rid``,
        ordered by timestamp — the programmatic reconstruction of one
        request's story across threads, attempts, and failovers.

        An event correlates when its args carry ``rid == rid`` (the
        emitters thread the user rid through attempt clones, so a
        retried/hedged request's sibling attempts all land here, each
        distinguished by its ``arid``/``lineage`` args) or when it is a
        flow event with ``id == rid``.  Accepts either the wire form
        (``"p0/7"``) or a bare local request id, normalized through
        :func:`corr_rid` — emitters always record the prefixed form."""
        rid = corr_rid(rid)
        with self._lock:
            events = list(self._events)
        out = [e for e in events
               if e.get("args", {}).get("rid") == rid
               or (e.get("cat") == "request" and e.get("id") == rid)]
        out.sort(key=lambda e: e["ts"])
        return out

    def device_window(self, name: str, seconds: float, steps: int = 1,
                      **args) -> None:
        """Record a window-settled device span ending *now*.

        Called right after a boundary drain/sync: the window's wall time
        is attributed to the synthetic device track, one span per
        window (NOT per step — per-step device times do not exist
        without per-step syncs, and we refuse to add those).
        """
        t1 = time.perf_counter()
        self._record(name, t1 - seconds, seconds, DEVICE_TID,
                     {"steps": steps, **args})

    def _record(self, name: str, t0: float, dur: float, tid: int,
                args: dict) -> None:
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
                return
            ev = {"name": name, "ph": "X",
                  "ts": (t0 - self._t0) * 1e6, "dur": dur * 1e6,
                  "pid": self._meta["pid"], "tid": tid}
            if args:
                ev["args"] = args
            self._events.append(ev)

    # ---- export -------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def to_chrome(self) -> dict:
        """The Chrome trace-event JSON object (Perfetto-loadable)."""
        with self._lock:
            events = list(self._events)
        meta = [{"name": "thread_name", "ph": "M", "pid": self._meta["pid"],
                 "tid": DEVICE_TID,
                 "args": {"name": "device (window-settled)"}}]
        return {"traceEvents": meta + events, "displayTimeUnit": "ms",
                "otherData": {"dropped_events": self.dropped}}

    def save(self, path: str) -> str:
        """Write the trace to ``path`` (gzipped when it ends in .gz)."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "wt") as f:
            json.dump(self.to_chrome(), f)
        return path


# ---------------------------------------------------------------------------
# jax.profiler trace parsing (folded in from scripts/trace_utils.py — the
# script path re-exports these, so existing `from trace_utils import ...`
# callers keep working)
# ---------------------------------------------------------------------------

# On this backend the XLA op events live at pid 3 / tid 3; each carries
# ``hlo_category`` and ``bytes_accessed`` in its args.
XLA_PID = XLA_TID = 3


def xla_events(trace_dir: str) -> list:
    """XLA op events of the newest jax.profiler trace under ``trace_dir``.

    The tensorboard_plugin_profile converter is incompatible with this
    box's TF, so the raw Chrome-trace JSON is parsed directly.
    """
    import glob
    path = sorted(glob.glob(
        trace_dir + "/plugins/profile/*/*.trace.json.gz"))[-1]
    with gzip.open(path, "rt") as f:
        trace = json.load(f)
    return [e for e in trace["traceEvents"]
            if e.get("ph") == "X" and e.get("pid") == XLA_PID
            and e.get("tid") == XLA_TID]


def aggregate(events, key_fn):
    """Sum durations/calls/bytes of ``events`` grouped by ``key_fn``.

    Returns (groups, total_s): groups maps key -> [dur_s, calls,
    hlo_category, bytes_accessed], sorted by descending time.
    """
    import collections
    groups = collections.defaultdict(lambda: [0.0, 0, "", 0.0])
    total = 0.0
    for e in events:
        dur = e.get("dur", 0) / 1e6          # us -> s
        total += dur
        args = e.get("args", {})
        rec = groups[key_fn(e, args)]
        rec[0] += dur
        rec[1] += 1
        rec[2] = args.get("hlo_category", rec[2])
        try:
            rec[3] += float(args.get("bytes_accessed", 0) or 0)
        except (TypeError, ValueError):
            pass
    ordered = dict(sorted(groups.items(), key=lambda kv: -kv[1][0]))
    return ordered, total


_NULL_CTX = contextlib.nullcontext()


class NullTracer:
    """Disabled tracer: every operation is a near-zero no-op (a shared
    nullcontext for spans), so call sites never branch on 'is tracing
    on' themselves."""

    dropped = 0

    def span(self, name: str, **args):
        return _NULL_CTX

    def instant(self, name: str, **args) -> None:
        pass

    def counter(self, name: str, value: float) -> None:
        pass

    def flow(self, name: str, fid: int, phase: str = "step",
             **args) -> None:
        pass

    def request_timeline(self, rid: int) -> list:
        return []

    def device_window(self, name: str, seconds: float, steps: int = 1,
                      **args) -> None:
        pass

    def __len__(self) -> int:
        return 0

    def to_chrome(self) -> dict:
        return {"traceEvents": [], "displayTimeUnit": "ms"}

    def save(self, path: str) -> str:
        raise ValueError("tracing is disabled; nothing to save")


NULL_TRACER = NullTracer()
