"""Unified observability: span tracing, recompile sentinel, goodput/MFU
accounting, latency percentiles — for train AND serve loops.

The production triad the ROADMAP's north star needs (traces, utilization
accounting, tail latencies), built to the PR-1 rule: nothing in here may
add a host↔device sync to a hot loop.  See each module's docstring:

trace      span("data"/"dispatch"/"drain") → Chrome trace JSON (Perfetto),
           window-settled device track, jax.profiler annotations,
           request-correlated flow events + request_timeline(rid), the
           audited span/event catalogs
recompile  jit-cache sentinel: unexpected retraces are named, with the
           differing abstract args (warn / raise / silent)
goodput    analytic model FLOPs (LM from config, CNNs from netspec),
           chip peaks, per-window MFU / tokens-per-sec / vs-roofline
hist       streaming log-bucketed histogram: p50/p95/p99 in fixed memory
observer   the Observer facade every loop takes (~3 lines per call site)
export     boundary-sampled continuous metrics: JSONL series, Prometheus
           text + opt-in http scrape endpoint, window-delta sources
slo        declarative SLO targets over the exported series: error
           budgets, burn-rate alerts, crossings as trace events

Quick start::

    from dtdl_tpu.obs import Observer, GoodputMeter, lm_train_flops

    obs = Observer(trace_path="trace.json",
                   goodput=GoodputMeter(
                       flops_per_step=lm_train_flops(model, bs, seq),
                       tokens_per_step=bs * (seq - 1)))
    train_epoch(step, state, loader, strategy, reporter=rep, observer=obs)
    obs.close()                       # writes the Perfetto-loadable trace
"""

from dtdl_tpu.obs.export import (  # noqa: F401
    JsonlSeriesSink, MetricsExporter, PrometheusSink, prometheus_text,
)
from dtdl_tpu.obs.goodput import (  # noqa: F401
    GoodputMeter, lm_decode_flops, lm_forward_flops, lm_prefill_flops,
    lm_train_flops, lm_verify_flops, netspec_flops, peak_flops_per_chip,
)
from dtdl_tpu.obs.hist import LogHistogram  # noqa: F401
from dtdl_tpu.obs.observer import NULL_OBSERVER, Observer  # noqa: F401
from dtdl_tpu.obs.recompile import (  # noqa: F401
    RecompileError, RecompileEvent, RecompileSentinel,
)
from dtdl_tpu.obs.slo import (  # noqa: F401
    SLO, SLOEvaluator, default_train_slos,
)
from dtdl_tpu.obs.trace import (  # noqa: F401
    EVENT_CATALOG, NULL_TRACER, SPAN_CATALOG, Tracer, aggregate,
    corr_rid, proc_tag, set_proc_tag, xla_events,
)
