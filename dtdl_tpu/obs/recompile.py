"""Recompile sentinel: turn silent retraces into named, arg-diffed events.

A recompile on TPU is a multi-second stall that the async dispatch
pipeline hides until the drain — the loop just gets mysteriously slow.
The tests already police this by hand (``_cache_size()`` asserts in
tests/test_serve.py, the compile-count receipts in bench.py); this
module is that pattern made a reusable runtime guard: wrap any jitted
callable with :meth:`RecompileSentinel.watch` and every call compares
the function's jit-cache size before/after.  Growth past the expected
compile budget produces a :class:`RecompileEvent` naming the function
and **which abstract args changed** (shape/dtype diff against the
signature that compiled last time — the two things a retrace can key
on that a loop author actually controls).

Policies: ``'warn'`` logs through the ``dtdl_tpu`` logger (default —
observability must not change program behavior), ``'raise'`` turns the
event into a :class:`RecompileError` (CI mode: fail the run where the
retrace happens, not 40 minutes later in a profile), ``'silent'`` only
records.  A callable policy receives the event.

The sentinel reads only host-side jit bookkeeping — no device syncs,
no effect on what compiles.  Persistent-compile-cache note: a disk
cache hit (DTDL_TEST_CACHE, opt-in) still *traces* the function, so it
still counts here — correctly so, because tracing + cache lookup is
the stall being policed.  Functions without ``_cache_size`` (plain
Python callables, non-jit wrappers) pass through unwrapped.
"""

from __future__ import annotations

import dataclasses
import logging
from collections import OrderedDict
from typing import Any, Callable, Optional

_log = logging.getLogger("dtdl_tpu")

# per-sentinel LRU bound on watched-function states: each state pins its
# jit (executables + closed-over params) via a strong ref, so a process
# that churns through many step fns / engines must not grow unboundedly.
# Evicting a state forgets that fn's compile count (a re-watch restarts
# its budget) — the bound is sized so only genuinely churny workloads hit
# it, the same trade loop.py's _BUNDLED_CACHE makes.
_MAX_WATCH_STATES = 64


class RecompileError(RuntimeError):
    """An unexpected retrace under policy='raise'."""


def abstract_signature(args: tuple, kwargs: dict) -> dict:
    """Flat {path: 'f32[8,64]'} view of a call's abstract leaves.

    jax's own cache key also includes static argnums and tree
    structure; shapes/dtypes are the part a training/serving loop
    author can act on, so that is what the diff speaks in.
    """
    import jax
    import numpy as np

    def leaf_str(x) -> str:
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is not None and dtype is not None:
            return f"{np.dtype(dtype).name}[{','.join(map(str, shape))}]"
        if isinstance(x, (bool, int, float, str)):
            return f"{type(x).__name__}:{x!r}"        # static-ish leaf
        return type(x).__name__

    out = {}
    for tree, root in ((args, "args"), (kwargs, "kwargs")):
        flat = jax.tree_util.tree_flatten_with_path(tree)[0]
        for path, leaf in flat:
            out[root + jax.tree_util.keystr(path)] = leaf_str(leaf)
    return out


def diff_signatures(old: Optional[dict], new: dict) -> dict:
    """{path: 'old -> new'} for every leaf that changed (or appeared)."""
    if not old:
        return {}
    out = {}
    for k, v in new.items():
        if old.get(k) != v:
            out[k] = f"{old.get(k, '<absent>')} -> {v}"
    for k in old:
        if k not in new:
            out[k] = f"{old[k]} -> <absent>"
    return out


@dataclasses.dataclass
class RecompileEvent:
    """One unexpected retrace."""
    name: str
    n_compiles: int            # total traces of this fn since watch()
    cache_size: int            # jit cache entries after this call
    signature: dict            # the signature that (re)traced
    diff: dict                 # vs the signature that compiled before

    def message(self) -> str:
        changed = ("; ".join(f"{k}: {v}" for k, v in self.diff.items())
                   or "signature change outside shapes/dtypes "
                      "(static arg / tree structure)")
        return (f"unexpected retrace #{self.n_compiles} of "
                f"{self.name!r} (jit cache now {self.cache_size} "
                f"entries) — changed args: {changed}")


class _WatchState:
    """Per-underlying-function sentinel state, owned by the sentinel and
    SHARED across wrappers: re-watching the same jit (every train_epoch
    call wraps anew) must not grant a fresh compile budget, or a genuine
    epoch-2 retrace would be silently absorbed as 'the first compile'."""

    __slots__ = ("fn", "compiles", "last_sig")

    def __init__(self, fn: Callable):
        self.fn = fn                 # strong ref: pins id(fn) while kept
        self.compiles = 0
        self.last_sig: Optional[dict] = None


class _Watched:
    """Callable wrapper over a jit + its shared sentinel state.

    Delegates every attribute to the wrapped jit (``.lower``,
    ``._cache_size`` — so ``InferenceEngine.compile_stats`` and
    ``dump_graph`` keep working on a watched function).
    """

    def __init__(self, fn: Callable, name: str, expected: int,
                 sentinel: "RecompileSentinel", state: _WatchState):
        self._fn = fn
        self._name = name
        self._expected = expected
        self._sentinel = sentinel
        self._state = state

    def __call__(self, *args, **kwargs):
        fn = self._fn
        st = self._state
        before = fn._cache_size()
        out = fn(*args, **kwargs)
        after = fn._cache_size()
        if after > before:
            st.compiles += 1
            sig = abstract_signature(args, kwargs)
            if st.compiles > self._expected:
                self._sentinel._fire(RecompileEvent(
                    name=self._name, n_compiles=st.compiles,
                    cache_size=after, signature=sig,
                    diff=diff_signatures(st.last_sig, sig)))
            st.last_sig = sig
        return out

    def __getattr__(self, item):
        return getattr(self._fn, item)


class RecompileSentinel:
    """Watches jitted callables for unexpected retraces (see module
    docstring).  One sentinel serves a whole process; events accumulate
    on :attr:`events` whatever the policy."""

    def __init__(self, policy: str | Callable = "warn"):
        if not callable(policy) and policy not in ("warn", "raise",
                                                   "silent"):
            raise ValueError(f"policy must be 'warn', 'raise', 'silent' "
                             f"or callable, got {policy!r}")
        self.policy = policy
        self.events: list[RecompileEvent] = []
        self._states: OrderedDict[int, _WatchState] = OrderedDict()

    def watch(self, fn: Callable, name: str | None = None,
              expected: int = 1) -> Callable:
        """Wrap ``fn``; the first ``expected`` traces are the compile
        budget (1 for a plain jit; 2 for an unroll bundle whose ragged
        tail legitimately recompiles once).  Re-watching the same fn
        (loops re-wrap per epoch/leg) resumes its existing compile
        count rather than re-granting the budget.  Non-jit callables
        (no ``_cache_size``) are returned unwrapped."""
        if hasattr(fn, "_sentinel") and getattr(fn, "_sentinel") is self:
            fn = fn._fn              # re-watching a wrapper: unwrap first
        if not hasattr(fn, "_cache_size"):
            return fn
        state = self._states.get(id(fn))
        if state is None or state.fn is not fn:
            state = self._states[id(fn)] = _WatchState(fn)
        self._states.move_to_end(id(fn))
        while len(self._states) > _MAX_WATCH_STATES:
            self._states.popitem(last=False)
        return _Watched(fn, name or getattr(fn, "__name__", "jit_fn"),
                        expected, self, state)

    def _fire(self, event: RecompileEvent) -> None:
        self.events.append(event)
        if callable(self.policy):
            self.policy(event)
        elif self.policy == "warn":
            _log.warning("%s", event.message())
        elif self.policy == "raise":
            raise RecompileError(event.message())

    def summary(self) -> dict:
        return {"recompile_events": len(self.events),
                "recompiled_fns": sorted({e.name for e in self.events})}


class NullSentinel:
    """Disabled sentinel: watch() is identity, nothing records."""

    events: list = []

    def watch(self, fn: Callable, name: str | None = None,
              expected: int = 1) -> Callable:
        return fn

    def summary(self) -> dict:
        return {}


NULL_SENTINEL = NullSentinel()
