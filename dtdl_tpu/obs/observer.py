"""Observer: the one handle every loop takes for the obs subsystem.

Call sites (train_epoch / Trainer / fit / Estimator / Solver /
serve.Scheduler / bench.py) add ~3 lines each:

    obs = observer or NULL_OBSERVER            # default: all no-ops
    step = obs.watch(step, "train_step")       # recompile sentinel
    with obs.span("dispatch"): ...             # tracer phases
    payload.update(obs.window(steps, secs))    # goodput per drained window

Everything composes with the PR-1 async discipline by construction:
spans time host phases, the sentinel reads jit bookkeeping, the goodput
meter and step-time histogram consume only window numbers the drain
already settled — an Observer can never add a host↔device sync (pinned
by the sync-counting test in tests/test_obs.py).

The default :data:`NULL_OBSERVER` short-circuits every method (shared
nullcontext spans, identity watch, ``{}`` windows), so a loop wired for
observability costs nothing when it is off — bench.py's observability
row keeps the on-vs-off overhead receipt (<2% steps/sec).
"""

from __future__ import annotations

from typing import Callable, Optional

from dtdl_tpu.obs.goodput import GoodputMeter
from dtdl_tpu.obs.hist import LogHistogram
from dtdl_tpu.obs.recompile import (NULL_SENTINEL, RecompileSentinel)
from dtdl_tpu.obs.trace import NULL_TRACER, Tracer


class Observer:
    """Bundles tracer + recompile sentinel + goodput meter + step-time
    histogram behind one object (see module docstring).

    ``trace``: True / a Tracer for span recording (False = off);
    ``sentinel``: a policy string ('warn' / 'raise' / 'silent'), a
    RecompileSentinel, or None (off);
    ``goodput``: a configured GoodputMeter or None;
    ``trace_path``: where :meth:`save` / :meth:`close` write the Chrome
    trace (also enables tracing when ``trace`` was not given).
    """

    enabled = True

    def __init__(self, trace=None, sentinel="warn",
                 goodput: Optional[GoodputMeter] = None,
                 trace_path: Optional[str] = None):
        if isinstance(trace, (Tracer,)):
            self.tracer = trace
        elif trace or (trace is None and trace_path):
            self.tracer = Tracer()
        else:
            self.tracer = NULL_TRACER
        if isinstance(sentinel, RecompileSentinel):
            self.sentinel = sentinel
        elif sentinel:
            self.sentinel = RecompileSentinel(policy=sentinel)
        else:
            self.sentinel = NULL_SENTINEL
        self.goodput = goodput
        self.trace_path = trace_path
        self.step_time_s = LogHistogram()

    # ---- the four verbs ----------------------------------------------

    def span(self, name: str, **args):
        """Host-phase span (context manager); no-op when tracing is off."""
        return self.tracer.span(name, **args)

    def event(self, name: str, **args) -> None:
        """Zero-duration marker on the trace (e.g. the resil guard's
        ``guard_bad_step`` / ``guard_rollback``, the serve scheduler's
        containment events).  Host-side only, like every verb here."""
        self.tracer.instant(name, **args)

    def flow(self, name: str, fid, phase: str = "step",
             **args) -> None:
        """Chrome-trace flow event (start/step/end) joining spans across
        threads under one correlation id — the serve layers call this
        with the USER request rid so a hedged, failed-over request reads
        as one arrow chain in Perfetto.  No-op when tracing is off."""
        self.tracer.flow(name, fid, phase, **args)

    def request_timeline(self, rid) -> list:
        """All recorded events correlated with user request ``rid``,
        ordered (see :meth:`Tracer.request_timeline`)."""
        return self.tracer.request_timeline(rid)

    def watch(self, fn: Callable, name: str | None = None,
              expected: int = 1) -> Callable:
        """Recompile-sentinel wrap (identity for non-jit callables)."""
        return self.sentinel.watch(fn, name, expected=expected)

    def window(self, steps: int, seconds: float, name: str = "device") ->\
            dict:
        """Account one settled window: feeds the step-time histogram and
        the settled-device trace track, returns the goodput fields to
        merge into the window's reporter payload.  Host floats only."""
        if steps <= 0 or seconds <= 0:
            return {}
        self.step_time_s.add(seconds / steps)
        self.tracer.device_window(name, seconds, steps)
        if self.goodput is None:
            return {}
        return self.goodput.window(steps, seconds)

    def summary(self) -> dict:
        """Run-level rollup: step-time tails, goodput totals, sentinel
        events, trace volume."""
        out = dict(self.step_time_s.summary("step_time_s_"))
        if self.goodput is not None:
            out.update(self.goodput.totals())
        out.update(self.sentinel.summary())
        n = len(self.tracer)
        if n:
            out["trace_events"] = n
        return out

    # ---- lifecycle ----------------------------------------------------

    def save(self, path: Optional[str] = None) -> Optional[str]:
        """Write the Chrome trace (to ``path`` or the configured
        ``trace_path``); returns the path written, or None."""
        path = path or self.trace_path
        if not path or self.tracer is NULL_TRACER:
            return None
        return self.tracer.save(path)

    def close(self) -> None:
        self.save()

    def __enter__(self) -> "Observer":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


class _NullObserver(Observer):
    """The default observer: every verb is a no-op (shared instance)."""

    enabled = False

    def __init__(self):
        super().__init__(trace=False, sentinel=None, goodput=None)

    def window(self, steps: int, seconds: float, name: str = "device") ->\
            dict:
        return {}

    def summary(self) -> dict:
        return {}


NULL_OBSERVER = _NullObserver()
