"""Batched LM serving: compiled prefill/decode pair + continuous batcher.

The request path the training stack feeds (ROADMAP north star: serve
heavy traffic): train anywhere (flax/GSPMD or the 4D megatron engine),
bridge to the flax model, and drive it here —

    engine = InferenceEngine(model, params, n_slots=8)
    sched = Scheduler(engine)
    sched.submit(Request(prompt, max_new_tokens=64))
    done = sched.run()

See engine.py (the two-XLA-program contract), scheduler.py (slot-based
continuous batching), sampling.py (per-slot greedy/temperature/top-k/
top-p), metrics.py (async serving telemetry).
"""

from dtdl_tpu.serve.engine import (  # noqa: F401
    InferenceEngine, default_buckets,
)
from dtdl_tpu.serve.metrics import ServeMetrics  # noqa: F401
from dtdl_tpu.serve.sampling import (  # noqa: F401
    GREEDY, SampleParams, sample,
)
from dtdl_tpu.serve.scheduler import Request, Scheduler  # noqa: F401
