"""Batched LM serving: compiled prefill/decode/verify + continuous batcher.

The request path the training stack feeds (ROADMAP north star: serve
heavy traffic): train anywhere (flax/GSPMD or the 4D megatron engine),
bridge to the flax model, and drive it here —

    engine = InferenceEngine(model, params, n_slots=8)
    sched = Scheduler(engine)
    sched.submit(Request(prompt, max_new_tokens=64))
    done = sched.run()

Speculative decoding is one field away — ``Request(..., speculate=4)``
verifies up to 4 drafted tokens per parameter sweep, losslessly
(greedy output is token-identical; sampling is distribution-identical):

    sched = Scheduler(engine, draft=NGramDraft())   # the default source
    sched.submit(Request(prompt, 64, speculate=4))

A **block-paged KV arena** with cross-request prefix caching is one
constructor argument away — pages replace the dense per-slot rows, so a
short request pins only the pages it reaches and identical prompt
prefixes (system prompts) prefill ONCE and are shared read-only:

    engine = InferenceEngine(model, params, n_slots=16,
                             page_size=64, n_pages=256)   # overcommit
    sched = Scheduler(engine)          # prefix_cache=True by default

A **health-checked fleet** of N replicas — least-loaded routing,
circuit-breaker failure detection, deterministic failover/retry,
straggler hedging, and rolling restarts — is one more layer up
(replicas share the engine's compiled programs; greedy retries are
token-identical by determinism):

    router = Router(engine, n_replicas=2)       # thread-hosted replicas
    done = router.run([Request(p, 32) for p in prompts])
    router.shutdown()                           # or `with Router(...)`

**Multi-tenant serving** (round 22, serve/tenant/) rides the same slot
machinery: per-slot LoRA adapters batched inside ONE compiled step
(adapter ids are data gathered from a device-resident bank — no
per-tenant programs), grammar-constrained decoding via token-level DFAs
folded into the sampler as per-slot masks, and incremental token
streaming at the lag-harvest boundaries:

    engine = InferenceEngine(model, params, n_slots=8,
                             lora_rank=8, lora_adapters=4)
    dfa = compile_json_schema(schema, vocab, eos_id=eos)
    sched.submit(Request(p, 64, adapter="ckpts/tenant_a",
                         grammar=dfa, eos_id=eos,
                         stream=TokenStream()))

A **hierarchical KV cache** (round 23) extends the prefix cache past
HBM: evicted refcount-0 cached pages spill through the batched
extract path into a bounded host-DRAM store (and overflow onward to a
checksummed mmap'd disk file), a prefix miss restores them via inject
instead of recomputing, and the Router keeps a fleet-wide chain-hash →
replica prefix directory so warm-prefix traffic routes to the replica
already holding the pages:

    sched = Scheduler(engine, spill_host_bytes=1 << 30,
                      spill_dir="/var/kv", spill_disk_bytes=16 << 30)
    router = Router(engine, n_replicas=2,
                    sched_kwargs=dict(spill_host_bytes=1 << 30))

See engine.py (the compiled-program contract), scheduler.py (slot-based
continuous batching + spec integration), paged.py (page allocator +
radix-style prefix cache), draft.py (draft sources), sampling.py
(per-slot greedy/temperature/top-k/top-p + the accept/resample kernel),
metrics.py (async serving telemetry), health.py (the per-replica state
machine), fleet.py (the Router/Replica fleet layer), tenant/ (batched
multi-LoRA, grammar DFAs, token streams).
"""

from dtdl_tpu.serve.draft import (  # noqa: F401
    DraftSource, ModelDraft, NGramDraft,
)
from dtdl_tpu.serve.engine import (  # noqa: F401
    InferenceEngine, PromptTooLongError, default_buckets,
)
from dtdl_tpu.serve.fleet import (  # noqa: F401
    FleetMetrics, PrefixDirectory, Replica, Router, default_fleet_slos,
)
from dtdl_tpu.serve.health import (  # noqa: F401
    DRAINING, EVICTED, HEALTHY, SUSPECT, ReplicaHealth,
)
from dtdl_tpu.serve.metrics import (  # noqa: F401
    ERROR_KINDS, UNAVAILABLE_KINDS, ServeMetrics, error_kind,
)
from dtdl_tpu.serve.paged import (  # noqa: F401
    GARBAGE_PAGE, DiskPageStore, HostPageStore, PageAllocator,
    PagePoolExhaustedError, SpillCorruptEntryError, page_chain_hashes,
)
from dtdl_tpu.serve.sampling import (  # noqa: F401
    GREEDY, SampleParams, accept_resample, filter_logits,
    filter_logits_sorted, mask_words, pack_mask, sample, unpack_mask,
)
from dtdl_tpu.serve.scheduler import Request, Scheduler  # noqa: F401
from dtdl_tpu.serve.tenant import (  # noqa: F401
    AdapterBank, AdapterBankFullError, TokenDFA, TokenStream,
    adapter_template, byte_vocab, compile_json_schema, compile_regex,
    json_schema_to_regex, merge_adapter,
)
