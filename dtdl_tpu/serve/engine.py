"""Batched LM inference engine: three XLA program families, a slotted KV
arena.

The serving problem on TPU is a *compile-shape* problem: XLA programs are
shape-specialized, so a naive "pad the batch to the longest request and
re-jit per prompt length" serving loop recompiles on every new shape and
stalls every request behind the longest one.  This engine fixes the
shapes once and routes all traffic through a handful of programs per
model (the Orca/vLLM decomposition, rebuilt XLA-native on static shapes):

* ``prefill(params, arena, last, tokens[1, T], length, slot, ...)`` —
  one compiled program per **prompt-length bucket** T (powers of two up
  to ``max_seq``), built lazily on first use and jit-cached forever
  after.  A prompt is right-padded to its bucket, embedded through the
  model's chunked decode path at scalar cache index 0, and its K/V rows
  are scattered into row ``slot`` of the arena.  Pad positions write
  garbage K/V beyond ``length`` — harmless, because a position is only
  ever attended after the decode step that overwrites it (causal mask
  ``<= index``, and the write at ``index`` happens before the attend in
  the same program).  The first output token is sampled in-program from
  the last *real* position's logits (``return_hidden`` + a dtype-matched
  head einsum, the same never-materialize-the-[T, V]-logits discipline
  as ``generate``).
* ``decode(params, arena, last[B], active[B], ...)`` — ONE compiled
  program total: every slot advances one token against its own cache
  row at its own position (the model's vector-index cache path,
  models/transformer.py:_verify_attend_slots at S=1).  Inactive slots
  compute garbage that is masked out of the state (their index does not
  advance); occupancy is a runtime *value*, never a compile shape.
* ``verify(params, arena, last[B], draft[B, k], draft_len[B], ...)`` —
  the THIRD program family, one per draft width k (the scheduler
  buckets k to powers of two, so the family stays as small as the
  prefill one): speculative decoding's verify pass.  One parameter
  sweep scores the slot's last token plus k drafted candidates against
  the KV arena (k+1 query positions through the same vector-index
  path), then per-slot acceptance runs ON DEVICE (exact prefix match
  for greedy rows, one-hot residual rejection sampling otherwise —
  dtdl_tpu/serve/sampling.py:accept_resample), the accepted tokens come
  back as a [B, k+1] window with per-slot counts, and each slot's cache
  index advances by its own *variable* ``n_accepted + 1`` (the index
  leaves are rolled back from the model's +k+1; the stale K/V rows of
  rejected candidates are overwritten before they are ever attended,
  the same discipline as prefill padding).  Decode is HBM-bandwidth
  bound — one token per full parameter read — so verify converts the
  same read into up to k+1 tokens while staying token-losslessly
  equivalent (SCALING.md "Speculative decoding arithmetic").

The **arena** comes in two layouts.  Dense (default): the fixed
[n_slots, H, max_seq, head_dim] per-block K/V buffer pair plus a
per-slot position vector (``cache_shapes(..., per_slot_index=True)``)
— every slot charged max_seq worth of KV bytes up front.  **Paged**
(``page_size > 0``): a fixed pool of [n_pages, H, page_size, head_dim]
pages that per-slot page tables map logical positions onto
(models/transformer.py:_paged_attend_slots), so a slot pins only the
pages its sequence has reached (fragmentation < page_size tokens/slot)
and identical prompt prefixes can SHARE read-only pages across requests
(the scheduler's prefix cache, dtdl_tpu/serve/paged.py) — far more
concurrent slots per HBM byte, and cache-hit prompts skip the shared
prefix's prefill entirely.  Crucially the paged layout reuses the SAME
three program families: page tables and the active mask are plain data
inputs, and a prefix-hit prefill re-enters through the suffix's
(smaller) bucket.  Either arena is donated to every program, so the
cache is updated in place on device — no per-step reallocation of the
largest buffer in serving.  Sampling knobs ride along as per-slot
device arrays (dtdl_tpu/serve/sampling.py), so greedy and nucleus
requests share the same compiled step.

The engine is the functional core: it owns the model, the (unboxed)
params, and the compile caches, and threads ``(arena, last_tokens)``
state the caller owns.  Continuous batching policy — admission, slot
lifecycle, EOS, telemetry — lives in dtdl_tpu/serve/scheduler.py.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from dtdl_tpu.ops.attention import block_table_entry, resolve_blocks
from dtdl_tpu.ops.paged_attention import paged_kernel_enabled
from dtdl_tpu.quant import (Fp8UnsupportedError, canon_kv_dtype,
                            canon_weight_quant, quantize_params, tree_bytes)
from dtdl_tpu.serve.sampling import (FILTER_IMPL, SampleParams,
                                     accept_resample, mask_words, pack,
                                     pack_mask, sample)


class PromptTooLongError(ValueError):
    """A prompt exceeds the largest configured prefill bucket.

    Raised by :meth:`InferenceEngine.bucket_for` BEFORE any prefill
    program is built or traced, with the configured bucket list in the
    message — the scheduler surfaces it as a rejected request
    (``Request.error``) instead of letting one oversized prompt crash a
    run with other requests in flight (dtdl_tpu/serve/scheduler.py).
    """


def default_buckets(max_seq: int, start: int = 16) -> tuple[int, ...]:
    """Power-of-two prompt buckets up to ``max_seq`` (always included):
    each prompt pays at most 2x its own prefill FLOPs in padding, for
    log2(max_seq) compiled prefill programs worst case."""
    out, b = [], start
    while b < max_seq:
        out.append(b)
        b *= 2
    out.append(max_seq)
    return tuple(out)


def _paged_cache(arena, page_table, active, index=None):
    """Insert the per-call data leaves (page tables + active mask, and
    optionally an index override) into every block's attn cache dict of
    a paged arena — the leaves :meth:`Attention._paged_attend_slots`
    reads but the arena does not store (they are inputs, re-supplied by
    the host each dispatch; remapping pages never recompiles)."""
    def conv(tree):
        if isinstance(tree, dict):
            if "pages_key" in tree:
                out = dict(tree, page_table=page_table, active=active)
                if index is not None:
                    out["index"] = index
                return out
            return {k: conv(v) for k, v in tree.items()}
        return tree
    return conv(arena)


def _dense_index(arena, index):
    """Override every block's per-slot ``index`` leaf of a DENSE arena
    with the given [n_slots] vector — the chunked-prefill hook (round
    19): a prefill chunk's cache position is host-deterministic, so the
    verify program takes it as DATA (``pos_set``) instead of trusting a
    freed slot's stale index leaf.  Non-forced slots are passed their
    own arena value back, so the override is the identity for them."""
    def conv(tree):
        if isinstance(tree, dict):
            if "key" in tree and "index" in tree:
                return dict(tree, index=index)
            return {k: conv(v) for k, v in tree.items()}
        return tree
    return conv(arena)


def _strip_paged(cache):
    """Drop the per-call leaves back out of a mutated paged cache so the
    returned arena keeps the stable pool+index structure."""
    def conv(tree):
        if isinstance(tree, dict):
            if "pages_key" in tree:
                return {k: v for k, v in tree.items()
                        if k not in ("page_table", "active")}
            return {k: conv(v) for k, v in tree.items()}
        return tree
    return conv(cache)


def _lora_vars(bank, aids):
    """Insert the per-call adapter-id vector into every attention node
    of the LoRA bank tree — the 'lora' collection leaf
    :class:`~dtdl_tpu.models.transformer.Attention` gathers its
    adapter rows by (round 22).  Same per-call-data pattern as
    :func:`_paged_cache`: adapter ids are inputs, never shapes."""
    def conv(tree):
        if isinstance(tree, dict):
            if "q_a" in tree:
                return dict(tree, aid=aids)
            return {k: conv(v) for k, v in tree.items()}
        return tree
    return conv(bank)


class InferenceEngine:
    """Compiled prefill/decode pair over a slotted KV arena (see module
    docstring).  ``n_slots`` is the decode batch width — the one shape
    the decode program is specialized to.

    ``page_size > 0`` switches the arena to the **block-paged** layout:
    instead of ``[n_slots, max_seq]`` K/V rows, a pool of ``n_pages``
    pages of ``page_size`` tokens each (page 0 reserved as the garbage
    page) that per-slot page tables map logical positions onto.  The
    SAME three program families serve both layouts — page tables and
    the active mask enter decode/verify as plain int32/bool inputs, and
    prefill takes the slot's table row plus a ``start`` offset (the
    prefix-cached token count), so a prefix-cache hit re-enters through
    a *smaller suffix bucket* instead of a new program.  ``n_pages``
    defaults to dense-equivalent capacity
    (``n_slots * max_seq / page_size + 1``); undersizing it overcommits
    HBM and shifts admission to the scheduler's page accounting
    (dtdl_tpu/serve/paged.py).

    **Quantized serving** (dtdl_tpu/quant) is two more kwargs.
    ``quantize_weights=True`` swaps the model for its
    ``clone(quantize=True)`` (int8 kernels, dequant fused into every
    matmul) and converts the given f32/bf16 params through
    ``quant.quantize_params`` at construction — decode's per-token
    parameter read drops to one byte per weight.  ``kv_dtype='int8'``
    builds the int8+scales arena variant (dense or paged), halving
    K/V bytes vs bf16 (quartering vs f32) with quantize-on-scatter /
    dequant-on-gather folded into the attention programs.  Both ride
    the SAME three program families — quantization is weights+arena
    layout, never a new compile shape — and ``compile_stats()['quant']``
    carries the exact byte receipts.  For paged arenas,
    ``kv_pool_bytes`` sizes ``n_pages`` from an HBM byte budget
    instead: at a fixed budget an int8 pool holds ~2x the pages of a
    bf16 one (~4x an f32 one) — the slots-per-HBM-byte win.

    **Kernel round 2** adds the fp8 variants through the same kwargs —
    ``quantize_weights='w8f'`` (float8_e4m3fn kernels, bf16 scales) and
    ``kv_dtype='fp8'`` (fp8 pools, bf16 write-once scale sidecars) —
    and ``paged_kernel=`` ('auto' default: on TPU, paged decode/verify
    attend through the Pallas paged-attention kernel in
    dtdl_tpu/ops/paged_attention.py — page-table walk inside the
    kernel, page-granular DMA, dequant folded into the tile loads;
    elsewhere the round-6 gather path.  ``True`` forces the kernel —
    on CPU that means the Pallas interpreter, tests only).  Unsupported
    fp8 combinations refuse by NAME at construction
    (quant.Fp8UnsupportedError), never inside a traced program."""

    def __init__(self, model, params, n_slots: int = 8, buckets=None,
                 observer=None, page_size: int = 0,
                 n_pages: int | None = None,
                 quantize_weights=False, kv_dtype=None,
                 kv_pool_bytes: int | None = None, paged_kernel="auto",
                 mesh=None, rules="tp", lora_rank: int = 0,
                 lora_adapters: int = 0):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        if lora_rank < 0 or lora_adapters < 0:
            raise ValueError("lora_rank/lora_adapters must be >= 0")
        if bool(lora_rank) != bool(lora_adapters):
            raise ValueError("pass lora_rank AND lora_adapters together "
                             "(both 0 disables the adapter bank)")
        if lora_adapters == 1:
            raise ValueError("lora_adapters must be >= 2: row 0 is the "
                             "reserved all-zeros base adapter")
        # canonicalization raises the NAMED fp8 errors here, at
        # construction (Fp8UnsupportedError on builds without
        # float8_e4m3fn), never from inside a traced program
        self.weight_mode = canon_weight_quant(quantize_weights)
        self.quantized_weights = self.weight_mode
        self.kv_dtype = canon_kv_dtype(kv_dtype)
        if self.weight_mode == "w8f" and mesh is not None \
                and not isinstance(rules, str):
            raise Fp8UnsupportedError(
                "fp8 weights (quantize_weights='w8f') under a mesh need "
                "a NAMED rule preset (parallel/tensor.py RULE_PRESETS): "
                "the quant rule map derives fp8 kernel+scale specs from "
                "the f32 twin per preset; got a raw rules sequence")
        # kernel round 2: resolve the paged-attention kernel flag ONCE
        # ('auto' -> TPU only; True forces the interpreter on CPU) and
        # bake it into the model as a static field — same three program
        # families, the kernel only changes what decode/verify contain
        self._paged_kernel_flag = paged_kernel
        self.paged_kernel = (paged_kernel_enabled(paged_kernel)
                             and page_size > 0)
        if self.paged_kernel:
            model = model.clone(paged_kernel=True)
        if self.weight_mode:
            # params are the UNQUANTIZED tree the caller trained/loaded;
            # the quantized clone declares the payload+scale schema
            # (int8+f32 or fp8+bf16).  On a mesh, the quant-aware rule
            # map below (round 20) shards the quantized kernels on their
            # f32 twins' logical axes and each _scale sibling alongside
            # its tensor.
            params = quantize_params(model, params, self.weight_mode)
            model = model.clone(quantize=self.weight_mode)
        self.model = model
        self.params = nn.unbox(params)   # plain leaves either way
        # tensor-parallel serving proper (round 19, ROADMAP item 3): a
        # mesh plus a parallel/tensor.py rule preset shards the params
        # (flax logical axes -> mesh axes via logical_shardings) and the
        # KV arena (heads dim on the TP axis) — the engine's jitted
        # programs then run under GSPMD on that mesh, with XLA inserting
        # the Megatron collectives.  A serving engine no longer needs
        # the 4D training mesh: megatron.serve_engine is a thin caller.
        self.mesh = mesh
        self.rules = rules if mesh is not None else None
        self._arena_sh = None
        if mesh is not None:
            import functools

            from dtdl_tpu.parallel.tensor import (heads_axis_size,
                                                  logical_shardings,
                                                  quant_logical_shardings)
            tp = heads_axis_size(mesh, rules)
            if self.model.n_heads % tp:
                raise ValueError(
                    f"n_heads={self.model.n_heads} must divide by the "
                    f"mesh's tensor-parallel axis size {tp} "
                    f"(rules={rules!r})")
            if self.weight_mode:
                # the quantized tree carries no flax logical metadata;
                # the quant rule map derives quantized-kernel + scale
                # specs from the f32 twin (parallel/tensor.py, round
                # 20; mode-aware since kernel round 2 — fp8 leaves
                # shard exactly like their int8 counterparts)
                param_sh = quant_logical_shardings(mesh, self.model,
                                                   rules,
                                                   mode=self.weight_mode)
            else:
                abs_boxed = jax.eval_shape(
                    functools.partial(self.model.init,
                                      jax.random.PRNGKey(0)),
                    jnp.zeros((1, 1), jnp.int32))["params"]
                param_sh = logical_shardings(mesh, abs_boxed, rules)
            self.params = jax.device_put(self.params, param_sh)
        # batched multi-LoRA (round 22): a device-resident adapter bank
        # whose rows per-slot int32 ids gather INSIDE the compiled
        # steps (models/transformer.py) — adapter identity is data, so
        # a mixed-adapter batch rides the same three program families.
        # Row 0 stays all-zeros (the base model); the host registry
        # hot-loads/evicts rows through the manifest-integrity
        # checkpoint path (dtdl_tpu/serve/tenant/lora.py).
        self.lora_rank = lora_rank
        self.lora_adapters = lora_adapters
        self.adapter_bank = None
        if lora_rank:
            from dtdl_tpu.serve.tenant.lora import (AdapterBank,
                                                    adapter_template,
                                                    bank_pspecs,
                                                    init_bank)
            bank = init_bank(self.params, lora_rank, lora_adapters)
            if mesh is not None:
                from jax.sharding import NamedSharding
                bank = jax.tree.map(
                    lambda l, s: jax.device_put(
                        l, NamedSharding(mesh, s)),
                    bank, bank_pspecs(bank))
            self.adapter_bank = AdapterBank(
                bank, adapter_template(self.params, lora_rank),
                observer=observer)
        # neutral per-call tenant inputs, allocated once: the all-zeros
        # adapter-id vector and all-true grammar masks keep every
        # unconstrained dispatch bit-identical to the pre-tenant
        # programs WITHOUT re-uploading per-step arrays.  Masks travel
        # PACKED (round 23): uint32 bitset words, ceil(V/32) per row —
        # 8x fewer host->device bytes than the dense [*, V] bools, which
        # the programs expand on device (sampling.unpack_mask).  Every
        # dispatch packs, so the compiled signature is always uint32 and
        # constrained/unconstrained traffic share one program.
        self._zero_aids = jnp.zeros((n_slots,), jnp.int32)
        self._mask_words = mask_words(model.vocab_size)
        _full = np.uint32(0xFFFFFFFF)
        self._ones_decode = jnp.full((n_slots, self._mask_words), _full,
                                     jnp.uint32)
        self._ones_prefill = jnp.full((1, self._mask_words), _full,
                                      jnp.uint32)
        self._ones_verify: dict[int, object] = {}
        # obs facade: when set (directly or by the Scheduler), the
        # recompile sentinel wraps each compiled program — a retrace of
        # the decode program or a re-trace of an already-built prefill
        # bucket is exactly the serving bug the _cache_size tests pin
        self.observer = observer
        self.n_slots = n_slots
        self.max_seq = model.max_seq
        self.buckets = (tuple(sorted(set(buckets))) if buckets
                        else default_buckets(model.max_seq))
        if self.buckets[-1] > model.max_seq:
            raise ValueError(f"bucket {self.buckets[-1]} exceeds "
                             f"max_seq={model.max_seq}")
        self.paged = page_size > 0
        self.page_size = page_size
        self.page_bytes = 0
        if self.paged:
            if model.max_seq % page_size:
                raise ValueError(f"page_size={page_size} must divide "
                                 f"max_seq={model.max_seq}")
            self.n_ptab = model.max_seq // page_size
            # bytes ONE page pair costs across all blocks (K/V pages
            # plus, for int8, their scale rows) — the pool-sizing and
            # capacity-receipt arithmetic
            self.page_bytes = (
                tree_bytes(model.paged_cache_shapes(
                    1, 3, page_size, self.kv_dtype))
                - tree_bytes(model.paged_cache_shapes(
                    1, 2, page_size, self.kv_dtype)))
            if kv_pool_bytes is not None:
                if n_pages is not None:
                    raise ValueError("pass n_pages or kv_pool_bytes, "
                                     "not both")
                # fixed HBM budget -> as many pages as it holds (the
                # garbage page is part of the pool, so no +1); a
                # budget below the 2-page floor raises like every
                # other undersized geometry instead of silently
                # allocating past the caller's stated bytes
                n_pages = kv_pool_bytes // self.page_bytes
                if n_pages < 2:
                    raise ValueError(
                        f"kv_pool_bytes={kv_pool_bytes} holds "
                        f"{n_pages} pages of {self.page_bytes} bytes; "
                        f"the pool needs >= 2 (garbage page + one "
                        f"live page)")
            self.n_pages = (n_pages if n_pages is not None
                            else n_slots * self.n_ptab + 1)
            if self.n_pages < 2:
                raise ValueError(f"n_pages must be >= 2, got "
                                 f"{self.n_pages}")
        else:
            if n_pages is not None:
                raise ValueError("n_pages requires page_size > 0")
            if kv_pool_bytes is not None:
                raise ValueError("kv_pool_bytes requires page_size > 0")
            self.n_ptab = 0
            self.n_pages = 0
        # single-row cache template the dense prefill program zero-fills
        self._cache1 = model.cache_shapes(1, kv_dtype=self.kv_dtype)
        self._prefill_fns: dict[int, object] = {}
        self._decode_fn = None
        self._verify_fns: dict[int, object] = {}
        # prefill/decode disaggregation (round 19): the page-granular
        # KV handoff pair — one gather program (export a slot's prompt
        # pages to host) and one scatter program (adopt them into this
        # engine's pool + seed the slot's index/last) — both fixed
        # [pages_per_slot] shapes, so a fleet's handoffs never recompile
        self._extract_fn = None
        self._inject_fn = None
        # dispatch counters (NOT in compile_stats, which must stay
        # constant across calls): prefill invocations per bucket — the
        # FLOP receipt prefix-cache tests read, since prefill compute
        # is proportional to sum(bucket * calls)
        self.prefill_calls: dict[int, int] = {}

    # ---- state the caller threads ------------------------------------

    def init_arena(self):
        """Fresh zeroed KV arena (donated to every program): dense
        [n_slots, max_seq] rows, or the paged pool + per-slot indices.
        On a TP mesh the K/V leaves come back sharded heads-on-'model'
        (parallel/tensor.py:serve_arena_shardings), so the compiled
        programs inherit the tensor-parallel layout from their inputs."""
        if self.mesh is not None:
            if self._arena_sh is None:
                from dtdl_tpu.parallel.tensor import serve_arena_shardings
                self._arena_sh = serve_arena_shardings(
                    self.mesh, self.arena_shapes(), self.rules)
            return jax.tree.map(
                lambda s, sh: jax.device_put(
                    jnp.zeros(s.shape, s.dtype), sh),
                self.arena_shapes(), self._arena_sh)
        return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                            self.arena_shapes())

    def init_last_tokens(self):
        """The [n_slots] last-sampled-token vector (NOT donated: the
        scheduler's lag harvest holds references to past vectors)."""
        last = jnp.zeros((self.n_slots,), jnp.int32)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            last = jax.device_put(
                last, NamedSharding(self.mesh, PartitionSpec()))
        return last

    # ---- bucketing ----------------------------------------------------

    def bucket_for(self, length: int) -> int:
        for b in self.buckets:
            if length <= b:
                return b
        raise PromptTooLongError(
            f"prompt length {length} exceeds the largest prefill bucket "
            f"{self.buckets[-1]} (buckets={self.buckets}, "
            f"max_seq={self.max_seq})")

    # ---- compiled programs -------------------------------------------

    def _build_prefill(self, T: int):
        model, cache1 = self.model, self._cache1
        use_lora = self.lora_rank > 0

        def prefill(params, arena, last, tokens, length, slot, key,
                    temp, top_k, top_p, allowed, aid, lora):
            cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                                 cache1)
            variables = {"params": params, "cache": cache}
            if use_lora:
                variables["lora"] = _lora_vars(lora, aid[None])
            hidden, muts = model.apply(
                variables, tokens, decode=True,
                return_hidden=True, mutable=["cache"])
            # logits of the last REAL position only (pad rows beyond
            # `length` never touch the head)
            h_last = jax.lax.dynamic_slice_in_dim(
                hidden, length - 1, 1, axis=1)[:, 0]           # [1, D]
            logits = jnp.einsum(
                "bd,vd->bv", h_last,
                params["embed"].astype(model.dtype)).astype(jnp.float32)
            tok = sample(logits, key, temp, top_k, top_p,
                         allowed=allowed)                      # [1]

            def write(a, n):
                if n.ndim == 0:   # index leaf: the true prompt length,
                    return jax.lax.dynamic_update_slice(   # not bucket T
                        a, length[None].astype(a.dtype), (slot,))
                # K/V buffers [1,H,S,D] and (int8 arenas) their scale
                # rows [1,H,S] land in arena row `slot`
                return jax.lax.dynamic_update_slice(
                    a, n.astype(a.dtype), (slot,) + (0,) * (n.ndim - 1))
            arena = jax.tree.map(write, arena, muts["cache"])
            last = jax.lax.dynamic_update_slice(last, tok, (slot,))
            return arena, last, logits[0]

        return jax.jit(prefill, donate_argnums=(1,))

    def _build_prefill_paged(self, T: int):
        model = self.model
        use_lora = self.lora_rank > 0

        def prefill(params, arena, last, tokens, length, slot, start,
                    page_row, key, temp, top_k, top_p, allowed, aid,
                    lora):
            # a single-row paged view over the SHARED (donated) pool:
            # the slot's table row, index at `start` (= the number of
            # prefix-cached tokens already resident in shared pages) —
            # the suffix attends the cached prefix through the same
            # gather path decode uses, which is what makes a prefix hit
            # a smaller-bucket prefill instead of a new program family
            cache = _paged_cache(arena, page_row[None],
                                 jnp.ones((1,), bool),
                                 index=start[None])
            variables = {"params": params, "cache": cache}
            if use_lora:
                variables["lora"] = _lora_vars(lora, aid[None])
            hidden, muts = model.apply(
                variables, tokens, decode=True,
                return_hidden=True, mutable=["cache"])
            # logits of the last REAL suffix position only
            h_last = jax.lax.dynamic_slice_in_dim(
                hidden, length - 1, 1, axis=1)[:, 0]           # [1, D]
            logits = jnp.einsum(
                "bd,vd->bv", h_last,
                params["embed"].astype(model.dtype)).astype(jnp.float32)
            tok = sample(logits, key, temp, top_k, top_p,
                         allowed=allowed)                      # [1]
            new_cache = _strip_paged(muts["cache"])

            def write(a, n):
                if a.ndim == 1:   # [n_slots] index: start + true length
                    return jax.lax.dynamic_update_slice(
                        a, (start + length)[None].astype(a.dtype),
                        (slot,))
                return n          # the pool, updated through the table
            arena = jax.tree.map(write, arena, new_cache)
            last = jax.lax.dynamic_update_slice(last, tok, (slot,))
            return arena, last, logits[0]

        return jax.jit(prefill, donate_argnums=(1,))

    def _build_decode(self):
        model, paged = self.model, self.paged
        use_lora = self.lora_rank > 0

        def decode(params, arena, last, active, tables, key, temp,
                   top_k, top_p, allowed, aids, lora):
            cache = (_paged_cache(arena, tables, active) if paged
                     else arena)
            variables = {"params": params, "cache": cache}
            if use_lora:
                variables["lora"] = _lora_vars(lora, aids)
            logits, muts = model.apply(
                variables, last[:, None],
                decode=True, mutable=["cache"])
            new_cache = (_strip_paged(muts["cache"]) if paged
                         else muts["cache"])

            def fix(old, new):
                if old.ndim == 1:   # index: only active slots advance
                    return jnp.where(active, new, old)
                return new          # garbage K/V writes into dead slots
            arena = jax.tree.map(fix, arena, new_cache)  # (paged: routed
            # to the garbage page inside the model, never a live page)

            lg = logits[:, 0].astype(jnp.float32)              # [B, V]
            tok = sample(lg, key, temp, top_k, top_p, allowed=allowed)
            last = jnp.where(active, tok, last)
            return arena, last, lg

        return jax.jit(decode, donate_argnums=(1,))

    def _build_verify(self, k: int):
        model, paged = self.model, self.paged
        use_lora = self.lora_rank > 0

        def verify(params, arena, last, draft, draft_len, active,
                   forced, first_tok, pos_set, tables, key, temp,
                   top_k, top_p, allowed, aids, lora):
            # the slots' pre-step cache positions: every block's index
            # leaf carries the same per-slot values, take the first.
            # Chunked-prefill rows (forced) take their position from
            # pos_set instead — the prefill cursor is host truth, and a
            # freshly-admitted slot's arena index leaf is the previous
            # occupant's stale value
            pos = next(l for l in jax.tree.leaves(arena) if l.ndim == 1)
            pos = jnp.where(forced, pos_set, pos)
            cache = (_paged_cache(arena, tables, active, index=pos)
                     if paged else _dense_index(arena, pos))
            # forced rows feed their chunk's first token in place of the
            # last sampled one: x = the k+1-token window written at
            # pos..pos+k (prompt chunk for forced rows, last+drafts for
            # speculative ones — same program, per-slot data)
            x0 = jnp.where(forced, first_tok, last)
            x = jnp.concatenate([x0[:, None], draft], axis=1)  # [B,k+1]
            variables = {"params": params, "cache": cache}
            if use_lora:
                variables["lora"] = _lora_vars(lora, aids)
            logits, muts = model.apply(
                variables, x, decode=True,
                mutable=["cache"])
            new_cache = (_strip_paged(muts["cache"]) if paged
                         else muts["cache"])
            tokens, n_acc = accept_resample(
                logits.astype(jnp.float32), draft, draft_len, key,
                temp, top_k, top_p, forced=forced, allowed=allowed)
            n_em = n_acc + 1

            def fix(old, new):
                if old.ndim == 1:
                    # roll the index back from the model's +k+1 to the
                    # committed n_accepted+1; inactive slots stay put
                    return jnp.where(active, pos + n_em, old)
                return new      # garbage K/V past the committed index is
            arena = jax.tree.map(fix, arena, new_cache)  # overwritten
            # before it is attended (see module docstring)
            new_last = jnp.take_along_axis(
                tokens, n_acc[:, None], axis=1)[:, 0]
            last = jnp.where(active, new_last, last)
            tokens = jnp.where(active[:, None], tokens, 0)
            n_em = jnp.where(active, n_em, 0)
            return arena, last, tokens, n_em

        return jax.jit(verify, donate_argnums=(1,))

    def arena_shapes(self):
        """Abstract pytree of the engine's KV arena (no allocation)."""
        if self.paged:
            return self.model.paged_cache_shapes(
                self.n_slots, self.n_pages, self.page_size,
                self.kv_dtype)
        return self.model.cache_shapes(self.n_slots,
                                       per_slot_index=True,
                                       kv_dtype=self.kv_dtype)

    def compile_stats(self) -> dict:
        """Compiled-program counts — the no-per-request-recompile
        receipt: one entry per touched prefill bucket, one per touched
        verify draft-width bucket, one decode program, each with a jit
        cache size that must stay 1.  ``paged`` carries the arena
        layout (None = dense; else page geometry): the SAME program
        families serve both layouts, so a paged engine's receipt is the
        same shape as a dense one's — page tables are data, not shapes.
        (Per-call occupancy — pages_in_use, prefix hit rates — is
        scheduler state, reported by ServeMetrics; this dict stays
        constant across calls so receipts can be compared.)

        ``kernels`` is the kernel-configuration receipt (round 13):
        which attention block-table entry the model's (head_dim,
        max_seq) geometry resolves to — ``explicit`` must be True for
        every shipped preset (no silent fallback; the autotune table in
        dtdl_tpu/ops/attention.py is the single source of tile shapes)
        — and which sampling implementation the decode/verify programs
        fold in (``sortless`` = the threshold-bisection hot path).

        ``quant`` is the BYTE receipt of the quantization layer
        (SCALING.md "Quantized serving arithmetic"): ``param_bytes``
        (what every decode step re-reads), the arena split into K/V
        payload vs int8 scale sidecars, and
        ``decode_hbm_bytes_per_token`` — the full-occupancy
        bandwidth-model upper bound ``(param_bytes + kv_arena_bytes) /
        n_slots``, i.e. the numerator of the serving-latency roofline;
        shrinking it IS the TPU decode speedup."""
        def n(f):
            try:
                return f._cache_size()
            except AttributeError:   # pragma: no cover - jax internals
                return -1
        payload = scales = 0
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                self.arena_shapes())[0]:
            name = path[-1].key
            nbytes = (int(np.prod(leaf.shape))
                      * np.dtype(leaf.dtype).itemsize)
            if name.endswith("_scale"):
                scales += nbytes
            elif name != "index":
                payload += nbytes
        param_bytes = tree_bytes(self.params)
        hd = self.model.head_dim
        entry = block_table_entry(hd, self.max_seq, causal=True)
        # resolve through the same path the kernels use, so a retuned
        # table/default shows up here without touching this call site
        blocks = resolve_blocks(hd, self.max_seq, causal=True)
        return {"prefill": {T: n(f) for T, f in self._prefill_fns.items()},
                # disaggregation handoff pair (round 19): at most one
                # compiled program each, whatever the migration traffic
                "handoff": {
                    "extract": n(self._extract_fn)
                    if self._extract_fn else 0,
                    "inject": n(self._inject_fn)
                    if self._inject_fn else 0,
                },
                # tensor-parallel geometry (round 19): constant config,
                # None on a single-chip engine
                "tp": ({"rules": self.rules,
                        "mesh": dict(self.mesh.shape)}
                       if self.mesh is not None else None),
                "kernels": {
                    "attention_blocks": {
                        "head_dim": hd, "max_seq": self.max_seq,
                        "block_q": blocks[0], "block_k": blocks[1],
                        "explicit": entry is not None,
                    },
                    "sampling": FILTER_IMPL,
                    # kernel round 2: whether decode/verify attend
                    # through the Pallas paged kernel (page-granular
                    # DMA, scale fusion in the tile loads) instead of
                    # the whole-pool gather — same program families
                    # either way, so this is config, not a count
                    "paged_attention": {
                        "requested": self._paged_kernel_flag,
                        "enabled": self.paged_kernel,
                        "page_size": self.page_size,
                        "fused_scales": self.kv_dtype is not None,
                    },
                },
                "decode": n(self._decode_fn) if self._decode_fn else 0,
                "verify": {k: n(f) for k, f in self._verify_fns.items()},
                "paged": ({"page_size": self.page_size,
                           "n_pages": self.n_pages,
                           "pages_per_slot": self.n_ptab,
                           "page_bytes": self.page_bytes}
                          if self.paged else None),
                # multi-LoRA geometry (round 22): constant config — the
                # bank is a fixed [n_adapters, ...] allocation whatever
                # the load/evict traffic, and adapter ids are data, so
                # a LoRA engine's program counts above are unchanged
                "lora": ({"rank": self.lora_rank,
                          "n_adapters": self.lora_adapters,
                          "bank_bytes": tree_bytes(
                              self.adapter_bank.bank)}
                         if self.lora_rank else None),
                "quant": {
                    "weights": self.quantized_weights,
                    "kv_dtype": (None if self.kv_dtype is None
                                 else "int8"
                                 if self.kv_dtype == jnp.int8
                                 else "fp8"),
                    "param_bytes": param_bytes,
                    "kv_payload_bytes": payload,
                    "kv_scale_bytes": scales,
                    "kv_arena_bytes": payload + scales,
                    "decode_hbm_bytes_per_token": round(
                        (param_bytes + payload + scales)
                        / self.n_slots),
                }}

    # ---- the two entry points ----------------------------------------

    def _lora_args(self, adapter_ids, scalar: bool = False):
        """Normalize the per-call adapter ids + bank pair: the cached
        zero vector (base adapter everywhere) and the live bank tree
        for LoRA engines; unused scalar placeholders otherwise."""
        if self.lora_rank:
            if adapter_ids is None:
                aids = (jnp.zeros((), jnp.int32) if scalar
                        else self._zero_aids)
            else:
                aids = jnp.asarray(adapter_ids, jnp.int32)
            return aids, self.adapter_bank.bank
        if adapter_ids is not None:
            raise ValueError("adapter ids require an adapter bank "
                             "(lora_rank/lora_adapters > 0)")
        return jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32)

    def prefill(self, arena, last_tokens, slot: int, prompt,
                sampling: SampleParams = SampleParams(), key=None,
                page_row=None, start: int = 0, adapter_id=None,
                allowed=None):
        """Admit ``prompt`` into arena row ``slot``; returns the updated
        ``(arena, last_tokens, logits[V])`` — ``last_tokens[slot]`` is
        the request's first sampled token.

        Paged engines take two extras: ``page_row`` — the slot's
        [pages_per_slot] int32 page table row (prefix-cache-hit pages
        first, freshly allocated pages for the rest of the prompt,
        garbage-page 0 beyond) — and ``start``, the number of
        prefix-cached tokens already resident in shared pages
        (page-aligned).  ``prompt`` is then only the UNCACHED suffix:
        the program re-enters through the suffix's (smaller) bucket,
        which is exactly the prefill-FLOPs-skipped win a cache hit
        buys (see ``prefill_calls``)."""
        # audit: ok[host-sync-asarray] admission-time conversion of the caller's host prompt list
        prompt = np.asarray(prompt, np.int32).ravel()
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if not 0 <= slot < self.n_slots:
            raise ValueError(f"slot {slot} out of range "
                             f"[0, {self.n_slots})")
        if self.paged:
            if page_row is None:
                raise ValueError("paged engine prefill needs the slot's "
                                 "page_row (see Scheduler)")
            if start % self.page_size or start < 0:
                raise ValueError(f"start={start} must be a non-negative "
                                 f"multiple of page_size="
                                 f"{self.page_size}")
            # audit: ok[host-sync-asarray] admission-time conversion of the caller's host page_row
            page_row = np.asarray(page_row, np.int32).ravel()
            if page_row.size != self.n_ptab:
                raise ValueError(f"page_row must have {self.n_ptab} "
                                 f"entries, got {page_row.size}")
        elif page_row is not None or start:
            raise ValueError("page_row/start require a paged engine "
                             "(page_size > 0)")
        if start + prompt.size > self.max_seq:
            raise ValueError(f"prompt length {start + prompt.size} "
                             f"exceeds max_seq={self.max_seq}")
        T = self.bucket_for(prompt.size)
        if start + T > self.max_seq:
            # the PADDED window must fit too: the kernel clamps pos to
            # max_seq - T, so an overshooting bucket would silently
            # shift the whole write window backward over cached prefix
            # pages.  The scheduler caps prefix hits so this never
            # fires (_admit); reaching it means a caller supplied its
            # own too-large start.
            raise ValueError(
                f"prefix start {start} + padded bucket {T} exceeds "
                f"max_seq={self.max_seq}; map fewer prefix pages so "
                f"the suffix bucket fits")
        if T not in self._prefill_fns:
            fn = (self._build_prefill_paged(T) if self.paged
                  else self._build_prefill(T))
            if self.observer is not None:
                fn = self.observer.watch(fn, f"serve.prefill[{T}]")
            self._prefill_fns[T] = fn
        self.prefill_calls[T] = self.prefill_calls.get(T, 0) + 1
        padded = np.zeros((1, T), np.int32)
        padded[0, :prompt.size] = prompt
        key = jax.random.PRNGKey(0) if key is None else key
        aid, lora = self._lora_args(adapter_id, scalar=True)
        allowed = (self._ones_prefill if allowed is None
                   else jnp.asarray(pack_mask(allowed)))
        if self.paged:
            arena, last, logits = self._prefill_fns[T](
                self.params, arena, last_tokens, jnp.asarray(padded),
                jnp.asarray(prompt.size, jnp.int32),
                jnp.asarray(slot, jnp.int32),
                jnp.asarray(start, jnp.int32), jnp.asarray(page_row),
                key, *pack([sampling]), allowed, aid, lora)
        else:
            arena, last, logits = self._prefill_fns[T](
                self.params, arena, last_tokens, jnp.asarray(padded),
                jnp.asarray(prompt.size, jnp.int32),
                jnp.asarray(slot, jnp.int32), key, *pack([sampling]),
                allowed, aid, lora)
        return arena, last, logits

    def _tables_arg(self, page_tables):
        """Validate/normalize the decode/verify page-tables input: the
        [n_slots, pages_per_slot] int32 map for paged engines, a scalar
        placeholder (unused in the trace) for dense ones."""
        if not self.paged:
            if page_tables is not None:
                raise ValueError("page_tables require a paged engine")
            return jnp.zeros((), jnp.int32)
        if page_tables is None:
            raise ValueError("paged engine needs page_tables (see "
                             "Scheduler)")
        page_tables = jnp.asarray(page_tables, jnp.int32)
        if page_tables.shape != (self.n_slots, self.n_ptab):
            raise ValueError(f"page_tables must be [{self.n_slots}, "
                             f"{self.n_ptab}], got {page_tables.shape}")
        return page_tables

    def decode(self, arena, last_tokens, active, key, temp, top_k,
               top_p, page_tables=None, adapter_ids=None, allowed=None):
        """One token for every active slot; ``active`` is a [n_slots]
        bool mask (a runtime value — occupancy never recompiles).
        Paged engines additionally take the [n_slots, pages_per_slot]
        ``page_tables`` (data, re-supplied each call — remapping never
        recompiles).  Returns ``(arena, last_tokens,
        logits[n_slots, V])``."""
        if self._decode_fn is None:
            fn = self._build_decode()
            if self.observer is not None:
                fn = self.observer.watch(fn, "serve.decode")
            self._decode_fn = fn
        aids, lora = self._lora_args(adapter_ids)
        allowed = (self._ones_decode if allowed is None
                   else jnp.asarray(pack_mask(allowed)))
        return self._decode_fn(self.params, arena, last_tokens,
                               jnp.asarray(active),
                               self._tables_arg(page_tables), key,
                               temp, top_k, top_p, allowed, aids, lora)

    def verify(self, arena, last_tokens, draft_tokens, draft_len, active,
               key, temp, top_k, top_p, page_tables=None, forced=None,
               first_tok=None, pos_set=None, adapter_ids=None,
               allowed=None):
        """One speculative verify pass over every slot: score each slot's
        ``draft_len[b]`` candidate tokens (``draft_tokens[b, :]``, zero-
        padded to the program's width k) in one parameter sweep, accept a
        prefix on device, advance each slot's cache index by its own
        ``n_accepted + 1``.  Returns ``(arena, last_tokens,
        tokens[n_slots, k+1], n_emitted[n_slots])`` — ``tokens[b,
        :n_emitted[b]]`` is what slot b emitted this step (its last entry
        is the new ``last_tokens[b]``), inactive slots emit 0 tokens.

        **Chunked prefill rides this same program** (round 19): a row
        with ``forced[b]`` True is a prompt chunk, not a speculation —
        its window is ``first_tok[b]`` plus ``draft_len[b]`` further
        prompt tokens in ``draft_tokens[b]``, written at the
        host-supplied cache position ``pos_set[b]`` (the prefill cursor;
        a freed slot's arena index leaf is stale), accepted
        unconditionally (``n_emitted = draft_len + 1``), with the bonus
        token sampled from the last chunk position's target distribution
        — on the prompt's final chunk that IS the request's first
        generated token, from the same distribution whole-prompt prefill
        samples.  Decode steps, speculative verifies and prefill chunks
        therefore share ONE compiled step per width bucket: all three
        are per-slot data on the same program.  Omitting the three
        kwargs (or passing None) is exactly the pre-round-19 verify.

        The caller must guarantee every active slot has room for the
        full write window: ``index[b] + k + 1 <= max_seq`` (the
        scheduler settles worst-case indices before dispatch; a clamped
        scatter would corrupt live cache rows — for a forced row it
        would shift the window backward over its own already-written
        prompt positions).  ``k`` is a compile shape — one compiled
        program per draft width, see :meth:`compile_stats`.
        """
        draft_tokens = jnp.asarray(draft_tokens, jnp.int32)
        if draft_tokens.ndim != 2 or draft_tokens.shape[0] != self.n_slots:
            raise ValueError(f"draft_tokens must be [n_slots={self.n_slots}"
                             f", k], got {draft_tokens.shape}")
        k = int(draft_tokens.shape[1])
        if k < 1:
            raise ValueError("verify needs k >= 1 draft positions; use "
                             "decode for a plain step")
        if k + 1 > self.max_seq:
            raise ValueError(f"draft width {k} cannot fit "
                             f"max_seq={self.max_seq}")
        B = self.n_slots
        forced = (jnp.zeros((B,), bool) if forced is None
                  else jnp.asarray(forced, bool))
        first_tok = (jnp.zeros((B,), jnp.int32) if first_tok is None
                     else jnp.asarray(first_tok, jnp.int32))
        pos_set = (jnp.zeros((B,), jnp.int32) if pos_set is None
                   else jnp.asarray(pos_set, jnp.int32))
        if k not in self._verify_fns:
            fn = self._build_verify(k)
            if self.observer is not None:
                fn = self.observer.watch(fn, f"serve.verify[{k}]")
            self._verify_fns[k] = fn
        aids, lora = self._lora_args(adapter_ids)
        if allowed is None:
            if k not in self._ones_verify:
                self._ones_verify[k] = jnp.full(
                    (B, k + 1, self._mask_words),
                    np.uint32(0xFFFFFFFF), jnp.uint32)
            allowed = self._ones_verify[k]
        else:
            allowed = jnp.asarray(pack_mask(allowed))
        return self._verify_fns[k](
            self.params, arena, last_tokens, draft_tokens,
            jnp.asarray(draft_len, jnp.int32), jnp.asarray(active),
            forced, first_tok, pos_set,
            self._tables_arg(page_tables), key, temp, top_k, top_p,
            allowed, aids, lora)

    # ---- prefill/decode disaggregation: page-granular KV handoff ------

    def _build_extract(self):
        def extract(arena, ids):
            def conv(tree):
                if isinstance(tree, dict):
                    if "pages_key" in tree:
                        # every pool leaf (K/V pages and, on int8
                        # arenas, their scale siblings) gathered at the
                        # same page ids; the per-slot index stays home
                        return {k: jnp.take(v, ids, axis=0)
                                for k, v in tree.items() if k != "index"}
                    return {k: conv(v) for k, v in tree.items()}
                return tree
            return conv(arena)
        return jax.jit(extract)

    def _build_inject(self):
        def inject(arena, last, data, ids, slot, index, first):
            def conv(tree, dtree):
                if isinstance(tree, dict):
                    if "pages_key" in tree:
                        out = {}
                        for k, v in tree.items():
                            if k == "index":
                                # the adopted sequence decodes from its
                                # prompt length, exactly as if this
                                # engine had prefilled it
                                out[k] = jax.lax.dynamic_update_slice(
                                    v, index[None].astype(v.dtype),
                                    (slot,))
                            else:
                                # pad rows carry page id 0: their zero
                                # payload lands on the reserved garbage
                                # page, never a live one
                                out[k] = v.at[ids].set(
                                    dtree[k].astype(v.dtype))
                        return out
                    return {k: conv(v, dtree[k]) for k, v in tree.items()}
                return tree
            arena = conv(arena, data)
            last = jax.lax.dynamic_update_slice(last, first[None], (slot,))
            return arena, last
        return jax.jit(inject, donate_argnums=(0,))

    def extract_pages(self, arena, page_ids):
        """Export ``page_ids`` (a slot's prompt pages, logical order) to
        HOST memory — the source half of prefill/decode disaggregation
        (round 19): a prefill-role replica pulls the finished prompt's
        K/V pages off device here and the Router carries them to a
        decode replica's :meth:`inject_pages`.  Returns a host pytree
        mirroring the pool-leaf structure, each leaf ``[len(page_ids),
        ...]``.  This is the ONE deliberate device sync of the handoff
        path (the ``kv_handoff_s`` metric); everything else stays
        dispatch-only."""
        if not self.paged:
            raise ValueError("KV handoff requires a paged engine "
                             "(page_size > 0)")
        n = len(page_ids)
        if not 0 < n <= self.n_ptab:
            raise ValueError(f"need 1..{self.n_ptab} pages, got {n}")
        ids = np.zeros(self.n_ptab, np.int32)    # pad -> garbage page 0
        ids[:n] = page_ids
        if self._extract_fn is None:
            fn = self._build_extract()
            if self.observer is not None:
                fn = self.observer.watch(fn, "serve.kv_extract")
            self._extract_fn = fn
        # audit: ok[host-sync-get] the ONE deliberate sync of the KV handoff (metered as kv_handoff_s)
        host = jax.device_get(self._extract_fn(arena, jnp.asarray(ids)))
        return jax.tree.map(lambda a: a[:n], host)

    def inject_pages(self, arena, last_tokens, data, page_ids, slot: int,
                     index: int, first_token: int):
        """Adopt extracted prompt pages into THIS engine's pool: write
        ``data`` (an :meth:`extract_pages` result) into ``page_ids``
        (freshly allocated by the target scheduler), set slot ``slot``'s
        cache index to ``index`` (the prompt length) and its last-token
        entry to ``first_token`` — after which the slot decodes through
        the ordinary decode/verify programs exactly as if this engine
        had prefilled the prompt itself (greedy token-identity is the
        disaggregation acceptance oracle).  One compiled program, all
        arguments data.  Returns ``(arena, last_tokens)``."""
        if not self.paged:
            raise ValueError("KV handoff requires a paged engine "
                             "(page_size > 0)")
        n = len(page_ids)
        leaves = jax.tree.leaves(data)
        if not leaves or any(a.shape[0] != n for a in leaves):
            raise ValueError(f"data leaves must carry {n} pages "
                             f"(one per page id)")
        if not 0 < n <= self.n_ptab:
            raise ValueError(f"need 1..{self.n_ptab} pages, got {n}")
        if any(not 0 < p < self.n_pages for p in page_ids):
            raise ValueError(f"page ids must be in [1, {self.n_pages}), "
                             f"got {list(page_ids)}")
        if not 0 <= slot < self.n_slots:
            raise ValueError(f"slot {slot} out of range "
                             f"[0, {self.n_slots})")
        if not 0 < index < self.max_seq:
            raise ValueError(f"index {index} must be in (0, "
                             f"{self.max_seq}) — a full-to-the-brim "
                             f"sequence has nothing left to decode")
        ids = np.zeros(self.n_ptab, np.int32)
        ids[:n] = page_ids

        def pad(a):
            # audit: ok[host-sync-asarray] pads extract_pages output — already host memory
            a = np.asarray(a)
            out = np.zeros((self.n_ptab,) + a.shape[1:], a.dtype)
            out[:n] = a
            return out

        if self._inject_fn is None:
            fn = self._build_inject()
            if self.observer is not None:
                fn = self.observer.watch(fn, "serve.kv_inject")
            self._inject_fn = fn
        return self._inject_fn(
            arena, last_tokens, jax.tree.map(pad, data),
            jnp.asarray(ids), jnp.asarray(slot, jnp.int32),
            jnp.asarray(index, jnp.int32),
            jnp.asarray(first_token, jnp.int32))

    def extract_pages_batch(self, arena, page_ids):
        """Export ANY number of pages in ONE host sync — the spill-on-
        evict primitive (round 23).  ``page_ids`` is chunked into
        ``n_ptab``-wide dispatches of the SAME compiled gather as
        :meth:`extract_pages` (fixed ``[n_ptab]`` id shape — zero new
        program families), every chunk is dispatched before anything is
        read, and a single ``jax.device_get`` collects them all: the
        sync cost of spilling N evicted pages is one round trip, not N.
        Returns a host pytree mirroring the pool-leaf structure, each
        leaf ``[len(page_ids), ...]`` in input order."""
        if not self.paged:
            raise ValueError("KV handoff requires a paged engine "
                             "(page_size > 0)")
        n = len(page_ids)
        if n < 1:
            raise ValueError("need at least one page id")
        if self._extract_fn is None:
            fn = self._build_extract()
            if self.observer is not None:
                fn = self.observer.watch(fn, "serve.kv_extract")
            self._extract_fn = fn
        futs = []
        for i in range(0, n, self.n_ptab):
            chunk = page_ids[i:i + self.n_ptab]
            ids = np.zeros(self.n_ptab, np.int32)  # pad -> garbage page 0
            ids[:len(chunk)] = chunk
            futs.append(self._extract_fn(arena, jnp.asarray(ids)))
        # audit: ok[host-sync-get] the ONE deliberate sync of a batched spill (all chunks dispatched above; metered as spill_s)
        host = jax.device_get(futs)
        trimmed = [jax.tree.map(
            lambda a, m=min(self.n_ptab, n - i): a[:m], out)
            for i, out in zip(range(0, n, self.n_ptab), host)]
        if len(trimmed) == 1:
            return trimmed[0]
        return jax.tree.map(lambda *xs: np.concatenate(xs, axis=0),
                            *trimmed)

    def inject_pages_batch(self, arena, last_tokens, items):
        """Adopt several extracted page groups — ``items`` of ``(data,
        page_ids, slot, index, first_token)`` — in one dispatch-only
        pass: every group rides the SAME compiled scatter as
        :meth:`inject_pages` (the donated arena threads through), and
        since inject was never the sync side of the handoff there are
        ZERO host syncs here regardless of group count.  Returns
        ``(arena, last_tokens)``."""
        for data, page_ids, slot, index, first_token in items:
            arena, last_tokens = self.inject_pages(
                arena, last_tokens, data, page_ids, slot, index,
                first_token)
        return arena, last_tokens
