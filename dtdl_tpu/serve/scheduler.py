"""Slot-based continuous batcher over the InferenceEngine.

Orca-style iteration-level scheduling on fixed XLA shapes: the engine's
programs always step all ``n_slots`` arena rows; this module decides
*what occupies the rows*.  A request is admitted into the first free
slot (one bucketed prefill), decodes in lockstep with whatever else is
in flight, and retires the moment its budget is exhausted — freeing the
row for the next queued request **mid-flight**, while the other slots
keep decoding.  Short requests never wait for long ones and the batch
never pads to the longest request; the only granularity is one step.

Dispatch discipline (PR 1, SCALING.md "Async dispatch discipline"): the
loop never reads a device value it just dispatched.  The decode feedback
path — sampled token back in as next input — stays ON DEVICE via the
``last_tokens`` vector, so back-to-back steps pipeline without any
host↔device round-trip.  Host-side bookkeeping uses only what the host
already knows at dispatch time.  Sampled tokens reach the host through a
**lag harvest**: each step's token window enters a bounded queue and is
converted ``harvest_lag`` steps later, when the device has long finished
(the same backpressure shape as metrics.MetricsQueue).  The one
consequence: EOS detection is late by up to ``harvest_lag`` steps, so a
slot decodes up to that many garbage steps past its stop token before
retiring — they are trimmed from the output at harvest.
``harvest_lag=0`` restores sync-every-step EOS exactness at
sync-every-step cost.

**Speculative decoding** rides the same discipline.  A request with
``speculate=k > 0`` gets per-step drafts from a host-side
:class:`~dtdl_tpu.serve.draft.DraftSource` — chosen from *lag-harvested
host state* (the source predicts ``gap + k`` tokens continuing the
harvested truth and the optimistic in-flight ``gap`` is skipped — see
``_dispatch_round``'s draft block), never by syncing the in-flight
step —
and the engine's ``verify`` program scores all candidates in one
parameter sweep, accepting a per-slot prefix ON DEVICE
(serve/sampling.py:accept_resample, lossless).  Consequences the
scheduler absorbs:

* **variable tokens per step** — a verify step emits 1..k+1 tokens per
  slot, known only on device, so pending entries carry a token *window*
  plus per-slot counts; budget and EOS checks run over the harvested
  window (EOS mid-window trims exactly, as in the plain path).
* **retirement on guaranteed progress** — the host can no longer count
  emitted tokens at dispatch; every step guarantees >= 1 token, so a
  slot retires when its guaranteed count reaches its budget (for
  non-speculative slots this is exactly the old dispatched count).
  Accepted tokens beyond the budget are trimmed at harvest.
* **worst-case index tracking** — verify writes a k+1-token window at
  the slot's cache position, so the scheduler tracks each slot's
  worst-case (all-accepted) index and, within k of ``max_seq``, settles
  in-flight steps before dispatching (the only data-dependent syncs, and
  only ever in the last k positions of a sequence).
* **adaptive draft length** — each slot tracks a trailing-acceptance
  EMA and halves/doubles its draft length k accordingly; the step's
  width is the power-of-two bucket of the largest per-slot k, so mixed
  spec/non-spec traffic shares one verify program per bucket
  (non-speculative slots ride along with ``draft_len=0`` and behave
  exactly like a decode step — token-identical, pinned by
  tests/test_spec_decode.py).

**Chunked prefill** (round 19, ``chunk_tokens=N``) makes prompt
processing incremental and schedulable: admission only binds a slot
(and maps its pages), then the prompt enters in per-step chunks of at
most N tokens riding the SAME verify program as ``forced`` rows —
"verify with no acceptance test" — so decode steps, speculative drafts
and prefill chunks share one compiled step and a long admission stops
stalling every in-flight decode by a whole-prompt prefill latency
(``decode_steps_delayed_by_prefill`` is the pre-change counter).  The
final chunk's bonus sample IS the request's first token, from the same
target distribution whole-prompt prefill samples — greedy output is
token-identical either way (tests/test_chunked_prefill.py).  A
``prefill_only`` request (the fleet's disaggregation, round 19)
finishes at that first token with a page-granular ``kv_handoff``
payload; a ``kv_inject`` request adopts one and decodes as if it had
prefilled locally.

**Paged KV** (an engine built with ``page_size > 0``) moves the
admission currency from slots to PAGES.  The scheduler owns the
host-side :class:`~dtdl_tpu.serve.paged.PageAllocator` (free list +
chained-hash prefix cache over full prompt pages): admission maps the
longest cached prompt-prefix read-only (shared, refcounted) and
prefills only the suffix through its (smaller) bucket — the TTFT win —
waiting in FIFO order when the pool cannot map the prompt yet; decode
growth allocates pages from the same worst-case ``pos_hi`` arithmetic
the overflow settling uses (no device reads, no new programs — the
fresh page table rides into the next dispatch as data); retirement
releases pages immediately (cached prefix pages stay warm, evictable
LRU).  A mid-flight slot the pool cannot grow for is shed with the
named :class:`~dtdl_tpu.serve.paged.PagePoolExhaustedError` message
(``requests_shed``) rather than stalling the batch.  Token streams are
identical to the dense arena's, pinned by tests/test_paged_kv.py.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from collections import deque
from typing import Any, Optional, Sequence

import jax
import numpy as np

from dtdl_tpu.obs.observer import NULL_OBSERVER
from dtdl_tpu.obs.trace import corr_rid
from dtdl_tpu.serve.draft import DraftSource, NGramDraft
from dtdl_tpu.serve.engine import InferenceEngine, PromptTooLongError
from dtdl_tpu.serve.metrics import ERROR_KINDS, ServeMetrics
from dtdl_tpu.serve.paged import (GARBAGE_PAGE, DiskPageStore,
                                  HostPageStore, PageAllocator,
                                  PagePoolExhaustedError, payload_nbytes)
from dtdl_tpu.serve.sampling import GREEDY, SampleParams
from dtdl_tpu.serve.tenant.lora import AdapterBankFullError

_ids = itertools.count()


@dataclasses.dataclass
class Request:
    """One generation request plus its lifecycle record.

    ``tokens`` fills with the generated tokens (eos included, post-eos
    trimmed) as they harvest; ``done`` flips when the last one lands.
    ``speculate`` is the request's maximum draft length (0 = plain
    decode); ``error`` is set instead of raising when the scheduler
    rejects the request at submit (e.g. prompt longer than the engine's
    largest prefill bucket, admission queue full, scheduler shut down),
    expires it past its deadline, or fails it during engine containment
    — one bad request never crashes a run with others in flight.
    ``error`` always starts with the terminal kind — ``rejected:`` /
    ``expired:`` / ``failed:`` / ``aborted:`` / ``shed:`` — so callers
    (the fleet Router above all) can branch on the flavor without
    parsing prose.

    Deadlines come in two spellings: ``deadline_s`` is a wall-clock
    budget *from this scheduler's submit* (the PR 5 semantics), while
    ``deadline_at`` is an **absolute** ``time.perf_counter()`` instant.
    A front queue (the fleet Router) sets ``deadline_at`` once at *its*
    intake, so time spent queued ahead of the scheduler counts against
    the budget — without it a request could wait out its whole
    allowance in a router queue and still get a fresh one at the
    engine.  When only ``deadline_s`` is given, ``submit`` derives
    ``deadline_at = t_submit + deadline_s``.

    ``origin_rid``/``lineage`` are the trace-correlation fields (round
    16): a fleet Router stamps each replica-local attempt clone with
    the USER request's rid and how the attempt came to be (``primary``
    / ``retry:N`` after N burned retries / ``requeue`` for a free
    backpressure re-dispatch / ``hedge`` / ``migrate`` for the decode
    half of a disaggregated flight), so every request-scoped
    trace event the
    scheduler emits carries the user rid and
    ``Tracer.request_timeline(rid)`` can reassemble a hedged,
    failed-over request across threads.  Standalone requests leave them
    at the defaults (their own rid is the correlation id).

    **Disaggregation fields (round 19).** ``prefill_only`` asks this
    scheduler for the PREFILL half only: the request finishes the
    moment its first token harvests, with ``kv_handoff`` set to the
    host-side page payload (prompt K/V pages + first token) a decode
    replica's ``kv_inject`` admission adopts — the fleet Router is the
    carrier (dtdl_tpu/serve/fleet.py).  Both require a paged engine;
    standalone callers normally leave them alone.
    """
    prompt: Sequence[int]
    max_new_tokens: int
    sampling: SampleParams = GREEDY
    eos_id: Optional[int] = None
    speculate: int = 0
    deadline_s: Optional[float] = None
    deadline_at: Optional[float] = None
    origin_rid: Optional[int] = None
    lineage: str = "primary"
    prefill_only: bool = False
    kv_inject: Optional[dict] = dataclasses.field(default=None,
                                                  repr=False)
    kv_handoff: Optional[dict] = dataclasses.field(default=None,
                                                   repr=False)
    # multi-tenant fields (round 22, dtdl_tpu/serve/tenant/):
    # ``adapter`` names a LoRA checkpoint path the engine's adapter
    # bank hot-loads (None = base weights); ``grammar`` is a compiled
    # tenant.grammar.TokenDFA constraining every emitted token (needs
    # ``eos_id``: the DFA legalizes EOS only in accepting states);
    # ``stream`` is a tenant.stream.TokenStream delivering tokens
    # incrementally at each lag-harvest (prefix-stable under fleet
    # retries/hedging — only the winning attempt streams).
    adapter: Optional[str] = None
    grammar: Any = dataclasses.field(default=None, repr=False)
    stream: Any = dataclasses.field(default=None, repr=False)
    rid: int = dataclasses.field(default_factory=lambda: next(_ids))
    tokens: list = dataclasses.field(default_factory=list)
    done: bool = False
    error: Optional[str] = None
    # wall-clock lifecycle (host side; first/done are harvest times, i.e.
    # when the host could actually observe the token)
    t_submit: float = 0.0
    t_admit: float = 0.0
    t_first: float = 0.0
    t_done: float = 0.0
    admit_step: int = -1
    # internal: tokens guaranteed emitted by dispatched steps (>= 1 per
    # step; exact for non-speculative slots) / slot retired / the
    # grammar automaton's state over the HARVESTED tokens (lives on the
    # request, not the slot: budget-retired slots keep harvesting
    # windows after the row is reassigned)
    _guaranteed: int = dataclasses.field(default=0, repr=False)
    _retired: bool = dataclasses.field(default=False, repr=False)
    _gq: int = dataclasses.field(default=0, repr=False)

    def __post_init__(self):
        if self.max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got "
                             f"{self.max_new_tokens}")
        if self.speculate < 0:
            raise ValueError(f"speculate must be >= 0, got "
                             f"{self.speculate}")

    def __repr__(self):
        # the dataclass default would dump the whole prompt and token
        # list — useless in a log line and unreadable for the fleet's
        # per-attempt diagnostics.  One compact line: identity, sizes,
        # lifecycle state, and the error if any.
        state = ("pending" if not self.done
                 else "error" if self.error else "done")
        err = f", error={self.error!r}" if self.error else ""
        return (f"Request(rid={self.rid}, prompt_len={len(self.prompt)}, "
                f"max_new_tokens={self.max_new_tokens}, "
                f"tokens={len(self.tokens)}, {state}{err})")


class _SlotState:
    """Host-side per-slot tracking while a request occupies the row.

    ``pos`` is the slot's cache index as of the last *harvested* step
    (exact); ``inflight`` holds each dispatched-but-unharvested step's
    draft length, so ``pos_hi`` bounds the device index from above (the
    all-accepted worst case) and ``gap`` is the optimistic number of
    tokens the device is ahead of the harvested truth — the draft
    source predicts *across* that gap fresh every step, so a
    misprediction self-heals at the next harvest instead of poisoning
    later drafts.  ``k_cur`` is the adaptive draft length, steered by a
    trailing-acceptance EMA.

    ``fill_next``/``fill_end`` are the CHUNKED-PREFILL cursor (round
    19): while ``fill_next < fill_end`` the slot is still absorbing its
    prompt in per-step chunks (``fill_next`` = the next prompt offset
    to write, advanced at chunk dispatch — host truth, always equal to
    ``pos_hi``) and never decodes, drafts, or emits.  Whole-prompt
    admission leaves them equal (nothing to fill).
    """

    __slots__ = ("rid", "pos", "k_cur", "k_max", "acc_ema", "inflight",
                 "fill_next", "fill_end", "fill_toks")

    def __init__(self, rid: int, pos: int, k_max: int,
                 fill_end: Optional[int] = None):
        self.rid = rid
        self.pos = pos
        self.k_max = k_max
        # start at 2 and let the acceptance EMA steer: doubles under
        # sustained acceptance (>0.8) up to the request's ``speculate``,
        # halves under <0.5 — so a weak draft source costs at most a few
        # over-drafted steps before settling at k=1
        self.k_cur = max(1, min(2, k_max))
        self.acc_ema = 1.0          # optimistic until measured
        self.inflight: deque = deque()
        self.fill_next = pos
        self.fill_end = pos if fill_end is None else fill_end
        # the prompt as one int32 array, materialized ONCE at chunked
        # admission: chunk building slices it per step — re-listing the
        # whole prompt per chunk would cost O(len^2/chunk) host work on
        # exactly the long-prompt path chunking exists for
        self.fill_toks = None

    @property
    def prefilling(self) -> bool:
        """Still absorbing prompt chunks — excluded from decode/draft."""
        return self.fill_next < self.fill_end

    @property
    def pos_hi(self) -> int:
        """Worst-case (all-accepted) device index — the overflow bound."""
        return self.pos + sum(dl + 1 for dl, _ in self.inflight)

    @property
    def gap_est(self) -> int:
        """EXPECTED tokens of the request's OUTPUT stream the device is
        ahead of harvested truth: one guaranteed per in-flight
        decode/verify step plus acceptance-EMA-weighted drafts.  At
        high acceptance this is the all-accepted count (aligned
        drafting, the payoff regime); at low acceptance it decays to
        one-per-step, which is what the device is actually doing —
        either way the skip stays close to the true offset.  In-flight
        PREFILL CHUNKS advance the cache index, never the output
        stream: an intermediate chunk contributes 0 and the final
        chunk exactly its bonus token — counting chunk widths here
        would make the first post-prefill draft windows skip ~a whole
        chunk of the proposal and reject guaranteed."""
        a = min(1.0, max(0.0, self.acc_ema))
        out = 0
        for dl, kind in self.inflight:
            if kind == 1:
                continue               # intermediate chunk: no output
            out += 1 if kind == 2 else 1 + int(round(dl * a))
        return out

    def dispatched(self, draft_len: int, kind: int = 0) -> None:
        self.inflight.append((draft_len, kind))

    def settle(self, draft_len: int, n_emitted: int) -> None:
        """One in-flight step harvested: exact index, acceptance EMA,
        and the multiplicative k adaptation (halve under ~50%% trailing
        acceptance, double — up to the request's ``speculate`` — above
        ~80%%)."""
        if self.inflight:
            self.inflight.popleft()
        self.pos += n_emitted
        if draft_len > 0:
            rate = (n_emitted - 1) / draft_len
            self.acc_ema = 0.5 * self.acc_ema + 0.5 * rate
            if self.acc_ema < 0.5:
                self.k_cur = max(1, self.k_cur // 2)
            elif self.acc_ema > 0.8:
                self.k_cur = min(max(1, self.k_cur * 2), self.k_max)


class Scheduler:
    """Continuous batcher (see module docstring).

    ``submit`` enqueues (or rejects — see :class:`Request` ``error``);
    ``step`` runs one admit+draft+decode/verify round; ``run`` drives
    until everything submitted has finished and returns the finished
    requests in completion order.  ``draft`` is the
    :class:`~dtdl_tpu.serve.draft.DraftSource` used for requests with
    ``speculate > 0`` (default: device-free n-gram prompt lookup).
    """

    def __init__(self, engine: InferenceEngine, seed: int = 0,
                 harvest_lag: int = 4, metrics: ServeMetrics = None,
                 observer=None, draft: Optional[DraftSource] = None,
                 max_queue: Optional[int] = None,
                 prefix_cache: bool = True, exporter=None,
                 chunk_tokens: Optional[int] = None,
                 spill_host_bytes: Optional[int] = None,
                 spill_dir: Optional[str] = None,
                 spill_disk_bytes: Optional[int] = None):
        if harvest_lag < 0:
            raise ValueError(f"harvest_lag must be >= 0, got "
                             f"{harvest_lag}")
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if chunk_tokens is not None and chunk_tokens < 1:
            raise ValueError(f"chunk_tokens must be >= 1, got "
                             f"{chunk_tokens}")
        # obs facade: thread-safe spans (admit/draft/dispatch/verify/
        # harvest) + the engine's recompile sentinel; defaults to no-ops
        self.observer = observer or NULL_OBSERVER
        if observer is not None and engine.observer is None:
            engine.observer = observer   # sentinel on the engine's jits
        # continuous metrics export (dtdl_tpu/obs/export.py): sampled at
        # the boundaries this loop already settles at — step's harvest
        # and drain() — never per token; the exporter throttles itself
        self.exporter = exporter
        self.engine = engine
        self.draft = draft if draft is not None else NGramDraft()
        draft_model = getattr(self.draft, "model", None)
        if draft_model is not None and \
                draft_model.vocab_size != engine.model.vocab_size:
            raise ValueError(
                f"draft model vocab ({draft_model.vocab_size}) must match "
                f"the served model's ({engine.model.vocab_size})")
        self.arena = engine.init_arena()
        self.last_tokens = engine.init_last_tokens()
        self.queue: deque[Request] = deque()
        self.slots: list[Optional[Request]] = [None] * engine.n_slots
        self.harvest_lag = harvest_lag
        self.metrics = metrics or ServeMetrics(n_slots=engine.n_slots)
        if exporter is not None:
            # this scheduler's window-delta feed; callers stack further
            # sources (goodput totals, guard counters) on the same
            # exporter before or after construction
            exporter.add_source("", self.metrics.window)
        self.finished: list[Request] = []
        self._reqs: dict[int, Request] = {}
        self._active = np.zeros(engine.n_slots, bool)
        self._state: list[Optional[_SlotState]] = [None] * engine.n_slots
        self._temp = np.zeros(engine.n_slots, np.float32)
        self._topk = np.zeros(engine.n_slots, np.int32)
        self._topp = np.ones(engine.n_slots, np.float32)
        self._key = jax.random.PRNGKey(seed)
        # lag harvest: (token window [B] or [B, k+1], per-slot counts or
        # None (=1 each), ((slot, rid, draft_len), ...))
        self._pending: deque[tuple[Any, Any, tuple]] = deque()
        self.step_count = 0
        # containment state: bounded admission + graceful shutdown +
        # the blast radius of an engine failure (see step()/shutdown())
        self.max_queue = max_queue
        self._closed = False
        self._containing = False
        self.last_engine_error: Optional[str] = None
        # watchdog early-out: stays False until a deadline-carrying
        # request is submitted, so the per-step queue/slot scan is free
        # for the (default) deadline-less workload
        self._deadlines_seen = False
        # paged KV arena (dtdl_tpu/serve/paged.py): host-side page
        # allocator + prefix cache, the per-slot page tables the
        # compiled programs consume as data, and the per-slot page
        # lists for release at retirement.  Admission is gated on FREE
        # PAGES, not free slots: a free slot whose prompt cannot be
        # mapped waits in the queue (FIFO backpressure) until
        # retirements free pages or the prefix cache eats the need.
        self.pages: Optional[PageAllocator] = None
        # hierarchical KV cache (round 23): the host-DRAM spill tier
        # (plus optional disk tier) behind the HBM prefix cache, and the
        # bounded receipt queue the fleet Router drains to keep its
        # prefix directory fresh — ("add", hash) when this replica
        # publishes a prefix page in ANY tier, ("drop", hash) when the
        # last tier forgets it, ("reset", 0) on containment.  A dropped
        # receipt (deque overflow) only makes the directory stale, and a
        # stale directory entry only costs a recompute.
        self.spill: Optional[HostPageStore] = None
        self.kv_receipts: deque = deque(maxlen=65536)
        if engine.paged:
            self.pages = PageAllocator(engine.n_pages, engine.page_size,
                                       prefix_cache=prefix_cache)
            self._ptab = np.full((engine.n_slots, engine.n_ptab),
                                 GARBAGE_PAGE, np.int32)
            self._slot_pages: list[list[int]] = \
                [[] for _ in range(engine.n_slots)]
            if spill_host_bytes is not None or spill_dir is not None:
                if not prefix_cache:
                    raise ValueError("spill tiers require "
                                     "prefix_cache=True (spilled pages "
                                     "are keyed by chain hash)")
                disk = (DiskPageStore(spill_dir, spill_disk_bytes)
                        if spill_dir is not None else None)
                self.spill = HostPageStore(
                    spill_host_bytes if spill_host_bytes is not None
                    else 0,
                    disk=disk,
                    on_drop=lambda h: self.kv_receipts.append(("drop", h)))
                self.pages.record_evictions = True
        elif spill_host_bytes is not None or spill_dir is not None:
            raise ValueError("spill_host_bytes/spill_dir require a paged "
                             "engine with prefix_cache=True")
        # chunked prefill (round 19, Sarathi-style): prompt processing
        # split into <= chunk_tokens-per-step windows riding the verify
        # program family, so a long admission no longer stalls every
        # in-flight decode by a whole-prompt prefill latency.  None =
        # the PR 2 whole-prompt behavior, token-identical under greedy
        # (tests/test_chunked_prefill.py pins both ways).
        self.chunk_tokens = chunk_tokens
        # paged+chunked: prefix-hash registration is deferred until the
        # prompt's pages are fully written (the final chunk's dispatch)
        self._slot_hashes: list = [None] * engine.n_slots
        # multi-tenant LoRA (round 22): per-slot adapter-bank row ids,
        # the [B] vector every decode/verify step consumes as DATA
        # (row 0 = the all-zeros base adapter).  The scheduler owns the
        # refcount lifecycle: acquire at admission, release at retire.
        self._aids = np.zeros(engine.n_slots, np.int32)
        if engine.adapter_bank is not None \
                and engine.adapter_bank.observer is None:
            engine.adapter_bank.observer = self.observer

    # ---- intake -------------------------------------------------------

    _ERROR_KINDS = ERROR_KINDS

    def _corr(self, req: Request) -> dict:
        """Trace-correlation args for request-scoped events: ``rid`` is
        the USER request id (the fleet Router stamps ``origin_rid`` on
        attempt clones; standalone requests are their own origin),
        ``arid`` the local attempt id — so
        ``Tracer.request_timeline(rid)`` collects every attempt's
        events under the one user rid while ``arid`` tells the sibling
        attempts apart.  Both land in the wire form (``corr_rid``:
        ``f"{proc_tag}/{n}"``, round 17) so multi-host traces merge
        without id collisions."""
        rid = req.origin_rid if req.origin_rid is not None else req.rid
        return {"rid": corr_rid(rid), "arid": corr_rid(req.rid)}

    def _finish_error(self, req: Request, reason: str,
                      metric_hook, kind: str) -> Request:
        """The one terminal-error path: ``req.error`` set to
        ``"<kind>: <reason>"`` (kind ∈ rejected / expired / failed /
        aborted / shed — the machine-checkable flavor a caller branches
        on), request finished, the given metrics hook (on_reject /
        on_expire / on_failure / on_abort / on_shed) counts it — every
        containment branch funnels through here so retirement
        bookkeeping and message format cannot drift."""
        assert kind in self._ERROR_KINDS, kind
        req.error = f"{kind}: {reason}"
        req.done = True
        req.t_done = time.perf_counter()
        self.finished.append(req)
        self._stream_terminal(req)
        metric_hook(req)
        if req.origin_rid is None and req.admit_step >= 0:
            # a STANDALONE request that was admitted started a flow
            # chain at admission — every terminal funnels through here,
            # so close it (never-admitted requests started none, and
            # fleet attempts' chains are closed by the Router's
            # request_done, which owns the user-level outcome)
            self.observer.flow("req", corr_rid(req.rid), "end")
        return req

    def _reject(self, req: Request, reason: str) -> Request:
        """Terminal submit-time rejection: ``req.error`` set, counted,
        run unharmed — the named-error-instead-of-crash path shared by
        oversized prompts, a full admission queue, and shutdown."""
        self._reqs[req.rid] = req
        return self._finish_error(req, reason, self.metrics.on_reject,
                                  "rejected")

    def _stream_terminal(self, req: Request) -> None:
        """Close out a streaming request's TokenStream at its terminal.

        Ownership protocol (tenant/stream.py): a STANDALONE request
        (``origin_rid`` is None) owns the user-facing stream outright,
        so its terminal reconciles and closes it — success delivers any
        suffix the lag harvest had not offered yet, an error closes
        without delivering.  A fleet ATTEMPT only *releases* its claim,
        and only on an error terminal, so a retry/hedge successor can
        take over and the stream stays prefix-stable — the Router's
        ``_finish_user`` owns the user-level close."""
        if req.stream is None:
            return
        if req.origin_rid is None:
            req.stream.finish(req.tokens, req.error)
        elif req.error is not None:
            req.stream.drop(req.rid)

    def _acquire_adapter(self, req: Request) -> Optional[int]:
        """Pin ``req``'s LoRA adapter in the engine's bank at admission
        (hot-loading it through the manifest-checked checkpoint path
        when cold).  Returns the bank row id (0 = base weights), or
        None after error-finishing the request with a named reason: a
        bank with every row pinned by live requests **sheds** with the
        :class:`AdapterBankFullError` message (a capacity signal,
        exactly the page-pool discipline), a corrupt or unreadable
        adapter checkpoint **fails** — neither crashes the loop."""
        if req.adapter is None:
            return 0
        try:
            return self.engine.adapter_bank.acquire(req.adapter)
        except AdapterBankFullError as e:
            self.queue.remove(req)
            self._finish_error(req, str(e), self.metrics.on_shed, "shed")
        except Exception as e:
            self.queue.remove(req)
            self._finish_error(
                req, f"adapter {req.adapter!r} failed to load: {e}",
                self.metrics.on_failure, "failed")
        return None

    def submit(self, req: Request) -> Request:
        """Enqueue ``req``; a request the scheduler cannot serve comes
        back *rejected* (``req.error`` set, ``req.done`` True, counted in
        ``requests_rejected``) instead of raising — one bad request must
        not crash a run with other requests in flight.  Rejection
        reasons: prompt past the largest prefill bucket, admission queue
        at ``max_queue`` (bounded intake: a traffic spike sheds load
        here, with a named reason, instead of growing an unbounded host
        queue), or a shut-down scheduler."""
        prompt_len = len(req.prompt)
        if prompt_len < 1:
            raise ValueError("empty prompt")
        req.t_submit = time.perf_counter()
        if self._closed:
            return self._reject(req, "scheduler is shut down")
        if self._containing:
            # a thread-hosted scheduler (the fleet Replica) can receive
            # a submit while _contain is mid-flight on the worker —
            # admitting into an arena being re-initialized would race;
            # the same named-reason rejection path applies (retryable)
            return self._reject(
                req, "engine containment in progress; retry shortly")
        if self.max_queue is not None and len(self.queue) >= self.max_queue:
            return self._reject(
                req, f"admission queue full ({self.max_queue} waiting); "
                     f"retry later")
        if req.adapter is not None and self.engine.adapter_bank is None:
            return self._reject(
                req, "adapter requests need an engine built with an "
                     "adapter bank (lora_rank/lora_adapters)")
        if req.grammar is not None:
            # the DFA legalizes EOS only in accepting states, which is
            # how a constrained request stops on a complete structure —
            # without an eos_id the constraint could never terminate
            if req.eos_id is None:
                return self._reject(
                    req, "grammar-constrained requests need eos_id (the "
                         "automaton legalizes EOS in accepting states)")
            if req.grammar.eos_id != req.eos_id:
                return self._reject(
                    req, f"grammar was compiled for eos_id="
                         f"{req.grammar.eos_id} but the request has "
                         f"eos_id={req.eos_id}")
            if req.grammar.allow.shape[1] != self.engine.model.vocab_size:
                return self._reject(
                    req, f"grammar was compiled over a vocab of "
                         f"{req.grammar.allow.shape[1]} tokens; the "
                         f"engine serves "
                         f"{self.engine.model.vocab_size}")
        if req.prefill_only and req.kv_inject is not None:
            raise ValueError("prefill_only and kv_inject are mutually "
                             "exclusive (one request is one half of a "
                             "disaggregated flight)")
        if (req.prefill_only or req.kv_inject is not None) \
                and self.pages is None:
            return self._reject(
                req, "prefill/decode disaggregation needs a paged "
                     "engine (page_size > 0): the KV handoff is "
                     "page-granular")
        if req.kv_inject is not None:
            # the decode half of a migrated flight: no prefill ever
            # runs, so the bucket check is irrelevant — validate the
            # payload geometry and that decoding has room instead
            pg = self.engine.page_size
            n_pg = int(req.kv_inject.get("n_pages", 0))
            if n_pg != -(-prompt_len // pg):
                return self._reject(
                    req, f"kv_inject payload carries {n_pg} pages but "
                         f"the prompt needs {-(-prompt_len // pg)} "
                         f"(page_size={pg})")
            if prompt_len >= self.engine.max_seq:
                return self._reject(
                    req, f"adopted prompt of {prompt_len} tokens "
                         f"leaves no room to decode "
                         f"(max_seq={self.engine.max_seq})")
            need = (prompt_len + 1 + pg - 1) // pg
            if need > self.pages.capacity:
                return self._reject(
                    req, f"page pool exhausted: adopted prompt needs "
                         f"{need} pages (page_size={pg}) but the pool "
                         f"has only {self.pages.capacity}")
            if req.deadline_at is not None or req.deadline_s is not None:
                self._deadlines_seen = True
            if req.deadline_at is None and req.deadline_s is not None:
                req.deadline_at = req.t_submit + req.deadline_s
            self._reqs[req.rid] = req
            self.queue.append(req)
            self.metrics.on_submit(req)
            return req
        try:
            self.engine.bucket_for(prompt_len)
        except PromptTooLongError as e:
            return self._reject(req, str(e))
        if self.pages is not None:
            # never-fits guard: a prompt whose pages (plus the first
            # generated token's) exceed the whole pool would wait at
            # admission forever — shed it NOW with the diagnosis
            pg = self.engine.page_size
            need = (prompt_len + 1 + pg - 1) // pg
            if need > self.pages.capacity:
                return self._reject(
                    req, f"page pool exhausted: prompt needs {need} "
                         f"pages (page_size={pg}) but the pool has "
                         f"only {self.pages.capacity}")
        if req.deadline_at is None and req.deadline_s is not None:
            # the PR 5 relative spelling: budget starts at THIS submit
            req.deadline_at = req.t_submit + req.deadline_s
        if req.deadline_at is not None:
            self._deadlines_seen = True
        self._reqs[req.rid] = req
        self.queue.append(req)
        self.metrics.on_submit(req)
        return req

    # ---- slot lifecycle ----------------------------------------------

    def _budget(self, req: Request) -> int:
        # the k-th decode step writes K/V at position len(prompt)+k-1,
        # which must stay < max_seq; prefill contributes token 1 for free
        return min(req.max_new_tokens,
                   self.engine.max_seq - len(req.prompt) + 1)

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _retire(self, slot: int):
        req = self.slots[slot]
        req._retired = True
        self.slots[slot] = None
        self._active[slot] = False
        # reset the slot's sampling knobs to greedy: a retired sampled
        # request must not keep jnp.all(greedy) False forever and
        # disable the all-greedy verify fast path for later traffic
        # (sampling params are data — no recompile)
        self._temp[slot] = 0.0
        self._topk[slot] = 0
        self._topp[slot] = 1.0
        # drop this slot's claim on its LoRA bank row: refcount 0 makes
        # the row LRU-evictable for the next cold adapter, while the
        # weights stay resident for a warm re-acquire (row 0, the base
        # adapter, is never refcounted — release(0) is a no-op)
        if self._aids[slot]:
            self.engine.adapter_bank.release(int(self._aids[slot]))
            self._aids[slot] = 0
        if self.pages is not None:
            # release the slot's pages (cached prefix pages become
            # evictable, private pages free immediately) and point the
            # stale table row at the garbage page — any still-in-flight
            # step for this slot was dispatched with its own table
            # snapshot, and the single device stream orders it before
            # whatever prefill reuses the pages (the same
            # overwritten-after-retire discipline as the dense arena)
            for p in self._slot_pages[slot]:
                self.pages.release(p)
            self._slot_pages[slot] = []
            self._ptab[slot] = GARBAGE_PAGE
        # a request retired mid-chunked-prefill (expire/cancel/shed)
        # must not leak its deferred prefix-hash registration to the
        # slot's next occupant — its partially-written pages were just
        # released above, exactly the satellite-bugfix path
        self._slot_hashes[slot] = None

    def _expire(self):
        """Deadline watchdog: retire any request past its wall-clock
        budget with ``req.error`` set — queued or in a slot.  Freeing a
        slot never touches the KV arena (the row is inactive until the
        next prefill overwrites it, the same discipline as retirement),
        and any in-flight harvest windows for the request are dropped by
        the existing ``req.done`` skip, so an expired request cannot
        poison later occupants of its row.  The scan costs nothing until
        the first deadline-carrying request is submitted."""
        if not self._deadlines_seen:
            return
        now = time.perf_counter()

        def expired(req):
            # deadline_at is the single source of truth (submit derives
            # it from deadline_s) — absolute, so front-queue time spent
            # before this scheduler's submit counts against the budget
            return req.deadline_at is not None and now >= req.deadline_at

        def budget(req):
            return (f"{req.deadline_s}s" if req.deadline_s is not None
                    else f"(absolute, {req.deadline_at - req.t_submit:+.3f}"
                         f"s from submit)")

        for req in [r for r in self.queue if expired(r)]:
            self.queue.remove(req)
            self._finish_error(
                req, f"deadline {budget(req)} exceeded before "
                     f"admission", self.metrics.on_expire, "expired")
            self.observer.event("request_expired", queued=1,
                                **self._corr(req))
        for slot, req in enumerate(self.slots):
            # every OCCUPIED slot is expirable — including a parked
            # prefill_only slot (active False while awaiting its
            # first-token harvest): an expired prefill half must not
            # go on to pay the extraction sync and migrate a dead
            # request
            if req is None or not expired(req):
                continue
            self._finish_error(
                req, f"deadline {budget(req)} exceeded after "
                     f"{len(req.tokens)} tokens", self.metrics.on_expire,
                "expired")
            self.observer.event("request_expired", slot=slot,
                                **self._corr(req))
            self._retire(slot)

    # ---- router-facing hooks (dtdl_tpu/serve/fleet.py) ----------------

    @property
    def load(self) -> int:
        """Host-side occupancy signal for least-loaded routing: queued
        plus slot-occupying requests (a parked prefill_only slot
        awaiting its handoff harvest still holds the slot).  Plain
        reads under the GIL — safe to sample from another thread
        without stopping the step loop."""
        return len(self.queue) + sum(s is not None for s in self.slots)

    def pending_requests(self) -> list:
        """Every submitted-but-unfinished request (queued, slotted, or
        retired-awaiting-harvest) — the outstanding-work export for a
        fleet/ops layer.  (The shipped Router re-dispatches an evicted
        replica's work from its OWN attempt table — it never trusts a
        possibly-wedged replica's bookkeeping — so this is the
        inspection surface, e.g. for drain monitoring, not the failover
        mechanism.)"""
        return [r for r in self._reqs.values() if not r.done]

    def cancel(self, rid: int, reason: str = "cancelled") -> bool:
        """Best-effort cancellation of one request by id: a queued
        request is removed, an in-slot one retires — both finish with
        ``error = "aborted: cancelled ..."`` and count under
        ``requests_aborted`` (a deliberate abort of an already-submitted
        request, exactly the shutdown-abort semantics, so the PR 5
        accounting invariant holds unchanged).  Returns False when it is
        too late to matter: unknown rid, already finished, or already
        retired on guaranteed budget with its tokens merely awaiting the
        lag harvest (those are computed — the harvest delivers them; a
        caller that must not double-deliver, e.g. the Router's hedge
        loser path, discards the completion instead)."""
        req = self._reqs.get(rid)
        if req is None or req.done:
            return False
        if req in self.queue:
            self.queue.remove(req)
            self._finish_error(
                req, f"cancelled before admission: {reason}",
                self.metrics.on_abort, "aborted")
            self.observer.event("request_cancelled", queued=1,
                                **self._corr(req))
            return True
        for slot, r in enumerate(self.slots):
            if r is req:
                self._finish_error(
                    req, f"cancelled after {len(req.tokens)} tokens: "
                         f"{reason}", self.metrics.on_abort, "aborted")
                self.observer.event("request_cancelled", slot=slot,
                                    **self._corr(req))
                self._retire(slot)
                return True
        return False     # retired-awaiting-harvest: let it finish

    def _contain(self, exc: BaseException):
        """Engine-failure blast radius: the in-flight batch.

        A compiled program failing mid-dispatch leaves the donated arena
        in an unknown state, so everything referencing it is condemned:
        every slotted request retires with ``req.error`` set and the
        arena/last-token state is re-initialized.  Harvest windows
        dispatched BEFORE the failure are intact output buffers from
        completed programs — they are delivered first (best-effort), so
        a request that already retired on guaranteed budget and was only
        waiting on the lag harvest still finishes cleanly rather than
        being orphaned ``done=False``; any such request the harvest
        could not settle is error-finished like the slotted ones.  The
        admission queue survives — the next step admits and serves it
        against the fresh arena."""
        self._containing = True
        try:
            self.last_engine_error = f"{type(exc).__name__}: {exc}"
            self.observer.event("engine_failure",
                                error=self.last_engine_error)
            pending_rids = {rid for _, _, entries in self._pending
                            for _, rid, _, _ in entries}
            try:
                while self._pending:
                    self._harvest_one()
            except Exception:      # device state unusable — drop the rest
                self._pending.clear()
            for slot, req in enumerate(self.slots):
                if req is None:
                    continue
                self._finish_error(
                    req, f"engine failure: {self.last_engine_error}",
                    self.metrics.on_failure, "failed")
                self._retire(slot)
                self._state[slot] = None
            for rid in pending_rids:  # retired-for-budget but unharvested
                req = self._reqs[rid]
                if not req.done:
                    self._finish_error(
                        req, f"engine failure: {self.last_engine_error}",
                        self.metrics.on_failure, "failed")
            self.arena = self.engine.init_arena()
            self.last_tokens = self.engine.init_last_tokens()
            if self.pages is not None:
                # the re-initialized arena invalidated every page's
                # contents — a stale prefix hit would be silent corruption
                self.pages.reset()
                self._ptab[:] = GARBAGE_PAGE
                self._slot_pages = [[] for _ in range(self.engine.n_slots)]
                # tell the fleet directory every HBM-resident hash this
                # replica advertised is gone (host/disk spill copies
                # survive — they are content-addressed host memory)
                self.kv_receipts.append(("reset", 0))
        finally:
            self._containing = False

    def _admit(self):
        if self._closed:
            return
        for slot in range(self.engine.n_slots):
            if self.slots[slot] is not None or not self.queue:
                continue
            req = self.queue[0]
            if req.kv_inject is not None:
                # the decode half of a disaggregated flight: adopt the
                # migrated pages instead of prefilling (round 19)
                if self._admit_inject(slot, req):
                    continue
                break                  # pool backpressure: FIFO waits
            aid = self._acquire_adapter(req)
            if aid is None:
                continue               # shed/failed with a named error
            chunked = self.chunk_tokens is not None
            suffix, start, row = req.prompt, 0, None
            hits, fresh, hashes, restored = [], [], [], []
            if self.pages is not None:
                # paged admission: gate on FREE PAGES.  Match the
                # longest cached run of full prompt pages (mapped
                # read-only, shared), allocate private pages for the
                # rest, and prefill only the uncached suffix — the
                # prefix-cache TTFT win.  A prompt the pool cannot map
                # right now WAITS (FIFO backpressure; retirements free
                # pages) instead of stealing a slot it cannot fill.
                pg = self.engine.page_size
                prompt = [int(t) for t in req.prompt]
                hits = self.pages.match_prefix(prompt)
                # hashing is O(prompt) host work on the TTFT path —
                # skip it entirely when the cache can never hit
                hashes = (self.pages.page_hashes(prompt)
                          if self.pages.prefix_cache else [])
                if self.spill is not None:
                    # restore-on-miss (round 23): continue the chain
                    # walk into the host/disk spill tiers — every
                    # payload found there is one page of prefill
                    # recompute skipped for a host->HBM copy
                    for i in range(len(hits),
                                   (len(prompt) - 1) // pg):
                        tier = self.spill.holds(hashes[i])
                        payload = (self.spill.get(hashes[i])
                                   if tier is not None else None)
                        if payload is None:
                            if tier == "disk":
                                # held by the manifest but failed its
                                # integrity check: quarantined by the
                                # store, recomputed by us
                                self.metrics.on_spill_quarantine(1)
                            break             # miss: recompute
                        restored.append((payload, tier))

                def resident() -> int:
                    # prompt pages already materialized across ALL
                    # tiers: HBM hits + spill-tier payloads to inject
                    return len(hits) + len(restored)

                def drop_one() -> None:
                    # trim trailing resident pages (restored first —
                    # they sit after the HBM hits on the chain; their
                    # payloads stay warm in the spill store)
                    (restored if restored else hits).pop()
                if chunked:
                    # chunks write EXACT positions (no padded bucket),
                    # so the bucket-overshoot cap does not apply; the
                    # one constraint is never stranding a 1-token final
                    # chunk at position max_seq-1 (a k>=1 verify window
                    # there would clamp backward over cached pages)
                    while resident() \
                            and len(prompt) == self.engine.max_seq \
                            and len(prompt) - resident() * pg < 2:
                        drop_one()
                else:
                    # the suffix's PADDED bucket must also fit max_seq —
                    # the kernel clamps an overshooting window backward,
                    # which would scatter over the cached pages
                    # themselves.  Dropping trailing resident pages
                    # grows the suffix (monotonic: zero resident == the
                    # submit-checked full prompt), so this always
                    # terminates on a valid configuration.
                    while resident() and (resident() * pg
                                          + self.engine.bucket_for(
                                              len(prompt)
                                              - resident() * pg)
                                          > self.engine.max_seq):
                        drop_one()
                start = resident() * pg
                n_prompt_pages = -(-len(prompt) // pg)
                need = n_prompt_pages - len(hits)
                # pinning an evictable (refcount-0) hit consumes one
                # available page too — count both demands
                evictable_hits = sum(
                    1 for p in hits if self.pages.refcount(p) == 0)
                if need + evictable_hits > self.pages.available:
                    if aid:   # un-pin the adapter row while FIFO waits:
                        self.engine.adapter_bank.release(aid)
                    break     # re-acquired (warm) when pages free up
                for p in hits:          # pin BEFORE alloc can evict them
                    self.pages.acquire(p)
                fresh = [self.pages.alloc() for _ in range(need)]
                # the alloc burst above may have evicted cached pages:
                # extract their payloads to the spill store NOW, before
                # the inject/prefill dispatches below rewrite them
                self._spill_evicted()
                row = np.full(self.engine.n_ptab, GARBAGE_PAGE, np.int32)
                row[:len(hits)] = hits
                row[len(hits):n_prompt_pages] = fresh
                suffix = prompt[start:]
            self.queue.popleft()
            sp = req.sampling
            corr = self._corr(req)
            if restored:
                # restore-on-miss, entry half: the spilled payloads
                # re-enter the arena through the SAME compiled scatter
                # as the PR 14 handoff (fresh pages fresh[:n_res];
                # dispatch-only — the suffix prefill below is ordered
                # after it on the device stream, and its index/last
                # seeding is overwritten by that prefill)
                t0 = time.perf_counter()
                payloads = [p for p, _ in restored]
                data = (payloads[0] if len(payloads) == 1
                        else jax.tree.map(
                            lambda *xs: np.concatenate(xs, axis=0),
                            *payloads))
                try:
                    self.arena, self.last_tokens = \
                        self.engine.inject_pages(
                            self.arena, self.last_tokens, data,
                            fresh[:len(restored)], slot, start, 0)
                except Exception as e:
                    self._contain(e)
                    self._finish_error(
                        req, f"engine failure: {self.last_engine_error}",
                        self.metrics.on_failure, "failed")
                    if aid:   # not slotted yet — _contain missed it
                        self.engine.adapter_bank.release(aid)
                    return
                dt = time.perf_counter() - t0
                nbytes = sum(payload_nbytes(p) for p in payloads)
                self.metrics.on_restore(
                    len(restored), nbytes, dt,
                    host_hits=sum(1 for _, t in restored if t == "host"),
                    disk_hits=sum(1 for _, t in restored if t == "disk"))
                self.observer.event(
                    "page_restored", slot=slot, pages=len(restored),
                    nbytes=nbytes, cached=len(hits) * pg, **corr)
            if not chunked:
                # whole-prompt prefill: one blocking compiled call —
                # every in-flight decode waits a full prefill latency
                # behind it (the interference the chunked path removes;
                # the counter is the before/after bench receipt)
                self.metrics.on_prefill_block(int(self._active.sum()))
                # grammar: the prefill's bonus sample IS the request's
                # first OUTPUT token, so it draws under the automaton's
                # start-state mask (the prompt itself never advances
                # the DFA — grammars constrain output only)
                g0 = (req.grammar.mask(req.grammar.start)[None, :]
                      if req.grammar is not None else None)
                try:
                    with self.observer.span("prefill", slot=slot,
                                            suffix_len=len(suffix),
                                            cached=start, **corr):
                        self.arena, self.last_tokens, _ = \
                            self.engine.prefill(
                                self.arena, self.last_tokens, slot,
                                suffix, sp, self._next_key(),
                                page_row=row, start=start,
                                adapter_id=(aid if self.engine.adapter_bank
                                            is not None else None),
                                allowed=g0)
                except Exception as e:
                    # the arena was donated into the failing program:
                    # condemn the in-flight batch (and this request),
                    # keep the queue
                    self._contain(e)
                    self._finish_error(
                        req, f"engine failure: {self.last_engine_error}",
                        self.metrics.on_failure, "failed")
                    if aid:   # not slotted yet — _contain missed it
                        self.engine.adapter_bank.release(aid)
                    return
            if self.pages is not None:
                self._ptab[slot] = row
                self._slot_pages[slot] = list(hits) + list(fresh)
                n_res = len(restored)
                # restored pages' contents are complete at the inject
                # dispatch above: publish them back into the HBM cache
                # now, whichever prefill path follows
                for i in range(len(hits), len(hits) + n_res):
                    self.pages.register(hashes[i], int(row[i]))
                    self.kv_receipts.append(("add", hashes[i]))
                if chunked:
                    # registration of the SUFFIX pages waits for the
                    # final chunk: only then are they fully written
                    self._slot_hashes[slot] = (hashes,
                                               len(hits) + n_res)
                else:
                    # publish the freshly-computed FULL prompt pages
                    # under their chain hashes — the next identical
                    # prefix hits (deterministic model: same tokens at
                    # same positions => identical K/V, so
                    # first-writer-wins is sound)
                    for i in range(len(hits) + n_res, len(hashes)):
                        self.pages.register(hashes[i], int(row[i]))
                        self.kv_receipts.append(("add", hashes[i]))
                # resident prefix pages — HBM hits AND spill restores —
                # all count as hits: their tokens skipped recompute
                self.metrics.on_prefix(len(hits) + n_res, len(hashes),
                                       start)
            self.slots[slot] = req
            self._active[slot] = True
            self._aids[slot] = aid
            if req.grammar is not None:
                req._gq = req.grammar.start
            self._state[slot] = _SlotState(
                req.rid, start if chunked else len(req.prompt),
                req.speculate,
                fill_end=len(req.prompt) if chunked else None)
            if chunked:
                # audit: ok[host-sync-asarray] chunked-prefill queue of the caller's host prompt list
                self._state[slot].fill_toks = np.asarray(req.prompt,
                                                         np.int32)
            self._temp[slot] = sp.temperature
            self._topk[slot] = sp.top_k
            self._topp[slot] = sp.top_p
            req.t_admit = time.perf_counter()
            req.admit_step = self.step_count
            # correlated admission marker on this worker's track: the
            # queue-wait is readable as (this ts - the submit/dispatch
            # event's), and the flow arrow joins the attempt to its
            # user request's chain (standalone requests START the flow
            # here; fleet attempts continue the router's)
            self.observer.event("request_admitted", slot=slot,
                                step=self.step_count,
                                prompt_len=len(req.prompt),
                                cached=start, lineage=req.lineage,
                                **corr)
            self.observer.flow(
                "req", corr["rid"],
                "step" if req.origin_rid is not None else "start")
            # prefill_tokens counts COMPUTED tokens: a prefix hit's
            # skipped tokens land in prefill_tokens_saved instead
            self.metrics.on_admit(req, slot, len(suffix))
            if chunked:
                # no token guaranteed yet: the first one is the final
                # chunk's bonus sample (_dispatch_round)
                continue
            req._guaranteed = 1
            self._state[slot].dispatched(0)
            self._pending.append(
                (self.last_tokens, None, ((slot, req.rid, 0, 0),)))
            if req._guaranteed >= self._budget(req):
                self._retire(slot)
            elif req.prefill_only:
                # prefill-role replica: park the slot (no decode steps)
                # until the first token harvests and the page payload
                # is extracted (_harvest_one -> _handoff_out)
                self._active[slot] = False

    def _admit_inject(self, slot: int, req: Request) -> bool:
        """Admission of a migrated (``kv_inject``) request: allocate
        fresh pages, write the extracted prompt K/V into the pool, seed
        the slot's cache index and last-token entry — after which the
        slot decodes through the ordinary programs exactly as if this
        scheduler had prefilled it (greedy token identity is the
        disaggregation oracle).  Returns False when the pool cannot map
        the payload yet (FIFO backpressure, like prefill admission)."""
        payload = req.kv_inject
        n_pg = int(payload["n_pages"])
        if n_pg > self.pages.available:
            return False
        aid = self._acquire_adapter(req)
        if aid is None:
            return True            # error-finished with a named reason
        if req.grammar is not None:
            # catch the automaton up over the tokens the prefill half
            # already delivered (the seeded first token): the migrated
            # stream must continue under the same constraint
            req._gq = req.grammar.walk(req.tokens)
            if req._gq < 0:
                self.queue.remove(req)
                self._finish_error(
                    req, "migrated tokens violate the request's grammar",
                    self.metrics.on_failure, "failed")
                if aid:
                    self.engine.adapter_bank.release(aid)
                return True
        self.queue.popleft()
        corr = self._corr(req)
        fresh = [self.pages.alloc() for _ in range(n_pg)]
        # evictions from the alloc burst spill before inject overwrites
        self._spill_evicted()
        row = np.full(self.engine.n_ptab, GARBAGE_PAGE, np.int32)
        row[:n_pg] = fresh
        t0 = time.perf_counter()
        try:
            with self.observer.span("prefill", slot=slot, suffix_len=0,
                                    cached=len(req.prompt), **corr):
                self.arena, self.last_tokens = self.engine.inject_pages(
                    self.arena, self.last_tokens, payload["data"],
                    fresh, slot, len(req.prompt),
                    int(payload["first_token"]))
        except Exception as e:
            self._contain(e)
            self._finish_error(
                req, f"engine failure: {self.last_engine_error}",
                self.metrics.on_failure, "failed")
            if aid:       # not slotted yet — _contain missed it
                self.engine.adapter_bank.release(aid)
            return True
        self._ptab[slot] = row
        self._slot_pages[slot] = list(fresh)
        # re-register the migrated FULL prompt pages under their chain
        # hashes: the target's prefix cache serves later identical
        # prompts locally (first-writer-wins, exactly as at prefill —
        # the satellite's "re-registered in the target allocator")
        if self.pages.prefix_cache:
            prompt = [int(t) for t in req.prompt]
            for h, p in zip(self.pages.page_hashes(prompt), fresh):
                self.pages.register(h, int(p))
                self.kv_receipts.append(("add", h))
        self.metrics.on_kv_handoff(n_pg, time.perf_counter() - t0)
        sp = req.sampling
        self.slots[slot] = req
        self._active[slot] = True
        self._aids[slot] = aid
        self._state[slot] = _SlotState(req.rid, len(req.prompt),
                                       req.speculate)
        self._temp[slot] = sp.temperature
        self._topk[slot] = sp.top_k
        self._topp[slot] = sp.top_p
        req.t_admit = time.perf_counter()
        req.admit_step = self.step_count
        self.observer.event("kv_handoff", side="inject", pages=n_pg,
                            **corr)
        self.observer.event("request_admitted", slot=slot,
                            step=self.step_count,
                            prompt_len=len(req.prompt),
                            cached=len(req.prompt),
                            lineage=req.lineage, **corr)
        self.observer.flow(
            "req", corr["rid"],
            "step" if req.origin_rid is not None else "start")
        # the first token was delivered by the prefill half (seeded in
        # req.tokens by the Router); this slot owes the remainder
        req._guaranteed = max(1, req._guaranteed)
        self.metrics.on_admit(req, slot, 0)
        if req._guaranteed >= self._budget(req):
            self._retire(slot)
        return True

    # ---- paged growth -------------------------------------------------

    def _grow_pages(self, step_act, lens):
        """Map pages covering every STEPPED slot's worst-case write
        window ``[0, pos_hi + draft_len + 1)`` before dispatch
        (``step_act`` is this round's dispatch mask — decoding slots
        plus the prefilling slots that drew a chunk; ``lens`` is the
        per-slot draft/chunk width minus one of the upcoming verify
        step, or None for a plain decode step).  Growth is host
        arithmetic over the same worst-case indices the overflow
        settling already tracks — no device reads, no new programs (the
        fresh table rides into the next dispatch as data).  A slot the
        pool cannot grow for — free list dry AND nothing evictable — is
        **shed** with the named :class:`PagePoolExhaustedError` message
        (``req.error``, counted in ``requests_shed``) and its pages
        free immediately, so the remaining traffic keeps stepping; the
        capacity signal is the error string, not a stall."""
        pg = self.engine.page_size
        for slot, req in enumerate(self.slots):
            if req is None or not step_act[slot]:
                continue
            st = self._state[slot]
            width = 1 + (int(lens[slot]) if lens is not None else 0)
            # pos_hi is a worst-case bound that runs one ahead of the
            # true engine index (the admission pseudo-window settles
            # into it), so near max_seq it can demand a page past the
            # table.  Clamp to the table: the kernel clamps any
            # actually-out-of-range write to position max_seq - 1,
            # which is always in the slot's own LAST page — never a
            # shared one, since prefix hits are capped at
            # (prompt_len - 1) // page_size full pages — and such
            # writes are post-budget garbage the harvest ignores
            # (exactly the dense arena's clamped-write discipline).
            need = min(-(-(st.pos_hi + width) // pg),
                       self.engine.n_ptab)
            pages = self._slot_pages[slot]
            try:
                while len(pages) < need:
                    p = self.pages.alloc()
                    self._ptab[slot, len(pages)] = p
                    pages.append(p)
            except PagePoolExhaustedError as e:
                self._finish_error(
                    req, f"{e} (shed after {len(req.tokens)} harvested "
                         f"tokens)", self.metrics.on_shed, "shed")
                self.observer.event("page_pool_shed", slot=slot,
                                    **self._corr(req))
                self._retire(slot)
        # growth may have evicted cached pages; spill them before the
        # caller's dispatch rewrites them
        self._spill_evicted()

    def _spill_evicted(self) -> None:
        """Drain the allocator's pending evictions into the spill store
        with ONE batched extract (round 23).  Must run after any alloc
        burst and BEFORE the next program dispatch rewrites the evicted
        pages — ``extract_pages_batch`` is a host sync, so the payloads
        are safely on the host before anything else reaches the device
        stream.  Best-effort by design: a failure here drops the
        payloads (those prefixes recompute later) and never breaks
        admission or a live decode."""
        if self.spill is None or self.pages is None \
                or not self.pages.pending_spills:
            return
        evs = self.pages.pending_spills
        self.pages.pending_spills = []
        t0 = time.perf_counter()
        try:
            data = self.engine.extract_pages_batch(
                self.arena, [p for _, p in evs])
        except Exception:
            return
        dt = time.perf_counter() - t0
        nbytes = 0
        for i, (h, _) in enumerate(evs):
            payload = jax.tree.map(lambda a, i=i: a[i:i + 1], data)
            nbytes += payload_nbytes(payload)
            self.spill.put(h, payload)
        self.metrics.on_spill(len(evs), nbytes, dt)
        self.observer.event("page_spilled", pages=len(evs),
                            nbytes=nbytes, host_pages=len(self.spill))

    # ---- drafting -----------------------------------------------------

    def _spec_desires(self):
        """Per-slot speculative draft desires ``{slot: k}`` for this
        step, over DECODING slots only (a prefilling slot has nothing
        to speculate about yet), each already clamped to its own room,
        budget, and adaptive k."""
        max_seq = self.engine.max_seq
        desires = {}
        for slot, req in enumerate(self.slots):
            if not self._active[slot]:
                continue
            st = self._state[slot]
            if st.prefilling or not req.speculate:
                continue
            room = max_seq - 1 - st.pos_hi
            remaining = self._budget(req) - req._guaranteed
            des = min(st.k_cur, req.speculate, remaining - 1, room)
            if des > 0:
                desires[slot] = des
        return desires

    def _plan_chunks(self):
        """Choose this step's prefill chunks ``{slot: width}`` under the
        per-step token budget (``chunk_tokens``), FIFO over the
        prefilling slots.  The one sequencing rule: a prompt that fills
        ``max_seq`` to the brim must never be left a 1-token final
        chunk — a verify window there (always >= 2 positions wide)
        would clamp backward over the prompt's own written positions —
        so the penultimate chunk shrinks (or the final pair goes out
        atomically, overshooting the budget by one token)."""
        if self.chunk_tokens is None:
            return {}
        max_seq = self.engine.max_seq
        plan = {}
        budget = self.chunk_tokens
        filling = [s for s in range(self.engine.n_slots)
                   if self._active[s] and self._state[s] is not None
                   and self._state[s].prefilling]
        for slot in sorted(filling, key=lambda s: self._state[s].rid):
            if budget < 1:
                break
            st = self._state[slot]
            remaining = st.fill_end - st.fill_next
            w = min(budget, remaining)
            if st.fill_end == max_seq and remaining - w == 1:
                w = remaining - 2 if remaining > 2 else 2
            plan[slot] = w
            budget -= w
        return plan

    # ---- the decode round --------------------------------------------

    def step(self) -> int:
        """One watchdog + admit + draft + decode/verify round; returns
        how many slots stepped.  Engine failures are contained to the
        in-flight batch (see :meth:`_contain`); deadline-expired
        requests retire with ``req.error`` before any work is spent on
        them this round."""
        self._expire()
        with self.observer.span("admit"):
            self._admit()
        # overflow settling: a speculative slot's worst-case index may
        # not leave room to write even one token — settle in-flight
        # steps until it does (only ever within k of max_seq)
        while self._pending and any(
                self._state[s].pos_hi > self.engine.max_seq - 1
                for s in range(self.engine.n_slots) if self._active[s]):
            with self.observer.span("harvest", forced=1):
                self._harvest_one()
        n_active = int(self._active.sum())
        if n_active:
            try:
                self._dispatch_round(n_active)
            except Exception as e:
                # containment: fail the in-flight batch, keep serving
                self._contain(e)
        self.step_count += 1
        self.metrics.on_step(n_active, self.engine.n_slots)
        if self.pages is not None:
            self.metrics.on_pages(self.pages.pages_in_use,
                                  self.pages.capacity)
        if len(self._pending) > self.harvest_lag:
            with self.observer.span("harvest"):
                while len(self._pending) > self.harvest_lag:
                    self._harvest_one()
        elif not n_active and self._pending:
            # nothing is decoding, so the lag buys no pipelining: a
            # parked prefill_only slot (awaiting its first-token
            # harvest to hand off) would otherwise sit under the lag
            # threshold forever
            with self.observer.span("harvest", idle=1):
                self._harvest_one()
        if self.exporter is not None:
            # harvest boundary: the metrics this samples were already
            # settled by the lag harvest above — host counters only,
            # and the exporter's own interval throttle decides whether
            # this boundary becomes a series point
            self.exporter.sample()
        return n_active

    def _dispatch_round(self, n_active: int):
        """The draft/chunk planning + decode/verify dispatch of one
        round (factored out so step() can contain an engine failure to
        this batch).  One compiled step serves the whole mix: decoding
        slots ride as before (plain or speculative), prefilling slots
        that drew a chunk this step ride the SAME verify program as
        forced rows (round 19) — so a long prompt's admission costs
        each decode step at most ``chunk_tokens`` of extra compute
        instead of a whole-prompt prefill stall."""
        B = self.engine.n_slots
        max_seq = self.engine.max_seq
        desires = self._spec_desires()
        chunk_plan = self._plan_chunks()
        # the step mask: decoding slots always; prefilling slots only
        # when they drew a chunk (their index must not advance a step
        # they are not part of)
        step_act = self._active.copy()
        for slot in range(B):
            st = self._state[slot]
            if st is not None and step_act[slot] and st.prefilling \
                    and slot not in chunk_plan:
                step_act[slot] = False
        # grammar gate: a constrained slot dispatches only when nothing
        # of its own is in flight — the token mask is a function of the
        # automaton state, which is exact only over HARVESTED truth.
        # Prefill chunks are exempt (prompt truth carries no automaton
        # state).  Speculation recovers the throughput the gate costs:
        # the one outstanding verify step still commits up to k+1
        # tokens, all masked by walking the DFA along the draft.
        gated = False
        for slot in range(B):
            req, st = self.slots[slot], self._state[slot]
            if req is None or req.grammar is None or not step_act[slot] \
                    or st.prefilling:
                continue
            if st.inflight:
                step_act[slot] = False
                desires.pop(slot, None)
                gated = True
        if not step_act.any():
            if gated and self._pending:
                # settle the oldest window so the gated automata advance
                # and the next round can dispatch them — without this a
                # lone constrained slot would never reach the lag
                # threshold and the loop would spin forever
                with self.observer.span("harvest", grammar=1):
                    self._harvest_one()
            return
        # the room bound covers EVERY active slot, stepped or not: the
        # dense verify scatter writes its k_prog+1 window into every
        # row (inactive rows write garbage at their own index), and a
        # window overflowing max_seq would CLAMP backward over a
        # sitting-out slot's committed prompt K/V — paged engines route
        # inactive writes to the garbage page, dense rows have no such
        # shield, so the transformer-layer contract (pos + s_new <=
        # max_seq for every row) is enforced fleet-wide here
        k_room = min(max_seq - 1 - self._state[s].pos_hi
                     for s in range(B) if self._active[s])
        if k_room < 1 and (desires or chunk_plan):
            # some stepped slot has room for exactly one more token (it
            # retires on this write): no k>=1 verify window fits, so
            # spec waits and chunks sit out one round — plain decode
            # clears the full slot and the next round resumes
            desires, chunk_plan = {}, {}
            for slot in range(B):
                st = self._state[slot]
                if st is not None and step_act[slot] and st.prefilling:
                    step_act[slot] = False
            if not step_act.any():
                return
        k_need = max([0] + list(desires.values())
                     + [w - 1 for w in chunk_plan.values()]
                     + ([1] if chunk_plan else []))
        drafts = lens = None
        n_drafted = 0
        if k_need > 0:
            k_prog = 1
            while k_prog < k_need:
                k_prog *= 2
            while k_prog > k_room and k_prog > 1:
                k_prog //= 2
            # re-cap chunks to the final program width (another slot's
            # room may have shrunk k_prog below the planned width)
            for slot in list(chunk_plan):
                st = self._state[slot]
                w = min(chunk_plan[slot], k_prog + 1)
                remaining = st.fill_end - st.fill_next
                if st.fill_end == max_seq and remaining - w == 1:
                    w -= 1          # never strand a 1-token final chunk
                if w < 1:
                    del chunk_plan[slot]
                    step_act[slot] = False
                else:
                    chunk_plan[slot] = w
            if not step_act.any():
                return
            drafts = np.zeros((B, k_prog), np.int32)
            lens = np.zeros(B, np.int32)
            forced = np.zeros(B, bool)
            first_tok = np.zeros(B, np.int32)
            pos_set = np.zeros(B, np.int32)
            t_draft = time.perf_counter()
            with self.observer.span("draft", n_active=n_active):
                for slot, des in desires.items():
                    req, st = self.slots[slot], self._state[slot]
                    want = min(des, k_prog)
                    gap = st.gap_est
                    # audit: ok[host-sync-asarray] drafting context from host prompt/token lists
                    ctx = np.asarray(list(req.prompt) + req.tokens,
                                     np.int32)
                    # audit: ok[host-sync-asarray] host-side draft source output (draft_s meters this phase)
                    pred = np.asarray(
                        self.draft.propose(ctx, gap + want), np.int32)
                    cand = pred[gap:gap + want]   # skip in-flight gap
                    if req.grammar is not None:
                        # trim at the first illegal draft token: the
                        # verify mask would reject everything from it
                        # on anyway (wasted k), and a shorter draft
                        # keeps the acceptance EMA honest.  gap is 0
                        # here (the grammar gate dispatches only with
                        # an empty inflight queue) so ``req._gq`` is
                        # exactly the state the draft continues from.
                        q, keep = req._gq, 0
                        for t in cand:
                            q = req.grammar.step(q, int(t))
                            if q < 0:
                                break
                            keep += 1
                        if keep < cand.size:
                            self.metrics.on_grammar_reject(
                                int(cand.size) - keep)
                            cand = cand[:keep]
                    dl = int(cand.size)
                    drafts[slot, :dl] = cand
                    lens[slot] = dl
                    n_drafted += dl
            self.metrics.on_draft(time.perf_counter() - t_draft)
            for slot, w in chunk_plan.items():
                st = self._state[slot]
                toks = st.fill_toks[st.fill_next:st.fill_next + w]
                first_tok[slot] = toks[0]
                drafts[slot, :w - 1] = toks[1:]
                lens[slot] = w - 1
                forced[slot] = True
                pos_set[slot] = st.fill_next
            if n_drafted == 0 and not chunk_plan:
                k_need = 0           # drafts came back empty: decode
        tables = None
        if self.pages is not None:
            self._grow_pages(step_act, lens if k_need > 0 else None)
            step_act &= self._active     # growth may have shed slots
            if not step_act.any():
                return
            tables = self._ptab          # snapshot copied at dispatch
        if k_need > 0:
            entries = []
            for slot in range(B):
                if not step_act[slot]:
                    continue
                req = self.slots[slot]
                if slot in chunk_plan:
                    st = self._state[slot]
                    w = chunk_plan[slot]
                    final = st.fill_next + w == st.fill_end
                    # kind 1 = intermediate chunk (nothing delivered),
                    # kind 2 = final chunk (deliver the bonus = the
                    # request's first token); dl rides as 0 so the
                    # harvest never counts prompt truth as speculation
                    entries.append((slot, req.rid, 0, 2 if final else 1))
                else:
                    entries.append((slot, req.rid, int(lens[slot]), 0))
            entries = tuple(entries)
            g_allowed = self._grammar_masks(step_act, chunk_plan,
                                            drafts, lens, k_prog)
            with self.observer.span("verify", n_active=n_active,
                                    k=k_prog):
                (self.arena, self.last_tokens, window,
                 counts) = self.engine.verify(
                    self.arena, self.last_tokens, drafts, lens,
                    step_act, self._next_key(), self._temp,
                    self._topk, self._topp, page_tables=tables,
                    forced=forced, first_tok=first_tok,
                    pos_set=pos_set, allowed=g_allowed,
                    adapter_ids=(self._aids if self.engine.adapter_bank
                                 is not None else None))
            self._pending.append((window, counts, entries))
            if n_drafted:
                self.metrics.on_verify(k_prog)
            for slot, rid, dl, kind in entries:
                st = self._state[slot]
                if kind == 0:
                    st.dispatched(dl)
                    continue
                w = chunk_plan[slot]
                st.dispatched(w - 1, kind)   # worst-case index += w;
                st.fill_next += w            # output gap += 0 or 1
                self.metrics.on_chunk(w)
                if kind == 2 and self.pages is not None \
                        and self._slot_hashes[slot] is not None:
                    # prompt fully dispatched: publish its pages under
                    # their chain hashes now (single device stream —
                    # any later prefix-hit attend is ordered after
                    # these writes)
                    hashes, n_hits = self._slot_hashes[slot]
                    row = self._ptab[slot]
                    for i in range(n_hits, len(hashes)):
                        self.pages.register(hashes[i], int(row[i]))
                        self.kv_receipts.append(("add", hashes[i]))
                    self._slot_hashes[slot] = None
        else:
            entries = tuple(
                (slot, req.rid, 0, 0)
                for slot, req in enumerate(self.slots)
                if step_act[slot])
            g_allowed = None
            g_rows = [s for s in range(B) if step_act[s]
                      and self.slots[s] is not None
                      and self.slots[s].grammar is not None]
            if g_rows:
                g_allowed = np.ones(
                    (B, self.engine.model.vocab_size), bool)
                for s in g_rows:
                    r = self.slots[s]
                    g_allowed[s] = r.grammar.mask(r._gq)
            with self.observer.span("dispatch", n_active=n_active):
                self.arena, self.last_tokens, _ = self.engine.decode(
                    self.arena, self.last_tokens, step_act,
                    self._next_key(), self._temp, self._topk,
                    self._topp, page_tables=tables, allowed=g_allowed,
                    adapter_ids=(self._aids if self.engine.adapter_bank
                                 is not None else None))
            self._pending.append((self.last_tokens, None, entries))
            for slot, rid, _, _ in entries:
                self._state[slot].dispatched(0)
        for slot, rid, dl, kind in entries:
            if kind == 1:
                continue             # no token guaranteed by a chunk
            req = self.slots[slot]
            req._guaranteed += 1
            if req._guaranteed >= self._budget(req):
                self._retire(slot)
            elif kind == 2 and req.prefill_only:
                # prefill-role replica: park until the first token
                # harvests and the page payload is extracted
                self._active[slot] = False

    def _grammar_masks(self, step_act, chunk_plan, drafts, lens,
                       k_prog):
        """Per-position allowed-token masks for one verify step, or
        None when no stepped slot is grammar-constrained (the engine
        then reuses its cached all-true mask — nothing uploads).

        Rows are host numpy slices of each DFA's precomputed ``allow``
        table — building the [B, k+1, V] block is pure host indexing at
        the dispatch boundary, uploaded as data like the page tables.
        For a decode/spec row, position 0 masks from the harvested
        state and each later position from the state after the
        corresponding (pre-trimmed, hence legal) draft token; for a
        chunk row only the FINAL chunk's bonus position is constrained
        (the request's first output token — start-state mask), prompt
        echo positions are forced-accept and stay all-true."""
        B = self.engine.n_slots
        rows = [s for s in range(B) if step_act[s]
                and self.slots[s] is not None
                and self.slots[s].grammar is not None]
        if not rows:
            return None
        allowed = np.ones((B, k_prog + 1,
                           self.engine.model.vocab_size), bool)
        for slot in rows:
            req = self.slots[slot]
            dfa = req.grammar
            if slot in chunk_plan:
                st = self._state[slot]
                w = chunk_plan[slot]
                if st.fill_next + w == st.fill_end:
                    allowed[slot, w - 1] = dfa.mask(dfa.start)
                continue
            q = req._gq
            allowed[slot, 0] = dfa.mask(q)
            for i in range(int(lens[slot])):
                q = dfa.step(q, int(drafts[slot, i]))
                allowed[slot, i + 1] = dfa.mask(q)
        return allowed

    # ---- harvest ------------------------------------------------------

    def _harvest_one(self):
        window, counts, entries = self._pending.popleft()
        # audit: ok[host-sync-asarray] the lag harvest — blocks only until the k-steps-lagged window
        arr = np.asarray(window)  # blocks only until THIS (lagged) step
        # audit: ok[host-sync-asarray] the lag harvest — the sanctioned boundary read (counts)
        cnt = np.asarray(counts) if counts is not None else None
        now = time.perf_counter()
        for slot, rid, dl, kind in entries:
            req = self._reqs[rid]
            n_em = int(cnt[slot]) if cnt is not None else 1
            if kind == 1:
                # intermediate prefill chunk: the window is prompt echo
                # plus a throwaway bonus prediction — nothing delivered
                toks = arr[slot, :0]
            elif kind == 2:
                # final prefill chunk: deliver ONLY the bonus sample —
                # the request's first generated token (the prompt echo
                # before it committed to cache, not to output)
                toks = arr[slot, n_em - 1:n_em]
            else:
                toks = (arr[slot, :n_em] if arr.ndim == 2
                        else arr[slot:slot + 1])
            st = self._state[slot]
            if st is not None and st.rid == rid:
                st.settle(dl, n_em)
            if dl:
                self.metrics.on_spec_harvest(dl, n_em - 1)
            if req.done:         # post-eos/budget garbage from the lag
                continue         # window (or spec overshoot)
            budget = self._budget(req)
            first_window = len(req.tokens) == 0
            delivered = 0
            for t in toks:
                req.tokens.append(int(t))
                delivered += 1
                if req.grammar is not None:
                    # advance the automaton over the delivered token —
                    # this is the state every later dispatch masks
                    # from.  A rejection here is defense in depth (the
                    # dispatch masks make it unreachable for sampled
                    # tokens): contain it as a failed request, never
                    # deliver the illegal token.
                    q = req.grammar.step(req._gq, int(t))
                    if q < 0:
                        req.tokens.pop()
                        delivered -= 1
                        self.observer.event(
                            "grammar_violation", token=int(t),
                            reason="illegal", **self._corr(req))
                        self._finish_error(
                            req, f"grammar violation: token {int(t)} "
                                 f"is illegal in automaton state "
                                 f"{req._gq}",
                            self.metrics.on_failure, "failed")
                        break
                    req._gq = q
                if len(req.tokens) == 1:
                    req.t_first = now
                    self.metrics.on_first_token(req)
                    self.observer.event("request_first_token",
                                        slot=slot, **self._corr(req))
                hit_eos = (req.eos_id is not None
                           and req.tokens[-1] == req.eos_id)
                if hit_eos or len(req.tokens) >= budget:
                    req.done = True
                    req.t_done = now
                    self.finished.append(req)
                    self.metrics.on_finish(req)
                    corr = self._corr(req)
                    self.observer.event("request_finished",
                                        tokens=len(req.tokens),
                                        eos=int(hit_eos), **corr)
                    if req.grammar is not None \
                            and not req.grammar.accept[req._gq]:
                        # token budget ran out mid-structure: the
                        # output is legal-so-far but not a complete
                        # utterance of the grammar — observable, not
                        # an error (EOS can only land in accepting
                        # states, so this is always a truncation)
                        self.observer.event("grammar_violation",
                                            reason="incomplete", **corr)
                    self.observer.flow(
                        "req", corr["rid"],
                        "step" if req.origin_rid is not None else "end")
                    break        # EOS mid-window trims exactly
            # decode-token accounting counts DELIVERED generated tokens
            # (the request's very first token is the prefill's)
            self.metrics.on_harvest_tokens(
                delivered - (1 if first_window and delivered else 0))
            if delivered:
                self.metrics.on_adapter_tokens(req.adapter or "base",
                                               delivered)
                if req.stream is not None:
                    # incremental delivery from the lag-harvested
                    # window: first offerer owns the stream (hedge
                    # losers get 0), extensions are prefix-guarded
                    n = req.stream.offer(req.rid, req.tokens)
                    if n:
                        self.metrics.on_stream(n)
                        self.observer.event("stream_delivery", tokens=n,
                                            **self._corr(req))
            if req.prefill_only and not req.done and req.tokens:
                # prefill-role completion: first token known, more
                # generation owed — export the page payload for the
                # decode half of the flight (round 19)
                self._handoff_out(slot, req)
            if req.done and req.error is None:
                # success terminal: a standalone request closes its
                # stream here (reconciling any unoffered suffix); a
                # fleet attempt leaves it to the Router's _finish_user
                self._stream_terminal(req)
            if req.done and self.slots[slot] is req:
                self._retire(slot)

    def _handoff_out(self, slot: int, req: Request):
        """Finish a ``prefill_only`` request by exporting its prompt's
        K/V pages to host (the ONE deliberate sync of the handoff path
        — its cost is the ``kv_handoff_s`` metric) and attaching the
        payload a decode replica's ``kv_inject`` admission adopts.  The
        slot's pages are released only after extraction (the caller's
        retire), so a mid-handoff expiry can never free them early."""
        pg = self.engine.page_size
        n_pg = -(-len(req.prompt) // pg)
        pages = self._slot_pages[slot][:n_pg]
        t0 = time.perf_counter()
        data = self.engine.extract_pages(self.arena, pages)
        dt = time.perf_counter() - t0
        req.kv_handoff = {
            "prompt": [int(t) for t in req.prompt],
            "first_token": int(req.tokens[0]),
            "n_pages": n_pg,
            "data": data,
            "t_first": req.t_first,
        }
        self.metrics.on_kv_handoff(n_pg, dt)
        corr = self._corr(req)
        self.observer.event("kv_handoff", side="extract", pages=n_pg,
                            **corr)
        req.done = True
        req.t_done = time.perf_counter()
        self.finished.append(req)
        self.metrics.on_finish(req)
        self.observer.event("request_finished", tokens=len(req.tokens),
                            eos=0, **corr)
        self.observer.flow(
            "req", corr["rid"],
            "step" if req.origin_rid is not None else "end")

    def drain(self):
        """Harvest everything still in flight (the boundary sync)."""
        with self.observer.span("drain"):
            while self._pending:
                self._harvest_one()
        if self.exporter is not None:
            self.exporter.sample()

    # ---- shutdown -----------------------------------------------------

    def shutdown(self, drain: bool = True) -> None:
        """Stop the intake and wind the scheduler down.

        ``drain=True`` (graceful): queued-but-unadmitted requests are
        aborted with a named error (they never started; re-submittable
        elsewhere), in-flight requests run to completion, and every
        pending harvest settles — no generated token is lost.
        ``drain=False`` (abort): no further steps are dispatched;
        already-computed harvest windows are still settled (pure host
        reads — a request that only awaited the lag harvest finishes
        cleanly instead of being orphaned), then the remaining in-flight
        requests retire with ``req.error`` set.  Idempotent; ``submit``
        after shutdown rejects.
        """
        already = self._closed
        self._closed = True
        while self.queue:
            # on_abort, not on_reject: these were counted by on_submit
            # already — on_reject's n_submitted increment would double-
            # count them and break the submitted == finished+rejected+
            # expired+failed+aborted invariant
            self._finish_error(self.queue.popleft(),
                               "scheduler shut down before admission",
                               self.metrics.on_abort, "aborted")
        if already:
            return
        self.observer.event("scheduler_shutdown", drain=int(drain))
        if drain:
            while any(s is not None for s in self.slots):
                self.step()
            self.drain()
            if self.exporter is not None:
                self.exporter.sample(force=True)   # the final point
            return
        self.drain()     # settle what the device already computed
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            # a deliberate abort, not an engine failure: counted under
            # requests_aborted so the failure alert stays meaningful
            self._finish_error(req, "scheduler shut down",
                               self.metrics.on_abort, "aborted")
            self._retire(slot)
        if self.exporter is not None:
            self.exporter.sample(force=True)

    def __enter__(self) -> "Scheduler":
        return self

    def __exit__(self, exc_type, *exc) -> bool:
        # clean exit drains gracefully; an exception aborts (stepping a
        # possibly-broken engine to drain would compound the failure)
        self.shutdown(drain=exc_type is None)
        return False

    # ---- driver -------------------------------------------------------

    def run(self, requests: Sequence[Request] = ()) -> list[Request]:
        for r in requests:
            self.submit(r)
        while self.queue or any(s is not None for s in self.slots):
            self.step()
        self.drain()
        return self.finished
