"""Slot-based continuous batcher over the InferenceEngine.

Orca-style iteration-level scheduling on fixed XLA shapes: the engine's
decode program always steps all ``n_slots`` arena rows; this module
decides *what occupies the rows*.  A request is admitted into the first
free slot (one bucketed prefill), decodes in lockstep with whatever else
is in flight, and retires the moment its budget is exhausted — freeing
the row for the next queued request **mid-flight**, while the other
slots keep decoding.  Short requests never wait for long ones and the
batch never pads to the longest request; the only granularity is one
decode step.

Dispatch discipline (PR 1, SCALING.md "Async dispatch discipline"): the
loop never reads a device value it just dispatched.  The decode feedback
path — sampled token back in as next input — stays ON DEVICE via the
``last_tokens`` vector, so back-to-back steps pipeline without any
host↔device round-trip.  Host-side bookkeeping uses only what the host
already knows at dispatch time (slot occupancy, per-request token
budgets).  Sampled tokens reach the host through a **lag harvest**: each
step's token vector enters a bounded queue and is converted
``harvest_lag`` steps later, when the device has long finished (the same
backpressure shape as metrics.MetricsQueue).  The one consequence: EOS
detection is late by up to ``harvest_lag`` steps, so a slot decodes up
to that many garbage tokens past its stop token before retiring — they
are trimmed from the output at harvest.  ``harvest_lag=0`` restores
sync-every-step EOS exactness at sync-every-step cost.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from collections import deque
from typing import Any, Optional, Sequence

import jax
import numpy as np

from dtdl_tpu.obs.observer import NULL_OBSERVER
from dtdl_tpu.serve.engine import InferenceEngine
from dtdl_tpu.serve.metrics import ServeMetrics
from dtdl_tpu.serve.sampling import GREEDY, SampleParams

_ids = itertools.count()


@dataclasses.dataclass
class Request:
    """One generation request plus its lifecycle record.

    ``tokens`` fills with the generated tokens (eos included, post-eos
    trimmed) as they harvest; ``done`` flips when the last one lands.
    """
    prompt: Sequence[int]
    max_new_tokens: int
    sampling: SampleParams = GREEDY
    eos_id: Optional[int] = None
    rid: int = dataclasses.field(default_factory=lambda: next(_ids))
    tokens: list = dataclasses.field(default_factory=list)
    done: bool = False
    # wall-clock lifecycle (host side; first/done are harvest times, i.e.
    # when the host could actually observe the token)
    t_submit: float = 0.0
    t_admit: float = 0.0
    t_first: float = 0.0
    t_done: float = 0.0
    admit_step: int = -1
    # internal: tokens dispatched / slot retired (budget exhausted)
    _dispatched: int = dataclasses.field(default=0, repr=False)
    _retired: bool = dataclasses.field(default=False, repr=False)

    def __post_init__(self):
        if self.max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got "
                             f"{self.max_new_tokens}")


class Scheduler:
    """Continuous batcher (see module docstring).

    ``submit`` enqueues; ``step`` runs one admit+decode round; ``run``
    drives until everything submitted has finished and returns the
    finished requests in completion order.
    """

    def __init__(self, engine: InferenceEngine, seed: int = 0,
                 harvest_lag: int = 4, metrics: ServeMetrics = None,
                 observer=None):
        if harvest_lag < 0:
            raise ValueError(f"harvest_lag must be >= 0, got "
                             f"{harvest_lag}")
        # obs facade: thread-safe spans (admit/dispatch/harvest) + the
        # engine's recompile sentinel; defaults to all-no-ops
        self.observer = observer or NULL_OBSERVER
        if observer is not None and engine.observer is None:
            engine.observer = observer   # sentinel on prefill/decode jits
        self.engine = engine
        self.arena = engine.init_arena()
        self.last_tokens = engine.init_last_tokens()
        self.queue: deque[Request] = deque()
        self.slots: list[Optional[Request]] = [None] * engine.n_slots
        self.harvest_lag = harvest_lag
        self.metrics = metrics or ServeMetrics(n_slots=engine.n_slots)
        self.finished: list[Request] = []
        self._reqs: dict[int, Request] = {}
        self._active = np.zeros(engine.n_slots, bool)
        self._temp = np.zeros(engine.n_slots, np.float32)
        self._topk = np.zeros(engine.n_slots, np.int32)
        self._topp = np.ones(engine.n_slots, np.float32)
        self._key = jax.random.PRNGKey(seed)
        # lag harvest: (token_vector_device, ((slot, rid, gen_idx), ...))
        self._pending: deque[tuple[Any, tuple]] = deque()
        self.step_count = 0

    # ---- intake -------------------------------------------------------

    def submit(self, req: Request) -> Request:
        # full admission validation HERE: a bad request rejected at
        # admit time would already be popped from the queue and would
        # strand every other in-flight request mid-run
        prompt_len = len(req.prompt)
        if prompt_len < 1:
            raise ValueError("empty prompt")
        if prompt_len > self.engine.buckets[-1]:
            raise ValueError(
                f"prompt length {prompt_len} exceeds the largest "
                f"prefill bucket {self.engine.buckets[-1]} "
                f"(max_seq={self.engine.max_seq})")
        req.t_submit = time.perf_counter()
        self._reqs[req.rid] = req
        self.queue.append(req)
        self.metrics.on_submit(req)
        return req

    # ---- slot lifecycle ----------------------------------------------

    def _budget(self, req: Request) -> int:
        # the k-th decode step writes K/V at position len(prompt)+k-1,
        # which must stay < max_seq; prefill contributes token 1 for free
        return min(req.max_new_tokens,
                   self.engine.max_seq - len(req.prompt) + 1)

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _retire(self, slot: int):
        req = self.slots[slot]
        req._retired = True
        self.slots[slot] = None
        self._active[slot] = False

    def _admit(self):
        for slot in range(self.engine.n_slots):
            if self.slots[slot] is not None or not self.queue:
                continue
            req = self.queue.popleft()
            sp = req.sampling
            self.arena, self.last_tokens, _ = self.engine.prefill(
                self.arena, self.last_tokens, slot, req.prompt, sp,
                self._next_key())
            self.slots[slot] = req
            self._active[slot] = True
            self._temp[slot] = sp.temperature
            self._topk[slot] = sp.top_k
            self._topp[slot] = sp.top_p
            req.t_admit = time.perf_counter()
            req.admit_step = self.step_count
            req._dispatched = 1
            self._pending.append(
                (self.last_tokens, ((slot, req.rid, 0),)))
            self.metrics.on_admit(req, slot, len(req.prompt))
            if req._dispatched >= self._budget(req):
                self._retire(slot)

    # ---- the decode round --------------------------------------------

    def step(self) -> int:
        """One admit + decode round; returns how many slots decoded."""
        with self.observer.span("admit"):
            self._admit()
        n_active = int(self._active.sum())
        if n_active:
            entries = []
            for slot, req in enumerate(self.slots):
                if self._active[slot]:
                    entries.append((slot, req.rid, req._dispatched))
            with self.observer.span("dispatch", n_active=n_active):
                self.arena, self.last_tokens, _ = self.engine.decode(
                    self.arena, self.last_tokens, self._active,
                    self._next_key(), self._temp, self._topk, self._topp)
            self._pending.append((self.last_tokens, tuple(entries)))
            for slot, req in enumerate(self.slots):
                if self._active[slot]:
                    req._dispatched += 1
                    if req._dispatched >= self._budget(req):
                        self._retire(slot)
        self.step_count += 1
        self.metrics.on_step(n_active, self.engine.n_slots)
        if len(self._pending) > self.harvest_lag:
            with self.observer.span("harvest"):
                while len(self._pending) > self.harvest_lag:
                    self._harvest_one()
        return n_active

    # ---- harvest ------------------------------------------------------

    def _harvest_one(self):
        vec, entries = self._pending.popleft()
        arr = np.asarray(vec)   # blocks only until THIS (lagged) step
        now = time.perf_counter()
        for slot, rid, gen_idx in entries:
            req = self._reqs[rid]
            if req.done:         # post-eos garbage from the lag window
                continue
            req.tokens.append(int(arr[slot]))
            if gen_idx == 0:
                req.t_first = now
                self.metrics.on_first_token(req)
            hit_eos = (req.eos_id is not None
                       and req.tokens[-1] == req.eos_id)
            if hit_eos and self.slots[slot] is req:
                # EOS observed `lag` steps after dispatch: stop decoding
                self._retire(slot)
            if hit_eos or (req._retired
                           and len(req.tokens) >= req._dispatched):
                req.done = True
                req.t_done = now
                self.finished.append(req)
                self.metrics.on_finish(req)

    def drain(self):
        """Harvest everything still in flight (the boundary sync)."""
        with self.observer.span("drain"):
            while self._pending:
                self._harvest_one()

    # ---- driver -------------------------------------------------------

    def run(self, requests: Sequence[Request] = ()) -> list[Request]:
        for r in requests:
            self.submit(r)
        while self.queue or any(s is not None for s in self.slots):
            self.step()
        self.drain()
        return self.finished
